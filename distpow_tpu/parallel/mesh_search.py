"""Multi-device search over a ``jax.sharding.Mesh`` — prefix -> core.

This is the TPU-native form of the coordinator's fan-out (SURVEY.md
section 2, strategies 1-2): inside one worker process, the worker's
thread-byte range is sub-partitioned across the devices of a mesh exactly
the way the coordinator partitions it across workers
(coordinator.go:326, worker.go:301-316) — prefix -> core instead of
prefix -> RPC peer.  The "first result wins, everyone stops" protocol
(coordinator.go:202-230) compresses onto ICI: every step ends in a
``lax.pmin`` of the per-device first-hit flat index, so all devices
observe a win at the same step boundary and the host stops dispatching —
the Found broadcast without any RPC.

Two sharding regimes, chosen automatically:

* **thread-byte split** (the common case): each device owns a contiguous
  slice of the thread-byte run and scans the same chunk range in lockstep.
* **chunk split** (when there are fewer thread bytes than devices): each
  device owns a contiguous slice of the chunk range instead.

Both regimes report hits as *global* flat indices (chunk-major,
thread-byte-minor over the whole worker partition), so the driver's decode
and the reference enumeration-order guarantee are identical to the
single-device path.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.registry import HashModel, get_hash_model
from ..ops.difficulty import nibble_masks
from ..ops.packing import build_tail_spec
from ..ops.search_step import (
    SENTINEL,
    _check_launch,
    _eval_candidates,
    cached_search_step,
    eval_dyn_candidates,
    fold_dyn_masks,
    mask_words_for,
    step_operands,
)
from .compat import pvary as _pvary
from .compat import shard_map as _shard_map
from .partition import contiguous_bounds
from .search import SearchResult, StepFactory, search

AXIS = "workers"

log = logging.getLogger("distpow.mesh_search")


def make_mesh(devices: Optional[Sequence] = None, axis: str = AXIS) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    from ..runtime.metrics import REGISTRY as metrics

    metrics.gauge("search.mesh_devices", len(devs))
    return Mesh(np.array(devs), (axis,))


@functools.lru_cache(maxsize=None)
def _dyn_mesh_step(
    mesh: Mesh,
    axis: str,
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch_local: int,
    tb_split: bool,
    log_ndev: int,
    launch_steps: int = 1,
    mask_words: int = 0,  # 0 => all digest words significant
):
    """Layout-keyed jitted mesh step (the dynamic regime of
    ops/search_step.py, spread over the device mesh).

    Returned fn: ``(init[S], base[n_blocks,16], masks[D],
    part[2]=(tb_lo, log_tbc), chunk0) -> uint32`` — the *global* first-hit
    flat index after the ``lax.pmin`` collective, or SENTINEL.

    ``launch_steps`` consecutive sub-batches run per dispatch in an
    on-device fori_loop (see ops/search_step.py); each sub-batch advances
    the global flat index by ``batch_local * n_dev`` and the chunk base by
    the same count of candidates — identical in both sharding regimes, so
    the loop body is regime-agnostic.
    """
    model = get_hash_model(model_name)
    one = jnp.uint32(1)
    mw = mask_words or model.digest_words
    batch_global = batch_local << log_ndev
    # same uint32 flat-index bound the single-device steps enforce
    # (ops/search_step.py _check_launch) — a MaxLaunchCandidates > 2^31
    # must raise here too, not silently wrap the global index
    _check_launch(batch_global, launch_steps)

    def body(init, base, masks, part, chunk0):
        d = jax.lax.axis_index(axis).astype(jnp.uint32)
        tb_lo, log_tbc = part[0], part[1]
        fl = jnp.arange(batch_local, dtype=jnp.uint32)
        if tb_split:
            log_tbl = log_tbc - jnp.uint32(log_ndev)
            chunk_off0 = fl >> log_tbl
            tb_local = fl & ((one << log_tbl) - one)
            tb = tb_lo + (d << log_tbl) + tb_local
            f_global0 = (chunk_off0 << log_tbc) + (d << log_tbl) + tb_local
        else:
            chunks_local = jnp.uint32(batch_local) >> log_tbc
            chunk_off0 = d * chunks_local + (fl >> log_tbc)
            tb_idx = fl & ((one << log_tbc) - one)
            tb = tb_lo + tb_idx
            f_global0 = (chunk_off0 << log_tbc) + tb_idx
        gchunks = jnp.uint32(batch_global) >> log_tbc  # chunks per sub-batch

        def sub(i):
            chunk = jnp.uint32(chunk0) + chunk_off0 + i * gchunks
            state = eval_dyn_candidates(
                model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk
            )
            hit = fold_dyn_masks(model, state, masks, mw)
            f_global = f_global0 + i * jnp.uint32(batch_global)
            return jnp.min(jnp.where(hit, f_global, jnp.uint32(SENTINEL)))

        if launch_steps == 1:
            m = sub(jnp.uint32(0))
        else:
            # the loop carry must already be device-varying (its updates
            # depend on axis_index), or shard_map rejects the fori_loop
            init_best = _pvary(jnp.uint32(SENTINEL), axis)
            m = jax.lax.fori_loop(
                0,
                launch_steps,
                lambda i, best: jnp.minimum(best, sub(i.astype(jnp.uint32))),
                init_best,
            )
        return jax.lax.pmin(m, axis)

    sharded = _shard_map(
        body, mesh=mesh, in_specs=(P(), P(), P(), P(), P()), out_specs=P()
    )
    return jax.jit(sharded)


def _width0_probe(nonce: bytes, difficulty: int, tb_lo: int, tbc: int,
                  model: HashModel, extra: bytes):
    """Width-0 factory result shared by every mesh factory: 256
    candidates max — no mesh benefit; the single-device layout-keyed
    probe is warmup-covered."""
    return (
        cached_search_step(
            bytes(nonce), 0, difficulty, tb_lo, tbc, 1,
            model.name, bytes(extra),
        ),
        1,
    )


def _chunk_split_budget(target_chunks: int, tbc: int, n_dev: int) -> int:
    """Per-device chunk budget for the chunk-split regime, shared by the
    XLA and pallas mesh factories: normalize to a multiple of 256 so
    batch_local — the compile key — is independent of which pow2
    tbc < n_dev the request carries (one warmed program serves every
    small partition), and divide the global budget by n_dev so one
    dispatch never covers n_dev x the configured launch volume."""
    eb_local = max(256, (target_chunks * tbc // n_dev) // 256 * 256)
    return max(1, eb_local // tbc)


@functools.lru_cache(maxsize=None)
def _dyn_pallas_mesh_step(
    mesh: Mesh,
    axis: str,
    model_name: str,
    tb_word: int,
    tb_shift: int,
    chunk_word_shifts,
    grid: int,
    sublanes: int,
    inner: int,
    interpret: bool,
    mask_words: int,
    tb_split: bool,
    log_ndev: int,
    batch_local: int,
    launch_steps: int,
):
    """The Pallas search kernel spread over the device mesh.

    One compiled kernel program serves every device: the kernel's
    partition descriptor and chunk base are runtime SMEM operands, so
    inside ``shard_map`` each device derives its own from
    ``axis_index`` — tb-split hands device ``d`` the thread-byte slice
    ``(tb_lo + d*tbl, log2 tbl)``; chunk-split hands it a contiguous
    chunk span ``chunk0 + d * launch_steps * chunks_local``.  The
    kernel's local first-hit flat index is then mapped back to the TRUE
    global flat index (chunk-major over the whole worker partition) and
    ``lax.pmin`` picks the first hit in reference enumeration order —
    identical driver semantics to the XLA mesh step.

    Note the chunk-split DEVICE assignment differs from the XLA mesh
    step's (contiguous spans here vs per-sub-batch interleaving there):
    both cover the same candidate set and both return the minimal
    global flat index, so results are bit-identical either way.
    """
    from ..ops.md5_pallas import _dyn_pallas_step

    kernel = _dyn_pallas_step(
        tb_word, tb_shift, chunk_word_shifts, grid, sublanes, interpret,
        inner, mask_words, model_name,
    )
    one = jnp.uint32(1)
    _check_launch(batch_local << log_ndev, launch_steps)
    span_local = jnp.uint32(launch_steps * batch_local)

    def body(init, base, masks, part, chunk0):
        d = jax.lax.axis_index(axis).astype(jnp.uint32)
        tb_lo, log_tbc = part[0], part[1]
        if tb_split:
            log_tbl = log_tbc - jnp.uint32(log_ndev)
            part_dev = jnp.stack(
                [tb_lo + (d << log_tbl), log_tbl]).astype(jnp.uint32)
            f_l = kernel(jnp.uint32(chunk0), init, base, masks, part_dev)
            chunk_off = f_l >> log_tbl
            rest = f_l & ((one << log_tbl) - one)
            f_g = (chunk_off << log_tbc) + (d << log_tbl) + rest
        else:
            chunk_span = span_local >> log_tbc  # chunks per device
            c0_dev = jnp.uint32(chunk0) + d * chunk_span
            f_l = kernel(c0_dev, init, base, masks, part)
            f_g = d * span_local + f_l
        f_g = jnp.where(f_l == jnp.uint32(SENTINEL), jnp.uint32(SENTINEL),
                        f_g)
        return jax.lax.pmin(f_g, axis)

    # check_vma=False: pallas_call's out_shape carries no varying-axes
    # annotation, so shard_map's per-value VMA typing cannot see that the
    # kernel output is device-varying; the explicit pmin below is the
    # collective that makes the result replicated regardless.
    sharded = _shard_map(
        body, mesh=mesh, in_specs=(P(), P(), P(), P(), P()), out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def _pallas_mesh_step_factory(
    nonce: bytes,
    difficulty: int,
    tb_lo: int,
    tbc: int,
    model: HashModel,
    mesh: Mesh,
    axis: str,
    sublanes: Optional[int] = None,
    inner: Optional[int] = None,
    interpret: bool = False,
    max_launch: Optional[int] = None,
) -> StepFactory:
    """Step factory backed by the Pallas kernel per device.

    Raises ValueError for configurations the kernel cannot express
    (non-pow2 device count or partition, multi-block tails, models
    without a kernel); ``PallasMeshBackend`` catches these per width and
    falls back to the XLA mesh factory transparently.
    """
    from ..ops.md5_pallas import (
        INTERPRET_XLA_FALLBACK,
        LANES,
        MODEL_GEOMETRY,
        default_geometry,
    )

    n_dev = int(mesh.devices.size)
    if n_dev & (n_dev - 1):
        raise ValueError("pallas mesh requires a power-of-two device count")
    if tbc & (tbc - 1):
        raise ValueError("pallas kernel requires power-of-two tb_count")
    if model.name not in MODEL_GEOMETRY:
        raise ValueError(f"no pallas kernel for model {model.name}")
    if interpret and model.name in INTERPRET_XLA_FALLBACK:
        # same guard as build_pallas_search_step: interpret mode would
        # hand the unrolled limb-pair tile to XLA:CPU (pathological
        # compile); the mesh backend maps this to its XLA fallback
        raise ValueError(
            f"{model.name} pallas tile is TPU-only (interpret-mode "
            f"XLA:CPU compile of the limb-pair graph is pathological)"
        )
    geom = default_geometry(model.name, interpret)
    if sublanes is None:
        sublanes = geom[0]
    if inner is None:
        inner = geom[1]
    tile = sublanes * LANES
    tb_split = tbc >= n_dev and tbc % n_dev == 0
    log_ndev = n_dev.bit_length() - 1
    tbl = tbc // n_dev if tb_split else tbc

    @functools.lru_cache(maxsize=32)
    def bind(vw: int, extra: bytes, chunks_local: int, launch_steps: int):
        spec = build_tail_spec(bytes(nonce), vw, model, extra)
        if spec.n_blocks != 1:
            raise ValueError("pallas kernel requires a single-block tail")
        batch_local = chunks_local * tbl
        mw = mask_words_for(difficulty, model)
        inner_eff = max(1, inner)
        tiles = batch_local * launch_steps // tile
        while tiles % inner_eff:
            inner_eff //= 2
        grid = tiles // inner_eff
        _, tb_w, tb_s = spec.tb_loc
        chunk_ws = tuple((w, s) for _, w, s in spec.chunk_locs)
        dyn = _dyn_pallas_mesh_step(
            mesh, axis, model.name, tb_w, tb_s, chunk_ws, grid, sublanes,
            inner_eff, interpret, mw, tb_split, log_ndev, batch_local,
            launch_steps,
        )
        init, base, masks = step_operands(spec, difficulty, model)
        part = jnp.asarray([tb_lo, tbc.bit_length() - 1], jnp.uint32)

        def step(chunk0):
            return dyn(init, base[0], masks, part, chunk0)

        return step

    def factory(vw: int, extra: bytes, target_chunks: int, launch_steps: int = 1):
        if vw == 0:
            return _width0_probe(nonce, difficulty, tb_lo, tbc, model, extra)
        if tb_split:
            chunks_local = max(1, target_chunks)
        else:
            chunks_local = _chunk_split_budget(target_chunks, tbc, n_dev)
        batch_local = chunks_local * tbl
        # round the per-device batch up to a whole tile grid
        if batch_local % tile:
            batch_local = ((batch_local // tile) + 1) * tile
            chunks_local = max(1, batch_local // tbl)
            batch_local = chunks_local * tbl
            if batch_local % tile:
                raise ValueError(
                    f"per-device batch {batch_local} (tbl={tbl}) cannot "
                    f"align to tile {tile}"
                )
        # re-clamp the launch multiplier to the rounded GLOBAL batch:
        # the driver computed launch_steps for the unrounded batch, and
        # the launch must respect both the dispatch budget and the
        # uint32/int32 flat-index bound
        batch_global = batch_local << log_ndev
        budget = min(max_launch or (1 << 31) - 1, (1 << 31) - 1)
        k = max(1, min(launch_steps, budget // batch_global))
        step = bind(vw, bytes(extra), chunks_local, k)
        global_chunks = (chunks_local if tb_split
                         else chunks_local * n_dev) * k
        return step, global_chunks

    # resolved geometry, exposed so tests can pin the interpret-mode
    # sublanes cap at this site (default_geometry's third caller)
    factory.sublanes = sublanes
    factory.inner = inner
    return factory


def _mesh_step_factory(
    nonce: bytes,
    difficulty: int,
    tb_lo: int,
    tbc: int,
    model: HashModel,
    mesh: Mesh,
    axis: str,
) -> StepFactory:
    n_dev = int(mesh.devices.size)
    tb_split = tbc >= n_dev and tbc % n_dev == 0
    pow2 = (tbc & (tbc - 1)) == 0 and (n_dev & (n_dev - 1)) == 0

    @functools.lru_cache(maxsize=32)
    def bind_dyn(vw: int, extra: bytes, chunks_local: int, launch_steps: int):
        spec = build_tail_spec(bytes(nonce), vw, model, extra)
        tbl = tbc // n_dev if tb_split else tbc
        dyn = _dyn_mesh_step(
            mesh, axis, model.name, spec.n_blocks, spec.tb_loc,
            spec.chunk_locs, chunks_local * tbl, tb_split,
            n_dev.bit_length() - 1, launch_steps,
            mask_words_for(difficulty, model),
        )
        init, base, masks = step_operands(spec, difficulty, model)
        part = jnp.asarray([tb_lo, tbc.bit_length() - 1], jnp.uint32)

        def step(chunk0):
            return dyn(init, base, masks, part, chunk0)

        return step

    @functools.lru_cache(maxsize=32)
    def build_static(vw: int, extra: bytes, chunks_local: int):
        """Fallback for non-power-of-two partitions or device counts."""
        # say at REQUEST time why this request is about to stall
        # (VERDICT r2 weak #5): these programs bake the nonce, so no
        # warmup can cover them and each fresh nonce recompiles
        log.warning(
            "compiling a nonce-keyed static mesh program (devices=%d, "
            "tbc=%d — not both powers of two): expect a multi-second "
            "compile stall for each fresh nonce on this mesh; real TPU "
            "slices are powers of two and serve from warmed layout-keyed "
            "programs instead", n_dev, tbc,
        )
        spec = build_tail_spec(bytes(nonce), vw, model, extra)
        masks = nibble_masks(difficulty, model)

        if tb_split:
            tbl = tbc // n_dev

            def body(chunk0):
                d = jax.lax.axis_index(axis).astype(jnp.uint32)
                fl = jnp.arange(chunks_local * tbl, dtype=jnp.uint32)
                chunk_off = fl // jnp.uint32(tbl)
                tb_local = fl % jnp.uint32(tbl)
                tb = jnp.uint32(tb_lo) + d * jnp.uint32(tbl) + tb_local
                chunk = jnp.uint32(chunk0) + chunk_off
                hit = _eval_candidates(spec, masks, model, tb, chunk)
                f_global = (
                    chunk_off * jnp.uint32(tbc)
                    + d * jnp.uint32(tbl)
                    + tb_local
                )
                m = jnp.min(jnp.where(hit, f_global, jnp.uint32(SENTINEL)))
                return jax.lax.pmin(m, axis)

        else:

            def body(chunk0):
                d = jax.lax.axis_index(axis).astype(jnp.uint32)
                fl = jnp.arange(chunks_local * tbc, dtype=jnp.uint32)
                chunk_off_local = fl // jnp.uint32(tbc)
                tb_idx = fl % jnp.uint32(tbc)
                chunk_off = d * jnp.uint32(chunks_local) + chunk_off_local
                tb = jnp.uint32(tb_lo) + tb_idx
                chunk = jnp.uint32(chunk0) + chunk_off
                hit = _eval_candidates(spec, masks, model, tb, chunk)
                f_global = chunk_off * jnp.uint32(tbc) + tb_idx
                m = jnp.min(jnp.where(hit, f_global, jnp.uint32(SENTINEL)))
                return jax.lax.pmin(m, axis)

        sharded = _shard_map(body, mesh=mesh, in_specs=P(), out_specs=P())
        return jax.jit(sharded)

    def factory(vw: int, extra: bytes, target_chunks: int, launch_steps: int = 1):
        if vw == 0:
            return _width0_probe(nonce, difficulty, tb_lo, tbc, model, extra)
        if tb_split:
            # every device scans the same chunks on its own tb slice
            chunks_local = max(1, target_chunks)
        else:
            chunks_local = _chunk_split_budget(target_chunks, tbc, n_dev)
        if pow2:
            k = max(1, launch_steps)
            step = bind_dyn(vw, bytes(extra), chunks_local, k)
        else:
            # nonce-keyed static fallback compiles per request anyway;
            # multi-sub-batch launches are not worth a bespoke program
            k = 1
            step = build_static(vw, bytes(extra), chunks_local)
        global_chunks = (chunks_local if tb_split else chunks_local * n_dev) * k
        return step, global_chunks

    return factory


@functools.lru_cache(maxsize=None)
def mesh_slot_search_step(
    mesh: Mesh,
    axis: str,
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch_local: int,
    n_slots: int,
):
    """Multi-slot serving step spread over the device mesh — the
    scheduler's ``mesh`` launch lane (sched/lanes.py, docs/SERVING.md).

    Same signature and contract as ``ops.search_step.slot_search_step``
    with ``batch = batch_local * n_dev``: ``(init[n, S],
    base[n, n_blocks, W], masks[n, D], tb_lo[n], log_tbc[n],
    chunk0[n]) -> uint32[n]``.  Device ``d`` evaluates the contiguous
    flat sub-range ``[d * batch_local, (d+1) * batch_local)`` of every
    slot's lane and ``lax.pmin`` folds the per-device minima, so the
    returned per-slot first-hit index is byte-identical to the
    single-device step over the same global span — one launch simply
    covers ``n_dev`` x the candidates (the lane-parity suite,
    tests/test_lanes.py, pins this).
    """
    model = get_hash_model(model_name)
    n_dev = int(mesh.devices.size)
    one = jnp.uint32(1)
    _check_launch(batch_local * n_dev, 1)

    def body(init, base, masks, tb_lo, log_tbc, chunk0):
        d = jax.lax.axis_index(axis).astype(jnp.uint32)
        f0 = d * jnp.uint32(batch_local) + jnp.arange(
            batch_local, dtype=jnp.uint32
        )

        def lane(init1, base1, masks1, tb_lo1, log_tbc1, chunk01):
            chunk = chunk01 + (f0 >> log_tbc1)
            tb = tb_lo1 + (f0 & ((one << log_tbc1) - one))
            state = eval_dyn_candidates(
                model, n_blocks, tb_loc, chunk_locs, init1, base1, tb, chunk
            )
            hit = fold_dyn_masks(model, state, masks1)
            return jnp.min(jnp.where(hit, f0, jnp.uint32(SENTINEL)))

        local = jax.vmap(lane)(init, base, masks, tb_lo, log_tbc, chunk0)
        return jax.lax.pmin(local, axis)

    # check_vma=False for the same reason as the pallas mesh step: the
    # explicit pmin is the collective that makes the result replicated;
    # the vmapped lane's varying-axes typing differs across JAX versions
    sharded = _shard_map(
        body, mesh=mesh, in_specs=(P(),) * 6, out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def mesh_persistent_step(
    mesh: Mesh,
    axis: str,
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch_local: int,
    static_tbc,  # None => power-of-two partition passed as log2 operand
    segments: int,
    mask_words: int = 0,
):
    """Persistent-loop serving step spread over the device mesh — the
    solo/persistent route's ``mesh`` lane (docs/SERVING.md).

    Mirrors ``ops.search_step.persistent_search_step`` with
    ``batch = batch_local * n_dev``: the same multi-segment on-device
    ``while_loop`` with early exit on hit or host stop flag, but each
    segment's candidate sub-batch is split across the mesh (device ``d``
    owns flat ``[d * batch_local, (d+1) * batch_local)`` within the
    segment) and a per-segment ``lax.pmin`` folds the device minima into
    the replicated carry — every device therefore observes a hit at the
    same segment boundary and exits together, the module-docstring
    "first result wins, everyone stops" protocol applied inside one
    dispatch.  Returned fn signature matches the single-device step:
    ``(init, base, masks, tb_lo, [log_tbc,] chunk0, stop) -> uint32[2]``
    (first-hit global flat index + segments executed).
    """
    model = get_hash_model(model_name)
    n_dev = int(mesh.devices.size)
    batch_global = batch_local * n_dev
    _check_launch(batch_global, segments)
    one = jnp.uint32(1)
    mw = mask_words or model.digest_words

    def make_step(take_log_tbc: bool):
        def step(init, base, masks, tb_lo, log_tbc, chunk0, stop):
            d = jax.lax.axis_index(axis).astype(jnp.uint32)
            f0 = d * jnp.uint32(batch_local) + jnp.arange(
                batch_local, dtype=jnp.uint32
            )

            def cond(state):
                seg, best = state
                return (
                    (seg < jnp.uint32(segments))
                    & (best == jnp.uint32(SENTINEL))
                    & (stop == jnp.uint32(0))
                )

            def seg_body(state):
                seg, best = state
                f = seg * jnp.uint32(batch_global) + f0
                if static_tbc is None:
                    chunk = jnp.uint32(chunk0) + (f >> log_tbc)
                    tb = tb_lo + (f & ((one << log_tbc) - one))
                else:
                    chunk = jnp.uint32(chunk0) + f // jnp.uint32(static_tbc)
                    tb = tb_lo + f % jnp.uint32(static_tbc)
                state_w = eval_dyn_candidates(
                    model, n_blocks, tb_loc, chunk_locs, init, base, tb,
                    chunk,
                )
                hit = fold_dyn_masks(model, state_w, masks, mw)
                found = jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))
                found = jax.lax.pmin(found, axis)
                return seg + one, jnp.minimum(best, found)

            seg, best = jax.lax.while_loop(
                cond, seg_body, (jnp.uint32(0), jnp.uint32(SENTINEL))
            )
            return jnp.stack([best, seg])

        if take_log_tbc:
            return step

        def step_static(init, base, masks, tb_lo, chunk0, stop):
            return step(init, base, masks, tb_lo, jnp.uint32(0), chunk0,
                        stop)

        return step_static

    n_in = 7 if static_tbc is None else 6
    # check_vma=False: the per-segment pmin inside the while_loop body is
    # what makes the carry replicated; the VMA/replication typing of a
    # collective inside a loop carry differs across JAX versions
    sharded = _shard_map(
        make_step(static_tbc is None), mesh=mesh, in_specs=(P(),) * n_in,
        out_specs=P(), check_vma=False,
    )
    return jax.jit(sharded)


def mesh_persistent_factory(
    nonce: bytes,
    difficulty: int,
    tb_lo: int,
    tbc: int,
    model: HashModel,
    mesh: Mesh,
    axis: str = AXIS,
):
    """Persistent step builder over the mesh — the ``step_builder`` hook
    of ``parallel.search.persistent_search`` (the solo/persistent
    route's mesh lane).

    Returns ``builder(vw, extra, target_chunks, segments) ->
    (bound(chunk0, stop), chunks_each, chunks_per_step)`` with the
    driver's exact accounting contract: each dispatch covers up to
    ``segments`` on-device segments of ``target_chunks`` GLOBAL chunks
    each.  Raises ValueError (from the builder) when the global segment
    batch does not divide across the mesh — the caller falls back to
    the single-device persistent step, same per-width contract as the
    pallas mesh factory.

    Bound operands are pre-placed replicated on the mesh at bind time
    (``jax.device_put`` with a replicated ``NamedSharding``), so steady-
    state dispatches move only the chunk cursor and stop flag.
    """
    from .compat import NamedSharding

    n_dev = int(mesh.devices.size)
    repl = NamedSharding(mesh, P())
    pow2 = tbc & (tbc - 1) == 0

    @functools.lru_cache(maxsize=32)
    def builder(vw: int, extra: bytes, target_chunks: int, segments: int):
        if vw == 0:
            raise ValueError(
                "width 0 has no persistent form; use cached_search_step"
            )
        batch_global = target_chunks * tbc
        if batch_global % n_dev:
            raise ValueError(
                f"segment batch {batch_global} (chunks={target_chunks}, "
                f"tbc={tbc}) does not divide across {n_dev} devices"
            )
        spec = build_tail_spec(bytes(nonce), vw, model, extra)
        mw = mask_words_for(difficulty, model)
        dyn = mesh_persistent_step(
            mesh, axis, model.name, spec.n_blocks, spec.tb_loc,
            spec.chunk_locs, batch_global // n_dev,
            None if pow2 else tbc, segments, mw,
        )
        init, base, masks = step_operands(spec, difficulty, model)
        init, base, masks = (jax.device_put(init, repl),
                             jax.device_put(base, repl),
                             jax.device_put(masks, repl))
        tb_lo_op = jax.device_put(jnp.uint32(tb_lo), repl)
        if pow2:
            log_tbc = jax.device_put(
                jnp.uint32(tbc.bit_length() - 1), repl)

            def bound(chunk0, stop):
                return dyn(init, base, masks, tb_lo_op, log_tbc, chunk0,
                           stop)

        else:

            def bound(chunk0, stop):
                return dyn(init, base, masks, tb_lo_op, chunk0, stop)

        return bound, target_chunks, target_chunks * segments

    return builder


def search_mesh(
    nonce: bytes,
    difficulty: int,
    thread_bytes: Sequence[int],
    *,
    mesh: Optional[Mesh] = None,
    axis: str = AXIS,
    model: Optional[HashModel] = None,
    step_factory: Optional[StepFactory] = None,
    **kwargs,
) -> Optional[SearchResult]:
    """Mesh-parallel ``search`` with identical semantics and result decode.

    ``step_factory`` overrides the default XLA mesh factory — the
    pallas-mesh backend plugs its kernel-backed factory in here.
    """
    model = model or get_hash_model("md5")
    mesh = mesh if mesh is not None else make_mesh()
    tb_lo, tbc = contiguous_bounds(thread_bytes)
    factory = step_factory or _mesh_step_factory(
        bytes(nonce), difficulty, tb_lo, tbc, model, mesh, axis
    )
    return search(
        nonce,
        difficulty,
        thread_bytes,
        model=model,
        step_factory=factory,
        **kwargs,
    )
