"""Multi-device search over a ``jax.sharding.Mesh`` — prefix -> core.

This is the TPU-native form of the coordinator's fan-out (SURVEY.md
section 2, strategies 1-2): inside one worker process, the worker's
thread-byte range is sub-partitioned across the devices of a mesh exactly
the way the coordinator partitions it across workers
(coordinator.go:326, worker.go:301-316) — prefix -> core instead of
prefix -> RPC peer.  The "first result wins, everyone stops" protocol
(coordinator.go:202-230) compresses onto ICI: every step ends in a
``lax.pmin`` of the per-device first-hit flat index, so all devices
observe a win at the same step boundary and the host stops dispatching —
the Found broadcast without any RPC.

Two sharding regimes, chosen automatically:

* **thread-byte split** (the common case): each device owns a contiguous
  slice of the thread-byte run and scans the same chunk range in lockstep.
* **chunk split** (when there are fewer thread bytes than devices): each
  device owns a contiguous slice of the chunk range instead.

Both regimes report hits as *global* flat indices (chunk-major,
thread-byte-minor over the whole worker partition), so the driver's decode
and the reference enumeration-order guarantee are identical to the
single-device path.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.registry import HashModel, get_hash_model
from ..ops.difficulty import nibble_masks
from ..ops.packing import build_tail_spec
from ..ops.search_step import SENTINEL, _eval_candidates
from .search import SearchResult, StepFactory, contiguous_bounds, search

AXIS = "workers"


def make_mesh(devices: Optional[Sequence] = None, axis: str = AXIS) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


def _mesh_step_factory(
    nonce: bytes,
    difficulty: int,
    tb_lo: int,
    tbc: int,
    model: HashModel,
    mesh: Mesh,
    axis: str,
) -> StepFactory:
    n_dev = mesh.devices.size
    tb_split = tbc >= n_dev and tbc % n_dev == 0

    @functools.lru_cache(maxsize=32)
    def build(vw: int, extra: bytes, chunks_local: int):
        spec = build_tail_spec(bytes(nonce), vw, model, extra)
        masks = nibble_masks(difficulty, model)

        if tb_split:
            tbl = tbc // n_dev

            def body(chunk0):
                d = jax.lax.axis_index(axis).astype(jnp.uint32)
                fl = jnp.arange(chunks_local * tbl, dtype=jnp.uint32)
                chunk_off = fl // jnp.uint32(tbl)
                tb_local = fl % jnp.uint32(tbl)
                tb = jnp.uint32(tb_lo) + d * jnp.uint32(tbl) + tb_local
                chunk = jnp.uint32(chunk0) + chunk_off
                hit = _eval_candidates(spec, masks, model, tb, chunk)
                f_global = (
                    chunk_off * jnp.uint32(tbc)
                    + d * jnp.uint32(tbl)
                    + tb_local
                )
                m = jnp.min(jnp.where(hit, f_global, jnp.uint32(SENTINEL)))
                return jax.lax.pmin(m, axis)

        else:

            def body(chunk0):
                d = jax.lax.axis_index(axis).astype(jnp.uint32)
                fl = jnp.arange(chunks_local * tbc, dtype=jnp.uint32)
                chunk_off_local = fl // jnp.uint32(tbc)
                tb_idx = fl % jnp.uint32(tbc)
                chunk_off = d * jnp.uint32(chunks_local) + chunk_off_local
                tb = jnp.uint32(tb_lo) + tb_idx
                chunk = jnp.uint32(chunk0) + chunk_off
                hit = _eval_candidates(spec, masks, model, tb, chunk)
                f_global = chunk_off * jnp.uint32(tbc) + tb_idx
                m = jnp.min(jnp.where(hit, f_global, jnp.uint32(SENTINEL)))
                return jax.lax.pmin(m, axis)

        sharded = jax.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P())
        return jax.jit(sharded)

    def factory(vw: int, extra: bytes, target_chunks: int):
        if vw == 0:
            chunks_local = 1
        elif tb_split:
            # every device scans the same chunks on its own tb slice
            chunks_local = max(1, target_chunks)
        else:
            chunks_local = max(1, target_chunks // n_dev)
        step = build(vw, bytes(extra), chunks_local)
        global_chunks = chunks_local if tb_split else chunks_local * n_dev
        if vw == 0:
            global_chunks = 1
        return step, global_chunks

    return factory


def search_mesh(
    nonce: bytes,
    difficulty: int,
    thread_bytes: Sequence[int],
    *,
    mesh: Optional[Mesh] = None,
    axis: str = AXIS,
    model: Optional[HashModel] = None,
    **kwargs,
) -> Optional[SearchResult]:
    """Mesh-parallel ``search`` with identical semantics and result decode."""
    model = model or get_hash_model("md5")
    mesh = mesh if mesh is not None else make_mesh()
    tb_lo, tbc = contiguous_bounds(thread_bytes)
    factory = _mesh_step_factory(
        bytes(nonce), difficulty, tb_lo, tbc, model, mesh, axis
    )
    return search(
        nonce,
        difficulty,
        thread_bytes,
        model=model,
        step_factory=factory,
        **kwargs,
    )
