"""Version-portable mesh/sharding API surface (docs/SERVING.md).

JAX has moved the mesh-programming primitives twice in the versions
this repo has run against: ``shard_map`` graduated from
``jax.experimental.shard_map`` (replication checking spelled
``check_rep``) to ``jax.shard_map`` (spelled ``check_vma``), the
varying-axes cast has been ``jax.lax.pcast``, ``jax.lax.pvary`` or
nothing at all, and the virtual-CPU-device knob is the
``jax_num_cpu_devices`` config option on new versions but only the
``--xla_force_host_platform_device_count`` XLA flag on older ones.

Every mesh call site in the repo (parallel/mesh_search.py, the
backends registry, the lane planner, the mesh/env tests) imports this
module instead of touching the moving target directly, so a JAX
upgrade is a one-file change here rather than a failure class across
the tree.

``shard_map`` / ``pvary`` resolve the available spelling at import
time; ``request_cpu_devices`` / ``cpu_devices_env`` cover the two
virtual-device mechanisms (in-process config vs pre-init env flag).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: F401

# new-style promoted API (jax.shard_map, check_vma) when present; the
# deprecation-module __getattr__ raises AttributeError on versions
# without it, which getattr maps to None
_NEW_SHARD_MAP = getattr(jax, "shard_map", None)
try:
    from jax.experimental.shard_map import shard_map as _EXP_SHARD_MAP
except ImportError:  # pragma: no cover - no known version lacks both
    _EXP_SHARD_MAP = None

#: True when SOME shard_map spelling exists — the version-gated skip
#: condition for the mesh tests (no known supported version lacks both).
HAS_SHARD_MAP = _NEW_SHARD_MAP is not None or _EXP_SHARD_MAP is not None


def shard_map(f, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """``shard_map`` under whichever spelling this JAX provides.

    ``check_vma`` is the new-style name for replication/varying-axes
    type checking; on versions that predate it the value is passed as
    ``check_rep`` (the same semantics under the older name).  ``None``
    keeps each version's default.
    """
    kwargs = {}
    if _NEW_SHARD_MAP is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _NEW_SHARD_MAP(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    if _EXP_SHARD_MAP is None:  # pragma: no cover - see HAS_SHARD_MAP
        raise NotImplementedError(
            "this JAX version provides neither jax.shard_map nor "
            "jax.experimental.shard_map"
        )
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _EXP_SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def pvary(x, axis: str):
    """Mark a replicated value as varying over ``axis`` (shard_map's
    varying-manual-axes typing); name differs across JAX versions and
    the oldest ones need no cast at all."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is not None:
        return fn(x, (axis,), to="varying")
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn(x, (axis,))
    return x


_HOST_COUNT_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> bool:
    """Ask for ``n`` virtual CPU devices, portably.

    New JAX versions take the ``jax_num_cpu_devices`` config option (and
    raise RuntimeError if the CPU backend is already initialized — the
    caller's clear_backends discipline, see ``__graft_entry__``).  Older
    versions only read the ``--xla_force_host_platform_device_count``
    XLA flag, which the backend consumes at its NEXT initialization — so
    on those this must run before the first device touch (or after a
    ``clear_backends``).  Returns True when the config option took
    effect in-process, False when only the pre-init env flag was set.
    """
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        return True
    except (AttributeError, ValueError):
        # AttributeError: option does not exist on this version;
        # ValueError: some versions reject unknown options this way
        pass
    flags = re.sub(rf"{_HOST_COUNT_FLAG}=\S+", "",
                   os.environ.get("XLA_FLAGS", "")).strip()
    os.environ["XLA_FLAGS"] = f"{flags} {_HOST_COUNT_FLAG}={int(n)}".strip()
    return False


def cpu_devices_env(n: int,
                    base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a SUBPROCESS that must boot with ``n`` virtual
    CPU devices: the pre-init env-flag mechanism works on every JAX
    version, so child arms (bench.py --mesh-serving, scripts/mesh_smoke)
    use it regardless of what the parent process supports."""
    env = dict(os.environ if base is None else base)
    flags = re.sub(rf"{_HOST_COUNT_FLAG}=\S+", "",
                   env.get("XLA_FLAGS", "")).strip()
    env["XLA_FLAGS"] = f"{flags} {_HOST_COUNT_FLAG}={int(n)}".strip()
    env["JAX_PLATFORMS"] = "cpu"
    return env
