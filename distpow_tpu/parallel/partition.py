"""Search-space partition algebra: byte-prefix sharding of the secret space.

The reference's single parallelism strategy (SURVEY.md section 2, component
10): the first secret byte is partitioned by a high-order worker-index
prefix.  The coordinator computes ``worker_bits = floor(log2(num_workers))``
(coordinator.go:326) and sends each worker its index as ``WorkerByte``
(coordinator.go:127,190-191).  Each worker expands that prefix into its set
of "thread bytes" — the possible first secret bytes it owns
(worker.go:301-316):

    remainder_bits = 8 - (worker_bits % 9)
    thread_bytes[i] = uint8((worker_byte << remainder_bits) | i)
                      for i in range(2 ** remainder_bits)

Quirks faithfully preserved (and documented, per SURVEY.md section 7):

* ``worker_bits`` truncates ``log2`` — for non-power-of-two worker counts
  the high-indexed workers' prefixes wrap around (uint8 conversion) and
  *overlap* the low workers' shards.  Coverage of the full byte space is
  preserved; work is duplicated.  This matches the reference bug-for-bug,
  because overlap is harmless (any valid secret is acceptable) while gaps
  would not be.
* ``% 9`` only matters for >= 512 workers where ``worker_bits`` exceeds 8.

On TPU the same algebra is applied twice: once across workers (this module,
driven by the coordinator) and once more across the devices of a worker's
mesh (``split_thread_bytes``), so the prefix -> core mapping of
BASELINE.json falls out of the same partition function.
"""

from __future__ import annotations

import math
from typing import List, Sequence


def worker_bits(num_workers: int) -> int:
    """``uint(math.Log2(num_workers))`` as in coordinator.go:326."""
    if num_workers <= 0:
        raise ValueError("num_workers must be positive")
    return int(math.log2(num_workers))


def remainder_bits(bits: int) -> int:
    """``8 - (worker_bits % 9)`` as in worker.go:302."""
    return 8 - (bits % 9)


def thread_bytes(worker_byte: int, bits: int) -> List[int]:
    """The worker's owned first-secret-byte values (worker.go:312-316).

    The ``& 0xFF`` reproduces Go's uint8 conversion, which makes
    out-of-range prefixes wrap (overlapping low shards) instead of erroring.
    """
    r = remainder_bits(bits)
    return [((worker_byte << r) | i) & 0xFF for i in range(1 << r)]


def split_thread_bytes(tbs: Sequence[int], num_shards: int) -> List[List[int]]:
    """Sub-partition a worker's thread bytes across mesh devices.

    Contiguous split so that each device owns a contiguous prefix range
    (prefix -> core).  When there are fewer thread bytes than devices the
    surplus devices receive empty shards (the mesh driver then falls back to
    chunk-range splitting).
    """
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    n = len(tbs)
    base, rem = divmod(n, num_shards)
    shards: List[List[int]] = []
    pos = 0
    for s in range(num_shards):
        size = base + (1 if s < rem else 0)
        shards.append(list(tbs[pos : pos + size]))
        pos += size
    return shards


def weighted_ranges(weights: Sequence[float]) -> "List[tuple[int, int]]":
    """Capability-weighted prefix split: per-worker ``(tb_lo, count)``
    contiguous first-byte ranges, sized proportionally to ``weights``
    (docs/FLEET.md "Weighted partition math").

    The reference has exactly one split — equal shares through the
    ``worker_bits``/``%9`` algebra above — so a 6 MH/s CPU worker and a
    TPU batching worker own the same slice of the first-byte space and
    the round ends when the SLOWEST shard's owner reports.  This split
    sizes each worker's slice by its advertised throughput (measured
    MH/s from the fleet capability advertisement) so expected
    per-shard wall-clock evens out.

    Contract:

    * **Equal weights reproduce the reference split byte-for-byte** —
      including the non-power-of-two uint8 wrap/overlap quirk and the
      ``% 9`` regime (bug-for-bug; see the module docstring).  A fleet
      with no capability spread is wire-identical to every earlier
      version.
    * Unequal weights yield a DISJOINT contiguous cover of the full
      0..255 space (largest-remainder apportionment): no overlap, no
      gap, and every positive-weight worker owns at least one byte —
      a zero-width shard would silently drop a worker from the race.
    * Weights must be positive and finite; > 256 workers cannot each
      own a byte, so that is an error (the reference algebra above
      keeps covering that regime via overlap).
    """
    ws = [float(w) for w in weights]
    n = len(ws)
    if n == 0:
        raise ValueError("weighted_ranges needs at least one weight")
    if any(w <= 0 or w != w or w == math.inf for w in ws):
        raise ValueError(f"weights must be positive and finite: {ws}")
    if all(w == ws[0] for w in ws):
        # the reference's equal split IS the equal-weight special case:
        # reuse the quirk-preserving algebra verbatim (overlap included)
        bits = worker_bits(n)
        out = []
        for wb in range(n):
            tbs = thread_bytes(wb, bits)
            out.append((tbs[0], len(tbs)))
        return out
    if n > 256:
        raise ValueError(
            f"cannot give {n} workers disjoint non-empty byte ranges"
        )
    total = sum(ws)
    shares = [w / total * 256.0 for w in ws]
    # math.floor, not int(): plain host floats, but the relaunch-loop-
    # sync rule reads int(name)-in-comprehension as a device sync
    counts = [math.floor(s) for s in shares]
    # every positive weight owns at least one byte before remainders
    for i in range(n):
        if counts[i] == 0:
            counts[i] = 1
    # largest-remainder apportionment of whatever is left (the floor +
    # minimum-1 adjustments may over- or under-shoot 256; correct by
    # remainder order, never below 1)
    def _adjust() -> None:
        delta = 256 - sum(counts)
        order = sorted(range(n), key=lambda i: shares[i] - int(shares[i]),
                       reverse=delta > 0)
        j = 0
        while delta != 0:
            i = order[j % n]
            if delta > 0:
                counts[i] += 1
                delta -= 1
            elif counts[i] > 1:
                counts[i] -= 1
                delta += 1
            j += 1

    _adjust()
    assert sum(counts) == 256 and all(c >= 1 for c in counts)
    out = []
    lo = 0
    for c in counts:
        out.append((lo, c))
        lo += c
    return out


def contiguous_bounds(thread_bytes: Sequence[int]) -> "tuple[int, int]":
    """(tb_lo, count) for a contiguous ascending thread-byte run.

    The partition algebra above (mirroring worker.go:312-316) always
    yields such runs; the device index maps and the native miner's dense
    enumeration both rely on it.  Lives here — not in parallel.search —
    so jax-free consumers (backends/native_miner.py) can validate runs
    without pulling the JAX compute path into their import graph
    (advisor r3).
    """
    tbs = list(thread_bytes)
    if not tbs:
        raise ValueError("empty thread byte set")
    lo = tbs[0]
    if tbs != list(range(lo, lo + len(tbs))):
        raise ValueError(f"thread bytes not a contiguous run: {tbs[:8]}...")
    return lo, len(tbs)
