"""Search-space partition algebra: byte-prefix sharding of the secret space.

The reference's single parallelism strategy (SURVEY.md section 2, component
10): the first secret byte is partitioned by a high-order worker-index
prefix.  The coordinator computes ``worker_bits = floor(log2(num_workers))``
(coordinator.go:326) and sends each worker its index as ``WorkerByte``
(coordinator.go:127,190-191).  Each worker expands that prefix into its set
of "thread bytes" — the possible first secret bytes it owns
(worker.go:301-316):

    remainder_bits = 8 - (worker_bits % 9)
    thread_bytes[i] = uint8((worker_byte << remainder_bits) | i)
                      for i in range(2 ** remainder_bits)

Quirks faithfully preserved (and documented, per SURVEY.md section 7):

* ``worker_bits`` truncates ``log2`` — for non-power-of-two worker counts
  the high-indexed workers' prefixes wrap around (uint8 conversion) and
  *overlap* the low workers' shards.  Coverage of the full byte space is
  preserved; work is duplicated.  This matches the reference bug-for-bug,
  because overlap is harmless (any valid secret is acceptable) while gaps
  would not be.
* ``% 9`` only matters for >= 512 workers where ``worker_bits`` exceeds 8.

On TPU the same algebra is applied twice: once across workers (this module,
driven by the coordinator) and once more across the devices of a worker's
mesh (``split_thread_bytes``), so the prefix -> core mapping of
BASELINE.json falls out of the same partition function.
"""

from __future__ import annotations

import math
from typing import List, Sequence


def worker_bits(num_workers: int) -> int:
    """``uint(math.Log2(num_workers))`` as in coordinator.go:326."""
    if num_workers <= 0:
        raise ValueError("num_workers must be positive")
    return int(math.log2(num_workers))


def remainder_bits(bits: int) -> int:
    """``8 - (worker_bits % 9)`` as in worker.go:302."""
    return 8 - (bits % 9)


def thread_bytes(worker_byte: int, bits: int) -> List[int]:
    """The worker's owned first-secret-byte values (worker.go:312-316).

    The ``& 0xFF`` reproduces Go's uint8 conversion, which makes
    out-of-range prefixes wrap (overlapping low shards) instead of erroring.
    """
    r = remainder_bits(bits)
    return [((worker_byte << r) | i) & 0xFF for i in range(1 << r)]


def split_thread_bytes(tbs: Sequence[int], num_shards: int) -> List[List[int]]:
    """Sub-partition a worker's thread bytes across mesh devices.

    Contiguous split so that each device owns a contiguous prefix range
    (prefix -> core).  When there are fewer thread bytes than devices the
    surplus devices receive empty shards (the mesh driver then falls back to
    chunk-range splitting).
    """
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    n = len(tbs)
    base, rem = divmod(n, num_shards)
    shards: List[List[int]] = []
    pos = 0
    for s in range(num_shards):
        size = base + (1 if s < rem else 0)
        shards.append(list(tbs[pos : pos + size]))
        pos += size
    return shards


def contiguous_bounds(thread_bytes: Sequence[int]) -> "tuple[int, int]":
    """(tb_lo, count) for a contiguous ascending thread-byte run.

    The partition algebra above (mirroring worker.go:312-316) always
    yields such runs; the device index maps and the native miner's dense
    enumeration both rely on it.  Lives here — not in parallel.search —
    so jax-free consumers (backends/native_miner.py) can validate runs
    without pulling the JAX compute path into their import graph
    (advisor r3).
    """
    tbs = list(thread_bytes)
    if not tbs:
        raise ValueError("empty thread byte set")
    lo = tbs[0]
    if tbs != list(range(lo, lo + len(tbs))):
        raise ValueError(f"thread bytes not a contiguous run: {tbs[:8]}...")
    return lo, len(tbs)
