"""distpow_tpu — a TPU-native distributed proof-of-work framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
Go system ``philipjesic/Distributed-Proof-Of-Work`` (mounted read-only at
/root/reference; see SURVEY.md for the structural analysis this build
follows).  Layering:

* ``models``   — puzzle semantics and pluggable hash models (MD5, SHA-256)
* ``ops``      — device ops: candidate packing, difficulty masks, fused
                 search step, Pallas kernel
* ``parallel`` — partition algebra, batched drivers, mesh (multi-chip) search
* ``runtime``  — RPC transport, distributed tracing, dominance cache, config
* ``backends`` — worker compute backends (python / jax / mesh / native C++)
* ``nodes``    — client library (powlib), client, coordinator, worker
* ``cli``      — process entry points mirroring the reference's cmd/ tree
"""

__version__ = "0.1.0"
