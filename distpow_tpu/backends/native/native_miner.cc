// Native CPU miner for the distpow_tpu framework.
//
// Plays the role of the reference worker's hot loop (worker.go:318-400)
// on the CPU path (BASELINE.md configs 1-2), with the two structural
// inefficiencies called out in BASELINE.md fixed: no per-candidate hex
// string formatting (the trailing-nibble check runs on the raw digest)
// and optional multi-threaded range splitting instead of the reference's
// single goroutine per worker.
//
// Exposed via a C ABI consumed through ctypes (backends/native_miner.py).
// Candidate enumeration contract (models/puzzle.py): secret =
// thread_byte ‖ chunk where chunk is the width-byte little-endian
// encoding of a chunk integer; for each chunk all thread bytes are tried
// in order (chunk-major, thread-byte-minor = reference order).
//
// MD5 implemented from the RFC 1321 specification, SHA-256 from FIPS
// 180-4 (single translation unit, no dependencies).  The hash is a
// compile-time trait of the templated scan loop, mirroring the
// framework's pluggable hash-model registry (models/registry.py): both
// algorithms share the enumeration, cancellation, and threading
// machinery exactly.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kInitState[4] = {0x67452301u, 0xefcdab89u, 0x98badcfeu,
                                    0x10325476u};

constexpr uint32_t kK[64] = {
    0xd76aa478u, 0xe8c7b756u, 0x242070dbu, 0xc1bdceeeu, 0xf57c0fafu,
    0x4787c62au, 0xa8304613u, 0xfd469501u, 0x698098d8u, 0x8b44f7afu,
    0xffff5bb1u, 0x895cd7beu, 0x6b901122u, 0xfd987193u, 0xa679438eu,
    0x49b40821u, 0xf61e2562u, 0xc040b340u, 0x265e5a51u, 0xe9b6c7aau,
    0xd62f105du, 0x02441453u, 0xd8a1e681u, 0xe7d3fbc8u, 0x21e1cde6u,
    0xc33707d6u, 0xf4d50d87u, 0x455a14edu, 0xa9e3e905u, 0xfcefa3f8u,
    0x676f02d9u, 0x8d2a4c8au, 0xfffa3942u, 0x8771f681u, 0x6d9d6122u,
    0xfde5380cu, 0xa4beea44u, 0x4bdecfa9u, 0xf6bb4b60u, 0xbebfbc70u,
    0x289b7ec6u, 0xeaa127fau, 0xd4ef3085u, 0x04881d05u, 0xd9d4d039u,
    0xe6db99e5u, 0x1fa27cf8u, 0xc4ac5665u, 0xf4292244u, 0x432aff97u,
    0xab9423a7u, 0xfc93a039u, 0x655b59c3u, 0x8f0ccc92u, 0xffeff47du,
    0x85845dd1u, 0x6fa87e4fu, 0xfe2ce6e0u, 0xa3014314u, 0x4e0811a1u,
    0xf7537e82u, 0xbd3af235u, 0x2ad7d2bbu, 0xeb86d391u};

constexpr int kS[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7,
                        12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20, 5, 9,
                        14, 20, 5,  9, 14, 20, 4, 11, 16, 23, 4, 11, 16,
                        23, 4,  11, 16, 23, 4, 11, 16, 23, 6, 10, 15, 21,
                        6,  10, 15, 21, 6,  10, 15, 21, 6, 10, 15, 21};

inline uint32_t Rotl(uint32_t x, int s) { return (x << s) | (x >> (32 - s)); }

// One MD5 block compression over a 64-byte block.
void CompressMd5(uint32_t state[4], const uint8_t block[64]) {
  uint32_t m[16];
  std::memcpy(m, block, 64);  // little-endian hosts only (x86/ARM LE)
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  for (int i = 0; i < 64; ++i) {
    uint32_t f;
    int g;
    if (i < 16) {
      f = (b & c) | (~b & d);
      g = i;
    } else if (i < 32) {
      f = (d & b) | (~d & c);
      g = (5 * i + 1) & 15;
    } else if (i < 48) {
      f = b ^ c ^ d;
      g = (3 * i + 5) & 15;
    } else {
      f = c ^ (b | ~d);
      g = (7 * i) & 15;
    }
    f += a + kK[i] + m[g];
    a = d;
    d = c;
    c = b;
    b += Rotl(f, kS[i]);
  }
  state[0] += a;
  state[1] += b;
  state[2] += c;
  state[3] += d;
}

// --- SHA-256 (FIPS 180-4) --------------------------------------------------

constexpr uint32_t kShaInit[8] = {0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u,
                                  0xa54ff53au, 0x510e527fu, 0x9b05688cu,
                                  0x1f83d9abu, 0x5be0cd19u};

constexpr uint32_t kShaK[64] = {
    0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
    0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
    0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
    0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
    0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
    0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
    0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
    0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
    0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
    0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
    0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
    0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
    0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};

inline uint32_t Rotr(uint32_t x, int s) { return (x >> s) | (x << (32 - s)); }

void CompressSha256(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i) {
    w[i] = (static_cast<uint32_t>(block[4 * i]) << 24) |
           (static_cast<uint32_t>(block[4 * i + 1]) << 16) |
           (static_cast<uint32_t>(block[4 * i + 2]) << 8) |
           static_cast<uint32_t>(block[4 * i + 3]);
  }
  for (int i = 16; i < 64; ++i) {
    const uint32_t s0 =
        Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    const uint32_t s1 =
        Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; ++i) {
    const uint32_t S1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
    const uint32_t ch = (e & f) ^ (~e & g);
    const uint32_t t1 = h + S1 + ch + kShaK[i] + w[i];
    const uint32_t S0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
    const uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    const uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

constexpr uint32_t kSha1Init[5] = {0x67452301u, 0xefcdab89u, 0x98badcfeu,
                                   0x10325476u, 0xc3d2e1f0u};
// one constant per 20-round group (FIPS 180-4 section 4.2.1)
constexpr uint32_t kSha1K[4] = {0x5a827999u, 0x6ed9eba1u, 0x8f1bbcdcu,
                                0xca62c1d6u};

void CompressSha1(uint32_t state[5], const uint8_t block[64]) {
  uint32_t w[80];
  for (int i = 0; i < 16; ++i) {
    w[i] = (static_cast<uint32_t>(block[4 * i]) << 24) |
           (static_cast<uint32_t>(block[4 * i + 1]) << 16) |
           (static_cast<uint32_t>(block[4 * i + 2]) << 8) |
           static_cast<uint32_t>(block[4 * i + 3]);
  }
  for (int i = 16; i < 80; ++i) {
    w[i] = Rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3],
           e = state[4];
  for (int i = 0; i < 80; ++i) {
    uint32_t f;
    if (i < 20) {
      f = (b & c) | (~b & d);
    } else if (i < 40) {
      f = b ^ c ^ d;
    } else if (i < 60) {
      f = (b & c) | (b & d) | (c & d);
    } else {
      f = b ^ c ^ d;
    }
    const uint32_t temp = Rotl(a, 5) + f + e + kSha1K[i / 20] + w[i];
    e = d; d = c; c = Rotl(b, 30); b = a; a = temp;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d; state[4] += e;
}

// --- RIPEMD-160 (ISO/IEC 10118-3; Dobbertin-Bosselaers-Preneel spec) -------

constexpr uint32_t kRmdInit[5] = {0x67452301u, 0xefcdab89u, 0x98badcfeu,
                                  0x10325476u, 0xc3d2e1f0u};
// per-16-round-group additive constants, left then right line
constexpr uint32_t kRmdKL[5] = {0x00000000u, 0x5a827999u, 0x6ed9eba1u,
                                0x8f1bbcdcu, 0xa953fd4eu};
constexpr uint32_t kRmdKR[5] = {0x50a28be6u, 0x5c4dd124u, 0x6d703ef3u,
                                0x7a6d76e9u, 0x00000000u};
constexpr uint8_t kRmdRL[80] = {
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
    7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
    3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
    1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
    4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13};
constexpr uint8_t kRmdRR[80] = {
    5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
    6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
    15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
    8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
    12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11};
constexpr uint8_t kRmdSL[80] = {
    11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
    7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
    11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
    11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
    9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6};
constexpr uint8_t kRmdSR[80] = {
    8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
    9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
    9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
    15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
    8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11};

inline uint32_t RmdF(int j, uint32_t x, uint32_t y, uint32_t z) {
  switch (j / 16) {
    case 0: return x ^ y ^ z;
    case 1: return (x & y) | (~x & z);
    case 2: return (x | ~y) ^ z;
    case 3: return (x & z) | (y & ~z);
    default: return x ^ (y | ~z);
  }
}

void CompressRipemd160(uint32_t state[5], const uint8_t block[64]) {
  uint32_t x[16];
  std::memcpy(x, block, 64);  // little-endian hosts only (matches MD5 path)
  uint32_t al = state[0], bl = state[1], cl = state[2], dl = state[3],
           el = state[4];
  uint32_t ar = al, br = bl, cr = cl, dr = dl, er = el;
  for (int j = 0; j < 80; ++j) {
    uint32_t t = Rotl(al + RmdF(j, bl, cl, dl) + x[kRmdRL[j]] +
                          kRmdKL[j / 16],
                      kRmdSL[j]) +
                 el;
    al = el; el = dl; dl = Rotl(cl, 10); cl = bl; bl = t;
    // right line runs the round functions in reverse group order
    t = Rotl(ar + RmdF(79 - j, br, cr, dr) + x[kRmdRR[j]] + kRmdKR[j / 16],
             kRmdSR[j]) +
        er;
    ar = er; er = dr; dr = Rotl(cr, 10); cr = br; br = t;
  }
  const uint32_t t = state[1] + cl + dr;
  state[1] = state[2] + dl + er;
  state[2] = state[3] + el + ar;
  state[3] = state[4] + al + br;
  state[4] = state[0] + bl + cr;
  state[0] = t;
}


// --- SHA-512 (FIPS 180-4) --------------------------------------------------
// 128-byte blocks, 16-byte length field, 64-bit words.  The framework
// carries SHA-512 state as 16 uint32 (hi, lo) pairs (models/sha512_py.py
// convention); this CPU path reassembles native uint64 limbs internally.

// init state as the framework's 16-uint32 (hi, lo) pairs, precomputed
// at compile time (a lazily-built runtime array would need
// synchronization under the multithreaded scan — review r4)
constexpr uint32_t kSha512Init32[16] = {
    0x6a09e667u, 0xf3bcc908u, 0xbb67ae85u, 0x84caa73bu,
    0x3c6ef372u, 0xfe94f82bu, 0xa54ff53au, 0x5f1d36f1u,
    0x510e527fu, 0xade682d1u, 0x9b05688cu, 0x2b3e6c1fu,
    0x1f83d9abu, 0xfb41bd6bu, 0x5be0cd19u, 0x137e2179u};

constexpr uint64_t kSha512K[80] = {
    0x428a2f98d728ae22ull, 0x7137449123ef65cdull, 0xb5c0fbcfec4d3b2full,
    0xe9b5dba58189dbbcull, 0x3956c25bf348b538ull, 0x59f111f1b605d019ull,
    0x923f82a4af194f9bull, 0xab1c5ed5da6d8118ull, 0xd807aa98a3030242ull,
    0x12835b0145706fbeull, 0x243185be4ee4b28cull, 0x550c7dc3d5ffb4e2ull,
    0x72be5d74f27b896full, 0x80deb1fe3b1696b1ull, 0x9bdc06a725c71235ull,
    0xc19bf174cf692694ull, 0xe49b69c19ef14ad2ull, 0xefbe4786384f25e3ull,
    0x0fc19dc68b8cd5b5ull, 0x240ca1cc77ac9c65ull, 0x2de92c6f592b0275ull,
    0x4a7484aa6ea6e483ull, 0x5cb0a9dcbd41fbd4ull, 0x76f988da831153b5ull,
    0x983e5152ee66dfabull, 0xa831c66d2db43210ull, 0xb00327c898fb213full,
    0xbf597fc7beef0ee4ull, 0xc6e00bf33da88fc2ull, 0xd5a79147930aa725ull,
    0x06ca6351e003826full, 0x142929670a0e6e70ull, 0x27b70a8546d22ffcull,
    0x2e1b21385c26c926ull, 0x4d2c6dfc5ac42aedull, 0x53380d139d95b3dfull,
    0x650a73548baf63deull, 0x766a0abb3c77b2a8ull, 0x81c2c92e47edaee6ull,
    0x92722c851482353bull, 0xa2bfe8a14cf10364ull, 0xa81a664bbc423001ull,
    0xc24b8b70d0f89791ull, 0xc76c51a30654be30ull, 0xd192e819d6ef5218ull,
    0xd69906245565a910ull, 0xf40e35855771202aull, 0x106aa07032bbd1b8ull,
    0x19a4c116b8d2d0c8ull, 0x1e376c085141ab53ull, 0x2748774cdf8eeb99ull,
    0x34b0bcb5e19b48a8ull, 0x391c0cb3c5c95a63ull, 0x4ed8aa4ae3418acbull,
    0x5b9cca4f7763e373ull, 0x682e6ff3d6b2b8a3ull, 0x748f82ee5defb2fcull,
    0x78a5636f43172f60ull, 0x84c87814a1f0ab72ull, 0x8cc702081a6439ecull,
    0x90befffa23631e28ull, 0xa4506cebde82bde9ull, 0xbef9a3f7b2c67915ull,
    0xc67178f2e372532bull, 0xca273eceea26619cull, 0xd186b8c721c0c207ull,
    0xeada7dd6cde0eb1eull, 0xf57d4f7fee6ed178ull, 0x06f067aa72176fbaull,
    0x0a637dc5a2c898a6ull, 0x113f9804bef90daeull, 0x1b710b35131c471bull,
    0x28db77f523047d84ull, 0x32caab7b40c72493ull, 0x3c9ebe0a15c9bebcull,
    0x431d67c49c100d4cull, 0x4cc5d4becb3e42b6ull, 0x597f299cfc657e2aull,
    0x5fcb6fab3ad6faecull, 0x6c44198c4a475817ull};

inline uint64_t Rotr64(uint64_t x, int s) {
  return (x >> s) | (x << (64 - s));
}

void CompressSha512(uint32_t state32[16], const uint8_t block[128]) {
  uint64_t w[80];
  for (int i = 0; i < 16; ++i) {
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) {
      v = (v << 8) | block[8 * i + j];
    }
    w[i] = v;
  }
  for (int i = 16; i < 80; ++i) {
    const uint64_t s0 =
        Rotr64(w[i - 15], 1) ^ Rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    const uint64_t s1 =
        Rotr64(w[i - 2], 19) ^ Rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t hs[8];
  for (int i = 0; i < 8; ++i) {
    hs[i] = (static_cast<uint64_t>(state32[2 * i]) << 32) | state32[2 * i + 1];
  }
  uint64_t a = hs[0], b = hs[1], c = hs[2], d = hs[3];
  uint64_t e = hs[4], f = hs[5], g = hs[6], h = hs[7];
  for (int i = 0; i < 80; ++i) {
    const uint64_t S1 = Rotr64(e, 14) ^ Rotr64(e, 18) ^ Rotr64(e, 41);
    const uint64_t ch = (e & f) ^ (~e & g);
    const uint64_t t1 = h + S1 + ch + kSha512K[i] + w[i];
    const uint64_t S0 = Rotr64(a, 28) ^ Rotr64(a, 34) ^ Rotr64(a, 39);
    const uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    const uint64_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  const uint64_t out[8] = {hs[0] + a, hs[1] + b, hs[2] + c, hs[3] + d,
                           hs[4] + e, hs[5] + f, hs[6] + g, hs[7] + h};
  for (int i = 0; i < 8; ++i) {
    state32[2 * i] = static_cast<uint32_t>(out[i] >> 32);
    state32[2 * i + 1] = static_cast<uint32_t>(out[i]);
  }
}

// --- hash traits bound into the templated scan loop ------------------------

struct Md5Traits {
  static constexpr int kBlockBytes = 64;
  static constexpr int kLengthBytes = 8;
  static constexpr int kStateWords = 4;
  static constexpr int kDigestBytes = 16;
  static constexpr bool kBigEndianLength = false;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kInitState; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressMd5(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    std::memcpy(out, state, 16);  // MD5 digest = LE state bytes
  }
};

struct Sha256Traits {
  static constexpr int kBlockBytes = 64;
  static constexpr int kLengthBytes = 8;
  static constexpr int kStateWords = 8;
  static constexpr int kDigestBytes = 32;
  static constexpr bool kBigEndianLength = true;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kShaInit; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha256(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    for (int i = 0; i < 8; ++i) {  // big-endian word serialization
      out[4 * i] = static_cast<uint8_t>(state[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(state[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(state[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(state[i]);
    }
  }
};

struct Sha1Traits {
  static constexpr int kBlockBytes = 64;
  static constexpr int kLengthBytes = 8;
  static constexpr int kStateWords = 5;
  static constexpr int kDigestBytes = 20;
  static constexpr bool kBigEndianLength = true;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kSha1Init; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha1(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    for (int i = 0; i < 5; ++i) {  // big-endian word serialization
      out[4 * i] = static_cast<uint8_t>(state[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(state[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(state[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(state[i]);
    }
  }
};

struct Ripemd160Traits {
  static constexpr int kBlockBytes = 64;
  static constexpr int kLengthBytes = 8;
  static constexpr int kStateWords = 5;
  static constexpr int kDigestBytes = 20;
  static constexpr bool kBigEndianLength = false;  // MD5-style padding
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kRmdInit; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressRipemd160(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    std::memcpy(out, state, 20);  // LE word serialization, like MD5
  }
};

struct Sha512Traits {
  static constexpr int kBlockBytes = 128;
  static constexpr int kLengthBytes = 16;  // 128-bit bit-length field
  static constexpr int kStateWords = 16;   // 8 x 64-bit as (hi, lo) pairs
  static constexpr int kDigestBytes = 64;
  static constexpr bool kBigEndianLength = true;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kSha512Init32; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha512(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    for (int i = 0; i < 16; ++i) {  // big-endian word serialization
      out[4 * i] = static_cast<uint8_t>(state[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(state[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(state[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(state[i]);
    }
  }
};

// SHA-384 = SHA-512's compression with its own init, digest truncated
// to the first six 64-bit words (FIPS 180-4 section 5.3.4, round 4).
constexpr uint32_t kSha384Init32[16] = {
    0xcbbb9d5du, 0xc1059ed8u, 0x629a292au, 0x367cd507u,
    0x9159015au, 0x3070dd17u, 0x152fecd8u, 0xf70e5939u,
    0x67332667u, 0xffc00b31u, 0x8eb44a87u, 0x68581511u,
    0xdb0c2e0du, 0x64f98fa7u, 0x47b5481du, 0xbefa4fa4u};

struct Sha384Traits {
  static constexpr int kBlockBytes = 128;
  static constexpr int kLengthBytes = 16;
  static constexpr int kStateWords = 16;  // full sha512 state carried
  static constexpr int kDigestBytes = 48;  // truncated serialization
  static constexpr bool kBigEndianLength = true;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kSha384Init32; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha512(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    for (int i = 0; i < 12; ++i) {  // first 12 of 16 state words
      out[4 * i] = static_cast<uint8_t>(state[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(state[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(state[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(state[i]);
    }
  }
};

// --- SHA3-256: Keccak-f[1600], FIPS 202 (round 4, seventh model) -----------
// The state is carried as 50 uint32 limbs in little-endian lane
// serialization order (LOW limb first — matching the JAX twin,
// models/sha3_py.py); real uint64 lanes are reassembled here since C++
// has them (same policy as CompressSha512's limbs).

constexpr uint64_t kKeccakRC[24] = {
    0x0000000000000001ull, 0x0000000000008082ull, 0x800000000000808Aull,
    0x8000000080008000ull, 0x000000000000808Bull, 0x0000000080000001ull,
    0x8000000080008081ull, 0x8000000000008009ull, 0x000000000000008Aull,
    0x0000000000000088ull, 0x0000000080008009ull, 0x000000008000000Aull,
    0x000000008000808Bull, 0x800000000000008Bull, 0x8000000000008089ull,
    0x8000000000008003ull, 0x8000000000008002ull, 0x8000000000000080ull,
    0x000000000000800Aull, 0x800000008000000Aull, 0x8000000080008081ull,
    0x8000000000008080ull, 0x0000000080000001ull, 0x8000000080008008ull};

// rotation offsets r[x][y], lane index = x + 5y
constexpr int kKeccakRot[5][5] = {{0, 36, 3, 41, 18},
                                  {1, 44, 10, 45, 2},
                                  {62, 6, 43, 15, 61},
                                  {28, 55, 25, 21, 56},
                                  {27, 20, 39, 8, 14}};

inline uint64_t Rotl64(uint64_t v, int n) {
  return n == 0 ? v : (v << n) | (v >> (64 - n));
}

void KeccakF(uint64_t A[25]) {
  for (int r = 0; r < 24; ++r) {
    uint64_t C[5], D[5], B[25];
    for (int x = 0; x < 5; ++x)
      C[x] = A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20];
    for (int x = 0; x < 5; ++x)
      D[x] = C[(x + 4) % 5] ^ Rotl64(C[(x + 1) % 5], 1);
    for (int i = 0; i < 25; ++i) A[i] ^= D[i % 5];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        B[y + 5 * ((2 * x + 3 * y) % 5)] =
            Rotl64(A[x + 5 * y], kKeccakRot[x][y]);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        A[x + 5 * y] =
            B[x + 5 * y] ^ (~B[(x + 1) % 5 + 5 * y] & B[(x + 2) % 5 + 5 * y]);
    A[0] ^= kKeccakRC[r];
  }
}

void CompressSha3(uint32_t state32[50], const uint8_t block[136]) {
  uint64_t A[25];
  for (int i = 0; i < 25; ++i)
    A[i] = static_cast<uint64_t>(state32[2 * i]) |
           (static_cast<uint64_t>(state32[2 * i + 1]) << 32);
  for (int i = 0; i < 17; ++i) {  // rate: 17 LE lanes = 136 bytes
    uint64_t lane = 0;
    for (int b = 7; b >= 0; --b) lane = (lane << 8) | block[8 * i + b];
    A[i] ^= lane;
  }
  KeccakF(A);
  for (int i = 0; i < 25; ++i) {
    state32[2 * i] = static_cast<uint32_t>(A[i]);
    state32[2 * i + 1] = static_cast<uint32_t>(A[i] >> 32);
  }
}

constexpr uint32_t kSha3Init[50] = {};  // the zero sponge state

struct Sha3_256Traits {
  static constexpr int kBlockBytes = 136;  // the RATE (1088 bits)
  static constexpr int kLengthBytes = 0;   // sponge: no length field
  static constexpr int kStateWords = 50;
  static constexpr int kDigestBytes = 32;
  static constexpr bool kBigEndianLength = false;  // unused
  static constexpr bool kSpongePadding = true;     // pad10*1 + 0x06
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kSha3Init; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha3(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    std::memcpy(out, state, 32);  // LE limb serialization, lo-first
  }
};

// --- BLAKE2b-256 (RFC 7693, round 4, eighth model) --------------------------
// The per-block-parameter hash: compression takes a byte counter and a
// finalization flag besides state and message.  Traits with
// kNeedsBlockParams expose CompressWithParams and the scan loop
// computes (t, last) per block; there are no padding marker bytes at
// all (zero-fill only).

constexpr uint64_t kBlake2bIV[8] = {
    0x6A09E667F3BCC908ull, 0xBB67AE8584CAA73Bull, 0x3C6EF372FE94F82Bull,
    0xA54FF53A5F1D36F1ull, 0x510E527FADE682D1ull, 0x9B05688C2B3E6C1Full,
    0x1F83D9ABFB41BD6Bull, 0x5BE0CD19137E2179ull};

constexpr uint8_t kBlake2bSigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t Rotr64b(uint64_t v, int n) {
  return (v >> n) | (v << (64 - n));
}

inline void Blake2bG(uint64_t v[16], int a, int b, int c, int d,
                     uint64_t x, uint64_t y) {
  v[a] = v[a] + v[b] + x;
  v[d] = Rotr64b(v[d] ^ v[a], 32);
  v[c] = v[c] + v[d];
  v[b] = Rotr64b(v[b] ^ v[c], 24);
  v[a] = v[a] + v[b] + y;
  v[d] = Rotr64b(v[d] ^ v[a], 16);
  v[c] = v[c] + v[d];
  v[b] = Rotr64b(v[b] ^ v[c], 63);
}

void CompressBlake2b(uint32_t state32[16], const uint8_t block[128],
                     uint64_t t, bool last) {
  uint64_t h[8], m[16], v[16];
  for (int i = 0; i < 8; ++i)
    h[i] = static_cast<uint64_t>(state32[2 * i]) |
           (static_cast<uint64_t>(state32[2 * i + 1]) << 32);
  for (int i = 0; i < 16; ++i) {
    uint64_t w = 0;
    for (int b2 = 7; b2 >= 0; --b2) w = (w << 8) | block[8 * i + b2];
    m[i] = w;
  }
  for (int i = 0; i < 8; ++i) v[i] = h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = kBlake2bIV[i];
  v[12] ^= t;  // t1 (v[13]) stays: real messages are < 2^64 bytes
  if (last) v[14] = ~v[14];
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = kBlake2bSigma[r];
    Blake2bG(v, 0, 4, 8, 12, m[s[0]], m[s[1]]);
    Blake2bG(v, 1, 5, 9, 13, m[s[2]], m[s[3]]);
    Blake2bG(v, 2, 6, 10, 14, m[s[4]], m[s[5]]);
    Blake2bG(v, 3, 7, 11, 15, m[s[6]], m[s[7]]);
    Blake2bG(v, 0, 5, 10, 15, m[s[8]], m[s[9]]);
    Blake2bG(v, 1, 6, 11, 12, m[s[10]], m[s[11]]);
    Blake2bG(v, 2, 7, 8, 13, m[s[12]], m[s[13]]);
    Blake2bG(v, 3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
  for (int i = 0; i < 8; ++i) {
    const uint64_t o = h[i] ^ v[i] ^ v[i + 8];
    state32[2 * i] = static_cast<uint32_t>(o);
    state32[2 * i + 1] = static_cast<uint32_t>(o >> 32);
  }
}

// h[0] ^= 0x01010000 | digest_length(32); limbs lo-first
constexpr uint32_t kBlake2bInit32[16] = {
    0xF2BDC928u, 0x6A09E667u, 0x84CAA73Bu, 0xBB67AE85u,
    0xFE94F82Bu, 0x3C6EF372u, 0x5F1D36F1u, 0xA54FF53Au,
    0xADE682D1u, 0x510E527Fu, 0x2B3E6C1Fu, 0x9B05688Cu,
    0xFB41BD6Bu, 0x1F83D9ABu, 0x137E2179u, 0x5BE0CD19u};

struct Blake2b256Traits {
  static constexpr int kBlockBytes = 128;
  static constexpr int kLengthBytes = 0;   // no length field
  static constexpr int kStateWords = 16;
  static constexpr int kDigestBytes = 32;
  static constexpr bool kBigEndianLength = false;  // unused
  static constexpr bool kSpongePadding = false;    // unused
  static constexpr bool kNeedsBlockParams = true;  // zero-fill, (t, last)
  static const uint32_t* Init() { return kBlake2bInit32; }
  static void CompressWithParams(uint32_t* state, const uint8_t* block,
                                 uint64_t t, bool last) {
    CompressBlake2b(state, block, t, last);
  }
  // no plain Compress member: EVERY block — prefix and tail — routes
  // through CompressWithParams (the scan loop's kNeedsBlockParams
  // branches), and if-constexpr discards the Compress call sites for
  // this trait at instantiation
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    std::memcpy(out, state, 32);  // LE limb serialization, lo-first
  }
};

struct Sha256dTraits {
  // Composed double SHA-256 (sha256d = sha256(sha256(m)), Bitcoin's
  // PoW digest; ninth registry model, r5).  Absorption and padding are
  // plain SHA-256 — the composition lives entirely in StoreDigest,
  // which runs the fixed-layout second compression (digest ‖ 0x80 ‖
  // zeros ‖ bit-length 256) before serializing, so the templated scan
  // loop needs no new branch.
  static constexpr int kBlockBytes = 64;
  static constexpr int kLengthBytes = 8;
  static constexpr int kStateWords = 8;
  static constexpr int kDigestBytes = 32;
  static constexpr bool kBigEndianLength = true;
  static constexpr bool kSpongePadding = false;
  static constexpr bool kNeedsBlockParams = false;
  static const uint32_t* Init() { return kShaInit; }
  static void Compress(uint32_t* state, const uint8_t* block) {
    CompressSha256(state, block);
  }
  static void StoreDigest(const uint32_t* state, uint8_t* out) {
    uint8_t block2[64];
    Sha256Traits::StoreDigest(state, block2);  // first digest, BE words
    block2[32] = 0x80;
    std::memset(block2 + 33, 0, 29);
    block2[62] = 0x01;  // 64-bit BE bit length = 256 = 0x0100
    block2[63] = 0x00;
    uint32_t st2[8];
    std::memcpy(st2, kShaInit, sizeof(st2));
    CompressSha256(st2, block2);
    Sha256Traits::StoreDigest(st2, out);
  }
};

// Trailing zero nibbles of the digest, scanned from the end: low nibble
// of the last byte first (hex-string order).
inline bool MeetsDifficulty(const uint8_t* digest, int digest_bytes,
                            uint32_t nibbles) {
  uint32_t full = nibbles / 2;
  for (uint32_t i = 0; i < full; ++i) {
    if (digest[digest_bytes - 1 - i] != 0) return false;
  }
  if (nibbles & 1) {
    if ((digest[digest_bytes - 1 - full] & 0x0f) != 0) return false;
  }
  return true;
}

struct SearchTask {
  const uint8_t* nonce;
  size_t nonce_len;
  uint32_t difficulty;
  const uint8_t* thread_bytes;
  size_t n_tb;
  uint32_t width;
  uint64_t chunk_start;
  uint64_t chunk_end;  // exclusive
  const volatile int32_t* cancel_flag;
};

struct Found {
  std::atomic<uint64_t> flat_index{UINT64_MAX};  // chunk_off * n_tb + tb_idx
  std::atomic<int> any{0};
};

// Scan [chunk_lo, chunk_hi) in reference order; update `found` with the
// minimum flat index seen.  Checks cancel/found every `poll` candidates.
template <typename Traits>
void ScanRange(const SearchTask& t, uint64_t chunk_lo, uint64_t chunk_hi,
               Found* found, uint64_t* hashes_out) {
  const size_t msg_len = t.nonce_len + 1 + t.width;
  // Tail spans at most two blocks: rem < kBlockBytes and the secret +
  // padding + length field add < kBlockBytes more (width <= 8,
  // kLengthBytes <= 16).
  constexpr size_t kBB = Traits::kBlockBytes;
  constexpr size_t kLB = Traits::kLengthBytes;
  uint8_t tail[2 * kBB];
  uint64_t hashes = 0;
  const uint64_t poll = 4096;
  uint64_t next_poll = poll;

  // Precompute the constant prefix state for long messages.
  uint32_t prefix_state[Traits::kStateWords];
  std::memcpy(prefix_state, Traits::Init(), sizeof(prefix_state));
  size_t absorbed = (t.nonce_len / kBB) * kBB;
  for (size_t off = 0; off < absorbed; off += kBB) {
    if constexpr (Traits::kNeedsBlockParams) {
      // never final: every candidate appends >= 1 secret byte
      Traits::CompressWithParams(prefix_state, t.nonce + off, off + kBB,
                                 false);
    } else {
      Traits::Compress(prefix_state, t.nonce + off);
    }
  }
  const uint8_t* rem = t.nonce + absorbed;
  const size_t rem_len = t.nonce_len - absorbed;
  const size_t tail_content = rem_len + 1 + t.width;
  // minimum padding: none for blake2 (zero-fill, finality is a
  // compression PARAMETER); one byte for the sponge's merged 0x86;
  // one byte 0x80 plus the length field for Merkle-Damgard
  const size_t min_pad = Traits::kNeedsBlockParams
                             ? 0 : (Traits::kSpongePadding ? 1 : 1 + kLB);
  const size_t tail_blocks = (tail_content + min_pad + kBB - 1) / kBB;
  const size_t tail_len = tail_blocks * kBB;

  std::memset(tail, 0, sizeof(tail));
  std::memcpy(tail, rem, rem_len);
  if (Traits::kNeedsBlockParams) {
    // no marker bytes at all; (t, last) flow into CompressWithParams
  } else if (Traits::kSpongePadding) {
    // SHA-3 pad10*1 with the domain bits: 0x06 after the message,
    // 0x80 into the last rate byte (XORs merge them when adjacent)
    tail[tail_content] ^= 0x06;
    tail[tail_len - 1] ^= 0x80;
  } else {
    tail[tail_content] = 0x80;
    // the bit length is a uint64; a 16-byte field's high bytes stay
    // zero (shifts >= 64 would be UB, hence the guard)
    const uint64_t bitlen = static_cast<uint64_t>(msg_len) * 8;
    for (size_t i = 0; i < kLB; ++i) {
      const size_t shift = Traits::kBigEndianLength
                               ? 8 * (kLB - 1 - i) : 8 * i;
      tail[tail_len - kLB + i] =
          shift < 64 ? static_cast<uint8_t>(bitlen >> shift) : 0;
    }
  }

  for (uint64_t chunk = chunk_lo; chunk < chunk_hi; ++chunk) {
    // chunk bytes (little-endian, fixed width) land after the thread byte
    for (uint32_t j = 0; j < t.width; ++j) {
      tail[rem_len + 1 + j] = static_cast<uint8_t>(chunk >> (8 * j));
    }
    for (size_t ti = 0; ti < t.n_tb; ++ti) {
      if (hashes >= next_poll) {
        next_poll = hashes + poll;
        if ((t.cancel_flag && *t.cancel_flag) ||
            found->any.load(std::memory_order_relaxed)) {
          *hashes_out += hashes;
          return;
        }
      }
      tail[rem_len] = t.thread_bytes[ti];
      uint32_t state[Traits::kStateWords];
      std::memcpy(state, prefix_state, sizeof(state));
      for (size_t b = 0; b < tail_blocks; ++b) {
        if constexpr (Traits::kNeedsBlockParams) {
          const bool last = b == tail_blocks - 1;
          const uint64_t tb_count =
              absorbed + (last ? tail_content : (b + 1) * kBB);
          Traits::CompressWithParams(state, tail + kBB * b, tb_count, last);
        } else {
          Traits::Compress(state, tail + kBB * b);
        }
      }
      ++hashes;
      uint8_t digest[Traits::kDigestBytes];
      Traits::StoreDigest(state, digest);
      if (MeetsDifficulty(digest, Traits::kDigestBytes, t.difficulty)) {
        const uint64_t flat =
            (chunk - t.chunk_start) * t.n_tb + static_cast<uint64_t>(ti);
        uint64_t cur = found->flat_index.load(std::memory_order_relaxed);
        while (flat < cur && !found->flat_index.compare_exchange_weak(
                                 cur, flat, std::memory_order_relaxed)) {
        }
        found->any.store(1, std::memory_order_relaxed);
        *hashes_out += hashes;
        return;
      }
    }
  }
  *hashes_out += hashes;
}

template <typename Traits>
int SearchRange(const SearchTask& task, uint64_t chunk_count,
                int32_t n_threads, Found* found, uint64_t* hashes) {
  if (n_threads <= 1 || chunk_count < 2) {
    ScanRange<Traits>(task, task.chunk_start, task.chunk_end, found, hashes);
  } else {
    const uint64_t nt = static_cast<uint64_t>(n_threads);
    const uint64_t per = (chunk_count + nt - 1) / nt;
    std::vector<std::thread> threads;
    std::vector<uint64_t> thread_hashes(nt, 0);
    for (uint64_t i = 0; i < nt; ++i) {
      const uint64_t lo = task.chunk_start + i * per;
      const uint64_t hi =
          lo + per < task.chunk_end ? lo + per : task.chunk_end;
      if (lo >= hi) break;
      threads.emplace_back([&, lo, hi, i] {
        ScanRange<Traits>(task, lo, hi, found, &thread_hashes[i]);
      });
    }
    for (auto& th : threads) th.join();
    for (uint64_t h : thread_hashes) *hashes += h;
  }
  return 0;
}

// Full digest of an arbitrary buffer (self-test hooks below).
template <typename Traits>
void DigestBuffer(const uint8_t* data, size_t len, uint8_t* out) {
  constexpr size_t kBB = Traits::kBlockBytes;
  constexpr size_t kLB = Traits::kLengthBytes;
  uint32_t state[Traits::kStateWords];
  std::memcpy(state, Traits::Init(), sizeof(state));
  if constexpr (Traits::kNeedsBlockParams) {
    // blake2: only blocks with KNOWN following data are non-final —
    // a message that is an exact block multiple ends with a FULL
    // final block (last=true), unlike the search tail
    const size_t n_nonfinal = len ? (len - 1) / kBB : 0;
    for (size_t b = 0; b < n_nonfinal; ++b)
      Traits::CompressWithParams(state, data + b * kBB, (b + 1) * kBB,
                                 false);
    uint8_t tail[kBB];
    std::memset(tail, 0, sizeof(tail));
    const size_t rem = len - n_nonfinal * kBB;
    std::memcpy(tail, data + n_nonfinal * kBB, rem);
    Traits::CompressWithParams(state, tail, len, true);
    Traits::StoreDigest(state, out);
  } else {
    // an if-constexpr early return would NOT discard this branch for
    // the params traits — only a real else does, and the params traits
    // have no plain Compress member
    size_t full = (len / kBB) * kBB;
    for (size_t off = 0; off < full; off += kBB)
      Traits::Compress(state, data + off);
    uint8_t tail[2 * kBB];
    std::memset(tail, 0, sizeof(tail));
    size_t rem = len - full;
    std::memcpy(tail, data + full, rem);
    const size_t min_pad = Traits::kSpongePadding ? 1 : 1 + kLB;
    size_t tail_len = rem + min_pad <= kBB ? kBB : 2 * kBB;
    if (Traits::kSpongePadding) {
      tail[rem] ^= 0x06;
      tail[tail_len - 1] ^= 0x80;
    } else {
      tail[rem] = 0x80;
      uint64_t bits = static_cast<uint64_t>(len) * 8;
      for (size_t i = 0; i < kLB; ++i) {
        const size_t shift = Traits::kBigEndianLength
                                 ? 8 * (kLB - 1 - i) : 8 * i;
        tail[tail_len - kLB + i] =
            shift < 64 ? static_cast<uint8_t>(bits >> shift) : 0;
      }
    }
    for (size_t b = 0; b < tail_len; b += kBB)
      Traits::Compress(state, tail + b);
    Traits::StoreDigest(state, out);
  }
}

}  // namespace

extern "C" {

// Searches chunk integers [chunk_start, chunk_start + chunk_count) over
// the given thread bytes at the given chunk byte width.
//
// Returns 1 if a secret was found (written to out_secret, length
// 1 + width), 0 if the range was exhausted, -1 if cancelled via
// cancel_flag.  out_hashes receives the number of digests computed.
//
// With n_threads > 1 the chunk range is split contiguously; the winner is
// the minimum flat index among per-thread first finds (exact reference
// order within each thread's range; across threads, first-in-order among
// the finds that happened before shutdown — any valid secret is
// acceptable per the puzzle contract, coordinator.go:202).
//
// `algo`: 0 = MD5 (reference parity), 1 = SHA-256 (the north-star hash
// option), 2 = SHA-1, 3 = RIPEMD-160, 4 = SHA-512, 5 = SHA-384,
// 6 = SHA3-256, 7 = BLAKE2b-256; -2 on any other value.
int distpow_search_range(const uint8_t* nonce, size_t nonce_len,
                         uint32_t difficulty, uint32_t algo,
                         const uint8_t* thread_bytes,
                         size_t n_tb, uint32_t width, uint64_t chunk_start,
                         uint64_t chunk_count, int32_t n_threads,
                         const volatile int32_t* cancel_flag,
                         uint64_t* out_hashes, uint8_t* out_secret) {
  if (n_tb == 0 || width > 8 || algo > 8) return -2;
  // a difficulty beyond the digest's nibble count would read past the
  // digest buffer in MeetsDifficulty (and the puzzle is unsatisfiable
  // anyway — the JAX paths reject it in nibble_masks)
  const uint32_t max_nibbles =
      2 * (algo == 0   ? Md5Traits::kDigestBytes
           : algo == 1 ? Sha256Traits::kDigestBytes
           : algo == 2 ? Sha1Traits::kDigestBytes
           : algo == 3 ? Ripemd160Traits::kDigestBytes
           : algo == 4 ? Sha512Traits::kDigestBytes
           : algo == 5 ? Sha384Traits::kDigestBytes
           : algo == 6 ? Sha3_256Traits::kDigestBytes
           : algo == 7 ? Blake2b256Traits::kDigestBytes
                       : Sha256dTraits::kDigestBytes);
  if (difficulty > max_nibbles) return -2;
  SearchTask task{nonce,        nonce_len,  difficulty,
                  thread_bytes, n_tb,       width,
                  chunk_start,  chunk_start + chunk_count, cancel_flag};
  Found found;
  uint64_t hashes = 0;

  if (algo == 0) {
    SearchRange<Md5Traits>(task, chunk_count, n_threads, &found, &hashes);
  } else if (algo == 1) {
    SearchRange<Sha256Traits>(task, chunk_count, n_threads, &found, &hashes);
  } else if (algo == 2) {
    SearchRange<Sha1Traits>(task, chunk_count, n_threads, &found, &hashes);
  } else if (algo == 3) {
    SearchRange<Ripemd160Traits>(task, chunk_count, n_threads, &found,
                                 &hashes);
  } else if (algo == 4) {
    SearchRange<Sha512Traits>(task, chunk_count, n_threads, &found, &hashes);
  } else if (algo == 5) {
    SearchRange<Sha384Traits>(task, chunk_count, n_threads, &found, &hashes);
  } else if (algo == 6) {
    SearchRange<Sha3_256Traits>(task, chunk_count, n_threads, &found,
                                &hashes);
  } else if (algo == 7) {
    SearchRange<Blake2b256Traits>(task, chunk_count, n_threads, &found,
                                  &hashes);
  } else {
    SearchRange<Sha256dTraits>(task, chunk_count, n_threads, &found,
                               &hashes);
  }

  if (out_hashes) *out_hashes = hashes;
  const uint64_t flat = found.flat_index.load();
  if (flat != UINT64_MAX) {
    const uint64_t chunk = chunk_start + flat / n_tb;
    out_secret[0] = thread_bytes[flat % n_tb];
    for (uint32_t j = 0; j < width; ++j) {
      out_secret[1 + j] = static_cast<uint8_t>(chunk >> (8 * j));
    }
    return 1;
  }
  if (cancel_flag && *cancel_flag) return -1;
  return 0;
}

// Self-test hooks: full digests of an arbitrary buffer (binding checks).
void distpow_md5(const uint8_t* data, size_t len, uint8_t out[16]) {
  DigestBuffer<Md5Traits>(data, len, out);
}

void distpow_sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  DigestBuffer<Sha256Traits>(data, len, out);
}

void distpow_sha1(const uint8_t* data, size_t len, uint8_t out[20]) {
  DigestBuffer<Sha1Traits>(data, len, out);
}

void distpow_ripemd160(const uint8_t* data, size_t len, uint8_t out[20]) {
  DigestBuffer<Ripemd160Traits>(data, len, out);
}

void distpow_sha512(const uint8_t* data, size_t len, uint8_t out[64]) {
  DigestBuffer<Sha512Traits>(data, len, out);
}

void distpow_sha384(const uint8_t* data, size_t len, uint8_t out[48]) {
  DigestBuffer<Sha384Traits>(data, len, out);
}

void distpow_sha3_256(const uint8_t* data, size_t len, uint8_t out[32]) {
  DigestBuffer<Sha3_256Traits>(data, len, out);
}

void distpow_blake2b_256(const uint8_t* data, size_t len, uint8_t out[32]) {
  DigestBuffer<Blake2b256Traits>(data, len, out);
}

void distpow_sha256d(const uint8_t* data, size_t len, uint8_t out[32]) {
  DigestBuffer<Sha256dTraits>(data, len, out);
}

}  // extern "C"
