"""ctypes binding for the native C++ CPU miner (backends/native/).

The native library is built on demand with the bundled Makefile (g++; no
external dependencies).  This is the CPU-performance counterpart of the
reference's Go worker loop for BASELINE.md configs 1-2 — same enumeration
contract as every other backend, verified against the hashlib oracle in
tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
import time
from typing import Callable, Optional, Sequence

from ..models import puzzle
from ..parallel.partition import contiguous_bounds
from ..runtime.metrics import REGISTRY as metrics

log = logging.getLogger("distpow.native")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdistpow_native.so")
_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    pass


def load_library(build: bool = True) -> ctypes.CDLL:
    """Load (building if needed) the native miner library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) and build:
            try:
                # distpow: ok no-blocking-under-lock -- one-shot lazy
                # build under the load lock is the point: concurrent
                # first-callers must block until the single make finishes
                # rather than race parallel builds of the same .so
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True, capture_output=True, text=True,
                )
            except (OSError, subprocess.CalledProcessError) as exc:
                detail = getattr(exc, "stderr", "") or str(exc)
                raise NativeUnavailable(
                    f"failed to build native miner: {detail}"
                ) from exc
        if not os.path.exists(_LIB_PATH):
            raise NativeUnavailable(f"native miner library missing: {_LIB_PATH}")
        lib = ctypes.CDLL(_LIB_PATH)
        lib.distpow_search_range.restype = ctypes.c_int
        lib.distpow_search_range.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,          # nonce
            ctypes.c_uint32,                            # difficulty
            ctypes.c_uint32,   # algo: 0 md5, 1 sha256, 2 sha1,
                               # 3 ripemd160, 4 sha512, 5 sha384,
                               # 6 sha3_256, 7 blake2b_256
            ctypes.c_char_p, ctypes.c_size_t,          # thread bytes
            ctypes.c_uint32,                            # width
            ctypes.c_uint64, ctypes.c_uint64,          # chunk start/count
            ctypes.c_int32,                             # n_threads
            ctypes.POINTER(ctypes.c_int32),            # cancel flag
            ctypes.POINTER(ctypes.c_uint64),           # out hashes
            ctypes.c_char_p,                            # out secret
        ]
        lib.distpow_md5.restype = None
        lib.distpow_md5.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha256.restype = None
        lib.distpow_sha256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha1.restype = None
        lib.distpow_sha1.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_ripemd160.restype = None
        lib.distpow_ripemd160.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha512.restype = None
        lib.distpow_sha512.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha384.restype = None
        lib.distpow_sha384.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha3_256.restype = None
        lib.distpow_sha3_256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_blake2b_256.restype = None
        lib.distpow_blake2b_256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.distpow_sha256d.restype = None
        lib.distpow_sha256d.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        _lib = lib
        return lib


ALGO_IDS = {"md5": 0, "sha256": 1, "sha1": 2, "ripemd160": 3,
            "sha512": 4, "sha384": 5, "sha3_256": 6, "blake2b_256": 7,
            "sha256d": 8}

# Digest sizes (bytes) for the native algorithms, fixed by RFC 1321 /
# FIPS 180-4.  max difficulty = hex nibbles = 2 * digest bytes; kept
# local (mirroring the C library's own rc=-2 guard) so the native hot
# path never imports the JAX model modules (advisor r3: resolving
# max_difficulty via models.registry pulled jax into native-only use).
DIGEST_BYTES = {"md5": 16, "sha256": 32, "sha1": 20, "ripemd160": 20,
                "sha512": 64, "sha384": 48, "sha3_256": 32,
                "blake2b_256": 32, "sha256d": 32}


def native_md5(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(16)
    lib.distpow_md5(data, len(data), out)
    return out.raw


def native_sha256(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(32)
    lib.distpow_sha256(data, len(data), out)
    return out.raw


def native_sha1(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(20)
    lib.distpow_sha1(data, len(data), out)
    return out.raw


def native_ripemd160(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(20)
    lib.distpow_ripemd160(data, len(data), out)
    return out.raw


def native_sha512(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(64)
    lib.distpow_sha512(data, len(data), out)
    return out.raw


def native_sha384(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(48)
    lib.distpow_sha384(data, len(data), out)
    return out.raw


def native_sha3_256(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(32)
    lib.distpow_sha3_256(data, len(data), out)
    return out.raw


def native_blake2b_256(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(32)
    lib.distpow_blake2b_256(data, len(data), out)
    return out.raw


def native_sha256d(data: bytes) -> bytes:
    lib = load_library()
    out = ctypes.create_string_buffer(32)
    lib.distpow_sha256d(data, len(data), out)
    return out.raw


class NativeBackend:
    """C++ brute-force miner behind the standard backend interface."""

    name = "native"

    def __init__(
        self,
        hash_model: str = "md5",
        n_threads: int = 0,
        range_size: int = 1 << 22,
        **_,
    ):
        if hash_model not in ALGO_IDS:
            raise ValueError(
                f"native backend implements {sorted(ALGO_IDS)}, "
                f"not {hash_model!r}"
            )
        self.hash_model = hash_model
        self.algo = ALGO_IDS[hash_model]
        self.n_threads = n_threads or (os.cpu_count() or 1)
        self.range_size = range_size
        self.lib = load_library()

    def search(
        self,
        nonce: bytes,
        difficulty: int,
        thread_bytes: Sequence[int],
        cancel_check: Optional[Callable[[], bool]] = None,
    ) -> Optional[bytes]:
        nonce = bytes(nonce)
        max_nibbles = 2 * DIGEST_BYTES[self.hash_model]
        if difficulty > max_nibbles:
            if cancel_check is None:
                # same guard as parallel/search.py (VERDICT r3 item 7):
                # with no gate the block below could never return
                raise ValueError(
                    f"difficulty {difficulty} exceeds {self.hash_model}'s "
                    f"{max_nibbles} digest nibbles (unsatisfiable) and no "
                    f"cancel_check was supplied; the search could never "
                    f"return"
                )
            # unsatisfiable: same contract as the JAX driver
            # (parallel/search.py) — the reference would brute-force
            # forever, so block on the cancel gate instead of burning
            # CPU (the C library also guards with rc=-2, so an
            # out-of-range difficulty can never over-read the digest
            # buffer in MeetsDifficulty).  cancel_check is non-None
            # here: the guard above raised otherwise.
            while True:
                if cancel_check():
                    metrics.inc("search.cancelled")
                    return None
                time.sleep(0.01)
        contiguous_bounds(thread_bytes)  # validates the run
        tb_buf = bytes(thread_bytes)
        cancel = ctypes.c_int32(0)
        hashes = ctypes.c_uint64(0)
        secret_buf = ctypes.create_string_buffer(16)

        stop_poll = threading.Event()
        if cancel_check is not None:
            # mirror the driver's between-batches poll as a tiny side thread
            # flipping the native cancel flag
            def poll():
                while not stop_poll.is_set():
                    if cancel_check():
                        cancel.value = 1
                        return
                    stop_poll.wait(0.01)

            threading.Thread(target=poll, daemon=True).start()

        def account() -> None:
            # the native call OVERWRITES its out-param each invocation
            # (*out_hashes = hashes, native_miner.cc) — per-call totals,
            # not accumulation
            metrics.inc("search.hashes", hashes.value)
            metrics.inc("search.launches")

        try:
            # the native path enumerates full-width chunk integers in
            # uint64 directly, so each width is one dense range (no
            # high-byte segmenting like the uint32-lane device kernels)
            for width in range(0, 8):
                full_lo, full_hi = (
                    (0, 1) if width == 0
                    else (256 ** (width - 1), 256 ** width)
                )
                start = full_lo
                while start < full_hi:
                    count = min(self.range_size, full_hi - start)
                    rc = self.lib.distpow_search_range(
                        nonce, len(nonce),
                        difficulty, self.algo,
                        tb_buf, len(tb_buf),
                        width,
                        start, count,
                        self.n_threads,
                        ctypes.byref(cancel),
                        ctypes.byref(hashes),
                        secret_buf,
                    )
                    account()
                    if rc == 1:
                        secret = secret_buf.raw[: 1 + width]
                        if not puzzle.check_secret(nonce, secret, difficulty,
                                                   algo=self.hash_model):
                            raise RuntimeError(
                                "native miner returned non-solving secret "
                                f"{secret.hex()}"
                            )
                        metrics.inc("search.found")
                        return secret
                    if rc == -1:
                        metrics.inc("search.cancelled")
                        return None
                    if rc < 0:
                        raise RuntimeError(f"native miner error rc={rc}")
                    start += count
            return None
        finally:
            stop_poll.set()
