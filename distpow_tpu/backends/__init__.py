"""Worker compute backends.

The reference worker has exactly one compute path — a single-goroutine
byte-at-a-time loop (worker.go:318-400).  Here the miner is a pluggable
backend selected by ``WorkerConfig.Backend``:

* ``python``   — hashlib loop, the CPU behavioral-parity baseline
* ``jax``      — fused XLA search step on the default device (TPU when
                 present), batched + pipelined (parallel/search.py)
* ``jax-mesh`` — shard_map over all local devices, prefix->core
                 (parallel/mesh_search.py)
* ``pallas``   — hand-written TPU kernels for the hot op
                 (ops/md5_pallas.py: every _TILE_FNS model) behind the
                 same driver
* ``pallas-mesh`` — the same kernels spread over the local device mesh
                 (prefix->core + ``lax.pmin``, parallel/mesh_search.py)
* ``native``   — C++ miner via ctypes (backends/native/), the CPU
                 performance path (every ALGO_IDS model)
* ``auto``     — resolve from the hardware at boot: the Pallas kernel
                 backends on TPU (mesh when >1 local device), the XLA
                 backends elsewhere — see ``get_backend``

Every backend implements ``search(nonce, difficulty, thread_bytes,
cancel_check) -> Optional[bytes]`` returning the first solving secret in
reference enumeration order, or None when cancelled.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from ..models import puzzle

log = logging.getLogger("distpow.backends")


class PythonBackend:
    """Reference-parity CPU loop (worker.go:318-400 minus string formatting)."""

    name = "python"

    def __init__(self, hash_model: str = "md5", **_):
        self.hash_model = hash_model

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        from ..runtime.metrics import REGISTRY as metrics

        def count_exit(reason: str) -> None:
            # the loop reports why it exited; re-evaluating cancel_check
            # here would misclassify budget exhaustion as a cancel when
            # the condition flipped after the loop stopped (and would
            # re-trigger the check's side effects)
            if reason != "exhausted":
                metrics.inc(f"search.{reason}")

        return puzzle.python_search(
            nonce,
            difficulty,
            thread_bytes,
            algo=self.hash_model,
            cancel_check=cancel_check,
            cancel_poll_interval=1024,
            on_progress=lambda n: metrics.inc("search.hashes", n),
            on_exit=count_exit,
        )


def _resolve_max_launch(max_launch: Optional[int], model) -> int:
    """One home for the backend default budget: explicit config wins;
    otherwise the model's cost-scaled budget (review r4: this
    expression was copy-pasted into three constructors)."""
    from ..parallel.search import scaled_launch_candidates

    return max_launch or scaled_launch_candidates(model.cost_ops)


def _warm_factory(factory, widths, target_chunks, tbc, max_launch) -> None:
    """Compile-and-dispatch each width's step once (tiny real launch)."""
    from ..parallel.search import launch_steps_for
    from ..runtime.watchdog import FIRST_COMPILE_GRACE_S, WATCHDOG

    # one beat per compiled program, and a grace window around each
    # compile+dispatch: a single XLA compile cannot beat, and the
    # largest graphs (sha512's 64-bit limb emulation) have out-waited a
    # 420 s watchdog window on a HEALTHY device (r4 hardware session) —
    # without the grace, arming DeviceHangTimeoutS would kill a sha512
    # worker during its own boot warmup
    with WATCHDOG.active():
        for vw in widths:
            WATCHDOG.beat()
            k = launch_steps_for(int(vw), target_chunks, tbc, max_launch)
            with WATCHDOG.grace(FIRST_COMPILE_GRACE_S):
                step, _ = factory(int(vw), b"", target_chunks, k)
                int(step(1))  # block_until_ready via the int() conversion


# One representative difficulty per mask-word compile bucket
# (ops/search_step.py mask_words_for: difficulties 1..8 share a program,
# 9..16 the next, ...).  Two buckets cover difficulty <= 16 nibbles
# (64 bits) — beyond any feasible puzzle; higher buckets compile on
# demand.
WARMUP_DIFFICULTIES = (1, 9)


def _warm_layouts(build, nonce_lens, widths, batch_size, tbc=256,
                  max_launch=None) -> None:
    """Warm the layout-keyed programs for every (nonce length, width,
    mask-word bucket).

    ``build(nonce, tbc, difficulty) -> StepFactory`` builds the factory
    for the full partition ``[0, tbc)``.  ``target_chunks`` and the
    per-width launch multiplier are derived exactly the way the serving
    path derives them (parallel/search.py: ``effective_batch`` with the
    same ``tbc``, ``launch_steps_for`` with the same budget) — which is
    what makes the warmed compile keys byte-identical to the ones serving
    dispatches.
    """
    from ..parallel.search import DEFAULT_LAUNCH_CANDIDATES, effective_batch

    if max_launch is None:
        max_launch = DEFAULT_LAUNCH_CANDIDATES
    target = max(1, effective_batch(batch_size) // tbc)
    for L in nonce_lens:
        for difficulty in WARMUP_DIFFICULTIES:
            _warm_factory(build(bytes(int(L)), tbc, difficulty), widths,
                          target, tbc, max_launch)


class JaxBackend:
    """Single-device fused-step search (the TPU path).

    ``loop`` selects the serving loop (docs/SERVING.md):
    ``"persistent"`` (default) drives the multi-segment on-device loop
    with the polling drain (parallel/search.py persistent_search);
    ``"serial"`` keeps the pre-PR-6 launch/fetch/relaunch loop — the
    bench baseline (``bench.py --serving-loop``) and the escape hatch.
    """

    name = "jax"

    def __init__(self, hash_model: str = "md5", batch_size: int = 1 << 20,
                 max_launch: Optional[int] = None,
                 loop: str = "persistent", **_):
        from ..models.registry import get_hash_model

        self.model = get_hash_model(hash_model)
        self.batch_size = batch_size
        self.max_launch = _resolve_max_launch(max_launch, self.model)
        if loop not in ("persistent", "serial"):
            raise ValueError(
                f"unknown search loop {loop!r}: expected 'persistent' "
                f"or 'serial'"
            )
        self.loop = loop

    def _persistent_warm_factory(self, nonce: bytes, tbc: int,
                                 difficulty: int):
        """StepFactory-shaped builder over the persistent step, so the
        shared ``_warm_layouts`` derivation (same target/k/mask-bucket
        keys as serving) warms the persistent programs too.  The warmup
        dispatch carries a SET stop flag: the on-device loop exits at
        its first condition check, so warming compiles the real program
        at near-zero device cost."""
        import jax.numpy as jnp

        from ..ops.search_step import (
            cached_persistent_step,
            cached_search_step,
        )

        stop_set = jnp.uint32(1)
        model_name = self.model.name

        def factory(vw, extra, target_chunks, launch_steps=1):
            if vw == 0:
                step = cached_search_step(
                    nonce, 0, difficulty, 0, tbc, 1, model_name, extra, 1
                )
                return step, 1
            bound = cached_persistent_step(
                nonce, vw, difficulty, 0, tbc, target_chunks, model_name,
                extra, launch_steps,
            )
            return (lambda chunk0: bound(chunk0, stop_set)[0]), \
                target_chunks * launch_steps

        return factory

    def warmup(self, nonce_lens: Sequence[int], widths: Sequence[int]) -> None:
        """Pre-compile the layout-keyed programs these nonce lengths hit.

        The dynamic regime (ops/search_step.py) keys compiles on (tail
        layout, batch) only, so warming with a zero nonce of the right
        length and the full 256-byte partition covers every future nonce
        of that length at any difficulty (one program per mask-word
        bucket, WARMUP_DIFFICULTIES) and any power-of-two partition.
        The warmed programs follow the configured loop: the persistent
        step's compile keys differ from the relaunch step's.
        """
        from ..parallel.search import default_step_factory

        if self.loop == "persistent":
            build = self._persistent_warm_factory
        else:
            def build(nonce, tbc, d):
                return default_step_factory(nonce, d, 0, tbc, self.model)

        _warm_layouts(
            build,
            nonce_lens, widths, self.batch_size, max_launch=self.max_launch,
        )

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        from ..parallel.search import persistent_search, search

        kwargs = {}
        if self.loop == "persistent":
            drive = persistent_search
            # launch-lane planning (sched/lanes.py): on a multi-device
            # host the persistent dispatches serve through the mesh
            # persistent step — byte-identical results, n_dev x the
            # per-dispatch coverage.  Single device resolves to None
            # and this stays the classic single-device loop.
            from ..parallel.partition import contiguous_bounds
            from ..sched.lanes import persistent_step_builder

            tb_lo, tbc = contiguous_bounds(thread_bytes)
            kwargs["step_builder"] = persistent_step_builder(
                bytes(nonce), difficulty, tb_lo, tbc, self.model
            )
        else:
            drive = search
        res = drive(
            nonce,
            difficulty,
            thread_bytes,
            model=self.model,
            batch_size=self.batch_size,
            cancel_check=cancel_check,
            launch_candidates=self.max_launch,
            **kwargs,
        )
        return None if res is None else res.secret


class JaxMeshBackend:
    """shard_map over the local device mesh (prefix -> core)."""

    name = "jax-mesh"

    def __init__(
        self,
        hash_model: str = "md5",
        batch_size: int = 1 << 20,
        mesh_devices: int = 0,
        max_launch: Optional[int] = None,
        **_,
    ):
        from ..models.registry import get_hash_model

        self.model = get_hash_model(hash_model)
        self.batch_size = batch_size
        self.mesh_devices = mesh_devices
        self.max_launch = _resolve_max_launch(max_launch, self.model)
        self._mesh = None

    def _get_mesh(self):
        if self._mesh is None:
            import jax

            from ..parallel.mesh_search import make_mesh

            devs = jax.devices()
            if self.mesh_devices:
                devs = devs[: self.mesh_devices]
            self._mesh = make_mesh(devs)
        return self._mesh

    def _step_factory(self, nonce: bytes, difficulty: int, tb_lo: int,
                      tbc: int):
        """Step-factory hook — the ONLY thing kernel-backed mesh
        subclasses override; warmup and search both build through it, so
        compile-key discipline is inherited, not duplicated."""
        from ..parallel.mesh_search import AXIS, _mesh_step_factory

        return _mesh_step_factory(
            nonce, difficulty, tb_lo, tbc, self.model, self._get_mesh(), AXIS
        )

    def warmup(self, nonce_lens: Sequence[int], widths: Sequence[int]) -> None:
        mesh = self._get_mesh()
        n_dev = int(mesh.devices.size)
        if n_dev & (n_dev - 1):
            # non-power-of-two mesh: the factory compiles nonce-content-
            # keyed static programs that cannot be reused by later
            # requests — warming them would burn compile time for nothing.
            # Warn loudly at boot (VERDICT r2 weak #5): every fresh nonce
            # on this mesh will pay a multi-second compile stall at
            # request time (mesh_search.build_static logs again there).
            log.warning(
                "mesh warmup skipped: %d devices is not a power of two, "
                "so search programs are nonce-keyed and compile per "
                "request (multi-second stall per fresh nonce); use a "
                "power-of-two device count for warmed zero-recompile "
                "serving", n_dev)
            return

        def build(nonce, tbc, difficulty):
            return self._step_factory(nonce, difficulty, 0, tbc)

        _warm_layouts(build, nonce_lens, widths, self.batch_size,
                      max_launch=self.max_launch)
        if n_dev > 1:
            # a partition smaller than the device count selects the
            # chunk-split regime (tb_split=False), a distinct compile key;
            # one representative tbc < n_dev warms it for every pow2
            # partition because batch_local is the 256-normalized
            # per-device budget in all of them (mesh_search.py factory)
            _warm_layouts(build, nonce_lens, widths, self.batch_size,
                          tbc=n_dev // 2, max_launch=self.max_launch)

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        from ..parallel.mesh_search import search_mesh
        from ..parallel.partition import contiguous_bounds

        nonce = bytes(nonce)
        tb_lo, tbc = contiguous_bounds(thread_bytes)
        res = search_mesh(
            nonce,
            difficulty,
            thread_bytes,
            mesh=self._get_mesh(),
            model=self.model,
            batch_size=self.batch_size,
            cancel_check=cancel_check,
            launch_candidates=self.max_launch,
            step_factory=self._step_factory(nonce, difficulty, tb_lo, tbc),
        )
        return None if res is None else res.secret


class PallasMeshBackend(JaxMeshBackend):
    """The Pallas kernel spread over the local device mesh.

    Same prefix->core sharding and ``lax.pmin`` found-collective as
    ``jax-mesh``, but each device runs the hand-written kernel
    (ops/md5_pallas.py) instead of the fused XLA step — one compiled
    kernel program serves every device because the partition descriptor
    is a runtime SMEM operand (parallel/mesh_search.py
    _dyn_pallas_mesh_step).  Configurations the kernel cannot express
    fall back to the XLA mesh factory per width, transparently.
    Warmup/search flow is inherited — only the step factory differs.
    """

    name = "pallas-mesh"

    def __init__(self, *args, interpret: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.interpret = interpret

    def _step_factory(self, nonce: bytes, difficulty: int, tb_lo: int,
                      tbc: int):
        from ..parallel.mesh_search import AXIS, _pallas_mesh_step_factory

        xla_factory = super()._step_factory(nonce, difficulty, tb_lo, tbc)
        try:
            pallas_factory = _pallas_mesh_step_factory(
                nonce, difficulty, tb_lo, tbc, self.model, self._get_mesh(),
                AXIS, interpret=self.interpret, max_launch=self.max_launch,
            )
        except ValueError as exc:
            log.info("pallas-mesh: %s; serving via the XLA mesh step", exc)
            return xla_factory

        fell_back = []

        def factory(vw, extra, target_chunks, launch_steps=1):
            try:
                return pallas_factory(vw, extra, target_chunks, launch_steps)
            except ValueError as exc:
                if not fell_back:  # log once per request factory
                    fell_back.append(True)
                    log.info("pallas-mesh: %s; serving width %d via the "
                             "XLA mesh step", exc, vw)
                return xla_factory(vw, extra, target_chunks, launch_steps)

        return factory


def get_backend(name: str, **kwargs):
    name = (name or "jax").lower()
    if name == "auto":
        # Resolve from the hardware, by this repo's own measurements
        # (docs/KERNELS.md standing table): on TPU the Pallas kernel
        # backends win for every model — dramatically for the 64-bit
        # limb models, whose fused-XLA serving steps are impractical to
        # even compile there (sha512: >30 min vs the kernel's ~5 s) —
        # and a multi-device host gets the mesh variant; off-TPU the
        # kernels don't lower, so the XLA backends serve (and the
        # pallas backends would fall back to the same steps anyway).
        # Deliberately NOT the config default: ``jax`` stays the
        # documented default for reference-parity predictability, and
        # ``auto`` imports jax, which the native-only path must not.
        import jax

        on_tpu = jax.default_backend() == "tpu"
        # jax.devices() is the GLOBAL list (the worker runs
        # maybe_init_distributed before building the backend), which is
        # the right mesh-vs-single signal: the mesh backends span the
        # global device set
        multi = len(jax.devices()) > 1
        name = ("pallas-mesh" if multi else "pallas") if on_tpu else \
            ("jax-mesh" if multi else "jax")
        log.info("backend auto -> %s (platform=%s, %d global device(s))",
                 name, jax.default_backend(), len(jax.devices()))
    if name == "python":
        return PythonBackend(**kwargs)
    if name == "jax":
        return JaxBackend(**kwargs)
    if name in ("jax-mesh", "mesh"):
        return JaxMeshBackend(**kwargs)
    if name == "pallas-mesh":
        return PallasMeshBackend(**kwargs)
    if name == "pallas":
        from .pallas_backend import PallasBackend

        return PallasBackend(**kwargs)
    if name == "native":
        from .native_miner import NativeBackend

        return NativeBackend(**kwargs)
    raise ValueError(f"unknown worker backend {name!r}")
