"""Worker backend driving the Pallas hash kernels through the search loop.

Plugs ``ops.md5_pallas`` (a hardware-swept tile for every registry
model) into ``parallel.search`` via the step-factory protocol.  Launch
geometry: the batch is rounded to a whole number of (sublanes, 128)
tiles; configurations the kernel cannot express (non-power-of-two
thread-byte runs, multi-block tails, TPU-only tiles under interpret
mode) fall back to the fused XLA step transparently.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

from ..models.registry import get_hash_model
from ..ops.md5_pallas import (
    LANES,
    cached_pallas_search_step,
    default_geometry,
)
from ..ops.search_step import cached_search_step
from ..parallel.partition import contiguous_bounds
from ..parallel.search import search


def plan_launch_geometry(target_chunks: int, tbc: int, tile: int,
                         inner: int, launch_steps: int,
                         max_launch: int) -> Tuple[int, int, int]:
    """Pick the kernel launch geometry ``(batch, chunks, k)`` for one
    dispatch — pure math, extracted so the k-selection rules are unit-
    testable on CPU (ISSUE 8 satellite; the advisor-r5 pow2-k fix lives
    here).

    * the batch rounds UP to a whole number of ``tile``-sized grids and
      ``k`` (the launch multiplier) re-clamps to the rounded batch so
      ``batch * k`` stays within ``max_launch``;
    * for non-power-of-two tiles with ``inner > 1``, ``k`` is rounded
      down to a power of two ONLY when that keeps (or makes, after a
      marginal <=2% whole-tile batch growth) the inner loop effective —
      when the growth conditions fail, the ORIGINAL k is kept: rounding
      unconditionally cost non-pow2 tiles with a non-pow2 multiplier up
      to ~2x launch amortization for nothing (advisor r5 low #1).
    """
    chunks = max(1, target_chunks)
    batch = chunks * tbc
    # round the batch up to a whole tile grid
    if batch % tile:
        batch = ((batch // tile) + 1) * tile
        chunks = max(1, batch // tbc)
    # re-clamp the launch multiplier to the ROUNDED batch: the driver
    # computed launch_steps for the unrounded one, and rounded_batch * k
    # must stay within the uint32 flat-index bound (_check_launch) and
    # the dispatch budget
    k = max(1, min(launch_steps, max_launch // batch))
    # keep the tuned inner effective for non-power-of-two tiles (the
    # sweep-best sublanes=24 geometries): the kernel shrinks inner until
    # it divides the per-dispatch tile count, and a 24-sublane tile
    # leaves 2^21 candidates at 683 tiles — prime — so with an odd
    # launch multiplier the inner loop collapses all the way to 1 (the
    # review-r4 trap that kept those geometries unshippable).  Two
    # bounded moves fix it: round k down to a power of two (so the
    # dispatch tile count carries pow2 factors), then grow the batch by
    # whole tiles until k*n_tiles divides inner — but ONLY when the
    # growth is marginal (<=2%) and the k clamp is unaffected; otherwise
    # keep the old shrink-inner behavior (review r5: an uncapped version
    # of this grew small width segments 4x and blew the dispatch budget
    # the k clamp above enforces).  For all-power-of-two geometries
    # every condition already holds: no-op.
    if inner > 1 and (tile & (tile - 1)):
        # the pow2 rounding only commits together with a batch that
        # makes inner effective; when the growth conditions fail, the
        # ORIGINAL k is kept (shrink-inner behavior) — advisor r5 low #1
        k2 = 1 << (k.bit_length() - 1)
        need = inner // math.gcd(k2, inner)
        n = batch // tile
        if n % need == 0:
            k = k2
        else:
            cap = batch + max(tile, batch // 50)
            grown = n + (need - n % need)
            while grown * tile <= cap and (grown * tile) % tbc:
                grown += need
            gbatch = grown * tile
            # the k in use must still fit the budget at the grown batch
            # (compare in pow2-rounded space)
            reclamp = max(1, min(launch_steps, max_launch // gbatch))
            if (gbatch <= cap and gbatch % tbc == 0
                    and 1 << (reclamp.bit_length() - 1) >= k2):
                batch = gbatch
                chunks = max(1, batch // tbc)
                k = k2
    return batch, chunks, k


class PallasBackend:
    name = "pallas"

    def __init__(
        self,
        hash_model: str = "md5",
        batch_size: int = 1 << 20,
        sublanes: Optional[int] = None,
        inner: Optional[int] = None,
        interpret: bool = False,
        max_launch: Optional[int] = None,
        **_,
    ):
        from . import _resolve_max_launch

        self.model = get_hash_model(hash_model)
        self.batch_size = batch_size
        # per-model tuned tile geometry unless explicitly overridden
        # (default_geometry caps interpret-mode sublanes at 8 — the
        # serving geometry's interpret compile is pathological on
        # XLA:CPU, see its docstring)
        default_geom = default_geometry(self.model.name, interpret)
        self.sublanes = sublanes if sublanes is not None else default_geom[0]
        self.inner = inner if inner is not None else default_geom[1]
        self.interpret = interpret
        self.max_launch = _resolve_max_launch(max_launch, self.model)

    def _factory(self, nonce: bytes, difficulty: int, tb_lo: int, tbc: int):
        tile = self.sublanes * LANES

        def factory(vw: int, extra: bytes, target_chunks: int, launch_steps: int = 1):
            if vw == 0:
                # tiny width-0 probe: XLA step is fine
                return (
                    cached_search_step(
                        nonce, vw, difficulty, tb_lo, tbc, 1,
                        self.model.name, extra,
                    ),
                    1,
                )
            batch, chunks, k = plan_launch_geometry(
                target_chunks, tbc, tile, self.inner, launch_steps,
                self.max_launch,
            )
            try:
                # launch_steps just extends the kernel's sequential grid
                # (ops/md5_pallas.py), so the kernel serves the big
                # amortized serving launches too — this is the path that
                # was missing in round 1 (VERDICT weak #1)
                step = cached_pallas_search_step(
                    nonce, vw, difficulty, tb_lo, tbc, chunks,
                    self.model.name, extra,
                    self.sublanes, self.interpret, k, self.inner,
                )
            except ValueError:
                step = cached_search_step(
                    nonce, vw, difficulty, tb_lo, tbc, chunks,
                    self.model.name, extra, k,
                )
            return step, chunks * k

        return factory

    def warmup(self, nonce_lens, widths) -> None:
        from . import _warm_layouts

        _warm_layouts(
            lambda nonce, tbc, d: self._factory(nonce, d, 0, tbc),
            nonce_lens, widths, self.batch_size, max_launch=self.max_launch,
        )

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None) -> Optional[bytes]:
        nonce = bytes(nonce)
        tb_lo, tbc = contiguous_bounds(thread_bytes)
        res = search(
            nonce,
            difficulty,
            thread_bytes,
            model=self.model,
            batch_size=self.batch_size,
            cancel_check=cancel_check,
            step_factory=self._factory(nonce, difficulty, tb_lo, tbc),
            launch_candidates=self.max_launch,
        )
        return None if res is None else res.secret
