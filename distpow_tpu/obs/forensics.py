"""Cross-node request forensics assembly (ISSUE 14; docs/FORENSICS.md).

The span layer (runtime/spans.py) gives every node a bounded ring of
per-trace timing spans and a ``Node.Spans`` RPC to export them; this
module turns those per-node rings into ONE answer to "which
shard/slot/launch made this request slow":

* :func:`fetch_spans` sweeps every fleet member's ``Node.Spans``
  concurrently under one shared deadline — the scraper discipline
  (obs/scrape.py): per-node poll threads, an unreachable or frozen
  node is reported, never waited for, and distpow-lint's
  ``serial-rpc-fanout`` rule keeps a serial fetch loop from quietly
  coming back;
* :func:`stitch_timeline` merges the per-node span lists into one
  wall-clock-ordered timeline (dedup by ``(node, seq, name, ts)`` —
  in-process harnesses share a ring, so every node answers with the
  union), anchors relative offsets at the earliest span, and
  attributes the request's slowness: the slowest SEGMENT overall and
  the slowest *shard-attributed* segment (``worker.solve`` /
  ``worker.result_forward`` / ``coord.reassign`` — the spans that name
  a shard), which is the "here is the shard that made it slow" verdict
  the CLI and the smoke assert on.

Clock caveat: spans carry wall-clock start timestamps, so cross-node
offsets are only as honest as the fleet's clock sync — within one
machine (the harnesses) they are exact; across hosts, NTP-grade skew
shifts whole nodes' lanes without changing any span's duration, and
durations are what the slowness verdicts rank.

Consumers: ``python -m distpow_tpu.cli.forensics``,
``scripts/forensics_smoke.py`` (``ci.sh --forensics-smoke``).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional

from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import RPCClient, RPCError

#: umbrella spans cover the whole request by construction — they can
#: never be the "slowest segment" verdict (they'd always win).
UMBRELLA_SPANS = frozenset({"powlib.mine", "coord.mine"})

#: attr keys that name a shard on a span (docs/FORENSICS.md span
#: vocabulary).  Deliberately excludes ``winner_byte``: the
#: first-result span's winner is the FASTEST shard, and ranking it as
#: "slow" would invert the verdict.
_SHARD_KEYS = ("shard", "worker_byte")


def shard_of(span: Optional[dict]) -> Optional[int]:
    """The shard a span names, or None for unattributed spans."""
    if not span:
        return None
    attrs = span.get("attrs") or {}
    for k in _SHARD_KEYS:
        v = attrs.get(k)
        if v is not None:
            return int(v)
    return None


def fetch_spans(addrs: List[str], trace_id: Optional[int] = None,
                deadline_s: float = 5.0, dial_timeout_s: float = 2.0,
                limit: int = 512) -> dict:
    """Concurrent ``Node.Spans`` sweep over ``addrs`` under one shared
    deadline.  With a ``trace_id``, each node answers with its spans
    for that trace; without one, with summaries of its recent traces
    (how a caller finds the trace worth fetching).  Returns
    ``{"nodes": {addr: reply}, "unreachable": {addr: error}}`` — the
    sweep always completes within ~``deadline_s``."""
    metrics.inc("forensics.fetches")
    results: Dict[str, dict] = {}
    errors: Dict[str, str] = {}
    lock = threading.Lock()
    deadline = time.monotonic() + float(deadline_s)

    def poll(addr: str) -> None:
        client = None
        try:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("sweep deadline exhausted")
            client = RPCClient(addr,
                               timeout=min(dial_timeout_s, remaining))
            params: dict = {"limit": int(limit)}
            if trace_id is not None:
                params["trace_id"] = int(trace_id)
            remaining = max(0.05, deadline - time.monotonic())
            reply = client.go("Node.Spans", params).result(
                timeout=remaining)
            with lock:
                results[addr] = reply or {}
        except (OSError, RPCError, RuntimeError, TimeoutError,
                FutureTimeout) as exc:
            metrics.inc("forensics.fetch_failures")
            with lock:
                errors[addr] = f"{type(exc).__name__}: {exc}"
        finally:
            if client is not None:
                try:
                    client.close()
                except OSError:
                    pass

    threads = []
    for addr in addrs:
        t = threading.Thread(target=poll, args=(addr,), daemon=True,
                             name=f"forensics-{addr}")
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()) + 0.25)
    return {"nodes": results, "unreachable": errors}


def slowest_request_timelines(addrs: List[str], k: int = 5,
                              deadline_s: float = 5.0) -> List[dict]:
    """Top-k slowest recent request timelines across a REMOTE fleet —
    the cross-process twin of ``SPANS.slowest_traces`` (same shape:
    per-trace summaries with their span trees attached).  One
    summaries sweep ranks the candidates; one per-trace sweep fetches
    each tree (k is small and bounded).  Used by the SLO engine's
    breach evidence when the judging process has no local span ring —
    the ``cli/slo.py`` gate observing a separate-process cluster."""
    summaries = fetch_spans(addrs, trace_id=None, deadline_s=deadline_s)
    ranked: Dict[int, dict] = {}
    for reply in (summaries.get("nodes") or {}).values():
        for t in reply.get("traces") or []:
            tid = t.get("trace_id")
            if tid is None:
                continue
            cur = ranked.get(tid)
            if cur is None or float(t.get("dur_s") or 0.0) > \
                    float(cur.get("dur_s") or 0.0):
                ranked[tid] = dict(t)
    top = sorted(ranked.values(),
                 key=lambda t: -float(t.get("dur_s") or 0.0))[:k]
    out = []
    for t in top:
        fetched = fetch_spans(addrs, trace_id=t["trace_id"],
                              deadline_s=deadline_s)
        t["spans"] = stitch_timeline(fetched, t["trace_id"])["spans"]
        out.append(t)
    return out


def slowest_trace_id(fetched: dict) -> Optional[int]:
    """From a summaries sweep (``fetch_spans`` with no trace_id), the
    id of the slowest recent trace across every node that answered."""
    best_tid = None
    best_dur = -1.0
    for reply in (fetched.get("nodes") or {}).values():
        for t in reply.get("traces") or []:
            d = float(t.get("dur_s") or 0.0)
            if d > best_dur:
                best_dur = d
                best_tid = t.get("trace_id")
    return best_tid


def stitch_timeline(fetched: dict, trace_id: int) -> dict:
    """Merge the per-node span lists into one request timeline
    (module docstring).  The returned dict is the forensics CLI's
    ``--json`` shape — and ``scripts/trace_profile.py`` accepts it as
    its third input format, so offline and live forensics share one
    per-request breakdown renderer."""
    spans: List[dict] = []
    seen = set()
    for label, reply in (fetched.get("nodes") or {}).items():
        answering = reply.get("node") or label
        for s in reply.get("spans") or []:
            node = s.get("node") or answering
            key = (node, s.get("seq"), s.get("name"), s.get("ts"))
            if key in seen:
                continue
            seen.add(key)
            spans.append(dict(s, node=node))
    spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("seq", 0)))
    out: dict = {
        "format": "spans",
        "trace_id": int(trace_id),
        "spans": spans,
        "nodes": sorted({s["node"] for s in spans}),
        "unreachable": dict(fetched.get("unreachable") or {}),
    }
    if not spans:
        return out
    epoch = min(s["ts"] for s in spans)
    for s in spans:
        s["rel_ms"] = round((s["ts"] - epoch) * 1000.0, 3)
    out["wall_s"] = round(
        max(s["ts"] + s.get("dur_s", 0.0) for s in spans) - epoch, 6)
    segments = [s for s in spans if s["name"] not in UMBRELLA_SPANS]
    if segments:
        out["slowest"] = max(segments, key=lambda s: s.get("dur_s", 0.0))
    shard_segs = [s for s in segments if shard_of(s) is not None]
    if shard_segs:
        seg = max(shard_segs, key=lambda s: s.get("dur_s", 0.0))
        out["slowest_shard_segment"] = seg
        out["slow_shard"] = shard_of(seg)
    return out


def _fmt_attrs(attrs: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))


def render_timeline(tl: dict) -> str:
    """Human one-screen timeline: per-span rows in wall-clock order
    with relative offsets, closed by the slowness verdicts."""
    head = [f"# trace {tl['trace_id']}: {len(tl.get('spans') or [])} "
            f"span(s) across {len(tl.get('nodes') or [])} node(s)"
            + (f", {tl['wall_s']:.3f}s wall" if "wall_s" in tl else "")]
    for addr, err in sorted((tl.get("unreachable") or {}).items()):
        head.append(f"# unreachable: {addr} ({err})")
    rows = []
    for s in tl.get("spans") or []:
        rows.append(
            f"  {s.get('rel_ms', 0.0):>10.1f}ms "
            f"+{s.get('dur_s', 0.0) * 1000.0:>9.1f}ms "
            f"[{s.get('node', '?')}] {s['name']}  "
            f"{_fmt_attrs(s.get('attrs') or {})}".rstrip()
        )
    tail = []
    slow = tl.get("slowest")
    if slow is not None:
        tail.append(f"# slowest segment: {slow['name']} on "
                    f"{slow.get('node', '?')} "
                    f"({slow.get('dur_s', 0.0) * 1000.0:.1f}ms)")
    seg = tl.get("slowest_shard_segment")
    if seg is not None:
        tail.append(f"# slow shard: {tl['slow_shard']} via {seg['name']} "
                    f"on {seg.get('node', '?')} "
                    f"({seg.get('dur_s', 0.0) * 1000.0:.1f}ms)")
    return "\n".join(head + rows + tail)
