"""Fleet scraper — every node's ``Stats`` RPC, one shared deadline.

The control-plane scaling law applies to observability too (ISSUE 8 /
docs/RPC.md "Control-plane concurrency"): a sweep that dials and calls
N nodes one after another costs O(N x RTT) and lets ONE frozen node
(SIGSTOP'd, half-crashed, black-holed — TCP accepted, nothing answers)
stall the whole cluster view for its full timeout.  Here every node is
polled concurrently — per-node poll threads issue their dial plus a
``RPCClient.go()`` Stats future, all bounded by one shared sweep
deadline — and a node that misses the deadline is marked ``stale`` with
its last-seen age while its LAST-KNOWN snapshot keeps contributing to
the merged view (flagged, never silently fresh).  The sweep itself
always completes within ~``deadline_s``; distpow-lint's
``serial-rpc-fanout`` rule covers this package so a serial scrape loop
cannot quietly come back (docs/LINT.md).

Connections are dialed lazily and kept across sweeps, so the wire-v2
negotiation (PR 5) runs once per node, not once per poll, and repeat
sweeps ride the binary codec.  A failed poll tears its connection down;
the next sweep re-dials.

Consumers: ``cli/stats.py --cluster``, ``cli/slo.py``, the load
harness (distpow_tpu/load/harness.py), ``bench.py --load-slo``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import RPCClient, RPCError
from ..runtime.telemetry import RECORDER

#: Stats service names per role; "auto" resolves on first contact and
#: the resolved service is cached on the target state.  Auto tries the
#: role-agnostic ``Node.Stats`` alias FIRST — every current node
#: answers it, so discovery is error-free; the role-specific fallbacks
#: cover pre-alias nodes at the cost of one unknown-method error
#: (``rpc.handler_errors`` on the probed node) on first contact.
_SERVICES = {
    "coordinator": ("CoordRPCHandler.Stats",),
    "worker": ("WorkerRPCHandler.Stats",),
    "auto": ("Node.Stats", "CoordRPCHandler.Stats",
             "WorkerRPCHandler.Stats"),
}


@dataclass
class NodeTarget:
    """One scrape target.  ``name`` labels the node in merged output
    (defaults to the address); ``role`` picks the Stats service."""

    addr: str
    name: str = ""
    role: str = "auto"

    def __post_init__(self) -> None:
        if not self.name:
            self.name = self.addr
        if self.role not in _SERVICES:
            raise ValueError(f"unknown scrape role {self.role!r}")


@dataclass
class _NodeState:
    """Mutable per-target scrape state (guarded by the scraper lock)."""

    target: NodeTarget
    client: Optional[RPCClient] = None
    service: Optional[str] = None  # resolved Stats method
    snapshot: Optional[dict] = None
    last_seen: Optional[float] = None  # monotonic, successful poll
    error: str = ""
    generation: int = 0  # sweep id of the freshest successful poll
    lock: threading.Lock = field(default_factory=threading.Lock)


class FleetScraper:
    """Concurrent Stats sweeps over a fixed node set (module docstring).

    ``sweep()`` returns the merged cluster snapshot
    (:func:`..obs.merge.merge_snapshots` shape) with per-node
    ``status``/``age_s`` riding in ``per_node``.
    """

    def __init__(self, targets: List[NodeTarget], deadline_s: float = 5.0,
                 dial_timeout_s: float = 2.0):
        if not targets:
            raise ValueError("FleetScraper needs at least one target")
        names = [t.name for t in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate target names: {sorted(names)}")
        self.deadline_s = float(deadline_s)
        self.dial_timeout_s = float(dial_timeout_s)
        self._states = {t.name: _NodeState(t) for t in targets}
        self._sweep_n = 0

    # -- one node -----------------------------------------------------------
    def _poll_one(self, st: _NodeState, deadline: float, gen: int) -> None:
        """Dial (if needed) and call Stats, bounded by the shared sweep
        deadline.  Runs on its own thread; a poll that outlives the
        deadline is abandoned by the sweep — if it succeeds later, its
        snapshot is kept for the NEXT sweep (a late write updates
        last-seen, never this sweep's already-rendered verdict)."""
        # one in-flight poll per node: a previous sweep's abandoned poll
        # may still own the client slot (e.g. wedged mid-dial against a
        # SIGSTOP'd peer) — bounded acquire, so this poll gives up at
        # the deadline instead of queueing behind it forever
        if not st.lock.acquire(timeout=max(0.0, deadline - time.monotonic())):
            st.error = "previous poll still in flight"
            metrics.inc("obs.scrape_failures")
            return
        try:
            # (acquire/release rather than `with`: the acquire above is
            # BOUNDED by the sweep deadline, and every blocking step in
            # here is too, so holding the per-node lock across the poll
            # is safe by construction)
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("sweep deadline exhausted")
                client = st.client
                if client is None or client.dead:
                    client = RPCClient(
                        st.target.addr,
                        timeout=min(self.dial_timeout_s, remaining),
                    )
                    st.client = client
                snap: Optional[dict] = None
                last: Exception = RPCError("no Stats service answered")
                for method in ((st.service,) if st.service
                               else _SERVICES[st.target.role]):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("sweep deadline exhausted")
                    try:
                        snap = client.go(method, {}).result(timeout=remaining)
                        st.service = method
                        break
                    except (RPCError, FutureTimeout) as exc:
                        # FutureTimeout only aliases an OSError-derived
                        # builtin on 3.11+; catch it explicitly
                        last = exc
                        if client.dead:
                            raise
                if snap is None:
                    raise last
                st.snapshot = snap
                st.last_seen = time.monotonic()
                st.error = ""
                st.generation = max(st.generation, gen)
            except (OSError, RPCError, RuntimeError, TimeoutError,
                    FutureTimeout) as exc:
                st.error = f"{type(exc).__name__}: {exc}"
                metrics.inc("obs.scrape_failures")
                if st.client is not None:
                    try:
                        st.client.close()
                    except OSError:
                        pass
                    st.client = None
        finally:
            st.lock.release()

    # -- the sweep ----------------------------------------------------------
    def sweep(self, deadline_s: Optional[float] = None) -> dict:
        """Poll every target concurrently; merge what answered.

        Always returns within ~``deadline_s`` plus scheduling slack:
        nodes still pending at the deadline are reported ``stale`` with
        ``age_s`` since their last successful poll (``never_seen`` nodes
        carry ``age_s: null``) while their last-seen snapshot, if any,
        stays in the merge — flagged via ``per_node`` and
        ``stale_nodes``."""
        from .merge import merge_snapshots

        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        deadline = time.monotonic() + budget
        self._sweep_n += 1
        gen = self._sweep_n
        metrics.inc("obs.scrapes")
        threads = []
        for st in self._states.values():
            t = threading.Thread(
                target=self._poll_one, args=(st, deadline, gen),
                name=f"obs-scrape-{st.target.name}", daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()) + 0.25)
        with metrics.time("obs.sweep_s"):
            # assembling the merged view is pure local compute, but the
            # histogram work is O(nodes x series) — worth a distribution
            now = time.monotonic()
            snaps: Dict[str, dict] = {}
            meta: Dict[str, dict] = {}
            for name, st in self._states.items():
                fresh = st.generation >= gen
                age = None if st.last_seen is None else \
                    round(now - st.last_seen, 3)
                m = {
                    "status": "ok" if fresh else "stale",
                    "age_s": 0.0 if fresh else age,
                    "addr": st.target.addr,
                }
                if not fresh:
                    m["error"] = st.error or "deadline"
                    RECORDER.record("obs.node_stale", node=name,
                                    addr=st.target.addr, age_s=age,
                                    error=m["error"])
                meta[name] = m
                if st.snapshot is not None:
                    snaps[name] = st.snapshot
            stale = {n: m for n, m in meta.items()}
            merged = merge_snapshots(snaps, stale)
            # targets that have NEVER answered contribute no snapshot but
            # must still be visible in the node table
            for name, m in meta.items():
                if name not in merged["per_node"]:
                    merged["per_node"][name] = dict(m, role="unknown")
                    if name not in merged["stale_nodes"]:
                        merged["stale_nodes"].append(name)
            merged["stale_nodes"] = sorted(merged["stale_nodes"])
            merged["deadline_s"] = budget
        return merged

    def last_snapshots(self) -> Dict[str, dict]:
        """Raw last-seen per-node snapshots (post-sweep; the single-node
        oracle side of merge cross-checks — bench.py --load-slo)."""
        return {name: dict(st.snapshot)
                for name, st in self._states.items()
                if st.snapshot is not None}

    def close(self) -> None:
        for st in self._states.values():
            c = st.client
            st.client = None
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass


def scrape_cluster(addrs: List[str], deadline_s: float = 5.0,
                   role: str = "auto") -> dict:
    """One-shot sweep over ``addrs`` (the ``stats --cluster`` path)."""
    scraper = FleetScraper(
        [NodeTarget(addr=a, role=role) for a in addrs],
        deadline_s=deadline_s,
    )
    try:
        return scraper.sweep()
    finally:
        scraper.close()
