"""Cluster observability plane (ISSUE 8; docs/SLO.md).

PR 3 gave every node its own registry, histograms, and ``stats --prom``
— per-node observability.  This package is the *cluster-level* layer on
top of it:

* :mod:`.merge`  — bucket-wise merging of the per-node log-bucketed
  histogram snapshots into cluster percentiles, plus counter/gauge
  aggregation with per-node and per-hash-model breakdowns;
* :mod:`.scrape` — a fleet scraper that polls every node's ``Stats``
  RPC concurrently under one shared deadline (the PR 5 futures + wire
  codec), marking unreachable or frozen nodes ``stale`` with their
  last-seen age instead of stalling the sweep;
* :mod:`.slo`    — a declarative SLO engine: objectives in a checked-in
  config file (config/slo.json) evaluated over merged snapshots with
  fast/slow burn-rate windows, producing a typed verdict, a nonzero
  exit code for CI, and a flight-recorder breach event + critical-path
  dump (with the top-k slowest request timelines) on breach;
* :mod:`.forensics` — per-request cross-node forensics (ISSUE 14,
  docs/FORENSICS.md): concurrent ``Node.Spans`` sweeps over the fleet
  and timeline stitching that names the shard/segment a slow Mine
  spent its time in;
* :mod:`.timeseries` — bounded multi-resolution retention of merged
  sweeps (ISSUE 18, docs/SOAK.md): tiered downsampling on the shared
  log-bucket grid, windowed delta queries (the SLO engine's burn
  windows read these), gauge trajectories for the leak sentinels, and
  a rotated JSONL spool for post-mortem replay.

Consumers: ``python -m distpow_tpu.cli.stats --cluster``, ``python -m
distpow_tpu.cli.slo``, the open-loop load harness
(distpow_tpu/load/), ``bench.py --load-slo``, and
``scripts/ci.sh --slo-smoke``.
"""

from .forensics import fetch_spans, render_timeline, stitch_timeline
from .merge import merge_histograms, merge_snapshots, merged_percentile
from .scrape import FleetScraper, NodeTarget, scrape_cluster
from .slo import (
    ObjectiveVerdict,
    SLOConfigError,
    SLOEngine,
    SLOVerdict,
    load_slo_config,
)
from .timeseries import DEFAULT_TIERS, Tier, TimeSeriesStore, replay_spool

__all__ = [
    "fetch_spans",
    "stitch_timeline",
    "render_timeline",
    "merge_histograms",
    "merge_snapshots",
    "merged_percentile",
    "FleetScraper",
    "NodeTarget",
    "scrape_cluster",
    "SLOConfigError",
    "SLOEngine",
    "SLOVerdict",
    "ObjectiveVerdict",
    "load_slo_config",
    "Tier",
    "TimeSeriesStore",
    "DEFAULT_TIERS",
    "replay_spool",
]
