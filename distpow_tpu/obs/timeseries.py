"""Bounded multi-resolution retention for fleet sweeps (docs/SOAK.md).

Every sensor built so far — the SLO engine's burn windows (PR 7), span
forensics (PR 9), membership (PR 8/10) — judges the PRESENT.  Nothing
retains history, so "p95 was flat for six hours and then bent" is
unanswerable, and the SLO engine carries its own ad-hoc snapshot deque
as a private workaround.  This module is the retention substrate: a
:class:`TimeSeriesStore` holds timestamped MERGED cluster snapshots
(the ``obs.merge.merge_snapshots`` shape the fleet scraper produces) in
resolution tiers, answers the windowed delta queries the SLO engine's
burn windows need, and spools every accepted point to append-only JSONL
for post-mortem replay.

Tier math (the downsampling discipline): snapshots are CUMULATIVE —
counters and histogram bucket counts only grow — so "downsample to one
point per 10 s" means *keep the last snapshot of each 10-second
interval*, not averaging.  A windowed query is a bucket-wise delta
between two retained snapshots (``obs.merge.delta_merged``), and
bucket counts subtract exactly on the shared log grid, so a percentile
over a downsampled tier is *bit-identical* to the full-resolution
oracle evaluated at the same two snapshots; the only degradation from
downsampling is that the window BOUNDARY lands up to one resolution
step earlier than requested, which widens the window slightly and can
move the estimate by at most one log-grid bucket (~19%, the same bound
the PR 7 merge pins — tests/test_timeseries.py property-tests this
against a full-resolution oracle).

Each tier is a bounded deque: points older than the tier's retention
are evicted on append, and a hard ``maxlen`` backstops the math (a
stalled clock must not grow memory).  The finest tier (resolution 0)
keeps every sweep; coarser tiers keep the last point per resolution
interval.  Queries search finest-first so recent windows get full
resolution and older windows degrade gracefully.

The JSONL spool reuses the flight-recorder rotation machinery
(``runtime.telemetry.rotate_if_over``): one ``{"ts": ..., "merged":
...}`` object per line, size-capped segments ``spool.jsonl.N``, and
:func:`replay_spool` walks the segments oldest-first to rebuild a
store (or feed any offline analysis) after the process is gone.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from ..runtime.metrics import REGISTRY as metrics
from ..runtime.telemetry import iter_rotated_jsonl, rotate_if_over
from .merge import delta_merged

log = logging.getLogger("distpow.timeseries")

DEFAULT_SPOOL_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_SPOOL_KEEP = 3

#: per-tier hard point cap: retention/resolution bounds the count when
#: time flows normally; this backstops a stalled or hostile clock.
_TIER_MAXLEN = 4096


@dataclass(frozen=True)
class Tier:
    """One retention tier: keep (at most) one point per
    ``resolution_s`` interval, for ``retention_s`` back.
    ``resolution_s == 0`` keeps every appended point."""

    resolution_s: float
    retention_s: float


#: every sweep for 5 min -> 10 s downsamples for 1 h -> 1 min for a day
DEFAULT_TIERS: Tuple[Tier, ...] = (
    Tier(0.0, 300.0),
    Tier(10.0, 3600.0),
    Tier(60.0, 86400.0),
)


class TimeSeriesStore:
    """Tiered in-memory retention of merged cluster snapshots, with an
    optional rotated JSONL spool (module docstring)."""

    def __init__(self, tiers: Tuple[Tier, ...] = DEFAULT_TIERS,
                 spool_path: Optional[str] = None,
                 spool_max_bytes: int = DEFAULT_SPOOL_MAX_BYTES,
                 spool_keep: int = DEFAULT_SPOOL_KEEP):
        if not tiers:
            raise ValueError("need at least one tier")
        ordered = sorted(tiers, key=lambda t: t.resolution_s)
        for t in ordered:
            if t.retention_s <= 0:
                raise ValueError(f"tier retention must be positive: {t}")
        self._tiers: Tuple[Tier, ...] = tuple(ordered)
        self._points: List[deque] = [
            deque(maxlen=_TIER_MAXLEN) for _ in ordered
        ]
        self._lock = threading.Lock()
        self._spool_path = spool_path
        self._spool_max_bytes = int(spool_max_bytes)
        self._spool_keep = int(spool_keep)

    @property
    def tiers(self) -> Tuple[Tier, ...]:
        return self._tiers

    # -- ingest -------------------------------------------------------------
    def append(self, merged: dict, ts: Optional[float] = None) -> None:
        """Retain one merged cluster snapshot.  ``ts`` defaults to the
        snapshot's own ``ts`` (wall-clock: the scraper stamps it) —
        deterministic tests pass explicit timestamps."""
        t = float(ts if ts is not None
                  else merged.get("ts") or time.time())
        with self._lock:
            for tier, points in zip(self._tiers, self._points):
                if tier.resolution_s <= 0:
                    points.append((t, merged))
                else:
                    slot = int(t // tier.resolution_s)
                    if points and int(points[-1][0]
                                      // tier.resolution_s) == slot:
                        # same resolution interval: the LAST cumulative
                        # snapshot wins (tier math, module docstring)
                        points[-1] = (t, merged)
                    else:
                        points.append((t, merged))
                while points and points[0][0] < t - tier.retention_s:
                    points.popleft()
            self._spool_locked(t, merged)

    def _spool_locked(self, ts: float, merged: dict) -> None:
        if not self._spool_path:
            return
        try:
            with open(self._spool_path, "a") as fh:
                fh.write(json.dumps({"ts": ts, "merged": merged}) + "\n")
        except OSError as exc:
            log.warning("time-series spool append failed: %s", exc)
            return
        if rotate_if_over(self._spool_path, self._spool_max_bytes,
                          self._spool_keep):
            metrics.inc("obs.spool_rotations")

    # -- point queries ------------------------------------------------------
    def __len__(self) -> int:
        """Distinct retained points (a snapshot present in several tiers
        counts once)."""
        with self._lock:
            return len({t for points in self._points for t, _ in points})

    def tier_points(self, i: int) -> List[Tuple[float, dict]]:
        """One tier's retained ``(ts, merged)`` points (tests)."""
        with self._lock:
            return list(self._points[i])

    def latest(self) -> Optional[Tuple[float, dict]]:
        with self._lock:
            return self._latest_locked()

    def _latest_locked(self) -> Optional[Tuple[float, dict]]:
        best: Optional[Tuple[float, dict]] = None
        for points in self._points:
            if points and (best is None or points[-1][0] > best[0]):
                best = points[-1]
        return best

    def snapshot_at(self, ts: float) -> Optional[Tuple[float, dict]]:
        """The newest retained snapshot with ``ts' <= ts`` — searched
        finest-tier-first so recent boundaries resolve at full
        resolution and older ones fall back to downsampled points."""
        with self._lock:
            return self._snapshot_at_locked(ts)

    def _snapshot_at_locked(self, ts: float) -> Optional[Tuple[float, dict]]:
        best: Optional[Tuple[float, dict]] = None
        for points in self._points:
            for t, snap in reversed(points):
                if t <= ts:
                    if best is None or t > best[0]:
                        best = (t, snap)
                    break
        return best

    def _oldest_locked(self) -> Optional[Tuple[float, dict]]:
        best: Optional[Tuple[float, dict]] = None
        for points in self._points:
            if points and (best is None or points[0][0] < best[0]):
                best = points[0]
        return best

    # -- windowed queries ---------------------------------------------------
    def window(self, window_s: float,
               now: Optional[float] = None) -> Optional[dict]:
        """The windowed cluster view ``delta_merged(latest, boundary)``
        where the boundary is the newest snapshot at least ``window_s``
        old (the SLO engine's burn-window contract).  With history
        shallower than the window the OLDEST point stands in — the
        widest window actually observed; with fewer than two points the
        latest snapshot is returned as-is (cumulative degradation, same
        as the engine's one-shot mode).  Returns None when empty."""
        with self._lock:
            latest = self._latest_locked()
            if latest is None:
                return None
            t_now = float(now if now is not None else latest[0])
            boundary = self._snapshot_at_locked(t_now - float(window_s))
            if boundary is None:
                oldest = self._oldest_locked()
                if oldest is not None and oldest[0] < latest[0]:
                    boundary = oldest
        return delta_merged(latest[1], boundary[1] if boundary else None)

    def range_window(self, start_ts: float,
                     end_ts: float) -> Optional[dict]:
        """The windowed view between two HISTORICAL instants: the delta
        between the retained snapshots at ``end_ts`` and ``start_ts``
        (each resolved by the :meth:`snapshot_at` contract, so a
        downsampled tier answers for older instants).  Degrades to
        cumulative when no point precedes ``start_ts``; None when no
        point precedes ``end_ts`` at all.  This is the soak harness's
        per-phase judgment query (load/soak.py)."""
        with self._lock:
            end = self._snapshot_at_locked(float(end_ts))
            if end is None:
                return None
            start = self._snapshot_at_locked(float(start_ts))
            if start is not None and start[0] >= end[0]:
                start = None
        return delta_merged(end[1], start[1] if start else None)

    def counter_rate(self, name: str, window_s: float,
                     now: Optional[float] = None) -> Optional[float]:
        """Windowed per-second rate of a (merged, cumulative) counter;
        None with no usable window."""
        win = self.window(window_s, now)
        if not win:
            return None
        dt = float(win.get("window_s") or 0.0)
        if dt <= 0:
            return None
        return float((win.get("counters") or {}).get(name, 0)) / dt

    def gauge_series(self, name: str, window_s: Optional[float] = None,
                     now: Optional[float] = None,
                     node: Optional[str] = None) -> List[Tuple[float, float]]:
        """The retained ``(ts, value)`` trajectory of one gauge —
        fleet-summed by default, one node's with ``node=`` — deduped
        across tiers and sorted by time.  This is what the leak
        sentinels' trend detector consumes (runtime/health.py)."""
        with self._lock:
            by_ts: Dict[float, float] = {}
            for points in self._points:
                for t, snap in points:
                    if node is None:
                        g = snap.get("gauges") or {}
                    else:
                        g = ((snap.get("per_node") or {}).get(node)
                             or {}).get("gauges") or {}
                    if name in g:
                        by_ts[t] = float(g[name])
            series = sorted(by_ts.items())
        if window_s is not None and series:
            t_now = float(now if now is not None else series[-1][0])
            series = [p for p in series if p[0] >= t_now - float(window_s)]
        return series

    def gauge_names(self) -> List[str]:
        """Every gauge name seen in any retained snapshot."""
        with self._lock:
            names = set()
            for points in self._points:
                for _, snap in points:
                    names.update((snap.get("gauges") or {}).keys())
        return sorted(names)


def replay_spool(path: str) -> Iterator[Tuple[float, dict]]:
    """Yield ``(ts, merged)`` from a (possibly rotated) spool, oldest
    first — the post-mortem entry point: ``store = TimeSeriesStore();
    for ts, m in replay_spool(p): store.append(m, ts)`` rebuilds the
    windowed-query surface from disk."""
    for obj in iter_rotated_jsonl(path):
        if isinstance(obj, dict) and "merged" in obj:
            try:
                yield float(obj.get("ts", 0.0)), obj["merged"]
            except (TypeError, ValueError):
                continue
