"""Bucket-wise merging of per-node metric snapshots into cluster views.

Every node's ``Stats`` RPC ships ``runtime/metrics.py`` snapshots whose
histograms are LOG-BUCKETED with a single global geometry (4 buckets
per octave — the bucket bounds are value-derived, not configured), so
two nodes' histograms for the same series are defined over the same
bucket grid and merge exactly: summing the per-bucket counts of N nodes
yields the histogram a single node observing the union stream would
have built.  Cluster percentiles computed over the merged buckets
therefore carry the SAME error bound as node-local ones — the estimate
errs high by at most one bucket width (~19%) — which is what lets
``bench.py --load-slo`` cross-check a merged p95 against a single-node
oracle within one bucket (tests/test_obs.py pins the merge against a
combined-stream oracle exactly).

Counters and gauges sum; ``min``/``max`` combine; per-node and
per-hash-model breakdowns ride alongside the merged series so a
cluster-wide regression can be attributed without a second sweep.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..runtime.metrics import _LOG_GROWTH

#: one log bucket's width, as a ratio: bounds grow by 2^(1/4)
#: (runtime/metrics.py _BUCKETS_PER_OCTAVE) — "within one bucket" means
#: within this factor.
BUCKET_RATIO = 2.0 ** 0.25

#: histogram families whose suffix is a hash-model name
#: (``worker.solve_s.<model>`` — nodes/worker.py): the per-model
#: breakdown the per-model SLO objectives read.
PER_MODEL_HISTOGRAM_PREFIX = "worker.solve_s."


def _snap_bound(bound: float) -> float:
    """Snapshot bucket bounds are rounded to 9 decimals
    (``Histogram.to_dict``); snap them back onto the exact log grid so
    merged percentile estimates are bit-identical to what the owning
    registry itself would report (tests/test_obs.py pins merge ==
    combined-stream oracle exactly)."""
    if bound <= 0.0:
        return bound
    return math.exp(round(math.log(bound) / _LOG_GROWTH) * _LOG_GROWTH)


def merged_percentile(buckets: List[Tuple[float, int]], count: int,
                      mn: Optional[float], mx: Optional[float],
                      q: float) -> Optional[float]:
    """Estimated q-quantile over a merged ``[[upper_bound, count], ...]``
    bucket list — the same estimator ``runtime/metrics.py``
    ``Histogram.percentile`` applies to a single node's buckets (each
    estimate is its bucket's upper bound, clamped to the observed
    extremes; a leading 0.0 bucket counts non-positive samples)."""
    if count <= 0:
        return None
    rank = q * count
    cum = 0
    last_bound: Optional[float] = None
    for bound, n in sorted(buckets):
        cum += n
        last_bound = bound
        if cum >= rank:
            if bound == 0.0:
                return 0.0
            est = _snap_bound(bound)
            return min(max(est, mn if mn is not None else est),
                       mx if mx is not None else est)
    # fewer bucketed samples than rank (possible after a clamped delta
    # across a counter reset): fall back like the single-node estimator
    return mx if mx is not None else last_bound


def _hist_stats(buckets: List[Tuple[float, int]], count: int, total: float,
                mn: Optional[float], mx: Optional[float]) -> dict:
    """Assemble the ``Histogram.to_dict`` shape from merged pieces."""
    return {
        "count": count,
        "sum": round(total, 9),
        "min": mn,
        "max": mx,
        "p50": merged_percentile(buckets, count, mn, mx, 0.50),
        "p95": merged_percentile(buckets, count, mn, mx, 0.95),
        "p99": merged_percentile(buckets, count, mn, mx, 0.99),
        "buckets": [[b, c] for b, c in sorted(buckets)],
    }


def merge_histograms(hists: Iterable[dict]) -> dict:
    """Merge ``Histogram.to_dict`` snapshots bucket-wise.

    The inputs share one global bucket geometry, so buckets merge by
    exact upper-bound identity; count/sum add, min/max combine, and the
    percentile estimates are recomputed over the merged buckets.
    Exemplars (docs/FORENSICS.md) merge bucket-wise too: each merged
    bucket keeps the FRESHEST ``(trace_id, value, ts)`` any node
    retained — "which request last landed here" is a cluster-wide
    question with a single answer per bucket."""
    buckets: Dict[float, int] = {}
    exemplars: Dict[float, list] = {}
    count = 0
    total = 0.0
    mn: Optional[float] = None
    mx: Optional[float] = None
    for h in hists:
        if not h:
            continue
        count += int(h.get("count", 0))
        total += float(h.get("sum", 0.0))
        for bound, n in h.get("buckets", []):
            buckets[float(bound)] = buckets.get(float(bound), 0) + int(n)
        for bound, tid, v, ts in h.get("exemplars", []):
            cur = exemplars.get(float(bound))
            if cur is None or float(ts) > float(cur[3]):
                exemplars[float(bound)] = [float(bound), tid, v, ts]
        for v, pick in ((h.get("min"), min), (h.get("max"), max)):
            if v is None:
                continue
            if pick is min:
                mn = v if mn is None else min(mn, v)
            else:
                mx = v if mx is None else max(mx, v)
    out = _hist_stats(sorted(buckets.items()), count, total, mn, mx)
    if exemplars:
        out["exemplars"] = [exemplars[b] for b in sorted(exemplars)]
    return out


def delta_histogram(new: Optional[dict], old: Optional[dict]) -> dict:
    """The histogram of samples observed BETWEEN two cumulative
    snapshots of one series — the windowed view the SLO engine's
    fast/slow burn-rate evaluation runs on (docs/SLO.md).

    Bucket counts subtract (clamped at zero: a node restart resets its
    registry, and a negative bucket would poison the percentile walk);
    ``min``/``max`` are not recoverable from cumulative snapshots, so
    the delta keeps the NEW snapshot's extremes — percentile clamping
    stays conservative.  Exemplars keep the NEW snapshot's view too:
    "last request observed in this bucket" is already a point-in-time
    fact, not a cumulative one."""
    if not new:
        return _hist_stats([], 0, 0.0, None, None)
    if not old:
        return dict(new)
    ob = {float(b): int(n) for b, n in old.get("buckets", [])}
    buckets: Dict[float, int] = {}
    for bound, n in new.get("buckets", []):
        d = int(n) - ob.get(float(bound), 0)
        if d > 0:
            buckets[float(bound)] = d
    count = max(0, int(new.get("count", 0)) - int(old.get("count", 0)))
    total = max(0.0, float(new.get("sum", 0.0)) - float(old.get("sum", 0.0)))
    out = _hist_stats(sorted(buckets.items()), count, total,
                      new.get("min"), new.get("max"))
    if new.get("exemplars"):
        out["exemplars"] = [list(e) for e in new["exemplars"]]
    return out


def merge_snapshots(node_snaps: Dict[str, dict],
                    stale: Optional[Dict[str, dict]] = None) -> dict:
    """Merge per-node ``Stats`` snapshots into one cluster snapshot.

    ``node_snaps`` maps node name -> its snapshot (the dict the node's
    Stats RPC returned); ``stale`` maps node name -> status metadata for
    nodes whose snapshot is a LAST-SEEN copy rather than fresh (the
    scraper's shared-deadline contract: a frozen node is reported, not
    waited for).  Returns::

        {"ts", "counters", "gauges", "histograms",   # cluster-merged
         "per_node":  {name: {"role", "status", "age_s", ...}},
         "per_model": {model: {"solve_s": merged-histogram}},
         "stale_nodes": [names]}

    Counters sum (each node's registry counts disjoint local events);
    gauges sum too — the cluster's queue depth / active slots is the
    fleet total, and per-node values stay readable in ``per_node``.
    """
    stale = stale or {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hist_by_name: Dict[str, List[dict]] = {}
    per_node: Dict[str, dict] = {}
    for name, snap in node_snaps.items():
        snap = snap or {}
        meta = dict(stale.get(name) or {"status": "ok", "age_s": 0.0})
        meta.setdefault("status", "ok")
        meta["role"] = snap.get("role", meta.get("role", "unknown"))
        meta["uptime_secs"] = snap.get("uptime_secs")
        meta["counters"] = dict(snap.get("counters") or {})
        meta["gauges"] = dict(snap.get("gauges") or {})
        per_node[name] = meta
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (snap.get("gauges") or {}).items():
            gauges[k] = gauges.get(k, 0) + v
        for k, h in (snap.get("histograms") or {}).items():
            hist_by_name.setdefault(k, []).append(h)
    histograms = {k: merge_histograms(hs) for k, hs in hist_by_name.items()}
    per_model: Dict[str, dict] = {}
    for k, h in histograms.items():
        if k.startswith(PER_MODEL_HISTOGRAM_PREFIX):
            model = k[len(PER_MODEL_HISTOGRAM_PREFIX):]
            if model:
                per_model[model] = {"solve_s": h}
    return {
        "ts": round(time.time(), 6),
        "nodes": len(node_snaps),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "per_node": per_node,
        "per_model": per_model,
        "stale_nodes": sorted(n for n, m in stale.items()
                              if m.get("status") == "stale"),
    }


def delta_merged(new: dict, old: Optional[dict]) -> dict:
    """Windowed cluster snapshot: counter deltas (clamped at zero) and
    bucket-wise histogram deltas between two merged snapshots.  Gauges
    are point-in-time and keep the new values."""
    if not old:
        return new
    counters = {
        k: max(0, v - (old.get("counters") or {}).get(k, 0))
        for k, v in (new.get("counters") or {}).items()
    }
    histograms = {
        k: delta_histogram(h, (old.get("histograms") or {}).get(k))
        for k, h in (new.get("histograms") or {}).items()
    }
    out = dict(new)
    out["counters"] = counters
    out["histograms"] = histograms
    out["window_s"] = round(
        float(new.get("ts", 0.0)) - float(old.get("ts", 0.0)), 6)
    return out
