"""Declarative SLO engine over merged cluster snapshots (docs/SLO.md).

Objectives live in a checked-in JSON config (config/slo.json) and are
evaluated against the cluster snapshots the fleet scraper merges
(:mod:`.scrape` / :mod:`.merge`).  Three things distinguish this from a
shell script grepping ``--prom`` output:

* **typed verdicts** — every objective yields an
  :class:`ObjectiveVerdict` (pass / warn / breach / no_data with the
  observed value, threshold, and window evidence) rolled into one
  :class:`SLOVerdict` whose ``exit_code()`` is the CI contract: breach
  is nonzero, everything else is 0;
* **burn-rate windows** — objectives are judged over a FAST and a SLOW
  window (bucket-wise histogram deltas / counter deltas between merged
  snapshots): breach requires both windows over threshold (a sustained
  burn), fast-only is a warn (a spike), slow-only is a recovering warn.
  With too little history — the one-shot CI evaluation — both windows
  degrade to all-time cumulative, so a single sweep can still gate;
* **unknown-metric rejection** — every series named in the config is
  validated against the declared registries in ``runtime/metrics.py``
  (``KNOWN_HISTOGRAMS``/``KNOWN_COUNTERS`` + prefixes) at LOAD time.
  A typo'd objective is a config error (exit 2), never a silently
  green gate.

On breach the engine records one ``slo.breach`` flight-recorder event
per breached objective and dumps the whole ring — metrics snapshot,
verdict, (when a telemetry journal is configured) the ``trace_profile``
critical-path breakdown of the slowest recent rounds, and the top-k
slowest request timelines as full span trees (runtime/spans.py,
docs/FORENSICS.md) — so the evidence for *why* the objective burned is
captured by construction (the PR 3 dump-on-fault discipline).

Per-model objectives (``"per_model": true``) expand over the
``worker.solve_s.<model>`` histogram family (nodes/worker.py), because
per-hash performance spread is exactly why one global serving target
would be meaningless (HashCore; BENCH_r05's 30-60x serving gaps).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..runtime.metrics import (
    KNOWN_COUNTER_PREFIXES,
    KNOWN_COUNTERS,
    KNOWN_HISTOGRAM_PREFIXES,
    KNOWN_HISTOGRAMS,
)
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from .merge import PER_MODEL_HISTOGRAM_PREFIX
from .timeseries import Tier, TimeSeriesStore

_STATS = ("p50", "p95", "p99", "mean")
_STATUS_RANK = {"pass": 0, "no_data": 0, "warn": 1, "breach": 2}

DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 300.0


class SLOConfigError(ValueError):
    """Malformed or unknown-metric SLO config — the gate must fail
    loudly at load time, not evaluate green against a series that can
    never exist."""


def _known_histogram(name: str) -> bool:
    return name in KNOWN_HISTOGRAMS or any(
        name.startswith(p) and len(name) > len(p)
        for p in KNOWN_HISTOGRAM_PREFIXES
    )


def _known_counter(name: str) -> bool:
    return name in KNOWN_COUNTERS or any(
        name.startswith(p) and len(name) > len(p)
        for p in KNOWN_COUNTER_PREFIXES
    )


@dataclass(frozen=True)
class Objective:
    """One declared objective (see docs/SLO.md for the JSON schema)."""

    name: str
    max: float
    histogram: Optional[str] = None
    stat: str = "p95"
    ratio: Optional[Tuple[str, str]] = None  # (numerator, denominator)
    per_model: bool = False
    models: Dict[str, float] = field(default_factory=dict)
    description: str = ""


@dataclass(frozen=True)
class SLOConfig:
    objectives: Tuple[Objective, ...]
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    source: str = "<dict>"


def load_slo_config(src) -> SLOConfig:
    """Parse and VALIDATE an SLO config (path or already-loaded dict).

    Raises :class:`SLOConfigError` on any malformed objective or any
    metric name the registry declarations don't know."""
    source = "<dict>"
    if isinstance(src, (str, os.PathLike)):
        source = str(src)
        try:
            with open(src) as fh:
                src = json.load(fh)
        except (OSError, ValueError) as exc:
            raise SLOConfigError(f"unreadable SLO config {source}: {exc}")
    if not isinstance(src, dict):
        raise SLOConfigError(f"SLO config must be a JSON object, "
                             f"got {type(src).__name__}")
    windows = src.get("windows") or {}
    fast = float(windows.get("fast_s", DEFAULT_FAST_WINDOW_S))
    slow = float(windows.get("slow_s", DEFAULT_SLOW_WINDOW_S))
    if not (0 < fast <= slow):
        raise SLOConfigError(
            f"windows must satisfy 0 < fast_s <= slow_s "
            f"(got fast_s={fast}, slow_s={slow})")
    raw = src.get("objectives")
    if not isinstance(raw, list) or not raw:
        raise SLOConfigError("SLO config needs a non-empty 'objectives' list")
    objectives: List[Objective] = []
    seen = set()
    for i, o in enumerate(raw):
        where = f"objective[{i}]"
        if not isinstance(o, dict):
            raise SLOConfigError(f"{where} must be an object")
        name = o.get("name")
        if not name or not isinstance(name, str):
            raise SLOConfigError(f"{where} needs a string 'name'")
        where = f"objective {name!r}"
        if name in seen:
            raise SLOConfigError(f"duplicate objective name {name!r}")
        seen.add(name)
        try:
            mx = float(o["max"])
        except (KeyError, TypeError, ValueError):
            raise SLOConfigError(f"{where} needs a numeric 'max' threshold")
        if mx <= 0:
            raise SLOConfigError(f"{where}: 'max' must be positive")
        hist = o.get("histogram")
        ratio = o.get("ratio")
        if (hist is None) == (ratio is None):
            raise SLOConfigError(
                f"{where} needs exactly one of 'histogram' or 'ratio'")
        stat = o.get("stat", "p95")
        per_model = bool(o.get("per_model", False))
        models = {str(k): float(v) for k, v in (o.get("models") or {}).items()}
        if hist is not None:
            if stat not in _STATS:
                raise SLOConfigError(
                    f"{where}: unknown stat {stat!r} (one of {_STATS})")
            if not _known_histogram(hist):
                raise SLOConfigError(
                    f"{where}: unknown histogram {hist!r} — not declared in "
                    f"runtime/metrics.py KNOWN_HISTOGRAMS/_PREFIXES")
            if per_model:
                base = PER_MODEL_HISTOGRAM_PREFIX.rstrip(".")
                if hist != base:
                    raise SLOConfigError(
                        f"{where}: per_model applies to the {base!r} family "
                        f"only (got {hist!r})")
                for m in models:
                    if not _known_histogram(f"{hist}.{m}"):
                        raise SLOConfigError(
                            f"{where}: per-model series {hist}.{m!r} matches "
                            f"no declared histogram family")
            elif models:
                raise SLOConfigError(
                    f"{where}: 'models' requires 'per_model': true")
            obj = Objective(name=name, max=mx, histogram=hist, stat=stat,
                            per_model=per_model, models=models,
                            description=str(o.get("description", "")))
        else:
            if not (isinstance(ratio, dict)
                    and isinstance(ratio.get("num"), str)
                    and isinstance(ratio.get("den"), str)):
                raise SLOConfigError(
                    f"{where}: 'ratio' must be "
                    f'{{"num": counter, "den": counter}}')
            for part in (ratio["num"], ratio["den"]):
                if not _known_counter(part):
                    raise SLOConfigError(
                        f"{where}: unknown counter {part!r} — not declared "
                        f"in runtime/metrics.py KNOWN_COUNTERS/_PREFIXES")
            if per_model or models:
                raise SLOConfigError(f"{where}: per_model is histogram-only")
            obj = Objective(name=name, max=mx,
                            ratio=(ratio["num"], ratio["den"]),
                            description=str(o.get("description", "")))
        objectives.append(obj)
    return SLOConfig(objectives=tuple(objectives), fast_window_s=fast,
                     slow_window_s=slow, source=source)


@dataclass
class ObjectiveVerdict:
    name: str
    status: str  # pass | warn | breach | no_data
    value: Optional[float]  # fast-window observation
    threshold: float
    slow_value: Optional[float] = None
    series: str = ""
    model: Optional[str] = None
    fast_window_s: float = 0.0
    slow_window_s: float = 0.0
    burn: Optional[float] = None  # value / threshold
    detail: str = ""

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if v not in (None, "")}
        d["status"] = self.status
        return d


@dataclass
class SLOVerdict:
    status: str
    objectives: List[ObjectiveVerdict]
    ts: float
    stale_nodes: List[str] = field(default_factory=list)
    dump_path: Optional[str] = None

    def exit_code(self) -> int:
        """The CI contract: 0 unless some objective breached."""
        return 1 if self.status == "breach" else 0

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "ts": self.ts,
            "stale_nodes": list(self.stale_nodes),
            "dump_path": self.dump_path,
            "objectives": [o.to_dict() for o in self.objectives],
        }

    def render(self) -> str:
        """Human one-screen verdict."""
        out = [f"SLO verdict: {self.status.upper()}"
               + (f"  (stale: {', '.join(self.stale_nodes)})"
                  if self.stale_nodes else "")]
        for o in self.objectives:
            tag = o.name if o.model is None else f"{o.name}[{o.model}]"
            val = "-" if o.value is None else f"{o.value:.4g}"
            burn = "" if o.burn is None else f"  burn={o.burn:.2f}x"
            extra = f"  ({o.detail})" if o.detail else ""
            out.append(f"  {o.status.upper():7s} {tag:32s} "
                       f"{val} vs max {o.threshold:.4g}{burn}{extra}")
        return "\n".join(out)


def _hist_stat(h: Optional[dict], stat: str) -> Optional[float]:
    if not h or not h.get("count"):
        return None
    if stat == "mean":
        return float(h.get("sum", 0.0)) / max(1, int(h["count"]))
    return h.get(stat)


class SLOEngine:
    """Evaluate a :class:`SLOConfig` over retained merged snapshots.

    Feed every sweep through :meth:`observe` (or pass it straight to
    :meth:`evaluate`); history lives in a
    :class:`~distpow_tpu.obs.timeseries.TimeSeriesStore` (pass your own
    ``store`` to share retention with a soak harness — the engine's
    burn windows and the soak verdict's phase windows then read the
    SAME points) and the fast/slow windows are the store's windowed
    delta queries.  ``ts`` parameters exist for deterministic tests —
    production callers omit them."""

    def __init__(self, config: SLOConfig, history: int = 512,
                 journal_path: Optional[str] = None,
                 span_addrs: Optional[List[str]] = None,
                 store: Optional[TimeSeriesStore] = None):
        self.config = config
        # a private store sized to the burn windows when none is shared:
        # full resolution across the slow window (plus slack), coarse
        # beyond — `history` survives as the finest tier's point cap
        # proxy via retention, so existing constructors keep working
        self.store = store if store is not None else TimeSeriesStore(
            tiers=(
                Tier(0.0, max(2 * config.slow_window_s, 600.0)),
                Tier(10.0, 3600.0),
            ))
        self._journal_path = journal_path
        # where to fetch slow-request span trees from when THIS process
        # has no local ring evidence (the cli/slo.py gate judging a
        # separate-process cluster): the scraped fleet's addresses, for
        # a Node.Spans sweep on breach (docs/FORENSICS.md)
        self._span_addrs = list(span_addrs or [])

    # -- history ------------------------------------------------------------
    def observe(self, merged: dict, ts: Optional[float] = None) -> None:
        self.store.append(merged, ts if ts is not None else time.time())

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, merged: Optional[dict] = None,
                 ts: Optional[float] = None,
                 breach_hooks: bool = True) -> SLOVerdict:
        """Judge every objective against the latest merged snapshot.

        ``merged`` (if given) is observed first.  ``breach_hooks=False``
        suppresses the flight-recorder side effects (the load harness's
        mid-run peeks must not dump on a transient warm-up spike)."""
        if merged is not None:
            self.observe(merged, ts)
        latest = self.store.latest()
        if latest is None:
            raise ValueError("no merged snapshot to evaluate")
        metrics.inc("slo.evaluations")
        now = latest[0]
        fast = self.store.window(self.config.fast_window_s, now) or latest[1]
        slow = self.store.window(self.config.slow_window_s, now) or latest[1]
        verdict = self._judge_windows(fast, slow, now,
                                      latest[1].get("stale_nodes") or [])
        if verdict.status == "breach" and breach_hooks:
            self._on_breach(verdict)
        return verdict

    def judge_range(self, start_ts: float, end_ts: float) -> SLOVerdict:
        """Judge every objective over ONE historical window — the delta
        between the retained snapshots at ``end_ts`` and ``start_ts``
        (both resolved by the store's snapshot_at contract).  Fast and
        slow collapse to the same window: a phase is judged as a whole,
        not as a burn rate.  No breach side effects — the soak verdict
        (load/soak.py) aggregates these per shape phase and carries its
        own evidence hooks."""
        win = self.store.range_window(start_ts, end_ts)
        if win is None:
            raise ValueError("no retained snapshot inside the range")
        return self._judge_windows(win, win, end_ts,
                                   win.get("stale_nodes") or [])

    def _judge_windows(self, fast: dict, slow: dict, now: float,
                       stale_nodes) -> SLOVerdict:
        verdicts: List[ObjectiveVerdict] = []
        for obj in self.config.objectives:
            verdicts.extend(self._judge(obj, fast, slow))
        status = max((v.status for v in verdicts),
                     key=lambda s: _STATUS_RANK[s], default="pass")
        return SLOVerdict(status=status, objectives=verdicts, ts=now,
                          stale_nodes=list(stale_nodes))

    def _judge(self, obj: Objective, fast: dict,
               slow: dict) -> List[ObjectiveVerdict]:
        if obj.ratio is not None:
            return [self._judge_ratio(obj, fast, slow)]
        if not obj.per_model:
            return [self._judge_hist(obj, fast, slow, obj.histogram or "",
                                     obj.max, None)]
        out = []
        prefix = PER_MODEL_HISTOGRAM_PREFIX
        seen = {
            name[len(prefix):]
            for name in (fast.get("histograms") or {})
            if name.startswith(prefix)
        }
        for model in sorted(seen | set(obj.models)):
            out.append(self._judge_hist(
                obj, fast, slow, f"{obj.histogram}.{model}",
                obj.models.get(model, obj.max), model,
            ))
        if not out:
            out.append(ObjectiveVerdict(
                name=obj.name, status="no_data", value=None,
                threshold=obj.max, series=f"{obj.histogram}.*",
                detail="no per-model series observed yet",
            ))
        return out

    def _verdict(self, obj: Objective, series: str, threshold: float,
                 v_fast: Optional[float], v_slow: Optional[float],
                 model: Optional[str], fast: dict, slow: dict,
                 detail: str = "") -> ObjectiveVerdict:
        if v_fast is None and v_slow is None:
            status = "no_data"
        else:
            over_fast = v_fast is not None and v_fast > threshold
            over_slow = v_slow is not None and v_slow > threshold
            if over_fast and over_slow:
                status = "breach"
            elif over_fast:
                status, detail = "warn", detail or "fast-window spike"
            elif over_slow:
                status, detail = "warn", detail or "recovering (slow window)"
            else:
                status = "pass"
        ref = v_fast if v_fast is not None else v_slow
        return ObjectiveVerdict(
            name=obj.name, status=status, value=v_fast, slow_value=v_slow,
            threshold=threshold, series=series, model=model,
            fast_window_s=float(fast.get("window_s") or 0.0),
            slow_window_s=float(slow.get("window_s") or 0.0),
            burn=None if ref is None else round(ref / threshold, 4),
            detail=detail,
        )

    def _judge_hist(self, obj: Objective, fast: dict, slow: dict,
                    series: str, threshold: float,
                    model: Optional[str]) -> ObjectiveVerdict:
        v_fast = _hist_stat((fast.get("histograms") or {}).get(series),
                            obj.stat)
        v_slow = _hist_stat((slow.get("histograms") or {}).get(series),
                            obj.stat)
        return self._verdict(obj, f"{series}:{obj.stat}", threshold,
                             v_fast, v_slow, model, fast, slow)

    def _judge_ratio(self, obj: Objective, fast: dict,
                     slow: dict) -> ObjectiveVerdict:
        num, den = obj.ratio  # type: ignore[misc]

        def rate(win: dict) -> Optional[float]:
            c = win.get("counters") or {}
            d = float(c.get(den, 0))
            return None if d <= 0 else float(c.get(num, 0)) / d
        return self._verdict(obj, f"{num}/{den}", obj.max,
                             rate(fast), rate(slow), None, fast, slow)

    # -- breach side effects ------------------------------------------------
    def _on_breach(self, verdict: SLOVerdict) -> None:
        """Flight-recorder evidence (module docstring): one event per
        breached objective, then one dump carrying the verdict plus the
        trace_profile critical-path breakdown when a telemetry journal
        exists.  Dumping is best-effort — with no dump directory
        configured the events still land in the in-memory ring."""
        metrics.inc("slo.breaches")
        for o in verdict.objectives:
            if o.status != "breach":
                continue
            RECORDER.record(
                "slo.breach", objective=o.name, series=o.series,
                model=o.model, value=o.value, slow_value=o.slow_value,
                threshold=o.threshold, burn=o.burn,
                fast_window_s=o.fast_window_s, slow_window_s=o.slow_window_s,
            )
        extra = {"verdict": verdict.to_dict()}
        profile = self._critical_path()
        if profile is not None:
            extra["critical_path"] = profile
        # the forensics upgrade (ISSUE 14, docs/FORENSICS.md): the dump
        # attaches the top-k slowest REQUEST timelines — full span
        # trees, not just round milestones — so "which request burned
        # the objective, and where inside it" is in the evidence file
        # by construction.  In-process harnesses read the shared local
        # ring; the production gate process (cli/slo.py observing a
        # separate-process cluster) has an EMPTY local ring and sweeps
        # the scraped fleet's Node.Spans instead — best-effort, like
        # every other evidence hook (a breach verdict must never crash
        # on its own evidence collection).
        slow = SPANS.slowest_traces(5)
        if not slow and self._span_addrs:
            try:
                from .forensics import slowest_request_timelines

                slow = slowest_request_timelines(self._span_addrs, k=5)
            except Exception:
                slow = []
        if slow:
            extra["slow_requests"] = slow
        verdict.dump_path = RECORDER.dump("slo-breach", extra=extra)

    def _critical_path(self, top_n: int = 5) -> Optional[list]:
        """Per-request queue->fanout->first-result->cancel breakdown of
        the slowest recent Mines, from the flight-recorder journal via
        scripts/trace_profile.py (best-effort: None when no journal is
        configured or the profiler is unavailable)."""
        path = self._journal_path or getattr(RECORDER, "_journal_path", None)
        if not path:
            return None
        try:
            RECORDER.flush_journal()  # the breach-window events must be in
            profiler = _load_trace_profiler()
            if profiler is None or not os.path.exists(path):
                return None
            reqs = profiler.profile_journal(path)
            # slowest rounds first: cancel-complete spans the whole
            # round when present, first-result otherwise
            reqs.sort(key=lambda r: -(r.get("cancel_propagation_s")
                                      or r.get("first_result_s") or 0.0))
            return reqs[:top_n]
        except Exception:
            # evidence collection must never turn a breach verdict into
            # a crash — the verdict (and the ring events) already stand
            return None


def _load_trace_profiler():
    """scripts/trace_profile.py as a module (scripts/ is not a package;
    outside a repo checkout this degrades to None and the dump simply
    omits the critical-path section)."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "trace_profile.py")
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location(
        "_distpow_trace_profile", path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
