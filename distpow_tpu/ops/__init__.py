from .difficulty import meets_difficulty, nibble_masks
from .packing import TailSpec, build_tail_spec, make_words
from .search_step import SENTINEL, build_search_step, cached_search_step

__all__ = [
    "meets_difficulty", "nibble_masks", "TailSpec", "build_tail_spec",
    "make_words", "SENTINEL", "build_search_step", "cached_search_step",
]
