"""Pallas TPU kernel for the fused MD5 proof-of-work search step.

The hot op of the framework (SURVEY.md section 7 layer 4, the "north
star"): one kernel launch evaluates a dense tile grid of candidates —
index -> message words -> 64 MD5 rounds -> trailing-nibble mask -> argmin
— entirely in VMEM/registers.  Only scalars enter the kernel (the chunk
base, the nonce's packed constant words, the absorbed init state, the
difficulty masks, and the partition descriptor — all in SMEM) and one
uint32 per grid tile leaves it; candidate messages are never materialized
anywhere, not even in HBM.

Compilation is *layout-keyed*: the kernel program depends only on the
tail-byte layout (where the thread byte and chunk bytes land in the
16-word block — a function of nonce length mod 64 and chunk width) and
the batch geometry.  The nonce content, difficulty, and thread-byte
partition are runtime SMEM operands, so a worker compiles each layout
once and serves every subsequent request at any difficulty/partition with
zero recompiles (mirroring ops/search_step.py's dynamic regime).

Layout: each grid step processes a (SUBLANES, 128) tile of flat candidate
indices (uint32 native tile is (8, 128); SUBLANES is a multiple of 8).
The flat index decomposes as ``f = chunk_offset * tb_count + tb_index``
with ``tb_count`` a power of two (the partition algebra only produces
power-of-two runs, worker.go:312-316), so the decomposition is a shift
and a mask — no integer division in the kernel.

The same computation expressed in plain jnp (ops/search_step.py) leaves
fusion decisions to XLA; this kernel pins them.  Both paths share the
packing template and difficulty masks, and tests/test_pallas.py checks
them equal in interpret mode; bench.py compares them on hardware.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.md5_jax import MD5_K, MD5_S
from ..models.registry import get_hash_model
from .difficulty import nibble_masks
from .packing import build_tail_spec
from .search_step import SENTINEL

LANES = 128
DEFAULT_SUBLANES = 256  # (256, 128) tile = 32768 candidates per grid step
_I32_MISS = 0x7FFFFFFF  # in-kernel miss marker (int32 reduction domain)


def _rotl(x, s: int):
    return (x << s) | (x >> (32 - s))


def _md5_tile(words, init):
    """Unrolled 64-round MD5 on a tile; ``words[g]`` is an array or scalar."""
    a0, b0, c0, d0 = init
    a, b, c, d = a0, b0, c0, d0
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        m = words[g]
        if not hasattr(m, "dtype"):
            m = jnp.uint32(m)
        f = f + a + jnp.uint32(MD5_K[i]) + m
        a, d, c = d, c, b
        b = b + _rotl(f, MD5_S[i])
    return (a0 + a, b0 + b, c0 + c, d0 + d)


@functools.lru_cache(maxsize=None)
def _dyn_pallas_step(
    tb_word: int,
    tb_shift_in_word: int,
    chunk_word_shifts,  # tuple of (word, shift) per little-endian chunk byte
    grid: int,
    sublanes: int,
    interpret: bool,
):
    """Layout-keyed pallas program.

    Returned jitted fn: ``(chunk0, init[4], base[16], masks[4],
    part[2]=(tb_lo, log_tbc)) -> uint32`` (flat first-hit index or
    SENTINEL).
    """
    tile = sublanes * LANES

    def kernel(chunk0_ref, init_ref, base_ref, masks_ref, part_ref, out_ref):
        i = pl.program_id(0)
        chunk0 = chunk0_ref[0]
        tb_lo = part_ref[0]
        log_tbc = part_ref[1]
        row = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 0)
        col = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 1)
        f = (
            jnp.uint32(i) * jnp.uint32(tile)
            + row * jnp.uint32(LANES)
            + col
        )
        chunk = chunk0 + (f >> log_tbc)
        tb = tb_lo + (f & ((jnp.uint32(1) << log_tbc) - jnp.uint32(1)))

        words = [base_ref[w] for w in range(16)]
        words[tb_word] = words[tb_word] | (tb << tb_shift_in_word)
        for j, (w_i, s_i) in enumerate(chunk_word_shifts):
            byte_j = (chunk >> jnp.uint32(8 * j)) & jnp.uint32(0xFF)
            words[w_i] = words[w_i] | (byte_j << s_i)

        a, b, c, d = _md5_tile(
            words, (init_ref[0], init_ref[1], init_ref[2], init_ref[3])
        )
        acc = (
            (a & masks_ref[0]) | (b & masks_ref[1])
            | (c & masks_ref[2]) | (d & masks_ref[3])
        )
        hit = acc == jnp.uint32(0)
        # Mosaic has no unsigned-integer reductions; flat indices are far
        # below 2^31, so reduce in int32 with int32-max as the in-kernel
        # miss marker and translate back to SENTINEL outside.
        tile_min = jnp.min(
            jnp.where(hit, f.astype(jnp.int32), jnp.int32(_I32_MISS))
        )

        # TPU grid steps run sequentially on the core, so a single SMEM
        # cell accumulates the global min across the grid.
        @pl.when(i == 0)
        def _init():
            out_ref[0, 0] = tile_min

        @pl.when(i > 0)
        def _acc():
            out_ref[0, 0] = jnp.minimum(out_ref[0, 0], tile_min)

    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 5,
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def step(chunk0, init, base, masks, part):
        chunk0 = jnp.asarray(chunk0, jnp.uint32).reshape((1,))
        m = call(chunk0, init, base, masks, part)[0, 0]
        return jnp.where(
            m == jnp.int32(_I32_MISS), jnp.uint32(SENTINEL), m.astype(jnp.uint32)
        )

    return step


def build_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: int = DEFAULT_SUBLANES,
    interpret: bool = False,
) -> Callable:
    """Build ``step(chunk0) -> uint32`` backed by the Pallas kernel.

    Same contract as ``ops.search_step.build_search_step``.  Requires
    ``tb_count`` to be a power of two and the MD5 model with a single-block
    tail (the overwhelmingly common configuration); callers fall back to
    the XLA path otherwise.
    """
    model = get_hash_model(model_name)
    if model.name != "md5":
        raise ValueError("pallas kernel currently implements the md5 model")
    if tb_count & (tb_count - 1):
        raise ValueError("pallas kernel requires power-of-two tb_count")

    spec = build_tail_spec(bytes(nonce), width, model, extra_const_chunk)
    if spec.n_blocks != 1:
        raise ValueError("pallas kernel requires a single-block tail")
    masks = nibble_masks(difficulty, model)

    batch = chunks_per_step * tb_count
    tile = sublanes * LANES
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    grid = batch // tile

    _, tb_w, tb_s = spec.tb_loc
    chunk_ws = tuple((w, s) for _, w, s in spec.chunk_locs)
    dyn = _dyn_pallas_step(tb_w, tb_s, chunk_ws, grid, sublanes, interpret)

    init = jnp.asarray(spec.init_state, jnp.uint32)
    base = jnp.asarray(spec.base_words[0], jnp.uint32)
    masks_arr = jnp.asarray(masks, jnp.uint32)
    part = jnp.asarray([tb_lo, tb_count.bit_length() - 1], jnp.uint32)

    def step(chunk0):
        return dyn(chunk0, init, base, masks_arr, part)

    return step


@functools.lru_cache(maxsize=512)
def cached_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: int = DEFAULT_SUBLANES,
    interpret: bool = False,
):
    return build_pallas_search_step(
        nonce, width, difficulty, tb_lo, tb_count, chunks_per_step,
        model_name, extra_const_chunk, sublanes, interpret,
    )
