"""Pallas TPU kernels for the fused proof-of-work search step — every
registry hash model has a tile (``_TILE_FNS``): MD5, SHA-256, SHA-1,
RIPEMD-160, SHA-512, SHA-384, SHA3-256, BLAKE2b-256.

The hot op of the framework (SURVEY.md section 7 layer 4, the "north
star"): one kernel launch evaluates a dense tile grid of candidates —
index -> message words -> 64 MD5 rounds -> trailing-nibble mask -> argmin
— entirely in VMEM/registers.  Only scalars enter the kernel (the chunk
base, the nonce's packed constant words, the absorbed init state, the
difficulty masks, and the partition descriptor — all in SMEM) and one
uint32 per grid tile leaves it; candidate messages are never materialized
anywhere, not even in HBM.

Compilation is *layout-keyed*: the kernel program depends only on the
tail-byte layout (where the thread byte and chunk bytes land in the
16-word block — a function of nonce length mod 64 and chunk width) and
the batch geometry.  The nonce content, difficulty, and thread-byte
partition are runtime SMEM operands, so a worker compiles each layout
once and serves every subsequent request at any difficulty/partition with
zero recompiles (mirroring ops/search_step.py's dynamic regime).

Layout: each grid step processes a (SUBLANES, 128) tile of flat candidate
indices (uint32 native tile is (8, 128); SUBLANES is a multiple of 8).
The flat index decomposes as ``f = chunk_offset * tb_count + tb_index``
with ``tb_count`` a power of two (the partition algebra only produces
power-of-two runs, worker.go:312-316), so the decomposition is a shift
and a mask — no integer division in the kernel.

The same computation expressed in plain jnp (ops/search_step.py) leaves
fusion decisions to XLA; this kernel pins them.  Both paths share the
packing template and difficulty masks, and tests/test_pallas.py checks
them equal in interpret mode; bench.py compares them on hardware.

SHA-256 shares the whole scaffold (grid, SMEM operands, index
decomposition, min accumulation) with a different tile function and tile
geometry.  Unlike MD5, where the kernel only matched XLA, SHA-256 is
where explicit geometry should PAY: the unrolled XLA step compiles to
one loop fusion but runs at ~77% of the measured VPU roofline
(BENCH round 3) — consistent with register spills from the ~24-value
live set (16-word schedule window + 8 working vars).  And it does pay:
the round-3 hardware sweep measured the kernel at 1.3x the XLA serving
step, ~99% of the measured roofline, at sublanes=16 (see
MODEL_GEOMETRY; the one-vreg-per-live-value sublanes=8 guess lost to
per-tile fixed cost).  The tile
function uses the functional A/E form (a_r/e_r sequences instead of the
8-var shuffle), which makes the difficulty-bucket dead-code elimination
exact: digest word j reads A[63-j] (j<4) or E[67-j] (j>=4), so for the
dominant mask_words=1 bucket the A-chain stops at round 56, the E-chain
at 60, and schedule words 61-63 are never formed.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.md5_jax import MD5_K, MD5_S
from ..models.registry import get_hash_model
from ..models.ripemd160_py import _f as rmd_f
from ..models.ripemd160_py import _KL as RMD_KL
from ..models.ripemd160_py import _KR as RMD_KR
from ..models.ripemd160_py import _RL as RMD_RL
from ..models.ripemd160_py import _RR as RMD_RR
from ..models.ripemd160_py import _SL as RMD_SL
from ..models.ripemd160_py import _SR as RMD_SR
from ..models.sha1_jax import SHA1_K
from ..models.sha256_jax import SHA256_INIT, SHA256_K
from .difficulty import nibble_masks
from .packing import build_tail_spec
from .search_step import SENTINEL, _check_launch, mask_words_for

LANES = 128
# Per-model (sublanes, inner) tile geometry — see module docstring.
# MD5: (64, 128) tile x 512 inner fori_loop iterations per grid step:
# the tile height bounds live registers through the unrolled round chain
# (taller tiles spill — 256 sublanes measured ~25% slower), the inner
# loop amortizes per-grid-step fixed cost (TPU v5e sweep, BENCH_r02:
# ~10.0 GH/s at (64, 512) vs 2.34 GH/s for round 1's flat (256,) grid;
# inner auto-shrinks to divide smaller launches).  SHA-256: the round-3
# hardware sweep (scripts/sweep_sha256_pallas.py, TPU v5e) measured
# Hardware-swept geometries (docs/artifacts/r4b/sweep_*.log, TPU v5e,
# 2026-07-31): sha256 (32, 256) at 2025.5 MH/s = 1.35x its XLA serving
# step (the round-3 sweep's (16, 1024) measured 1954; sublanes=32 edged
# it out on the re-sweep); sha1 (32, 2048) at 4335.1 MH/s = 2.28x XLA
# (the old by-analogy (16, 1024) entry measured 3851 — the sweep bought
# +12.5%).  At sublanes=8 the per-tile fixed cost (iota, hit
# accumulation) is amortized over half as many candidates and dominates.
# ripemd160 (32, 512) measured 2840.5 MH/s = 2.25x its XLA serving
# step (r4c sweep).  The sweep's absolute best was (24, 2048) at
# 2895.4, but sublanes=24 gives tile = 24*128 = 3072, which does not
# divide the power-of-two batches serving and the bench dispatch —
# build_pallas_search_step would reject the bench batch outright and
# serving's tile-rounding would leave a prime tile count that the
# inner-shrink loop collapses to unswept territory (review r4) — so the
# best power-of-two-compatible point ships (2% below the sweep max).
# (The r4b bench's 69 MH/s ripemd160-pallas line was transient tunnel
# degradation, not the tile: the r4c sweep re-measured the same
# (16, 1024) geometry at 2421 MH/s minutes later, and the degradation
# window also swallowed the sha512 compile right after.)
# sha512 (32, 256) measured 538.9 MH/s = 43.5x the XLA serving step's
# 12.4 MH/s (r4c sweep — the sweep max (24, 256) at 544.7 is again not
# power-of-two-compatible); the geometry surface is nearly flat
# (498-545 across the whole sweep), consistent with Mosaic keeping the
# limb live-set in VMEM at every height.  sha384 shares the tile and
# the geometry (two extra live rounds from its truncation, same
# structure).
MODEL_GEOMETRY = {"md5": (64, 512), "sha256": (32, 256),
                  "sha1": (32, 2048), "ripemd160": (32, 512),
                  "sha512": (32, 256), "sha384": (32, 256),
                  # keccak's ~100-limb live set is the largest of the
                  # tiles and prefers the SHORTEST full-vreg tile:
                  # (8, 2048) measured 560.7 MH/s, monotonically
                  # falling to 425 at sublanes=32 (r4c sweep,
                  # docs/artifacts/r4c/); BELOW a vreg's 8-sublane
                  # height the lanes go half-used — sublanes=4 measured
                  # 285, sublanes=2 144 (r4 probe)
                  "sha3_256": (8, 2048),
                  # blake2b (32, 128) measured 974.9 MH/s = 61x the XLA
                  # loop step's 16.0 (r4c sweep; the absolute best
                  # (24, 1024) at 977.4 is again not pow2-compatible).
                  # Unlike keccak it prefers TALLER tiles — the v
                  # working set is half the sponge state's
                  "blake2b_256": (32, 128),
                  # composed double-sha256 (r5 ninth model): starts on
                  # sha256's swept geometry — the live set is one
                  # sha256 chain at a time (stage 2 starts after stage
                  # 1's digest collapses to 8 words), so the same
                  # height should hold; hardware sweep queued
                  # (scripts/tpu_session_r5b.sh — r5.sh was already
                  # armed when the model landed)
                  "sha256d": (32, 256)}
_I32_MISS = 0x7FFFFFFF  # in-kernel miss marker (int32 reduction domain)

# Models whose tile only serves on REAL TPU hardware: interpret mode
# (the off-TPU dev knob) would hand the fully-unrolled 64-bit limb-pair
# graph to XLA:CPU, whose compile on that shape is pathological (>5 min
# vs seconds for everything else).  build_pallas_search_step raises
# ValueError for these under interpret=True and callers fall back to
# the fused XLA step, exactly like a model with no tile at all.
INTERPRET_XLA_FALLBACK = frozenset(
    {"sha512", "sha384", "sha3_256", "blake2b_256",
     # the composed tile doubles sha256's unrolled graph — whose
     # single copy already does not terminate in XLA:CPU codegen at
     # serving height (models/sha256_jax.py platform note)
     "sha256d"})


def default_geometry(model_name: str, interpret: bool = False):
    """Resolve the (sublanes, inner) geometry for a kernel launch.

    Serving uses the model's hardware-swept MODEL_GEOMETRY entry (models
    without one get md5's; the kernel builder rejects unimplemented
    models before geometry matters).  Interpret mode — the off-TPU dev
    knob — caps sublanes at 8: kernel semantics are geometry-
    independent, and XLA:CPU's codegen on the interpreted sha256 tile
    is superlinear in tile height (the (16, 128) serving geometry
    compiles for ~20 min where (8, 128) takes ~3).  Every sublane
    resolution site (the builder, PallasBackend, the pallas-mesh step
    factory) goes through here so the cap cannot be bypassed by a
    caller resolving geometry itself.
    """
    geom = MODEL_GEOMETRY.get(model_name, MODEL_GEOMETRY["md5"])
    return (min(geom[0], 8), geom[1]) if interpret else geom


def _rotl(x, s: int):
    return (x << s) | (x >> (32 - s))


def _round_key(k: int, m):
    """``K[i] + w[i]`` as one grouped addend, shared by every tile.

    For a CONSTANT message word (python int or 0-d scalar) the round
    constant folds into it on the scalar unit — one scalar-vector add
    in the consuming expression instead of two (XLA's static regime
    gets this from compile-time constant folding; Mosaic cannot, so
    the fold happens here).  For a batch word the grouping is
    op-count-neutral (uint32 wraparound adds are associative), so the
    call sites need no branch."""
    if hasattr(m, "ndim") and m.ndim == 0 or not hasattr(m, "dtype"):
        return jnp.uint32(k) + jnp.uint32(m)
    return jnp.uint32(k) + m


def _md5_tile(words, init, mask_words: int = 4):
    """Unrolled 64-round MD5 on a tile; ``words[g]`` is an array or scalar.

    ``mask_words`` is how many TRAILING digest words the difficulty check
    reads (ops/search_step.py mask_words_for).  Trailing zero nibbles
    live in the last digest words, so for low difficulties only ``d``
    (and then ``c``, ...) matter; the rotation schedule means the last
    rounds' expensive f/rotl chains feed only the leading digest words —
    final ``b`` is produced by round 63, ``c`` by 62, ``a`` by 61 via the
    ``a,d,c = d,c,b`` shuffle — so those rounds are skipped entirely when
    their outputs are dead.  This is the same dead-code elimination XLA
    performs on the fused step (where the unused digest words are simply
    never consumed); Mosaic cannot see through the runtime mask operands,
    so the bucket is a compile key here too.
    """
    a0, b0, c0, d0 = init
    a, b, c, d = a0, b0, c0, d0
    # final digest word <- round whose new-b produces it: b <- 63,
    # c <- 62, d <- 61, a <- 60.  Keeping the last mask_words digest
    # words therefore needs rounds through 61 (mw=1), 62 (mw=2), or all
    # 64 (mw>=3, since final b is round 63's output).
    mw = max(1, min(4, mask_words))
    last_round = 64 - max(0, 3 - mw)
    for i in range(last_round):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        f = f + a + _round_key(MD5_K[i], words[g])
        a, d, c = d, c, b
        b = b + _rotl(f, MD5_S[i])
    # un-shuffle the skipped rounds: after round r the registers hold the
    # values that WOULD rotate into place; digest word j (a=0,b=1,c=2,d=3)
    # is live iff j >= 4 - mask_words
    regs = [a, b, c, d]
    for _ in range(64 - last_round):
        # each skipped round performs a,d,c = d,c,b with a new b nobody
        # alive consumes; inverse-rotate the register file instead
        regs = [regs[3], None, regs[1], regs[2]]
    out = []
    for j, (r, r0) in enumerate(zip(regs, (a0, b0, c0, d0))):
        out.append(None if j < 4 - mw else r0 + r)
    return tuple(out)


def _rotr(x, s: int):
    return (x >> s) | (x << (32 - s))


def _sha256_tile(words, init, mask_words: int = 8):
    """DCE'd SHA-256 compression on a tile; ``words[g]`` array or scalar.

    Functional A/E form: with ``A[r]``/``E[r]`` the new ``a``/``e`` after
    round ``r`` (and ``A[-1..-4] = a0..d0``, ``E[-1..-4] = e0..h0``), one
    round is

        t1   = E[r-4] + S1(E[r-1]) + Ch(E[r-1..r-3]) + (K[r] + w[r])
        E[r] = A[r-4] + t1
        A[r] = t1 + S0(A[r-1]) + Maj(A[r-1..r-3])

    and digest word j is ``init[j] + A[63-j]`` (j < 4) or
    ``init[j] + E[67-j]`` (j >= 4).  ``mask_words`` trailing digest words
    are live (ops/search_step.py mask_words_for), so the chains stop at

        maxE = 59 + min(mask_words, 4)      (t1/E needed through there)
        maxA = maxE - 4, or 59 + (mask_words - 4) when mask_words > 4

    — for the dominant difficulty <= 8-nibble bucket that skips 3 full
    rounds, 7 A-side updates, and schedule words 61-63, the same pruning
    XLA's DCE applies to the fused step (2,909 vs 3,165 cost_analysis
    ops/hash).  Returns 8 entries, ``None`` where dead.
    """
    mw = max(1, min(8, mask_words))
    maxE = 59 + min(mw, 4)
    maxA = max(maxE - 4, 59 + (mw - 4) if mw > 4 else -1)

    w = list(words)
    for i in range(16, maxE + 1):
        w15, w7, w2 = w[i - 15], w[i - 7], w[i - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        w.append(w[i - 16] + s0 + w7 + s1)

    A = {-4: init[3], -3: init[2], -2: init[1], -1: init[0]}
    E = {-4: init[7], -3: init[6], -2: init[5], -1: init[4]}
    for r in range(maxE + 1):
        e1, f1, g1, h1 = E[r - 1], E[r - 2], E[r - 3], E[r - 4]
        S1 = _rotr(e1, 6) ^ _rotr(e1, 11) ^ _rotr(e1, 25)
        ch = (e1 & f1) ^ (~e1 & g1)
        t1 = h1 + S1 + ch + _round_key(SHA256_K[r], w[r])
        E[r] = A[r - 4] + t1
        if r <= maxA:
            a1, b1, c1 = A[r - 1], A[r - 2], A[r - 3]
            S0 = _rotr(a1, 2) ^ _rotr(a1, 13) ^ _rotr(a1, 22)
            maj = (a1 & b1) ^ (a1 & c1) ^ (b1 & c1)
            A[r] = t1 + S0 + maj

    out = []
    for j in range(8):
        if j < 8 - mw:
            out.append(None)
        else:
            out.append(init[j] + (A[63 - j] if j < 4 else E[67 - j]))
    return tuple(out)


def _sha256d_tile(words, init, mask_words: int = 8):
    """Composed double-SHA-256 tile: sha256d(m) = sha256(sha256(m)).

    Stage 1 is the plain SHA-256 tile at FULL digest width (every word
    feeds stage 2, so no DCE there); stage 2 hashes the fixed-layout
    second block — digest words ‖ 0x80 marker ‖ zeros ‖ bit-length 256
    (models/sha256d_jax.py SECOND_BLOCK_TAIL_WORDS) — from the constant
    SHA-256 init, with the difficulty-bucket DCE applied to ITS trailing
    chains (mask_words_for semantics compose through unchanged).  The
    word byteorder is big-endian on both sides, so stage 1's digest
    words are stage 2's message words verbatim.
    """
    d = _sha256_tile(words, init, mask_words=8)
    # uint32-wrap the marker word: 0x80000000 as a bare python int
    # overflows int32 argument parsing in the schedule adds
    second = list(d) + [jnp.uint32(c)
                        for c in (0x80000000, 0, 0, 0, 0, 0, 0, 256)]
    init2 = tuple(jnp.uint32(c) for c in SHA256_INIT)
    return _sha256_tile(second, init2, mask_words=mask_words)


def _sha1_tile(words, init, mask_words: int = 5):
    """DCE'd SHA-1 compression on a tile; ``words[g]`` array or scalar.

    Functional single-chain form: with ``X[r]`` the new ``a`` after
    round ``r``, the other four working registers are just delayed,
    rotated copies of the chain — the round inputs are

        a = X[r-1],  b = X[r-2],  c = rotl(X[r-3], 30),
        d = rotl(X[r-4], 30),  e = rotl(X[r-5], 30)

    (with the raw init words standing in at the seam, rounds 0-4), so
    one round computes only

        X[r] = rotl(a, 5) + f(b, c, d) + e + (K[r//20] + w[r])

    and digest word j is ``init[j] + X[79-j]`` for j < 2 or
    ``init[j] + rotl(X[79-j], 30)`` for j >= 2.  ``mask_words``
    trailing digest words are live (ops/search_step.py mask_words_for),
    so the chain stops at round ``74 + mask_words`` — the dominant
    difficulty <= 8-nibble bucket (mw=1) skips 4 rounds and schedule
    words 76-79.  Returns 5 entries, ``None`` where dead.
    """
    mw = max(1, min(5, mask_words))
    last = 74 + mw  # highest X index needed

    w = list(words)
    for i in range(16, last + 1):
        w.append(_rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1))

    a0, b0, c0, d0, e0 = init
    # seam: rounds 0-4 draw some inputs from the raw init words, which
    # are NOT rotl-related to the chain.  Unrolling the first rounds by
    # hand shows c/d/e all follow the same rule: raw X[idx] for
    # idx <= -3 (c0/d0/e0 are already in final orientation), rotl for
    # idx >= -2 (a0/b0 enter the c/d/e positions via the b->c rotation).
    X = {-1: a0, -2: b0, -3: c0, -4: d0, -5: e0}

    def rot_in(idx):
        return X[idx] if idx <= -3 else _rotl(X[idx], 30)

    for r in range(last + 1):
        a = X[r - 1]
        b = X[r - 2]
        c = rot_in(r - 3)
        d = rot_in(r - 4)
        e = rot_in(r - 5)
        if r < 20:
            f = (b & c) | (~b & d)
        elif r < 40:
            f = b ^ c ^ d
        elif r < 60:
            f = (b & c) | (b & d) | (c & d)
        else:
            f = b ^ c ^ d
        X[r] = _rotl(a, 5) + f + e + _round_key(SHA1_K[r // 20], w[r])

    out = []
    for j in range(5):
        if j < 5 - mw:
            out.append(None)
        else:
            x = X[79 - j]
            out.append(init[j] + (x if j < 2 else _rotl(x, 30)))
    return tuple(out)


def _ripemd160_tile(words, init, mask_words: int = 5):
    """DCE'd RIPEMD-160 compression on a tile (round 4, fourth model).

    Two independent 80-round lines over the same 16 message words, each
    in the SHA-1 functional single-chain form: with ``X[r]`` the value
    written to ``b`` in round ``r`` of a line, the round inputs are

        b = X[r-1],  c = X[r-2],  d = rotl(X[r-3], 10),
        e = rotl(X[r-4], 10),  a = rotl(X[r-5], 10)

    so one round is ``X[r] = rotl(a + f + (K[r//16] + w[R[r]]), S[r])
    + e``.  Seam rule (unrolling rounds 0-4 against the register form):
    chain indices <= -3 enter RAW (d0/e0/a0 are already in final
    orientation), indices >= -2 rotate — identical in shape to the
    SHA-1 tile's seam, with rotl 10 instead of 30.  The two lines are
    explicit ILP: Mosaic can interleave them with no dependence, which
    the single-chain MD5/SHA tiles cannot offer.

    Final combine (spec): digest word j draws on late chain values of
    BOTH lines (e.g. word 3 needs XR[79]), so mask-word DCE saves at
    most one trailing round per line — computed per line from the live
    words rather than assumed.  Returns 5 entries, ``None`` where dead.
    """
    mw = max(1, min(5, mask_words))
    # per digest word j: (left chain index, right chain index) consumed
    need = ((78, 77), (77, 76), (76, 75), (75, 79), (79, 78))
    live = range(5 - mw, 5)
    last_l = max(need[j][0] for j in live)
    last_r = max(need[j][1] for j in live)

    a0, b0, c0, d0, e0 = init

    def line(K, R, S, reverse_f: bool, last: int):
        X = {-1: b0, -2: c0, -3: d0, -4: e0, -5: a0}

        def rot_in(idx):
            return X[idx] if idx <= -3 else _rotl(X[idx], 10)

        for r in range(last + 1):
            b = X[r - 1]
            c = X[r - 2]
            d = rot_in(r - 3)
            e = rot_in(r - 4)
            a = rot_in(r - 5)
            fj = rmd_f(79 - r if reverse_f else r, b, c, d)
            X[r] = _rotl(a + fj + _round_key(K[r // 16], words[R[r]]),
                         S[r]) + e
        return X

    XL = line(RMD_KL, RMD_RL, RMD_SL, False, last_l)
    XR = line(RMD_KR, RMD_RR, RMD_SR, True, last_r)

    # combine: final registers are bl=XL[79], cl=XL[78],
    # dl=rotl(XL[77],10), el=rotl(XL[76],10), al=rotl(XL[75],10) (and
    # the same pattern on the right); h' per the spec's cross-line sum
    h0, h1, h2, h3, h4 = (jnp.uint32(s) for s in init)
    combine = (
        lambda: h1 + XL[78] + _rotl(XR[77], 10),
        lambda: h2 + _rotl(XL[77], 10) + _rotl(XR[76], 10),
        lambda: h3 + _rotl(XL[76], 10) + _rotl(XR[75], 10),
        lambda: h4 + _rotl(XL[75], 10) + XR[79],
        lambda: h0 + XL[79] + XR[78],
    )
    return tuple(combine[j]() if j >= 5 - mw else None for j in range(5))


def _sha512_tile_impl(words, init, mask_words: int, digest_words32: int):
    """DCE'd SHA-512/384 compression on a tile, in uint32 limb pairs.

    The same functional A/E form as ``_sha256_tile`` stretched to 80
    rounds, with every 64-bit quantity carried as a (hi, lo) pair of
    uint32 values (TPU VPUs have no uint64 lanes) using the limb algebra
    from ``models/sha512_jax.py`` — one round is

        t1   = E[r-4] + S1(E[r-1]) + Ch(E[r-1..r-3]) + (K[r] + w[r])
        E[r] = A[r-4] + t1
        A[r] = t1 + S0(A[r-1]) + Maj(A[r-1..r-3])

    with 64-bit digest word j = ``init64[j] + A[79-j]`` (j < 4) or
    ``init64[j] + E[83-j]`` (j >= 4), serialized hi-limb-first into the
    uint32 digest vector.  ``mask_words`` counts trailing *uint32*
    digest words (the shared mask_words_for bucket): the dominant
    difficulty <= 8-nibble bucket keeps only the LOW limb of the last
    64-bit word, so the chains stop at E[76] — three full rounds, every
    A-side update past 72, and schedule words 77-79 are skipped.

    ``words`` is ``2 * words_per_block`` uint32 entries (big-endian
    64-bit message words, hi limb first — exactly the packing
    template's serialization); ``init`` is 16 uint32 entries (8 pairs);
    ``digest_words32`` is 16 (sha512) or 12 (sha384: same state, first
    6 of 8 64-bit words emitted).  Returns ``digest_words32`` entries,
    ``None`` where dead.
    """
    from ..models.sha512_jax import (
        _add64, _add64_many, _k_pair, _rotr64, _shr64, _xor64,
    )

    mw = max(1, min(digest_words32, mask_words))
    n64 = digest_words32 // 2
    first_live = digest_words32 - mw  # first live uint32 digest index
    live64 = [j for j in range(n64) if 2 * j + 1 >= first_live]
    needA = [79 - j for j in live64 if j < 4]
    needE = [83 - j for j in live64 if j >= 4]
    R = max(needE + needA)  # mw >= 1 keeps the last 64-bit word live
    maxA = max(needA + [R - 4])  # E[r] consumes A[r-4]

    W = [(words[2 * i], words[2 * i + 1]) for i in range(16)]
    for i in range(16, R + 1):
        w15, w2 = W[i - 15], W[i - 2]
        s0 = _xor64(_rotr64(w15, 1), _rotr64(w15, 8), _shr64(w15, 7))
        s1 = _xor64(_rotr64(w2, 19), _rotr64(w2, 61), _shr64(w2, 6))
        W.append(_add64_many(W[i - 16], s0, W[i - 7], s1))

    ip = [(init[2 * j], init[2 * j + 1]) for j in range(8)]
    A = {-4: ip[3], -3: ip[2], -2: ip[1], -1: ip[0]}
    E = {-4: ip[7], -3: ip[6], -2: ip[5], -1: ip[4]}
    for r in range(R + 1):
        e1, f1, g1, h1 = E[r - 1], E[r - 2], E[r - 3], E[r - 4]
        S1 = _xor64(_rotr64(e1, 14), _rotr64(e1, 18), _rotr64(e1, 41))
        ch = ((e1[0] & f1[0]) ^ (~e1[0] & g1[0]),
              (e1[1] & f1[1]) ^ (~e1[1] & g1[1]))
        t1 = _add64_many(h1, S1, ch, _k_pair(r), W[r])
        E[r] = _add64(A[r - 4], t1)
        if r <= maxA:
            a1, b1, c1 = A[r - 1], A[r - 2], A[r - 3]
            S0 = _xor64(_rotr64(a1, 28), _rotr64(a1, 34), _rotr64(a1, 39))
            maj = ((a1[0] & b1[0]) ^ (a1[0] & c1[0]) ^ (b1[0] & c1[0]),
                   (a1[1] & b1[1]) ^ (a1[1] & c1[1]) ^ (b1[1] & c1[1]))
            A[r] = _add64(t1, _add64(S0, maj))

    out = [None] * digest_words32
    for j in live64:
        hi, lo = _add64(ip[j], A[79 - j] if j < 4 else E[83 - j])
        out[2 * j], out[2 * j + 1] = hi, lo
    return tuple(out)


def _sha3_tile(words, init, mask_words: int = 8):
    """SHA3-256 sponge absorb on a tile: XOR + unrolled Keccak-f[1600].

    Limb-pair form like the sha512 tile but in little-endian (lo, hi)
    order (models/sha3_py.py).  ``words`` is 34 uint32 entries (one
    136-byte rate block), ``init`` 50 (the sponge state after host
    absorption — all zeros for short nonces).  Keccak admits no
    chain-truncation DCE — theta mixes every lane into every other
    each round — so the only mask-word savings is the FINAL round's
    chi/iota, computed just for the lanes the live digest words read
    (digest = lanes 0-3 of the final state; the dominant <=8-nibble
    bucket needs only lane 3, skipping 24 of 25 final chi lanes).
    Returns 8 entries, ``None`` where dead.
    """
    # the (lo, hi) pair rotation is shared with the fori_loop compress
    # (keccak's little-endian lane convention — the OPPOSITE limb order
    # from the sha512 tile's big-endian (hi, lo) pairs)
    from ..models.sha3_jax import _rotl64 as _rotl64_lohi
    from ..models.sha3_py import KECCAK_RC, KECCAK_ROT

    mw = max(1, min(8, mask_words))
    # digest uint32 word w = limb w%2 of lane w//2; live words w >= 8-mw
    need_lanes = sorted({w // 2 for w in range(8 - mw, 8)})

    A = []
    for i in range(25):
        lo, hi = init[2 * i], init[2 * i + 1]
        if 2 * i < 34:
            lo = lo ^ words[2 * i]
        if 2 * i + 1 < 34:
            hi = hi ^ words[2 * i + 1]
        A.append((lo, hi))

    for r in range(24):
        C = [
            (
                A[x][0] ^ A[x + 5][0] ^ A[x + 10][0] ^ A[x + 15][0]
                ^ A[x + 20][0],
                A[x][1] ^ A[x + 5][1] ^ A[x + 10][1] ^ A[x + 15][1]
                ^ A[x + 20][1],
            )
            for x in range(5)
        ]
        D = []
        for x in range(5):
            rl = _rotl64_lohi(C[(x + 1) % 5], 1)
            D.append((C[(x + 4) % 5][0] ^ rl[0], C[(x + 4) % 5][1] ^ rl[1]))
        A = [(A[i][0] ^ D[i % 5][0], A[i][1] ^ D[i % 5][1])
             for i in range(25)]
        B = [None] * 25
        for x in range(5):
            for y in range(5):
                B[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64_lohi(
                    A[x + 5 * y], KECCAK_ROT[x][y]
                )
        lanes = range(25) if r < 23 else need_lanes
        A2 = [None] * 25
        for i in lanes:
            x, y = i % 5, i // 5
            b0 = B[x + 5 * y]
            b1 = B[(x + 1) % 5 + 5 * y]
            b2 = B[(x + 2) % 5 + 5 * y]
            A2[i] = (b0[0] ^ (~b1[0] & b2[0]), b0[1] ^ (~b1[1] & b2[1]))
        if A2[0] is not None:
            rc = KECCAK_RC[r]
            A2[0] = (
                A2[0][0] ^ jnp.uint32(rc & 0xFFFFFFFF),
                A2[0][1] ^ jnp.uint32((rc >> 32) & 0xFFFFFFFF),
            )
        A = A2

    out = [None] * 8
    for w in range(8 - mw, 8):
        out[w] = A[w // 2][w % 2]
    return tuple(out)


def _sha512_tile(words, init, mask_words: int = 16):
    return _sha512_tile_impl(words, init, mask_words, 16)


def _sha384_tile(words, init, mask_words: int = 12):
    # same compression and full 16-word state; digest = first 6 of the
    # 8 64-bit state words (models/sha384_jax.py)
    return _sha512_tile_impl(words, init, mask_words, 12)


def _blake2b_tile(words, init, mask_words: int = 8):
    """Unrolled BLAKE2b-256 on a tile, in (lo, hi) uint32 limb pairs.

    ``words`` is 36 entries — 32 message limbs + the 4 baked parameter
    limbs (byte counter t, finalization word f0) the packing layer
    appends per block (``HashModel.block_param_words``); ``init`` is 16
    limbs (8 lanes lo-first).  12 rounds of 8 G mixes with the static
    SIGMA schedule; the limb helpers are shared with the fori_loop
    compress (models/blake2b_jax.py).  Like keccak, every round mixes
    every lane, so the only DCE is the FINAL round's diagonal G calls
    pruned to those writing a v-lane a live digest word reads (the
    dominant ≤8-nibble bucket keeps lane 3: v[3] via G(3,4,9,14) and
    v[11] via G(1,6,11,12) — 2 of 4 diagonals skipped).  Returns 8
    entries, ``None`` where dead.
    """
    from ..models.blake2b_jax import _add64, _rotr64_lohi
    from ..models.blake2b_py import BLAKE2B_IV, BLAKE2B_SIGMA, ROUNDS

    mw = max(1, min(8, mask_words))
    need_lanes = sorted({w // 2 for w in range(8 - mw, 8)})

    v = [(init[2 * i], init[2 * i + 1]) for i in range(8)]
    m = [(words[2 * i], words[2 * i + 1]) for i in range(16)]
    for i in range(8):
        v.append((jnp.uint32(BLAKE2B_IV[i] & 0xFFFFFFFF),
                  jnp.uint32((BLAKE2B_IV[i] >> 32) & 0xFFFFFFFF)))
    v[12] = (v[12][0] ^ words[32], v[12][1] ^ words[33])
    v[14] = (v[14][0] ^ words[34], v[14][1] ^ words[35])

    def G(a, b, c, d, x, y):
        alo, ahi = v[a]
        blo, bhi = v[b]
        clo, chi = v[c]
        dlo, dhi = v[d]
        alo, ahi = _add64(*_add64(alo, ahi, blo, bhi), x[0], x[1])
        dlo, dhi = _rotr64_lohi(dlo ^ alo, dhi ^ ahi, 32)
        clo, chi = _add64(clo, chi, dlo, dhi)
        blo, bhi = _rotr64_lohi(blo ^ clo, bhi ^ chi, 24)
        alo, ahi = _add64(*_add64(alo, ahi, blo, bhi), y[0], y[1])
        dlo, dhi = _rotr64_lohi(dlo ^ alo, dhi ^ ahi, 16)
        clo, chi = _add64(clo, chi, dlo, dhi)
        blo, bhi = _rotr64_lohi(blo ^ clo, bhi ^ chi, 63)
        v[a], v[b], v[c], v[d] = (alo, ahi), (blo, bhi), (clo, chi), \
            (dlo, dhi)

    COLS = ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15))
    DIAGS = ((0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14))
    for r in range(ROUNDS):
        s = BLAKE2B_SIGMA[r]
        for gi, (a, b, c, d) in enumerate(COLS):
            G(a, b, c, d, m[s[2 * gi]], m[s[2 * gi + 1]])
        for gi, (a, b, c, d) in enumerate(DIAGS):
            if r == ROUNDS - 1:
                writes = {a, b, c, d}
                if not any(j in writes or j + 8 in writes
                           for j in need_lanes):
                    continue
            G(a, b, c, d, m[s[8 + 2 * gi]], m[s[8 + 2 * gi + 1]])

    out = [None] * 8
    for w in range(8 - mw, 8):
        j, limb = w // 2, w % 2
        out[w] = init[2 * j + limb] ^ v[j][limb] ^ v[j + 8][limb]
    return tuple(out)


# model -> (tile fn, init-state words, digest words, block words); a
# model has a kernel iff it has an entry here, and MODEL_GEOMETRY above
# is checked against this at import so the two can't drift apart.
_TILE_FNS = {"md5": (_md5_tile, 4, 4, 16), "sha256": (_sha256_tile, 8, 8, 16),
             "sha1": (_sha1_tile, 5, 5, 16),
             "ripemd160": (_ripemd160_tile, 5, 5, 16),
             "sha512": (_sha512_tile, 16, 16, 32),
             "sha384": (_sha384_tile, 16, 12, 32),
             "sha3_256": (_sha3_tile, 50, 8, 34),
             # 36 = 32 message limbs + 4 baked parameter limbs
             "blake2b_256": (_blake2b_tile, 16, 8, 36),
             "sha256d": (_sha256d_tile, 8, 8, 16)}
assert set(_TILE_FNS) == set(MODEL_GEOMETRY), \
    "every pallas kernel model needs a MODEL_GEOMETRY entry and vice versa"


@functools.lru_cache(maxsize=None)
def _dyn_pallas_step(
    tb_word: int,
    tb_shift_in_word: int,
    chunk_word_shifts,  # tuple of (word, shift) per little-endian chunk byte
    grid: int,
    sublanes: int,
    interpret: bool,
    inner: int = 1,
    mask_words: int = 4,
    model_name: str = "md5",
):
    """Layout-keyed pallas program.

    Returned jitted fn: ``(chunk0, init[S], base[16], masks[mask_words],
    part[2]=(tb_lo, log_tbc)) -> uint32`` (flat first-hit index or
    SENTINEL), where ``S`` is the model's state width (md5 4, sha256 8,
    sha1 5).

    Each grid step evaluates ``inner`` consecutive (sublanes, 128) tiles
    in an on-device ``fori_loop``.  The split matters: sublanes bounds
    the live register set of the unrolled 64-round chain (too tall
    spills to VMEM), while inner amortizes the per-grid-step fixed cost
    (index iota, bookkeeping, the cross-lane min) — see MODEL_GEOMETRY
    for the measured TPU v5e sweep.

    ``mask_words`` (the trailing-digest-word bucket of
    ops.search_step.mask_words_for) is a compile key: the final MD5
    rounds whose outputs only feed dead digest words are skipped in
    ``_md5_tile``, matching the DCE XLA applies to the fused step.
    """
    tile = sublanes * LANES
    tile_fn, state_words, digest_words, block_words = _TILE_FNS[model_name]
    mw = max(1, min(digest_words, mask_words))

    def kernel(chunk0_ref, init_ref, base_ref, masks_ref, part_ref, out_ref):
        i = pl.program_id(0)
        chunk0 = chunk0_ref[0]
        tb_lo = part_ref[0]
        log_tbc = part_ref[1]
        row = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 0)
        col = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 1)
        f0 = (
            jnp.uint32(i) * jnp.uint32(tile * inner)
            + row * jnp.uint32(LANES)
            + col
        )
        init = tuple(init_ref[j] for j in range(state_words))
        consts = [base_ref[w] for w in range(block_words)]

        def tile_candidates(f):
            """Elementwise (sublanes, LANES) array of int32 flat indices:
            the candidate's own index where it hits, _I32_MISS where not.
            Kept elementwise so the inner loop accumulates with ONE
            vector minimum per tile; the expensive cross-lane min runs
            once per grid step, not once per tile.  (Mosaic has no
            unsigned reductions; flat indices are far below 2^31, so the
            int32 domain with int32-max as miss marker is exact.)"""
            chunk = chunk0 + (f >> log_tbc)
            tb = tb_lo + (f & ((jnp.uint32(1) << log_tbc) - jnp.uint32(1)))
            words = list(consts)
            words[tb_word] = words[tb_word] | (tb << tb_shift_in_word)
            for j, (w_i, s_i) in enumerate(chunk_word_shifts):
                byte_j = (chunk >> jnp.uint32(8 * j)) & jnp.uint32(0xFF)
                words[w_i] = words[w_i] | (byte_j << s_i)

            state = tile_fn(words, init, mw)
            acc = state[digest_words - mw] & masks_ref[0]
            for j in range(1, mw):
                acc = acc | (state[digest_words - mw + j] & masks_ref[j])
            hit = acc == jnp.uint32(0)
            return jnp.where(hit, f.astype(jnp.int32), jnp.int32(_I32_MISS))

        if inner == 1:
            m = jnp.min(tile_candidates(f0))
        else:
            best = jax.lax.fori_loop(
                0,
                inner,
                lambda j, best: jnp.minimum(
                    best,
                    tile_candidates(
                        f0 + j.astype(jnp.uint32) * jnp.uint32(tile)
                    ),
                ),
                jnp.full((sublanes, LANES), _I32_MISS, jnp.int32),
            )
            m = jnp.min(best)

        # TPU grid steps run sequentially on the core, so a single SMEM
        # cell accumulates the global min across the grid.
        @pl.when(i == 0)
        def _init():
            out_ref[0, 0] = m

        @pl.when(i > 0)
        def _acc():
            out_ref[0, 0] = jnp.minimum(out_ref[0, 0], m)

    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 5,
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def step(chunk0, init, base, masks, part):
        chunk0 = jnp.asarray(chunk0, jnp.uint32).reshape((1,))
        m = call(chunk0, init, base, masks, part)[0, 0]
        return jnp.where(
            m == jnp.int32(_I32_MISS), jnp.uint32(SENTINEL), m.astype(jnp.uint32)
        )

    return step


def build_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: Optional[int] = None,
    interpret: bool = False,
    launch_steps: int = 1,
    inner: Optional[int] = None,
) -> Callable:
    """Build ``step(chunk0) -> uint32`` backed by the Pallas kernel.

    Same contract as ``ops.search_step.build_search_step``, including the
    ``launch_steps`` multiplier: one dispatch covers ``launch_steps *
    chunks_per_step * tb_count`` candidates.  Where the XLA path amortizes
    the per-dispatch round trip with an on-device ``fori_loop``, the
    kernel simply extends its sequential TPU grid — the flat index
    already spans ``program_id * tile``, so a larger grid IS the
    multi-sub-batch launch, with no extra machinery.  Requires
    ``tb_count`` to be a power of two, an implemented model (one with a
    ``_TILE_FNS`` entry), and a single-block tail (the overwhelmingly
    common configuration); callers fall back to the XLA path otherwise.

    ``sublanes``/``inner`` default to the model's tuned geometry
    (``default_geometry``, which caps interpret-mode sublanes at 8 —
    see its docstring); pass explicitly to sweep.
    """
    model = get_hash_model(model_name)
    if model.name not in _TILE_FNS:
        raise ValueError(
            f"pallas kernel implements {sorted(_TILE_FNS)}, not {model.name}"
        )
    if interpret and model.name in INTERPRET_XLA_FALLBACK:
        # interpret mode runs the traced tile through XLA:CPU, whose
        # compile on the fully-unrolled 64-bit limb-pair graph is
        # pathological (the same blowup as the unrolled fused step —
        # scripts/probe_sha512_forms.py timed out >5 min on CPU where
        # the loop form takes seconds).  Off-TPU dev serving of these
        # models goes through the XLA fallback; the kernel is a
        # TPU-hardware path.  ValueError = the signal every caller
        # (PallasBackend, the mesh step factory) already maps to a
        # transparent fallback.
        raise ValueError(
            f"{model.name} pallas tile is TPU-only (interpret-mode "
            f"XLA:CPU compile of the limb-pair graph is pathological)"
        )
    geom = default_geometry(model.name, interpret)
    if sublanes is None:
        sublanes = geom[0]
    if inner is None:
        inner = geom[1]
    if tb_count & (tb_count - 1):
        raise ValueError("pallas kernel requires power-of-two tb_count")

    spec = build_tail_spec(bytes(nonce), width, model, extra_const_chunk)
    if spec.n_blocks != 1:
        raise ValueError("pallas kernel requires a single-block tail")
    masks = nibble_masks(difficulty, model)

    batch = chunks_per_step * tb_count
    tile = sublanes * LANES
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    _check_launch(batch, launch_steps)
    tiles = batch * launch_steps // tile
    # the inner fori_loop length must divide the tile count; shrink to fit
    inner = max(1, inner)
    while tiles % inner:
        inner //= 2
    grid = tiles // inner

    mw = mask_words_for(difficulty, model)
    _, tb_w, tb_s = spec.tb_loc
    chunk_ws = tuple((w, s) for _, w, s in spec.chunk_locs)
    dyn = _dyn_pallas_step(
        tb_w, tb_s, chunk_ws, grid, sublanes, interpret, inner, mw,
        model.name,
    )

    init = jnp.asarray(spec.init_state, jnp.uint32)
    base = jnp.asarray(spec.base_words[0], jnp.uint32)
    # only the significant trailing mask words enter the kernel (their
    # count is the compile key, same discipline as step_operands)
    masks_arr = jnp.asarray(masks[model.digest_words - mw:], jnp.uint32)
    part = jnp.asarray([tb_lo, tb_count.bit_length() - 1], jnp.uint32)

    def step(chunk0):
        return dyn(chunk0, init, base, masks_arr, part)

    return step


@functools.lru_cache(maxsize=512)
def cached_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: Optional[int] = None,
    interpret: bool = False,
    launch_steps: int = 1,
    inner: Optional[int] = None,
):
    return build_pallas_search_step(
        nonce, width, difficulty, tb_lo, tb_count, chunks_per_step,
        model_name, extra_const_chunk, sublanes, interpret, launch_steps,
        inner,
    )
