"""Pallas TPU kernel for the fused MD5 proof-of-work search step.

The hot op of the framework (SURVEY.md section 7 layer 4, the "north
star"): one kernel launch evaluates a dense tile grid of candidates —
index -> message words -> 64 MD5 rounds -> trailing-nibble mask -> argmin
— entirely in VMEM/registers.  Nothing but one uint32 scalar (the chunk
base) enters the kernel and one uint32 per grid tile (the tile's first-hit
flat index, or SENTINEL) leaves it; candidate messages are never
materialized anywhere, not even in HBM.

Layout: each grid step processes a (SUBLANES, 128) tile of flat candidate
indices (uint32 native tile is (8, 128); SUBLANES is a multiple of 8).
The flat index decomposes as ``f = chunk_offset * tb_count + tb_index``
with ``tb_count`` a power of two (the partition algebra only produces
power-of-two runs, worker.go:312-316), so the decomposition is a shift
and a mask — no integer division in the kernel.

The same computation expressed in plain jnp (ops/search_step.py) leaves
fusion decisions to XLA; this kernel pins them.  Both paths share the
packing template and difficulty masks, and tests/test_pallas.py checks
them equal in interpret mode; bench.py compares them on hardware.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.md5_jax import MD5_INIT, MD5_K, MD5_S
from ..models.registry import get_hash_model
from .difficulty import nibble_masks
from .packing import build_tail_spec
from .search_step import SENTINEL

LANES = 128
DEFAULT_SUBLANES = 256  # (256, 128) tile = 32768 candidates per grid step
_I32_MISS = 0x7FFFFFFF  # in-kernel miss marker (int32 reduction domain)


def _rotl(x, s: int):
    return (x << s) | (x >> (32 - s))


def _md5_tile(words):
    """Unrolled 64-round MD5 on a tile; ``words[g]`` is an array or int."""
    a = jnp.uint32(MD5_INIT[0])
    b = jnp.uint32(MD5_INIT[1])
    c = jnp.uint32(MD5_INIT[2])
    d = jnp.uint32(MD5_INIT[3])
    a0, b0, c0, d0 = a, b, c, d
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        m = words[g]
        if not hasattr(m, "dtype"):
            m = jnp.uint32(m)
        f = f + a + jnp.uint32(MD5_K[i]) + m
        a, d, c = d, c, b
        b = b + _rotl(f, MD5_S[i])
    return (a0 + a, b0 + b, c0 + c, d0 + d)


def build_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: int = DEFAULT_SUBLANES,
    interpret: bool = False,
) -> Callable:
    """Build ``step(chunk0) -> uint32`` backed by the Pallas kernel.

    Same contract as ``ops.search_step.build_search_step``.  Requires
    ``tb_count`` to be a power of two and the MD5 model with a single-block
    tail (the overwhelmingly common configuration); callers fall back to
    the XLA path otherwise.
    """
    model = get_hash_model(model_name)
    if model.name != "md5":
        raise ValueError("pallas kernel currently implements the md5 model")
    if tb_count & (tb_count - 1):
        raise ValueError("pallas kernel requires power-of-two tb_count")

    spec = build_tail_spec(bytes(nonce), width, model, extra_const_chunk)
    if spec.n_blocks != 1:
        raise ValueError("pallas kernel requires a single-block tail")
    masks = nibble_masks(difficulty, model)

    batch = chunks_per_step * tb_count
    tile = sublanes * LANES
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    grid = batch // tile
    tb_shift = tb_count.bit_length() - 1  # log2(tb_count)

    base = spec.base_words[0]
    tb_b, tb_w, tb_s = spec.tb_loc

    def kernel(chunk0_ref, out_ref):
        i = pl.program_id(0)
        chunk0 = chunk0_ref[0]
        row = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 0)
        col = jax.lax.broadcasted_iota(jnp.uint32, (sublanes, LANES), 1)
        f = (
            jnp.uint32(i) * jnp.uint32(tile)
            + row * jnp.uint32(LANES)
            + col
        )
        chunk = chunk0 + (f >> tb_shift)
        tb = jnp.uint32(tb_lo) + (f & jnp.uint32(tb_count - 1))

        words = list(base)
        words[tb_w] = jnp.uint32(words[tb_w]) | (tb << tb_s)
        for j, (_, w_i, s_i) in enumerate(spec.chunk_locs):
            byte_j = (chunk >> (8 * j)) & jnp.uint32(0xFF)
            cur = words[w_i]
            cur = jnp.uint32(cur) if not hasattr(cur, "dtype") else cur
            words[w_i] = cur | (byte_j << s_i)

        a, b, c, d = _md5_tile(words)
        acc = None
        for wd, m in zip((a, b, c, d), masks):
            if m == 0:
                continue
            term = wd & jnp.uint32(m)
            acc = term if acc is None else (acc | term)
        hit = (acc == 0) if acc is not None else jnp.ones(f.shape, bool)
        # Mosaic has no unsigned-integer reductions; flat indices are far
        # below 2^31, so reduce in int32 with int32-max as the in-kernel
        # miss marker and translate back to SENTINEL outside.
        tile_min = jnp.min(
            jnp.where(hit, f.astype(jnp.int32), jnp.int32(_I32_MISS))
        )

        # TPU grid steps run sequentially on the core, so a single SMEM
        # cell accumulates the global min across the grid.
        @pl.when(i == 0)
        def _init():
            out_ref[0, 0] = tile_min

        @pl.when(i > 0)
        def _acc():
            out_ref[0, 0] = jnp.minimum(out_ref[0, 0], tile_min)

    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def step(chunk0):
        chunk0 = jnp.asarray(chunk0, jnp.uint32).reshape((1,))
        m = call(chunk0)[0, 0]
        return jnp.where(
            m == jnp.int32(_I32_MISS), jnp.uint32(SENTINEL), m.astype(jnp.uint32)
        )

    return step


@functools.lru_cache(maxsize=64)
def cached_pallas_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str = "md5",
    extra_const_chunk: bytes = b"",
    sublanes: int = DEFAULT_SUBLANES,
    interpret: bool = False,
):
    return build_pallas_search_step(
        nonce, width, difficulty, tb_lo, tb_count, chunks_per_step,
        model_name, extra_const_chunk, sublanes, interpret,
    )
