"""The fused proof-of-work search step — the framework's hot op.

One step evaluates ``chunks_per_step × tb_count`` candidates entirely on
device: flat index -> (chunk, thread byte) -> message words -> hash state
-> difficulty mask -> argmin of hits, returning a single uint32 (the flat
index of the first hit in reference enumeration order, or SENTINEL).

This replaces the reference's per-candidate loop body (worker.go:346-356).
Reference order is preserved exactly: the flat index is chunk-major,
thread-byte-minor, matching the nested loop at worker.go:318-319 where all
thread bytes are tried for each chunk value before the chunk advances.

Two compilation regimes:

* ``build_search_step`` bakes everything but the chunk base into the
  program — maximum constant folding, one compile per (nonce, difficulty,
  partition, batch).  Used where one configuration is re-dispatched many
  times (bench, graft entry).
* ``cached_search_step`` (the serving path) binds a *layout-keyed* dynamic
  program: the nonce's packed words, the absorbed prefix state, and the
  difficulty masks are runtime operands, and the thread-byte partition is
  two runtime scalars (``tb_lo``, ``log2 tb_count``).  The compile key is
  only (model, tail layout, batch), where the tail layout depends on the
  nonce length *mod block size* and the chunk width — so a worker that has
  compiled (nonce_len=4, width=2) once serves EVERY 4-byte-nonce request at
  ANY difficulty and ANY partition with zero recompiles.  The constant
  words cost nothing extra at runtime: they are loop-invariant scalars XLA
  hoists out of the batch dimension.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.registry import HashModel, get_hash_model
from .difficulty import meets_difficulty, nibble_masks
from .packing import TailSpec, build_tail_spec, make_words

SENTINEL = 0xFFFFFFFF

# Models whose fused XLA serving step is impractical to COMPILE on the
# TPU backend (>30 min observed for sha512's limb-emulation graph in
# both compress forms, r4c hardware session — docs/KERNELS.md): bench
# and sweep harnesses skip their XLA serving measurements rather than
# gamble a tunnel window, and serving routes through the Pallas kernel
# (ops/md5_pallas.py).  Distinct from INTERPRET_XLA_FALLBACK (an
# interpret-mode/XLA:CPU property): sha3_256 is interpret-fallback but
# its fori_loop serving step compiles fine.
XLA_SERVING_COMPILE_IMPRACTICAL = frozenset({"sha512", "sha384"})


def _eval_candidates(spec: TailSpec, masks, model: HashModel, tb, chunk):
    """Hash a broadcastable batch of candidates and return the hit mask."""
    state = spec.init_state
    for b in range(spec.n_blocks):
        words = make_words(spec, tb, chunk)[b]
        state = model.compress(state, words)
    if model.finalize is not None:  # composed hashes (sha256d)
        state = model.finalize(state)
    return meets_difficulty(state, masks)


def build_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model: HashModel,
    extra_const_chunk: bytes = b"",
    jit: bool = True,
    launch_steps: int = 1,
) -> Callable:
    """Build ``step(chunk0: uint32) -> uint32`` for one chunk width.

    The thread bytes scanned are ``tb_lo .. tb_lo + tb_count - 1`` (the
    partition algebra always yields contiguous runs; parallel/partition.py).

    One dispatch evaluates ``launch_steps`` consecutive sub-batches of
    ``chunks_per_step × tb_count`` candidates inside a ``fori_loop`` —
    only one sub-batch is ever materialized, so huge launches amortize the
    per-dispatch host<->device round trip without huge buffers.
    """
    spec = build_tail_spec(nonce, width, model, extra_const_chunk)
    masks = nibble_masks(difficulty, model)
    batch = chunks_per_step * tb_count
    _check_launch(batch, launch_steps)

    def sub(chunk0, f):
        chunk = jnp.uint32(chunk0) + f // jnp.uint32(tb_count)
        tb = jnp.uint32(tb_lo) + f % jnp.uint32(tb_count)
        hit = _eval_candidates(spec, masks, model, tb, chunk)
        return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

    def step(chunk0):
        f0 = jnp.arange(batch, dtype=jnp.uint32)
        if launch_steps == 1:
            return sub(chunk0, f0)

        def body(i, best):
            f = i.astype(jnp.uint32) * jnp.uint32(batch) + f0
            return jnp.minimum(best, sub(chunk0, f))

        return jax.lax.fori_loop(0, launch_steps, body, jnp.uint32(SENTINEL))

    return jax.jit(step) if jit else step


def _check_launch(batch: int, launch_steps: int) -> None:
    if launch_steps < 1:
        raise ValueError(f"launch_steps must be >= 1, got {launch_steps}")
    # strictly below 2^31: at exactly 2^31 the last flat index equals
    # the Pallas kernel's int32 miss marker (0x7FFFFFFF), making a hit
    # at that index indistinguishable from a miss
    if batch * launch_steps >= 1 << 31:
        raise ValueError(
            f"launch covers {batch * launch_steps} candidates; flat "
            f"indices require < 2^31 per dispatch"
        )


def eval_dyn_candidates(model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk):
    """Hash a batch against runtime-operand nonce words.

    The dynamic-regime twin of ``_eval_candidates``: the tail layout
    (``n_blocks``, ``tb_loc``, ``chunk_locs``) is static, while the
    absorbed prefix state ``init[S]`` and constant words
    ``base[n_blocks,16]`` are device operands.  Shared by the
    single-device and mesh dynamic steps.  Returns the state tuple.
    """
    state = tuple(init[i] for i in range(len(model.init_state)))
    for b in range(n_blocks):
        # row length = words_per_block + model.param_words (blake2's
        # baked per-block parameters ride at the end; packing.py)
        words = [base[b, w] for w in range(base.shape[1])]
        bb, w, s = tb_loc
        if bb == b:
            words[w] = words[w] | (tb << s)
        for j, (cb, cw, cs) in enumerate(chunk_locs):
            if cb == b:
                byte_j = (chunk >> jnp.uint32(8 * j)) & jnp.uint32(0xFF)
                words[cw] = words[cw] | (byte_j << cs)
        state = model.compress(state, words)
    if model.finalize is not None:  # composed hashes (sha256d)
        state = model.finalize(state)
    return state


def mask_words_for(difficulty: int, model) -> int:
    """Digest words the trailing-nibble masks can touch (from the end).

    Trailing nibbles live in the LAST digest words (8 nibbles per uint32
    word; ops/difficulty.py), so a difficulty <= 8 needs exactly one
    significant mask word.  Making this count a COMPILE key (not the
    difficulty itself) lets XLA dead-code-eliminate the rounds and final
    adds that only feed unused digest words, while any difficulty within
    the same bucket still shares one program.
    """
    return max(1, min(model.digest_words, -(-difficulty // 8)))


def fold_dyn_masks(model, state, masks, mask_words: Optional[int] = None):
    """Hit mask against runtime-operand difficulty masks.

    ``masks`` holds the ``mask_words`` significant masks for the LAST
    digest words (``step_operands`` slices them); None = all words.
    """
    d = model.digest_words
    k = d if mask_words is None else mask_words
    acc = state[d - k] & masks[0]
    for i in range(1, k):
        acc = acc | (state[d - k + i] & masks[i])
    return acc == 0


@functools.lru_cache(maxsize=None)
def _dyn_search_step(
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch: int,
    static_tbc,  # None => power-of-two partition passed as log2 operand
    launch_steps: int = 1,
    mask_words: int = 0,  # 0 => all digest words significant
):
    """Layout-keyed jitted step with nonce/difficulty/partition as operands.

    Signature of the returned jitted fn (all uint32):
    ``(init_state[S], base_words[n_blocks,W], masks[D], tb_lo,
    log_tbc_or_nothing, chunk0) -> uint32``.

    ``launch_steps`` sub-batches of ``batch`` candidates run inside one
    dispatch via ``fori_loop`` (see ``build_search_step``); the returned
    index spans the full ``launch_steps * batch`` range.
    """
    model = get_hash_model(model_name)
    _check_launch(batch, launch_steps)
    mw = mask_words or model.digest_words

    if static_tbc is None:

        def sub(tb_lo, log_tbc, init, base, masks, chunk0, f):
            chunk = jnp.uint32(chunk0) + (f >> log_tbc)
            tb = tb_lo + (f & ((jnp.uint32(1) << log_tbc) - jnp.uint32(1)))
            state = eval_dyn_candidates(
                model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk
            )
            hit = fold_dyn_masks(model, state, masks, mw)
            return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

        def step(init, base, masks, tb_lo, log_tbc, chunk0):
            f0 = jnp.arange(batch, dtype=jnp.uint32)
            if launch_steps == 1:
                return sub(tb_lo, log_tbc, init, base, masks, chunk0, f0)

            def body(i, best):
                f = i.astype(jnp.uint32) * jnp.uint32(batch) + f0
                return jnp.minimum(
                    best, sub(tb_lo, log_tbc, init, base, masks, chunk0, f)
                )

            return jax.lax.fori_loop(
                0, launch_steps, body, jnp.uint32(SENTINEL)
            )

    else:

        def sub(tb_lo, init, base, masks, chunk0, f):
            chunk = jnp.uint32(chunk0) + f // jnp.uint32(static_tbc)
            tb = tb_lo + f % jnp.uint32(static_tbc)
            state = eval_dyn_candidates(
                model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk
            )
            hit = fold_dyn_masks(model, state, masks, mw)
            return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

        def step(init, base, masks, tb_lo, chunk0):
            f0 = jnp.arange(batch, dtype=jnp.uint32)
            if launch_steps == 1:
                return sub(tb_lo, init, base, masks, chunk0, f0)

            def body(i, best):
                f = i.astype(jnp.uint32) * jnp.uint32(batch) + f0
                return jnp.minimum(best, sub(tb_lo, init, base, masks, chunk0, f))

            return jax.lax.fori_loop(
                0, launch_steps, body, jnp.uint32(SENTINEL)
            )

    return jax.jit(step)


@functools.lru_cache(maxsize=None)
def _dyn_search_step_w0(model_name: str, n_blocks: int, tb_loc, chunk_locs,
                        mask_words: int = 0):
    """Width-0 probe program: scan ALL 256 thread bytes, mask the ones
    outside the runtime partition.

    Width 0 has exactly ``tb_count`` candidates, so keying the batch on
    the partition would recompile per worker split — defeating warmup.
    Instead one fixed-shape (256) program serves every partition: the
    partition is the runtime pair (tb_lo, tbc) and out-of-partition hits
    are masked off.  Returns the partition-local flat index (tb - tb_lo)
    of the first hit, or SENTINEL — identical contract to the general
    step at width 0.
    """
    model = get_hash_model(model_name)
    mw = mask_words or model.digest_words

    def step(init, base, masks, tb_lo, tbc, chunk0):
        del chunk0  # width 0: no chunk bytes
        tb = jnp.arange(256, dtype=jnp.uint32)
        state = eval_dyn_candidates(
            model, n_blocks, tb_loc, chunk_locs, init, base, tb,
            jnp.uint32(0),
        )
        hit = fold_dyn_masks(model, state, masks, mw)
        hit = hit & (tb >= tb_lo) & (tb < tb_lo + tbc)
        return jnp.min(jnp.where(hit, tb - tb_lo, jnp.uint32(SENTINEL)))

    return jax.jit(step)


def step_operands(spec: TailSpec, difficulty: int, model: HashModel):
    """Device operands binding one (nonce, difficulty) onto a dyn step.

    The masks operand carries only the ``mask_words_for(difficulty)``
    significant trailing words — its LENGTH is part of the jit compile
    key, matching the ``mask_words`` the program was built with."""
    masks = nibble_masks(difficulty, model)
    mw = mask_words_for(difficulty, model)
    return (
        jnp.asarray(spec.init_state, jnp.uint32),
        jnp.asarray(spec.base_words, jnp.uint32),
        jnp.asarray(masks[model.digest_words - mw:], jnp.uint32),
    )


@functools.lru_cache(maxsize=512)
def cached_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str,
    extra_const_chunk: bytes = b"",
    launch_steps: int = 1,
):
    """Serving-path step: binds request operands onto a layout-keyed
    dynamic program (see module docstring).  Same contract as
    ``build_search_step``: one dispatch covers ``launch_steps *
    chunks_per_step * tb_count`` candidates."""
    model = get_hash_model(model_name)
    spec = build_tail_spec(bytes(nonce), width, model, extra_const_chunk)
    init, base, masks = step_operands(spec, difficulty, model)
    mw = mask_words_for(difficulty, model)
    tb_lo_op = jnp.uint32(tb_lo)

    if width == 0:
        w0 = _dyn_search_step_w0(
            model_name, spec.n_blocks, spec.tb_loc, spec.chunk_locs, mw
        )
        tbc_op = jnp.uint32(tb_count)

        def bound0(chunk0):
            return w0(init, base, masks, tb_lo_op, tbc_op, chunk0)

        return bound0

    batch = chunks_per_step * tb_count
    pow2 = tb_count & (tb_count - 1) == 0
    dyn = _dyn_search_step(
        model_name, spec.n_blocks, spec.tb_loc, spec.chunk_locs, batch,
        None if pow2 else tb_count, launch_steps, mw,
    )
    if pow2:
        log_tbc = jnp.uint32(tb_count.bit_length() - 1)

        def bound(chunk0):
            return dyn(init, base, masks, tb_lo_op, log_tbc, chunk0)

    else:

        def bound(chunk0):
            return dyn(init, base, masks, tb_lo_op, chunk0)

    return bound


def flat_to_candidate(
    f: int, chunk0: int, tb_lo: int, tb_count: int
) -> Tuple[int, int]:
    """Host-side inverse of the step's index map: flat -> (chunk, tb)."""
    return chunk0 + f // tb_count, tb_lo + f % tb_count


@functools.lru_cache(maxsize=None)
def persistent_search_step(
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch: int,
    static_tbc,  # None => power-of-two partition passed as log2 operand
    segments: int,
    mask_words: int = 0,
):
    """Persistent-loop serving step: a multi-segment on-device search
    (docs/SERVING.md).

    Where the ``fori_loop`` steps above run every sub-batch
    unconditionally, this step carries a device-resident found
    flag/result buffer through a ``while_loop``: each iteration
    evaluates one ``batch``-candidate sub-batch, folds its first hit
    into the carried best index, and the loop EXITS as soon as the
    carry holds a hit (or the host-writable ``stop`` operand is
    nonzero).  One dispatch therefore covers up to ``segments``
    sub-batches of device work with zero host round trips between them,
    a hit surfaces without paying for the launch's remaining segments,
    and a dispatch issued after the host flips its stop flag costs one
    loop-condition check.

    Signature of the returned jitted fn (all uint32):
    ``(init[S], base[n_blocks, W], masks[D], tb_lo, log_tbc_or_nothing,
    chunk0, stop) -> uint32[2]`` — ``[0]`` is the first-hit flat index
    over the full ``segments * batch`` span (reference enumeration
    order; segments scan in order and each segment folds its own
    minimum) or SENTINEL, ``[1]`` is the number of segments actually
    executed (the driver's evaluated-work accounting, and the
    ``search.persistent_steps`` instrument).
    """
    model = get_hash_model(model_name)
    _check_launch(batch, segments)
    mw = mask_words or model.digest_words

    def sub(init, base, masks, tb_lo, log_tbc, chunk0, f):
        if static_tbc is None:
            chunk = jnp.uint32(chunk0) + (f >> log_tbc)
            tb = tb_lo + (f & ((jnp.uint32(1) << log_tbc) - jnp.uint32(1)))
        else:
            chunk = jnp.uint32(chunk0) + f // jnp.uint32(static_tbc)
            tb = tb_lo + f % jnp.uint32(static_tbc)
        state = eval_dyn_candidates(
            model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk
        )
        hit = fold_dyn_masks(model, state, masks, mw)
        return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

    def make_step(take_log_tbc: bool):
        def step(init, base, masks, tb_lo, log_tbc, chunk0, stop):
            f0 = jnp.arange(batch, dtype=jnp.uint32)

            def cond(state):
                seg, best = state
                return (
                    (seg < jnp.uint32(segments))
                    & (best == jnp.uint32(SENTINEL))
                    & (stop == jnp.uint32(0))
                )

            def body(state):
                seg, best = state
                f = seg * jnp.uint32(batch) + f0
                found = sub(init, base, masks, tb_lo, log_tbc, chunk0, f)
                return seg + jnp.uint32(1), jnp.minimum(best, found)

            seg, best = jax.lax.while_loop(
                cond, body, (jnp.uint32(0), jnp.uint32(SENTINEL))
            )
            return jnp.stack([best, seg])

        if take_log_tbc:
            return step

        def step_static(init, base, masks, tb_lo, chunk0, stop):
            return step(init, base, masks, tb_lo, jnp.uint32(0), chunk0, stop)

        return step_static

    return jax.jit(make_step(static_tbc is None))


@functools.lru_cache(maxsize=512)
def cached_persistent_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str,
    extra_const_chunk: bytes = b"",
    segments: int = 1,
):
    """Serving-path persistent step: binds request operands onto the
    layout-keyed multi-segment program, exactly as ``cached_search_step``
    binds the relaunch-loop program.  Returns ``bound(chunk0, stop) ->
    uint32[2]`` covering up to ``segments * chunks_per_step * tb_count``
    candidates per dispatch (early-exit on hit or stop).  Width 0 has a
    single 256-candidate probe and no chunk axis — the driver serves it
    through ``cached_search_step`` instead.
    """
    if width == 0:
        raise ValueError(
            "width 0 has no persistent form; use cached_search_step"
        )
    model = get_hash_model(model_name)
    spec = build_tail_spec(bytes(nonce), width, model, extra_const_chunk)
    init, base, masks = step_operands(spec, difficulty, model)
    mw = mask_words_for(difficulty, model)
    tb_lo_op = jnp.uint32(tb_lo)
    batch = chunks_per_step * tb_count
    pow2 = tb_count & (tb_count - 1) == 0
    dyn = persistent_search_step(
        model_name, spec.n_blocks, spec.tb_loc, spec.chunk_locs, batch,
        None if pow2 else tb_count, segments, mw,
    )
    if pow2:
        log_tbc = jnp.uint32(tb_count.bit_length() - 1)

        def bound(chunk0, stop):
            return dyn(init, base, masks, tb_lo_op, log_tbc, chunk0, stop)

    else:

        def bound(chunk0, stop):
            return dyn(init, base, masks, tb_lo_op, chunk0, stop)

    return bound


def _slot_lane(model: HashModel, n_blocks: int, tb_loc, chunk_locs,
               batch: int, launch_steps: int):
    """One slot's un-vmapped search lane — the shared core of the
    single-model ``slot_search_step`` and the mixed-hash
    ``mixed_slot_search_step`` (each vmaps it over its own slot axis)."""

    def one(init, base, masks, tb_lo, log_tbc, chunk0):
        def sub(f):
            chunk = jnp.uint32(chunk0) + (f >> log_tbc)
            tb = tb_lo + (f & ((jnp.uint32(1) << log_tbc) - jnp.uint32(1)))
            state = eval_dyn_candidates(
                model, n_blocks, tb_loc, chunk_locs, init, base, tb, chunk
            )
            hit = fold_dyn_masks(model, state, masks)
            return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

        f0 = jnp.arange(batch, dtype=jnp.uint32)
        if launch_steps == 1:
            return sub(f0)

        def body(i, best):
            return jnp.minimum(
                best, sub(i.astype(jnp.uint32) * jnp.uint32(batch) + f0)
            )

        return jax.lax.fori_loop(0, launch_steps, body, jnp.uint32(SENTINEL))

    return one


@functools.lru_cache(maxsize=None)
def slot_search_step(
    model_name: str,
    n_blocks: int,
    tb_loc,
    chunk_locs,
    batch: int,
    n_slots: int,
    launch_steps: int = 1,
):
    """Multi-slot serving step: ``n_slots`` independent searches in ONE
    dispatch (the continuous-batching scheduler's hot op, sched/engine.py).

    The single-slot dynamic regime (``_dyn_search_step``) already makes
    every per-request quantity a runtime operand; this step vmaps that
    lane over a leading slot axis, so one compiled program evaluates a
    whole *batch of searches* — each slot with its own nonce operands,
    its own difficulty masks, and its own partition — and returns the
    per-slot first-hit flat index (or SENTINEL) as a ``uint32[n_slots]``
    vector fetched in a single host<->device round trip.

    Signature of the returned jitted fn (all uint32):
    ``(init[n, S], base[n, n_blocks, W], masks[n, D], tb_lo[n],
    log_tbc[n], chunk0[n]) -> uint32[n]``.

    Differences from the single-slot step, both deliberate:

    * masks carry ALL digest words (``mask_words`` is not a compile
      key): per-slot difficulty is then purely an operand, so slots at
      different difficulties share one program — the whole point of
      packing them.
    * the partition rides ``log_tbc`` per slot (power-of-two partitions
      only; the scheduler falls back to solo search otherwise), so one
      lane's flat range ``[0, batch)`` spans ``batch >> log_tbc`` chunk
      values — lanes with narrower partitions simply cover more chunks
      per launch.
    """
    model = get_hash_model(model_name)
    _check_launch(batch, launch_steps)
    return jax.jit(jax.vmap(
        _slot_lane(model, n_blocks, tb_loc, chunk_locs, batch, launch_steps)
    ))


@functools.lru_cache(maxsize=None)
def mixed_slot_search_step(
    groups: tuple,
    batch: int,
    launch_steps: int = 1,
):
    """Mixed-hash multi-slot step: slots of DIFFERENT hash models share
    one device dispatch (docs/SERVING.md).

    ``groups`` is an ordered tuple of per-model sub-batch descriptors
    ``(model_name, n_blocks, tb_loc, chunk_locs, n_slots)`` — the
    compile key is therefore the full MODEL SET of the launch (plus
    each group's padded lane count), extending the single-model step's
    layout key exactly the way the scheduler's launch planner groups
    its slot table.  Inside the one compiled program each group runs
    its own vmapped lane stack (per-model compress functions cannot
    share lanes — different round structures — but they CAN share a
    launch, which is what restores batching to mixed-hash traffic that
    previously forfeited it to the solo fallback).

    The returned jitted fn takes a tuple of per-group operand tuples
    ``((init[n_i, S_i], base[n_i, b_i, W_i], masks[n_i, D_i],
    tb_lo[n_i], log_tbc[n_i], chunk0[n_i]), ...)`` and returns a tuple
    of per-group ``uint32[n_i]`` first-hit vectors, all fetched in one
    host<->device round trip.
    """
    lanes = tuple(
        _slot_lane(get_hash_model(m), nb, tl, cl, batch, launch_steps)
        for (m, nb, tl, cl, _n) in groups
    )
    _check_launch(batch, launch_steps)

    def step(group_ops):
        return tuple(
            jax.vmap(lane)(*ops) for lane, ops in zip(lanes, group_ops)
        )

    return jax.jit(step)
