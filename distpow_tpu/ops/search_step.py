"""The fused proof-of-work search step — the framework's hot op.

One step evaluates ``chunks_per_step × tb_count`` candidates entirely on
device: flat index -> (chunk, thread byte) -> message words -> hash state
-> difficulty mask -> argmin of hits, returning a single uint32 (the flat
index of the first hit in reference enumeration order, or SENTINEL).

This replaces the reference's per-candidate loop body (worker.go:346-356).
Reference order is preserved exactly: the flat index is chunk-major,
thread-byte-minor, matching the nested loop at worker.go:318-319 where all
thread bytes are tried for each chunk value before the chunk advances.

Everything except the chunk base is static, so each (nonce length, width,
difficulty, partition, batch) tuple compiles once and is re-dispatched with
a new ``chunk0`` scalar every step — no recompiles in the steady state, no
host<->device traffic beyond one scalar in and one scalar out.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from ..models.registry import HashModel, get_hash_model
from .difficulty import meets_difficulty, nibble_masks
from .packing import TailSpec, build_tail_spec, make_words

SENTINEL = 0xFFFFFFFF


def _eval_candidates(spec: TailSpec, masks, model: HashModel, tb, chunk):
    """Hash a broadcastable batch of candidates and return the hit mask."""
    state = spec.init_state
    for b in range(spec.n_blocks):
        words = make_words(spec, tb, chunk)[b]
        state = model.compress(state, words)
    return meets_difficulty(state, masks)


def build_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model: HashModel,
    extra_const_chunk: bytes = b"",
    jit: bool = True,
) -> Callable:
    """Build ``step(chunk0: uint32) -> uint32`` for one chunk width.

    The thread bytes scanned are ``tb_lo .. tb_lo + tb_count - 1`` (the
    partition algebra always yields contiguous runs; parallel/partition.py).
    """
    spec = build_tail_spec(nonce, width, model, extra_const_chunk)
    masks = nibble_masks(difficulty, model)
    batch = chunks_per_step * tb_count

    def step(chunk0):
        f = jnp.arange(batch, dtype=jnp.uint32)
        chunk = jnp.uint32(chunk0) + f // jnp.uint32(tb_count)
        tb = jnp.uint32(tb_lo) + f % jnp.uint32(tb_count)
        hit = _eval_candidates(spec, masks, model, tb, chunk)
        return jnp.min(jnp.where(hit, f, jnp.uint32(SENTINEL)))

    return jax.jit(step) if jit else step


@functools.lru_cache(maxsize=64)
def cached_search_step(
    nonce: bytes,
    width: int,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    chunks_per_step: int,
    model_name: str,
    extra_const_chunk: bytes = b"",
):
    """Memoized ``build_search_step`` keyed on every static parameter."""
    return build_search_step(
        nonce,
        width,
        difficulty,
        tb_lo,
        tb_count,
        chunks_per_step,
        get_hash_model(model_name),
        extra_const_chunk,
    )


def flat_to_candidate(
    f: int, chunk0: int, tb_lo: int, tb_count: int
) -> Tuple[int, int]:
    """Host-side inverse of the step's index map: flat -> (chunk, tb)."""
    return chunk0 + f // tb_count, tb_lo + f % tb_count
