"""Trailing-zero-nibble difficulty check as static uint32 word masks.

The reference hex-formats every digest and counts trailing ``'0'``
characters (worker.go:354-356) — a per-candidate string allocation in the
hot loop (called out in BASELINE.md as headroom).  A trailing ``'0'`` hex
character is exactly a zero nibble of the raw digest, scanned from the end:
low nibble of the last byte, high nibble of the last byte, low nibble of
the second-to-last byte, ...

For a *static* difficulty ``k`` (fixed per kernel launch) the predicate
"digest has >= k trailing zero nibbles" is therefore a constant bitmask per
digest word: OR together the masked words and compare with zero.  No
strings, no branches, pure VPU.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ..models.registry import HashModel


def nibble_masks(k: int, model: HashModel) -> Tuple[int, ...]:
    """Per-digest-word uint32 masks covering the last ``k`` nibbles.

    The digest has >= k trailing zero nibbles iff ``(word_i & mask_i) == 0``
    for every word.  ``k`` may be 0 (all masks zero => always true) up to
    ``model.max_difficulty``.
    """
    if k < 0:
        raise ValueError("difficulty must be non-negative")
    if k > model.max_difficulty:
        # A digest only has max_difficulty nibbles: such a puzzle is
        # unsatisfiable (the reference would search forever,
        # worker.go:246-256 can never reach the threshold).  Callers gate
        # on max_difficulty before building masks.
        raise ValueError(
            f"difficulty {k} exceeds {model.name}'s digest nibble count "
            f"({model.max_difficulty}); the puzzle is unsatisfiable"
        )
    masks = [0] * model.digest_words
    digest_bytes = model.digest_bytes
    for t in range(k):
        byte_idx = digest_bytes - 1 - t // 2
        nib = 0x0F if t % 2 == 0 else 0xF0
        word, j = divmod(byte_idx, 4)
        shift = 8 * j if model.word_byteorder == "little" else 8 * (3 - j)
        masks[word] |= nib << shift
    return tuple(masks)


def meets_difficulty(state: Sequence, masks: Sequence[int]):
    """Vectorized predicate: True where the digest words pass the masks."""
    acc = None
    for w, m in zip(state, masks):
        if m == 0:
            continue
        term = jnp.asarray(w, jnp.uint32) & jnp.uint32(m)
        acc = term if acc is None else (acc | term)
    if acc is None:
        ones = jnp.asarray(state[0], jnp.uint32)
        return jnp.ones(jnp.shape(ones), dtype=bool)
    return acc == 0
