"""Arithmetic candidate -> message-word packing.

The reference builds every candidate message as a byte buffer
(``nonce ‖ threadByte ‖ chunk``, worker.go:346-352) and hashes it.  On TPU
we never materialize bytes: a candidate is identified by the pair
``(thread_byte, chunk_int)`` (see ``models.puzzle`` for the chunk<->int
bijection) and the 16 uint32 message words of the hash's final block(s) are
computed *arithmetically* from those two integers plus a precomputed
constant template.

The template (``TailSpec``) is built once per (nonce, chunk width, hash
model) on the host:

* all complete 64-byte blocks of the constant nonce prefix are absorbed
  into the hash state host-side (``HashModel.py_absorb``), so arbitrarily
  long nonces cost nothing per candidate;
* the remaining tail — ``nonce_remainder ‖ thread_byte ‖ chunk ‖ 0x80
  padding ‖ bit-length`` — spans one or two blocks whose constant bytes are
  baked into ``base_words`` and whose two variable fields are described by
  (block, word, shift) byte locations.

On device, ``make_words`` turns broadcastable uint32 arrays of thread bytes
and chunk values into the per-candidate word lists consumed by
``HashModel.compress``; only the handful of words containing variable bytes
become batch-shaped arrays, the rest stay scalars that XLA constant-folds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax.numpy as jnp

from ..models.registry import HashModel

ByteLoc = Tuple[int, int, int]  # (block index, word index, bit shift)


@dataclass(frozen=True)
class TailSpec:
    """Device-side description of the final block(s) for one chunk width."""

    model_name: str
    nonce_len: int
    width: int                      # chunk byte width (0 => no chunk bytes)
    init_state: Tuple[int, ...]     # state after absorbing full nonce blocks
    n_blocks: int                   # tail blocks to compress on device (1-2)
    # [n_blocks][words_per_block + param_words] constant words (blake2's
    # baked per-block t/f parameter limbs ride at the end of each row)
    base_words: Tuple[Tuple[int, ...], ...]
    tb_loc: ByteLoc                 # where the thread byte lands
    chunk_locs: Tuple[ByteLoc, ...]  # where chunk byte j (LE) lands, j < width

    @property
    def secret_len(self) -> int:
        return 1 + self.width


def _byte_loc(pos: int, model: HashModel) -> ByteLoc:
    """Map a byte offset within the tail to (block, word, shift)."""
    block, off = divmod(pos, model.block_bytes)
    word, j = divmod(off, 4)
    shift = 8 * j if model.word_byteorder == "little" else 8 * (3 - j)
    return block, word, shift


def build_tail_spec(
    nonce: bytes, width: int, model: HashModel, extra_const_chunk: bytes = b""
) -> TailSpec:
    """Build the packing template for candidates ``nonce ‖ tb ‖ chunk``.

    ``width`` counts the chunk bytes that vary on device (<= 4, so the chunk
    fits a uint32 lane).  ``extra_const_chunk`` holds any *constant* high
    chunk bytes appended after the variable ones — the search driver uses
    this to reach chunk widths beyond 4 bytes by fixing the high bytes per
    launch segment.
    """
    if not 0 <= width <= 4:
        raise ValueError("variable chunk width must be in [0, 4]")
    nonce = bytes(nonce)
    state, rem, _ = model.py_absorb(nonce)
    msg_len = len(nonce) + 1 + width + len(extra_const_chunk)

    # Tail layout (padding="md"):
    #   rem ‖ [tb] ‖ [chunk×width] ‖ extra ‖ 0x80 ‖ 0… ‖ len64
    # (padding="sha3", the sponge's pad10*1 with the domain bits):
    #   rem ‖ [tb] ‖ [chunk×width] ‖ extra ‖ 0x06 ‖ 0… ‖ 0x80
    # where 0x06 and the final 0x80 merge to one 0x86 byte when
    # adjacent, and there is no length field.
    content = len(rem) + 1 + width + len(extra_const_chunk)
    if model.padding == "blake2":
        min_pad = 0  # zero-fill only; finality lives in the params
    elif model.padding == "sha3":
        min_pad = 1
    else:
        min_pad = 1 + model.length_bytes
    n_blocks = (content + min_pad + model.block_bytes - 1) \
        // model.block_bytes
    tail = bytearray(n_blocks * model.block_bytes)
    tail[: len(rem)] = rem
    # tb and chunk bytes stay zero in the template; recorded as locations.
    tb_pos = len(rem)
    chunk_pos0 = tb_pos + 1
    extra_pos = chunk_pos0 + width
    tail[extra_pos : extra_pos + len(extra_const_chunk)] = extra_const_chunk
    if model.padding == "blake2":
        pass  # no marker bytes; the param words carry t and f0
    elif model.padding == "sha3":
        tail[extra_pos + len(extra_const_chunk)] ^= 0x06
        tail[-1] ^= 0x80
    else:
        tail[extra_pos + len(extra_const_chunk)] = 0x80
        # the bit-length field: 8 bytes for 64-byte-block hashes, 16 for
        # SHA-384/512 (whose 2^128 length space no real message
        # exercises — the high half is always zero here, as in every
        # practical impl)
        tail[-model.length_bytes:] = (msg_len * 8).to_bytes(
            model.length_bytes, model.length_byteorder)

    fmt_order = model.word_byteorder
    absorbed = len(nonce) - len(rem)
    base_words: List[Tuple[int, ...]] = []
    for b in range(n_blocks):
        blk = tail[b * model.block_bytes : (b + 1) * model.block_bytes]
        row = tuple(
            int.from_bytes(blk[4 * w : 4 * w + 4], fmt_order)
            for w in range(model.words_per_block)
        )
        if model.block_param_words is not None:
            # per-block compression parameters (blake2's byte counter +
            # finalization flag) baked as extra constant template words
            extra = model.block_param_words(absorbed, content, b, n_blocks)
            assert len(extra) == model.param_words, (len(extra), model.name)
            row += tuple(extra)
        base_words.append(row)

    return TailSpec(
        model_name=model.name,
        nonce_len=len(nonce),
        width=width,
        init_state=tuple(state),
        n_blocks=n_blocks,
        base_words=tuple(base_words),
        tb_loc=_byte_loc(tb_pos, model),
        chunk_locs=tuple(_byte_loc(chunk_pos0 + j, model) for j in range(width)),
    )


def make_words(spec: TailSpec, tb, chunk) -> List[List]:
    """Materialize the tail block word lists for a batch of candidates.

    ``tb`` and ``chunk`` are broadcast-compatible uint32 arrays (or ints).
    Returns ``spec.n_blocks`` lists of ``len(base_words[0])`` entries
    (words_per_block, plus any baked param words), each an int (constant
    word) or an array (word containing variable bytes).
    """
    tb = jnp.asarray(tb, jnp.uint32)
    chunk = jnp.asarray(chunk, jnp.uint32)
    blocks: List[List] = [list(bw) for bw in spec.base_words]

    b, w, s = spec.tb_loc
    blocks[b][w] = jnp.uint32(blocks[b][w]) | (tb << s)
    for j, (b, w, s) in enumerate(spec.chunk_locs):
        byte_j = (chunk >> (8 * j)) & jnp.uint32(0xFF)
        cur = blocks[b][w]
        cur = jnp.uint32(cur) if not hasattr(cur, "dtype") else cur
        blocks[b][w] = cur | (byte_j << s)
    return blocks


def pack_reference_bytes(
    nonce: bytes, tb: int, chunk_int: int, width: int, extra_const_chunk: bytes = b""
) -> bytes:
    """Host-side twin of make_words for tests: the exact message bytes."""
    chunk = int(chunk_int).to_bytes(width, "little") if width else b""
    return bytes(nonce) + bytes([tb]) + chunk + extra_const_chunk
