"""Coordinator-side cluster plane: shard identity + the ``Cluster`` RPC.

One :class:`ClusterState` per pooled coordinator binds the pool's
:class:`..cluster.ring.HashRing` to this process's own member id.  The
coordinator consults it at the top of every Mine (nodes/coordinator.py):
a key the ring maps elsewhere earns a typed :class:`NotOwnerError`
redirect carrying a fresh ring snapshot — the client adopts the
snapshot and re-routes without a second discovery round trip.  A Mine
carrying ``no_redirect`` (powlib's hedged sibling retries and
failover sends) is served even when foreign: every coordinator fans
out over the SAME shared worker fleet, so correctness never depends on
ownership — only dominance-cache locality does.

The ``Cluster.Ring`` RPC (registered on both coordinator listeners)
serves the snapshot on demand; the same snapshot rides the extended
``rpc.hello`` ack (runtime/rpc.py ``hello_extra``), so a freshly dialed
client learns the ring in its very first exchange.

The service also carries the replication plane's two peer RPCs
(cluster/replication.py, docs/CLUSTER.md "Replication & HA"):
``Cluster.CacheSync`` (write-behind entry pushes and the anti-entropy
digest exchange) and ``Cluster.Handoff`` (warm shard handoff on
membership change).  Both funnel installs through the dominance order,
so a stale push can never regress an entry.  A single-coordinator
deployment registers the service with ``replicator=None`` — the two
RPCs then refuse politely and nothing about the pre-cluster wire
surface changes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..runtime.metrics import REGISTRY as metrics
from .ring import HashRing

if TYPE_CHECKING:  # import cycle: replication imports nothing from
    from .replication import CacheReplicator  # here, but keep it lazy


class NotOwnerError(Exception):
    """This coordinator does not own the request's nonce.

    Duck-typed by the RPC layer exactly like the admission plane's
    ``retry_after_s`` (runtime/rpc.py must not import cluster): the
    ``ring_wire`` attribute ships as the response frame's dedicated
    ``ring`` field, and the client surfaces the pair as a typed
    ``RPCNotOwner`` — machine-readable redirect, not a string to parse.
    """

    def __init__(self, owner: str, ring_wire: dict) -> None:
        super().__init__(
            f"NOT_OWNER: key is owned by shard {owner!r} "
            f"(ring v{ring_wire.get('version', 0)})"
        )
        self.owner = owner
        self.ring_wire = ring_wire


class ClusterState:
    """This coordinator's view of the pool: the ring + its own id."""

    __slots__ = ("ring", "self_id")

    def __init__(self, ring: HashRing, self_id: str) -> None:
        if ring.addr_of(self_id) is None:
            raise ValueError(
                f"self id {self_id!r} is not a ring member "
                f"({ring.member_ids()})"
            )
        self.ring = ring
        self.self_id = self_id

    def owns(self, nonce: bytes) -> bool:
        return self.ring.owner(nonce) == self.self_id

    def hello_extra(self) -> dict:
        """Payload merged into the ``rpc.hello`` ack (runtime/rpc.py):
        the ring reaches every dialing client in exchange zero."""
        return {"ring": self.ring.to_wire()}


class ClusterService:
    """The ``Cluster`` RPC service (``Cluster.Ring`` always;
    ``Cluster.CacheSync``/``Cluster.Handoff`` when a replicator is
    wired, i.e. only in pool mode)."""

    def __init__(self, state: ClusterState,
                 replicator: Optional["CacheReplicator"] = None) -> None:
        self._state = state
        self._replicator = replicator

    def Ring(self, params: dict) -> dict:
        metrics.inc("cluster.ring_serves")
        return {"ring": self._state.ring.to_wire(),
                "self": self._state.self_id}

    def CacheSync(self, params: dict) -> dict:
        """Replication peer traffic (cluster/replication.py).

        Two shapes share the method so the wire vocabulary stays small:
        ``{"digest": n_buckets, "self": peer}`` asks for this member's
        per-ring-range summary digests of the entries ``peer`` owns and
        the ring replicates here; ``{"entries": [...], "self": peer}``
        pushes entries, installed through the dominance order — the
        reply's ``stale`` count is the dominance order rejecting
        regressions, not an error.
        """
        repl = self._replicator
        if repl is None:
            raise ValueError("NO_REPLICATION: this coordinator has no "
                             "replication plane (single-member pool?)")
        if "digest" in params:
            return {"digest": repl.digests_for(
                str(params.get("self", "")), int(params["digest"]))}
        installed, stale = repl.install(params.get("entries"))
        return {"installed": installed, "stale": stale}

    def Handoff(self, params: dict) -> dict:
        """Warm shard handoff receiver: a member losing keys on a ring
        change pushes the remapped entries here BEFORE acking the new
        ring.  Same dominance-ordered install as CacheSync — arriving
        entries can never regress what this member already holds."""
        repl = self._replicator
        if repl is None:
            raise ValueError("NO_REPLICATION: this coordinator has no "
                             "replication plane (single-member pool?)")
        installed, stale = repl.install(params.get("entries"))
        return {"installed": installed, "stale": stale}
