"""Consistent-hash ring over the coordinator pool (docs/CLUSTER.md).

The scale-out plane partitions the Mine keyspace across N coordinators
by consistent hashing over the **nonce alone** — NEVER ``(nonce,
ntz)``.  The dominance cache's whole value is that a secret found at
``ntz=k`` serves every request at ``ntz<=k`` *for the same nonce*; a
ring keyed on the pair would scatter one nonce's difficulties across
shards and no shard's cache would ever dominate anything.  Keying on
the nonce pins every difficulty of a nonce to ONE shard by
construction, which the property tests in tests/test_cluster.py treat
as a contract, not an implementation detail.

Why a ring and not ``hash(nonce) % N``: modulo routing remaps ~every
key when membership changes (N -> N+1 moves a fraction ``N/(N+1)`` of
the keyspace), which would cold-start every shard's dominance cache on
every scale event.  Consistent hashing bounds the churn: adding one
member remaps ~``1/(N+1)`` of the keyspace — only the keys the new
member takes over — and the distpow-lint ``modulo-routing`` rule keeps
the modulo shape from creeping back in (docs/LINT.md).

Determinism: the ring is a pure function of ``(members, vnodes)`` —
``blake2b`` point placement, no process state, no randomness — so every
coordinator and every client that agrees on the member list computes
the IDENTICAL ring.  Snapshots travel on the wire (``Cluster.Ring``,
the extended ``rpc.hello`` ack, and the ``NOT_OWNER`` redirect's
``ring`` field — runtime/rpc.py) as plain dicts via
:meth:`HashRing.to_wire`/:meth:`HashRing.from_wire`.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

#: virtual nodes per member: enough that a 4-member ring's shares stay
#: within a few percent of equal, small enough that ring construction
#: is microseconds.  Part of the ring contract — every party must use
#: the same count, so it travels in the snapshot.
DEFAULT_VNODES = 64


def _point(data: bytes) -> int:
    """64-bit ring position.  blake2b, not ``hash()``: Python's hash is
    salted per process (PYTHONHASHSEED), and the ring must be identical
    across every process that builds it."""
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    )


class HashRing:
    """Immutable consistent-hash ring over ``(member_id, addr)`` pairs.

    ``version`` orders snapshots: a client holding version ``v`` adopts
    any snapshot with ``version >= v`` (the pool re-advertises the same
    ring under the same version; a future membership change bumps it).
    """

    __slots__ = ("members", "vnodes", "version", "_points", "_owners",
                 "_addrs")

    def __init__(self, members: Sequence[Tuple[str, str]],
                 vnodes: int = DEFAULT_VNODES, version: int = 0) -> None:
        if not members:
            raise ValueError("a hash ring needs at least one member")
        ids = [m for m, _ in members]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate member ids in ring: {ids}")
        self.members: Tuple[Tuple[str, str], ...] = tuple(
            (str(m), str(a)) for m, a in members
        )
        self.vnodes = int(vnodes)
        self.version = int(version)
        self._addrs: Dict[str, str] = dict(self.members)
        points: List[Tuple[int, str]] = []
        for member_id, _addr in self.members:
            for i in range(self.vnodes):
                points.append(
                    (_point(f"{member_id}#{i}".encode()), member_id)
                )
        # ties (vanishingly unlikely at 64-bit) resolve by member id so
        # every builder sorts identically
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [m for _, m in points]

    # -- routing ------------------------------------------------------------
    def key_point(self, nonce: bytes) -> int:
        """Ring position of a Mine key: the NONCE alone (module
        docstring — same-nonce requests at every difficulty must land
        on the same shard or the dominance cache stops dominating)."""
        return _point(bytes(nonce))

    def owner(self, nonce: bytes) -> str:
        """Member id owning ``nonce``: first point clockwise."""
        idx = bisect.bisect_right(self._points, self.key_point(nonce))
        if idx == len(self._points):
            idx = 0
        return self._owners[idx]

    def ordered(self, nonce: bytes) -> List[str]:
        """All member ids in clockwise walk order from the key's point
        — the owner first, then each distinct successor.  The sibling
        order hedged retries and failover use: deterministic per key,
        different keys spread their second choices across the pool."""
        idx = bisect.bisect_right(self._points, self.key_point(nonce))
        seen: List[str] = []
        n = len(self._points)
        for i in range(n):
            m = self._owners[(idx + i) % n]
            if m not in seen:
                seen.append(m)
                if len(seen) == len(self.members):
                    break
        return seen

    def addr_of(self, member_id: str) -> Optional[str]:
        return self._addrs.get(member_id)

    def member_ids(self) -> List[str]:
        return [m for m, _ in self.members]

    # -- wire ---------------------------------------------------------------
    def to_wire(self) -> dict:
        return {
            "version": self.version,
            "vnodes": self.vnodes,
            "members": [[m, a] for m, a in self.members],
        }

    @classmethod
    def from_wire(cls, data: dict) -> "HashRing":
        members = [(str(m), str(a)) for m, a in (data.get("members") or [])]
        return cls(
            members,
            vnodes=int(data.get("vnodes") or DEFAULT_VNODES),
            version=int(data.get("version") or 0),
        )

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, HashRing)
                and self.members == other.members
                and self.vnodes == other.vnodes)

    def __hash__(self):  # pragma: no cover - rings are not dict keys
        return hash((self.members, self.vnodes))

    def __repr__(self) -> str:
        return (f"HashRing(v{self.version}, {len(self.members)} members, "
                f"{self.vnodes} vnodes)")


def ring_from_peers(peers: Sequence[str], version: int = 0,
                    vnodes: int = DEFAULT_VNODES) -> HashRing:
    """The pool's canonical ring: member ids ``c0..cN-1`` in peer-list
    order.  Coordinators build it from ``CoordinatorConfig.ClusterPeers``
    and clients from ``ClientConfig.CoordAddrs`` — same list, same
    math, same ring (module docstring)."""
    return HashRing([(f"c{i}", a) for i, a in enumerate(peers)],
                    vnodes=vnodes, version=version)
