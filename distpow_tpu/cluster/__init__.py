"""Coordinator scale-out plane (docs/CLUSTER.md).

A pool of N coordinators partitions the Mine keyspace by consistent
hashing over the nonce (ring.py), advertises the ring through the
extended ``rpc.hello`` ack and the ``Cluster.Ring`` RPC (service.py),
and redirects misrouted keys with a typed ``NOT_OWNER`` reply carrying
a fresh ring snapshot.  powlib (nodes/powlib.py) is the cluster-aware
client: owner routing, hedged sibling retry on RETRY_AFTER, and
ring-guided failover when a shard dies.

replication.py makes the partition SURVIVE member death: write-behind
pushes to each key's ring successors, a slow anti-entropy digest loop
that heals missed pushes, and a warm shard handoff that moves remapped
ranges to their new owner before a ring change is acked
(docs/CLUSTER.md "Replication & HA").
"""

from .replication import Replicator, entry_wire, range_digests
from .ring import DEFAULT_VNODES, HashRing, ring_from_peers
from .service import ClusterService, ClusterState, NotOwnerError

__all__ = [
    "DEFAULT_VNODES",
    "HashRing",
    "ring_from_peers",
    "ClusterService",
    "ClusterState",
    "NotOwnerError",
    "Replicator",
    "entry_wire",
    "range_digests",
]
