"""Replicated dominance cache: write-behind pushes, anti-entropy, and
warm shard handoff (docs/CLUSTER.md "Replication & HA").

PR 10's ring gave every nonce ONE owner; this module makes the owner's
dominance-cache entries survive that owner's death.  Three cooperating
mechanisms, all riding the existing dominance order (runtime/cache.py
``add`` — install iff strictly more trailing zeros, or equal zeros and
a lexicographically greater secret), which makes every replica install
idempotent and convergent regardless of arrival order:

* **Write-behind replication** — on every accepted cache install the
  owner :meth:`Replicator.offer`\\ s the entry into a BOUNDED queue; a
  single persistent pusher thread drains it and pushes batches to the
  key's R ring successors (``CoordinatorConfig.ClusterCacheReplicas``)
  via ``Cluster.CacheSync``.  Off the Mine critical path by
  construction: a full queue drops the entry (``repl.push_failures``)
  rather than backpressure the handler, and anti-entropy heals the
  drop later.

* **Anti-entropy reconciliation** — a slow background loop exchanges
  per-ring-range summary digests (count + max-ntz + xor fingerprint
  over ``digest_buckets`` ranges of the 64-bit ring space) with each
  successor and pushes only the diverged ranges' entries, capped per
  sweep (``antientropy_max_entries``) so bandwidth stays bounded.
  This is what heals a replica that was down when the write-behind
  push happened — including a freshly restarted member that replayed
  its journal but missed traffic while dead.

* **Warm shard handoff** — on membership change the members losing
  keys compute exactly the remapped ranges (old ring's owner = self,
  new ring's owner = someone else) and push those entries to their new
  owner via ``Cluster.Handoff`` BEFORE the new ring is installed, one
  sender thread per target under one shared deadline — a frozen
  recipient costs at most ``ClusterHandoffDeadlineS``, never a wedged
  ring change (tests/test_cluster.py pins the exactly-the-remapped-
  keys property in both the N→N+1 and N+1→N directions).

A stale push (lower ntz than the replica already holds) is REJECTED by
the dominance order and counted as ``repl.stale_drops`` — evidence the
order held, never a regression.  Single-coordinator deployments never
construct a :class:`Replicator`, so every pre-cluster code path and
wire frame stays byte-identical (test-pinned).
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import RPCClient, RPCError
from ..runtime.telemetry import RECORDER

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # typing only: the replicator treats the cache as
    from ..runtime.cache import ResultCache  # an add/snapshot surface
from .ring import HashRing
from .service import ClusterState

log = logging.getLogger("distpow.replication")

#: entries per Cluster.Handoff call — small enough that one chunk's
#: send fits comfortably inside the per-call deadline slice, large
#: enough that a 10k-entry cache hands off in ~80 calls
HANDOFF_CHUNK = 128
#: entries drained per pusher wakeup — bounds one CacheSync frame
PUSH_BATCH = 64


def entry_wire(nonce: bytes, ntz: int, secret: bytes) -> dict:
    """One cache entry in CacheSync/Handoff wire form (all three keys
    are interned in the wire-v2 KEYS table)."""
    return {"nonce": bytes(nonce), "num_trailing_zeros": int(ntz),
            "secret": bytes(secret)}


def _fingerprint(nonce: bytes, ntz: int, secret: bytes) -> int:
    """64-bit per-entry fingerprint; a range's fingerprint is the XOR
    over its entries, so it is order-independent and updates cancel."""
    h = hashlib.blake2b(digest_size=8)
    h.update(bytes(nonce))
    h.update(ntz.to_bytes(4, "big"))
    h.update(bytes(secret))
    return int.from_bytes(h.digest(), "big")


def range_digests(entries: List[Tuple[bytes, int, bytes]],
                  ring: HashRing, n_buckets: int) -> List[List[int]]:
    """Per-ring-range summary digests: ``n_buckets`` triples of
    ``[count, max_ntz, xor_fingerprint]`` over the 64-bit ring space.
    Both reconciliation sides compute this over the SAME key filter, so
    equal sets digest identically and a diverged bucket names exactly
    the ranges worth re-pushing."""
    out = [[0, 0, 0] for _ in range(n_buckets)]
    for nonce, ntz, secret in entries:
        b = (ring.key_point(nonce) * n_buckets) >> 64
        d = out[b]
        d[0] += 1
        d[1] = max(d[1], int(ntz))
        d[2] ^= _fingerprint(nonce, ntz, secret)
    return out


class Replicator:
    """Per-pooled-coordinator replication engine (module docstring).

    Owns the bounded write-behind queue + single pusher thread, the
    anti-entropy loop, the replica-install path both ``Cluster`` RPCs
    funnel into, and the warm-handoff sender.  Constructed only by
    pooled coordinators (``Coordinator.set_cluster_peers``); single
    coordinators never see it.
    """

    def __init__(self, cache: "ResultCache", *, replicas: int = 1,
                 queue_depth: int = 1024,
                 antientropy_s: float = 5.0,
                 handoff_deadline_s: float = 5.0,
                 push_timeout_s: float = 5.0,
                 digest_buckets: int = 32,
                 antientropy_max_entries: int = 512) -> None:
        self._cache = cache
        self.replicas = max(0, int(replicas))
        self.antientropy_s = float(antientropy_s)
        self.handoff_deadline_s = float(handoff_deadline_s)
        self.push_timeout_s = float(push_timeout_s)
        self.digest_buckets = max(1, int(digest_buckets))
        self.antientropy_max_entries = max(1, int(antientropy_max_entries))
        # (nonce, ntz, secret, t_enqueue); BOUNDED — overflow drops,
        # the Mine path never blocks on replication (module docstring)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._state: Optional[ClusterState] = None
        self._clients: Dict[str, Tuple[str, RPCClient]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- lifecycle -----------------------------------------------------------
    def set_state(self, state: ClusterState) -> None:
        """Adopt the (new) ring + self id and lazily start the
        background threads.  Called from ``set_cluster_peers`` AFTER
        any warm handoff for the ring change has run."""
        with self._lock:
            self._state = state
        self._start_threads()

    def _start_threads(self) -> None:
        with self._lock:
            if self._started or self.replicas <= 0:
                return
            self._started = True
        pusher = threading.Thread(target=self._push_loop, daemon=True,
                                  name="repl-pusher")
        pusher.start()
        self._threads.append(pusher)
        if self.antientropy_s > 0:
            ae = threading.Thread(target=self._antientropy_loop,
                                  daemon=True, name="repl-antientropy")
            ae.start()
            self._threads.append(ae)

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for _addr, c in clients:
            c.close()

    # -- write-behind push path ----------------------------------------------
    def offer(self, nonce: bytes, ntz: int, secret: bytes) -> bool:
        """Enqueue one accepted cache install for replication; never
        blocks (the Mine critical path calls this).  False = dropped
        (queue full / replication off), counted and healed later."""
        with self._lock:
            state = self._state
        if (self.replicas <= 0 or state is None
                or len(state.ring.members) < 2):
            return False
        try:
            self._q.put_nowait((bytes(nonce), int(ntz), bytes(secret),
                                time.monotonic()))
            return True
        except queue.Full:
            metrics.inc("repl.push_failures")
            log.warning("replication queue full; dropping push for %s "
                        "(anti-entropy will heal)", bytes(nonce).hex())
            return False

    def _push_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [item]
            while len(batch) < PUSH_BATCH:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            try:
                self._push_batch(batch)
            except Exception:
                # the pusher must outlive any single bad batch; the
                # entries are dropped (counted) and anti-entropy heals
                metrics.inc("repl.push_failures", len(batch))
                log.exception("replication push batch failed")

    def _push_batch(self, batch: list) -> None:
        with self._lock:
            state = self._state
        if state is None:
            return
        ring, me = state.ring, state.self_id
        by_target: Dict[str, list] = {}
        for nonce, ntz, secret, t0 in batch:
            for succ in ring.ordered(nonce)[1:1 + self.replicas]:
                by_target.setdefault(succ, []).append(
                    (nonce, ntz, secret, t0))
        for target, items in sorted(by_target.items()):
            entries = [entry_wire(n, z, s) for n, z, s, _ in items]
            try:
                client = self._client(target, ring.addr_of(target))
                client.call("Cluster.CacheSync",
                            {"entries": entries, "self": me},
                            timeout=self.push_timeout_s)  # distpow: ok serial-rpc-fanout -- deliberately serial: the pusher is a single background thread OFF the Mine critical path, each call is bounded by push_timeout_s, and the loop spans at most ClusterCacheReplicas (default 1) successors per batch — concurrency here would buy nothing and cost a thread per replica
                metrics.inc("repl.pushes", len(items))
                now = time.monotonic()
                for _n, _z, _s, t0 in items:
                    metrics.observe("repl.push_lag_s", now - t0)
            except (OSError, RPCError, Exception):
                metrics.inc("repl.push_failures", len(items))
                log.warning("CacheSync push of %d entries to %s failed "
                            "(anti-entropy will heal)", len(items), target)
                self._drop_client(target)

    # -- replica install (both Cluster RPCs funnel here) ---------------------
    def install(self, entries: Optional[list]) -> Tuple[int, int]:
        """Install pushed entries through the dominance order; returns
        ``(installed, stale)``.  A stale push can never regress the
        replica — ``add`` rejects it and we count the proof."""
        installed = stale = 0
        for e in entries or []:
            try:
                nonce = bytes(e["nonce"])
                ntz = int(e["num_trailing_zeros"])
                secret = bytes(e["secret"])
            except (KeyError, TypeError, ValueError):
                log.warning("malformed replication entry dropped: %r", e)
                continue
            if self._cache.add(nonce, ntz, secret, trace=None):
                installed += 1
            else:
                stale += 1
        if installed:
            metrics.inc("repl.installs", installed)
        if stale:
            metrics.inc("repl.stale_drops", stale)
        return installed, stale

    # -- anti-entropy --------------------------------------------------------
    def _replicated_to(self, peer: str) -> List[Tuple[bytes, int, bytes]]:
        """Entries THIS member owns whose successor set includes
        ``peer`` — the exact set ``peer`` is supposed to replicate.
        The digest responder applies the mirror-image filter, so both
        reconciliation sides digest the same intended set."""
        with self._lock:
            state = self._state
        if state is None:
            return []
        ring, me = state.ring, state.self_id
        return [
            (n, z, s) for n, z, s in self._cache.entries_snapshot()
            if ring.owner(n) == me
            and peer in ring.ordered(n)[1:1 + self.replicas]
        ]

    def digests_for(self, requester: str, n_buckets: int) -> List[List[int]]:
        """Responder half of the digest exchange: summarize the entries
        this member holds that ``requester`` owns and that the ring
        says should be replicated HERE."""
        with self._lock:
            state = self._state
        if state is None:
            return []
        ring, me = state.ring, state.self_id
        n_buckets = max(1, min(int(n_buckets), 4096))
        held = [
            (n, z, s) for n, z, s in self._cache.entries_snapshot()
            if ring.owner(n) == requester
            and me in ring.ordered(n)[1:1 + self.replicas]
        ]
        return range_digests(held, ring, n_buckets)

    def _antientropy_loop(self) -> None:
        while not self._stop.wait(self.antientropy_s):
            try:
                self.antientropy_sweep()
            except Exception:
                log.exception("anti-entropy sweep failed; next interval "
                              "retries")

    def antientropy_sweep(self) -> int:
        """One reconciliation pass against every successor; returns the
        number of entries pushed to heal divergence.  Public so tests
        and operators can force a sweep without waiting the interval."""
        with self._lock:
            state = self._state
        if (state is None or self.replicas <= 0
                or len(state.ring.members) < 2):
            return 0
        ring, me = state.ring, state.self_id
        healed = 0
        peers = [m for m in ring.member_ids() if m != me]
        for peer in peers:
            mine = self._replicated_to(peer)
            if not mine:
                continue
            local = range_digests(mine, ring, self.digest_buckets)
            try:
                client = self._client(peer, ring.addr_of(peer))
                reply = client.call("Cluster.CacheSync",
                                    {"digest": self.digest_buckets,
                                     "self": me},
                                    timeout=self.push_timeout_s)  # distpow: ok serial-rpc-fanout -- deliberately serial: the anti-entropy loop is a slow BACKGROUND reconciliation (ClusterAntiEntropyS cadence), each digest exchange is bounded by push_timeout_s, and the loop spans the pool's other members (small by construction) — serializing it is the bandwidth bound the design wants
            except (OSError, RPCError, Exception):
                log.warning("anti-entropy digest exchange with %s failed; "
                            "next sweep retries", peer)
                self._drop_client(peer)
                continue
            remote = reply.get("digest") or []
            diverged = {
                i for i in range(self.digest_buckets)
                if list(local[i]) != list(
                    remote[i] if i < len(remote) else [0, 0, 0])
            }
            if not diverged:
                continue
            to_push = [
                (n, z, s) for n, z, s in mine
                if ((ring.key_point(n) * self.digest_buckets) >> 64)
                in diverged
            ][:self.antientropy_max_entries]
            if not to_push:
                continue
            entries = [entry_wire(n, z, s) for n, z, s in to_push]
            try:
                client.call("Cluster.CacheSync",
                            {"entries": entries, "self": me},
                            timeout=self.push_timeout_s)  # distpow: ok serial-rpc-fanout -- same bounded background loop as the digest exchange above: one capped (antientropy_max_entries) heal push per diverged peer per sweep
            except (OSError, RPCError, Exception):
                log.warning("anti-entropy heal push to %s failed; next "
                            "sweep retries", peer)
                self._drop_client(peer)
                continue
            metrics.inc("repl.pushes", len(to_push))
            healed += len(to_push)
            RECORDER.record("repl.antientropy_heal", peer=peer,
                            entries=len(to_push),
                            buckets=len(diverged))
            log.info("anti-entropy healed %d entries (%d ranges) to %s",
                     len(to_push), len(diverged), peer)
        metrics.inc("repl.antientropy_rounds")
        return healed

    # -- warm shard handoff --------------------------------------------------
    def handoff(self, old_ring: HashRing, new_ring: HashRing,
                deadline_s: Optional[float] = None) -> dict:
        """Push the remapped ranges' entries to their new owners BEFORE
        the ring change is acked (docs/CLUSTER.md "Replication & HA").

        Exactly the entries whose old-ring owner is this member and
        whose new-ring owner is someone else move — nothing else
        (tests/test_cluster.py property tests).  One sender thread per
        target under ONE shared deadline: a frozen recipient burns its
        own thread's slice of the deadline, never the ring change.
        Whatever the deadline cuts off, anti-entropy backfills.
        """
        with self._lock:
            state = self._state
        me = state.self_id if state is not None else None
        if me is None:
            return {"keys": 0, "expected": 0, "targets": 0,
                    "complete": True}
        deadline_s = (self.handoff_deadline_s if deadline_s is None
                      else float(deadline_s))
        moved: Dict[str, list] = {}
        for n, z, s in self._cache.entries_snapshot():
            if old_ring.owner(n) != me:
                continue
            new_owner = new_ring.owner(n)
            if new_owner != me:
                moved.setdefault(new_owner, []).append((n, z, s))
        expected = sum(len(v) for v in moved.values())
        if not moved:
            return {"keys": 0, "expected": 0, "targets": 0,
                    "complete": True}
        t0 = time.monotonic()
        deadline = t0 + deadline_s
        results: Dict[str, Tuple[int, bool]] = {}
        senders = []
        for target, entries in sorted(moved.items()):
            t = threading.Thread(
                target=self._handoff_to,
                args=(target, new_ring.addr_of(target), entries,
                      deadline, results),
                daemon=True, name=f"repl-handoff-{target}",
            )  # distpow: ok unbounded-thread-spawn -- bounded: one spawn per NEW owner of a remapped range (<= pool size, a handful), and every sender self-terminates at the shared handoff deadline — per-target threads are exactly how a frozen recipient is kept from serializing the other targets' handoffs
            t.start()
            senders.append(t)
        for t in senders:
            t.join(timeout=max(0.0, deadline - time.monotonic()) + 1.0)
        pushed = sum(k for k, _ok in results.values())
        complete = (len(results) == len(moved)
                    and all(ok for _k, ok in results.values()))
        dur = time.monotonic() - t0
        metrics.observe("repl.handoff_s", dur)
        RECORDER.record("repl.handoff", keys=pushed, expected=expected,
                        targets=len(moved), complete=complete,
                        dur_s=round(dur, 6))
        log.info("warm handoff: %d/%d keys to %d new owner(s) in %.3fs "
                 "(complete=%s)", pushed, expected, len(moved), dur,
                 complete)
        return {"keys": pushed, "expected": expected,
                "targets": len(moved), "complete": complete}

    def _handoff_to(self, target: str, addr: Optional[str], entries: list,
                    deadline: float, results: dict) -> None:
        with self._lock:
            state = self._state
        me = state.self_id if state is not None else "?"
        pushed, ok = 0, True
        client: Optional[RPCClient] = None
        try:
            if addr is None:
                results[target] = (0, False)
                return
            for i in range(0, len(entries), HANDOFF_CHUNK):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    ok = False
                    log.warning("handoff to %s hit the deadline with "
                                "%d/%d keys sent (anti-entropy heals "
                                "the rest)", target, pushed, len(entries))
                    break
                chunk = entries[i:i + HANDOFF_CHUNK]
                try:
                    if client is None:
                        client = RPCClient(addr,
                                           timeout=min(remaining, 5.0))
                    client.call(
                        "Cluster.Handoff",
                        {"entries": [entry_wire(n, z, s)
                                     for n, z, s in chunk],
                         "self": me},
                        timeout=remaining,
                    )
                except (OSError, RPCError, Exception):
                    ok = False
                    log.warning("handoff chunk to %s failed at %d/%d "
                                "keys (anti-entropy heals the rest)",
                                target, pushed, len(entries))
                    break
                pushed += len(chunk)
                metrics.inc("repl.handoff_keys", len(chunk))
            results[target] = (pushed, ok and pushed == len(entries))
        finally:
            if client is not None:
                client.close()

    # -- peer clients --------------------------------------------------------
    def _client(self, member: str, addr: Optional[str]) -> RPCClient:
        if addr is None:
            raise OSError(f"member {member!r} has no ring address")
        with self._lock:
            cached = self._clients.get(member)
            if cached is not None and cached[0] == addr:
                return cached[1]
        fresh = RPCClient(addr, timeout=self.push_timeout_s)
        stale: Optional[RPCClient] = None
        with self._lock:
            cached = self._clients.get(member)
            if cached is not None:
                stale = cached[1]
            self._clients[member] = (addr, fresh)
        if stale is not None:
            stale.close()
        return fresh

    def _drop_client(self, member: str) -> None:
        with self._lock:
            cached = self._clients.pop(member, None)
        if cached is not None:
            cached[1].close()

    # -- introspection -------------------------------------------------------
    def stats_view(self) -> dict:
        """Small JSON-able state block for the Stats snapshot."""
        return {
            "replicas": self.replicas,
            "queue_depth": self._q.qsize(),
            "antientropy_s": self.antientropy_s,
            "handoff_deadline_s": self.handoff_deadline_s,
        }
