"""SHA3-256 spec data + pure-Python twin (jax-free).

Seventh registry model (round 4) and the first NON-Merkle-Damgard
member: Keccak is a sponge — no init vector, no length field, pad10*1
with the SHA-3 domain byte — so it exercises the one packing-layer
assumption the first six models shared (``HashModel.padding``,
ops/packing.py).  FIPS 202 parameters for SHA3-256: rate 1088 bits
(136-byte blocks, 17 lanes), capacity 512, digest 32 bytes = the first
4 lanes of the state, serialized little-endian per 64-bit lane.

The framework carries the 25-lane state as 50 uint32 limbs in
little-endian serialization order — LOW limb first per lane (the
opposite of sha512's big-endian hi-first pairs), so the digest is
simply the leading 8 uint32 "words" with ``word_byteorder="little"``
and every digest/mask/packing layer works unchanged.

Oracle: hashlib.sha3_256 (guaranteed in CPython's hashlib since 3.6).
"""

from __future__ import annotations

from typing import List, Tuple

BLOCK_BYTES = 136          # rate: 1088 bits
DIGEST_WORDS = 8           # 32-byte digest as uint32 words
WORD_BYTEORDER = "little"  # lane serialization
LENGTH_BYTEORDER = "little"  # unused (sponge padding has no length field)
STATE_WORDS = 50           # 25 lanes x 2 uint32 limbs, lo-first
RATE_LANES = BLOCK_BYTES // 8

# all-zero sponge state, in the framework's uint32-limb convention
SHA3_INIT: Tuple[int, ...] = tuple(0 for _ in range(STATE_WORDS))

MASK64 = (1 << 64) - 1

# round constants, FIPS 202 / Keccak reference
KECCAK_RC: Tuple[int, ...] = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# rotation offsets r[x][y] (x = column, y = row; lane index = x + 5y)
KECCAK_ROT: Tuple[Tuple[int, ...], ...] = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)


def _rotl64(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & MASK64


def keccak_f(lanes: List[int]) -> List[int]:
    """Keccak-f[1600] on 25 uint64 lanes (index = x + 5y)."""
    A = list(lanes)
    for rc in KECCAK_RC:
        # theta
        C = [A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20]
             for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rotl64(C[(x + 1) % 5], 1) for x in range(5)]
        A = [A[i] ^ D[i % 5] for i in range(25)]
        # rho + pi
        B = [0] * 25
        for x in range(5):
            for y in range(5):
                B[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64(
                    A[x + 5 * y], KECCAK_ROT[x][y]
                )
        # chi
        A = [
            B[x + 5 * y] ^ ((~B[(x + 1) % 5 + 5 * y]) & MASK64
                            & B[(x + 2) % 5 + 5 * y])
            for y in range(5) for x in range(5)
        ]
        # iota
        A[0] ^= rc
    return A


def _limbs_to_lanes(state) -> List[int]:
    return [int(state[2 * i]) | (int(state[2 * i + 1]) << 32)
            for i in range(25)]


def _lanes_to_limbs(lanes) -> Tuple[int, ...]:
    out: List[int] = []
    for v in lanes:
        out.append(v & 0xFFFFFFFF)
        out.append((v >> 32) & 0xFFFFFFFF)
    return tuple(out)


def py_compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    """Absorb one 136-byte rate block: XOR into the state, permute."""
    assert len(block) == BLOCK_BYTES
    lanes = _limbs_to_lanes(state)
    for i in range(RATE_LANES):
        lanes[i] ^= int.from_bytes(block[8 * i: 8 * i + 8], "little")
    return _lanes_to_limbs(keccak_f(lanes))


def py_absorb(prefix: bytes) -> Tuple[Tuple[int, ...], bytes, int]:
    """Absorb the full rate blocks of ``prefix``; return the sponge
    state, the unabsorbed remainder, and the absorbed byte count."""
    state: Tuple[int, ...] = SHA3_INIT
    n_full = len(prefix) // BLOCK_BYTES
    for b in range(n_full):
        state = py_compress(
            state, prefix[b * BLOCK_BYTES: (b + 1) * BLOCK_BYTES]
        )
    absorbed = n_full * BLOCK_BYTES
    return state, prefix[absorbed:], absorbed


def py_digest(message: bytes) -> bytes:
    """SHA3-256 from the twin (oracle parity with hashlib.sha3_256)."""
    state, rem, _ = py_absorb(message)
    tail = bytearray(BLOCK_BYTES)
    tail[: len(rem)] = rem
    tail[len(rem)] ^= 0x06   # SHA-3 domain separation + first pad bit
    tail[-1] ^= 0x80         # final pad bit (merges when len(rem)==135)
    state = py_compress(state, bytes(tail))
    return b"".join(
        int(state[w]).to_bytes(4, "little") for w in range(DIGEST_WORDS)
    )
