"""BLAKE2b-256 as a vectorized JAX computation over uint32 limb pairs.

Eighth registry model: the per-block-parameter proof.  The compression
consumes 36 template words — 32 message limbs (16 little-endian 64-bit
words, lo limb first, exactly the packing serialization) plus the 4
parameter limbs the packing layer bakes per block
(``HashModel.block_param_words``): t_lo, t_hi (byte counter through
this block) and f_lo, f_hi (the finalization word, all-ones on the
last block).  For a fixed search layout these are compile-time
constants, which is what lets blake2's (state, message, t, f)
signature ride the framework's ``compress(state, words)`` shape
without changing any hash-agnostic layer.

Form: ``lax.fori_loop`` over the 12 rounds; the per-round message
schedule is a gather through the (12, 16) SIGMA table, and the carry
is the 16-lane working vector v stacked into ONE (32, batch) array
(the sha1/keccak shard_map carry lesson).  No unrolled XLA form —
the limb-graph compile pathology is established
(docs/artifacts/r4c/sha512_forms.json); the Pallas tile is the TPU
serving path.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .blake2b_py import (  # noqa: F401  (shared spec data + py twin)
    BLAKE2B_INIT,
    BLAKE2B_INIT64,
    BLAKE2B_IV,
    BLAKE2B_SIGMA,
    BLOCK_BYTES,
    DIGEST_WORDS,
    LENGTH_BYTEORDER,
    PARAM_WORDS,
    ROUNDS,
    STATE_WORDS,
    WORD_BYTEORDER,
    block_param_words,
    py_absorb,
    py_compress,
    py_digest,
)
from .sha512_jax import _u

U32 = jnp.uint32

_IV_LIMBS = tuple(
    w for v in BLAKE2B_IV for w in (v & 0xFFFFFFFF, (v >> 32) & 0xFFFFFFFF)
)


def _rotr64_lohi(lo, hi, n: int):
    """rotr of a (lo, hi) pair by a static amount."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n > 32:
        lo, hi, n = hi, lo, n - 32
    return (
        (lo >> n) | (hi << (32 - n)),
        (hi >> n) | (lo << (32 - n)),
    )


def _add64(alo, ahi, blo, bhi):
    lo = alo + blo
    carry = (lo < alo).astype(U32)
    return lo, ahi + bhi + carry


@jax.jit
def _blake2b_compress_jit(state, words):
    # one common shape up front: the fori carry must be shape-invariant
    # and limbs mix scalars (state, params) with batch message words
    all_limbs = jnp.broadcast_arrays(*(_u(x) for x in (
        tuple(state) + tuple(words))))
    h = all_limbs[:STATE_WORDS]
    m = all_limbs[STATE_WORDS: STATE_WORDS + 32]
    t_lo, t_hi, f_lo, f_hi = all_limbs[STATE_WORDS + 32:]

    m_lo = jnp.stack([m[2 * i] for i in range(16)])
    m_hi = jnp.stack([m[2 * i + 1] for i in range(16)])

    v = []
    for i in range(8):
        v.append((h[2 * i], h[2 * i + 1]))
    for i in range(8):
        iv_lo = jnp.broadcast_to(U32(_IV_LIMBS[2 * i]), h[0].shape)
        iv_hi = jnp.broadcast_to(U32(_IV_LIMBS[2 * i + 1]), h[0].shape)
        if i == 4:  # v[12] ^= t (t1 is always 0: real message sizes)
            iv_lo, iv_hi = iv_lo ^ t_lo, iv_hi ^ t_hi
        if i == 6:  # v[14] ^= f0
            iv_lo, iv_hi = iv_lo ^ f_lo, iv_hi ^ f_hi
        v.append((iv_lo, iv_hi))

    sigma = jnp.asarray(BLAKE2B_SIGMA, jnp.int32)  # (12, 16)

    # one G mixing function on the stacked carry, static lane indices,
    # dynamically gathered message words
    def g(st, a, b, c, d, xlo, xhi, ylo, yhi):
        alo, ahi = st[2 * a], st[2 * a + 1]
        blo, bhi = st[2 * b], st[2 * b + 1]
        clo, chi = st[2 * c], st[2 * c + 1]
        dlo, dhi = st[2 * d], st[2 * d + 1]
        alo, ahi = _add64(*_add64(alo, ahi, blo, bhi), xlo, xhi)
        dlo, dhi = _rotr64_lohi(dlo ^ alo, dhi ^ ahi, 32)
        clo, chi = _add64(clo, chi, dlo, dhi)
        blo, bhi = _rotr64_lohi(blo ^ clo, bhi ^ chi, 24)
        alo, ahi = _add64(*_add64(alo, ahi, blo, bhi), ylo, yhi)
        dlo, dhi = _rotr64_lohi(dlo ^ alo, dhi ^ ahi, 16)
        clo, chi = _add64(clo, chi, dlo, dhi)
        blo, bhi = _rotr64_lohi(blo ^ clo, bhi ^ chi, 63)
        for idx, val in ((2 * a, alo), (2 * a + 1, ahi), (2 * b, blo),
                         (2 * b + 1, bhi), (2 * c, clo), (2 * c + 1, chi),
                         (2 * d, dlo), (2 * d + 1, dhi)):
            st[idx] = val
        return st

    LANES_G = ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14),
               (3, 7, 11, 15), (0, 5, 10, 15), (1, 6, 11, 12),
               (2, 7, 8, 13), (3, 4, 9, 14))

    def round_body(r, stacked):
        st = [stacked[i] for i in range(32)]
        s = sigma[r]
        for gi, (a, b, c, d) in enumerate(LANES_G):
            xi, yi = s[2 * gi], s[2 * gi + 1]
            st = g(st, a, b, c, d,
                   m_lo[xi], m_hi[xi], m_lo[yi], m_hi[yi])
        return jnp.stack(st)

    # shard_map varying-axis typing: under a mesh step the message
    # words are device-varying while v[8..15] start as replicated IV
    # constants; one round would flip the fori carry's varying type and
    # break the carry-in == carry-out invariant (caught by the r5
    # multichip dryrun's blake2b leg).  XOR-in a zero derived from
    # every dynamic input: value-neutral (XLA folds it), but it
    # promotes the whole carry to the words' varying type up front.
    vz = (m_lo.sum(0) + m_hi.sum(0) + t_lo + t_hi + f_lo + f_hi) & U32(0)
    st0 = jnp.stack([limb ^ vz for pair in v for limb in pair])
    out = lax.fori_loop(0, ROUNDS, round_body, st0)

    res = []
    for i in range(8):
        res.append(h[2 * i] ^ out[2 * i] ^ out[2 * (i + 8)])
        res.append(h[2 * i + 1] ^ out[2 * i + 1] ^ out[2 * (i + 8) + 1])
    return tuple(res)


def blake2b_256_compress(state, words: Sequence):
    """One BLAKE2b compression, vectorized.

    ``state`` is 16 uint32 limbs (8 lanes lo-first); ``words`` is 36
    broadcast-compatible uint32 entries — 32 message limbs + the 4
    baked parameter limbs (module docstring).  Eager calls route
    through a module-level jit; under an outer jit it inlines.
    """
    return _blake2b_compress_jit(
        tuple(_u(x) for x in state), tuple(_u(x) for x in words)
    )
