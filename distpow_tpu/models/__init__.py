from . import puzzle
from .registry import MD5, SHA256, HashModel, get_hash_model, register_hash_model

__all__ = ["puzzle", "MD5", "SHA256", "HashModel", "get_hash_model", "register_hash_model"]
