"""Hash models: pure-Python puzzle oracle + pluggable JAX hash registry.

The registry (and through it the ``*_jax`` modules) imports jax, so it
is exposed lazily via module ``__getattr__`` (PEP 562): jax-free
consumers — the native C++ backend, the runtime layer, the CLI parsers —
can ``from ..models import puzzle`` without pulling the JAX compute path
into their import graph (advisor r3, backends/native_miner.py).
"""

from . import puzzle

_REGISTRY_EXPORTS = (
    "MD5", "SHA256", "HashModel", "get_hash_model", "register_hash_model",
)

__all__ = ["puzzle", *_REGISTRY_EXPORTS]


def __getattr__(name):
    if name in _REGISTRY_EXPORTS:
        from . import registry

        return getattr(registry, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
