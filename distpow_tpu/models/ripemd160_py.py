"""RIPEMD-160 constants and pure-Python implementation (jax-free).

Split out of ``ripemd160_jax`` (round 4 review) for two consumers that
must not import jax: ``models/puzzle.py``, which falls back to this
module when the host's OpenSSL build omits the legacy ripemd160 digest
(stock OpenSSL 3 without the legacy provider — ripemd160 is the first
registry model outside hashlib's guaranteed set), and the Pallas tile,
which shares the round tables.  ``ripemd160_jax`` re-exports everything
here, so there is exactly ONE copy of the spec data.

Tables and algorithm from the RIPEMD-160 specification (Dobbertin,
Bosselaers, Preneel; ISO/IEC 10118-3); pinned against the paper's
Appendix B vectors in tests/test_hash_models.py.
"""

from __future__ import annotations

import struct
from typing import Tuple

RIPEMD160_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)

BLOCK_BYTES = 64
DIGEST_WORDS = 5
WORD_BYTEORDER = "little"
LENGTH_BYTEORDER = "little"

# Per-16-round-group additive constants (left line then right line).
_KL = (0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E)
_KR = (0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000)

# Message-word selection order, left line (80 entries).
_RL = (
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
    7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
    3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
    1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
    4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13,
)
# Message-word selection order, right line.
_RR = (
    5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
    6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
    15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
    8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
    12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11,
)
# Rotation amounts, left line.
_SL = (
    11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
    7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
    11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
    11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
    9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6,
)
# Rotation amounts, right line.
_SR = (
    8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
    9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
    9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
    15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
    8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11,
)

_MASK = 0xFFFFFFFF


def _f(j: int, x, y, z):
    """Round function of group ``j // 16`` (left-line order; the right
    line uses group ``4 - j // 16``).  Polymorphic over Python ints and
    jnp uint32 arrays — the single copy of the spec's five boolean
    functions, shared by the int twin, the JAX compress, and the Pallas
    tile."""
    g = j // 16
    if g == 0:
        return x ^ y ^ z
    if g == 1:
        return (x & y) | (~x & z)
    if g == 2:
        return (x | ~y) ^ z
    if g == 3:
        return (x & z) | (y & ~z)
    return x ^ (y | ~z)


def py_compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    """Pure-Python RIPEMD-160 block compression on a 64-byte block."""
    assert len(block) == BLOCK_BYTES
    x = struct.unpack("<16I", block)
    h0, h1, h2, h3, h4 = state
    al, bl, cl, dl, el = state
    ar, br, cr, dr, er = state
    for j in range(80):
        t = (al + _f(j, bl, cl, dl) + x[_RL[j]] + _KL[j // 16]) & _MASK
        s = _SL[j]
        t = (((t << s) | (t >> (32 - s))) + el) & _MASK
        al, el, dl, cl, bl = el, dl, ((cl << 10) | (cl >> 22)) & _MASK, bl, t
        t = (ar + _f(79 - j, br, cr, dr) + x[_RR[j]] + _KR[j // 16]) & _MASK
        s = _SR[j]
        t = (((t << s) | (t >> (32 - s))) + er) & _MASK
        ar, er, dr, cr, br = er, dr, ((cr << 10) | (cr >> 22)) & _MASK, br, t
    return (
        (h1 + cl + dr) & _MASK,
        (h2 + dl + er) & _MASK,
        (h3 + el + ar) & _MASK,
        (h4 + al + br) & _MASK,
        (h0 + bl + cr) & _MASK,
    )


def py_absorb(prefix: bytes) -> Tuple[Tuple[int, ...], bytes, int]:
    """Absorb all complete 64-byte blocks of ``prefix``; returns
    ``(state, remainder_bytes, total_absorbed_len)`` (same contract as
    md5_jax.py_absorb — the packing layer is model-agnostic)."""
    state = RIPEMD160_INIT
    n_full = len(prefix) // BLOCK_BYTES
    for i in range(n_full):
        state = py_compress(state, prefix[i * BLOCK_BYTES:(i + 1) * BLOCK_BYTES])
    return state, prefix[n_full * BLOCK_BYTES:], n_full * BLOCK_BYTES


def py_digest(message: bytes) -> bytes:
    """Full RIPEMD-160 via the pure-Python compression (oracle)."""
    state, rem, _ = py_absorb(message)
    total = len(message)
    tail = rem + b"\x80"
    pad = (-len(tail) - 8) % BLOCK_BYTES
    tail += b"\x00" * pad + struct.pack("<Q", total * 8)
    for i in range(0, len(tail), BLOCK_BYTES):
        state = py_compress(state, tail[i:i + BLOCK_BYTES])
    return b"".join(w.to_bytes(4, "little") for w in state)


class Ripemd160:
    """Minimal hashlib-shaped shim over ``py_digest`` — the fallback
    ``models/puzzle.py`` hands out when ``hashlib.new("ripemd160")``
    raises (OpenSSL 3 without the legacy provider)."""

    name = "ripemd160"
    digest_size = 20
    block_size = BLOCK_BYTES

    def __init__(self, data: bytes = b""):
        self._buf = bytearray(data)

    def update(self, data: bytes) -> None:
        self._buf += data

    def digest(self) -> bytes:
        return py_digest(bytes(self._buf))

    def hexdigest(self) -> str:
        return self.digest().hex()

    def copy(self) -> "Ripemd160":
        return Ripemd160(bytes(self._buf))
