"""Double SHA-256 (sha256d) — Bitcoin's proof-of-work hash.

The ninth registry model adds the one structural axis the first eight
don't exercise: **hash composition**.  ``sha256d(m) =
sha256(sha256(m))`` — the first hash's 32-byte digest becomes the
message of a second SHA-256 whose layout is FIXED (one 64-byte block:
digest ‖ 0x80 ‖ zeros ‖ bit-length 256), independent of the search
candidate.  That second stage rides the registry's ``finalize`` hook
(models/registry.py): absorption, packing, partitioning, and the
layout-keyed compile discipline are all untouched — the composed stage
is a pure state→state function applied after the last compress, before
the difficulty check.

Reference role: the pluggable hash-kernel contract
(/root/reference/worker.go:353-356 — the reference hard-codes one
``md5.Sum``; this framework treats the kernel as a plug, and sha256d
shows a composed real-world kernel plugging in).

Everything SHA-256 (block geometry, byte orders, init state, compress,
python twins for absorption) is reused from models/sha256_jax.py; this
module adds only the composition stage and its twins.

Mask-word DCE composes for free: difficulty masks touch the SECOND
hash's trailing digest words, so XLA (and the Pallas tile's explicit
A/E-chain pruning) drops the unused tail of the second compression,
while the first compression always computes its full digest (every
word feeds the second message).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from .sha256_jax import (
    BLOCK_BYTES,
    DIGEST_WORDS,
    LENGTH_BYTEORDER,
    SHA256_INIT,
    WORD_BYTEORDER,
    py_absorb,
    py_compress,
    sha256_compress,
)

__all__ = [
    "BLOCK_BYTES", "DIGEST_WORDS", "LENGTH_BYTEORDER", "WORD_BYTEORDER",
    "SHA256_INIT", "py_absorb", "py_compress", "sha256d_finalize",
    "py_finalize", "SECOND_BLOCK_TAIL_WORDS",
]

# The second block's non-digest words: 0x80 padding marker directly
# after the 32 digest bytes, zeros, and the 64-bit big-endian
# bit-length field (32 bytes = 256 bits) — fixed by FIPS 180-4 for a
# 32-byte single-block message.
SECOND_BLOCK_TAIL_WORDS: Tuple[int, ...] = (
    0x80000000, 0, 0, 0, 0, 0, 0, 256,
)


def sha256d_finalize(state):
    """Second SHA-256 over the first digest, vectorized.

    ``state`` is the first compression's 8-word output (arrays over the
    candidate batch).  Because WORD_BYTEORDER is big-endian for both
    the digest serialization and the message-word packing, the second
    block's first 8 message words ARE the first hash's state words —
    no byte swapping.

    shard_map varying-axis typing: the second compression starts from
    the constant SHA256_INIT and half its message words are constants;
    on backends using the fori_loop compress form the rolling window
    carry would flip varying mid-loop (the exact class the blake2b r5
    dryrun leg caught).  A varying-typed zero derived from the incoming
    state is XOR'd into every constant entering the stage — value-free
    after XLA folding, but the carry's varying type is uniform from
    round 0.
    """
    s = [jnp.asarray(w, jnp.uint32) for w in state[:DIGEST_WORDS]]
    vz = s[0] & jnp.uint32(0)
    words = s + [jnp.uint32(c) ^ vz for c in SECOND_BLOCK_TAIL_WORDS]
    init2 = tuple(jnp.uint32(c) ^ vz for c in SHA256_INIT)
    return sha256_compress(init2, words)


def py_finalize(state: Tuple[int, ...]) -> Tuple[int, ...]:
    """Pure-Python twin of ``sha256d_finalize`` (host-side oracle)."""
    digest = b"".join(int(w).to_bytes(4, "big") for w in state[:DIGEST_WORDS])
    block = digest + b"\x80" + bytes(23) + (8 * len(digest)).to_bytes(8, "big")
    assert len(block) == BLOCK_BYTES
    return py_compress(SHA256_INIT, block)


def py_digest(message: bytes) -> bytes:
    """Full sha256d over ``message`` via the state-level twins — the
    hashlib-parity surface test_hash_models exercises per model.

    The first hash reuses sha256_jax's own ``py_digest`` (one canonical
    FIPS 180-4 padding implementation, review r5); its digest bytes ARE
    the first state big-endian, so re-unpacking them feeds the real
    ``py_finalize`` composition stage this module owns."""
    import struct

    from .sha256_jax import py_digest as _sha256_py_digest

    state = struct.unpack(">8I", _sha256_py_digest(message))
    return b"".join(
        int(w).to_bytes(4, "big") for w in py_finalize(state)
    )
