"""SHA-512 constants and pure-Python implementation (jax-free).

Companion of ``sha512_jax`` (round 4, fifth registry model) in the same
split as ``ripemd160_py``/``ripemd160_jax``: spec data + the int twin
live here, importable without jax.  Constants from FIPS 180-4.

SHA-512 is the interface-generality proof for the model layer: 128-byte
blocks, a 16-byte bit-length field, and 64-bit words — the framework
carries 64-bit state as (hi32, lo32) uint32 pairs end to end (16 uint32
state words, big-endian serialization), because the packing/difficulty/
search layers speak uint32 lanes (a TPU has no native uint64 VPU type).
"""

from __future__ import annotations

import struct
from typing import Tuple

BLOCK_BYTES = 128
DIGEST_WORDS = 16          # 8 x 64-bit = 16 uint32 (hi, lo) pairs
WORD_BYTEORDER = "big"
LENGTH_BYTEORDER = "big"
LENGTH_BYTES = 16          # 128-bit message bit-length field

# FIPS 180-4 section 5.3.5: initial hash value (64-bit words).
SHA512_INIT64 = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

# Section 4.2.3: eighty 64-bit round constants.
SHA512_K64 = (
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F,
    0xE9B5DBA58189DBBC, 0x3956C25BF348B538, 0x59F111F1B605D019,
    0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118, 0xD807AA98A3030242,
    0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235,
    0xC19BF174CF692694, 0xE49B69C19EF14AD2, 0xEFBE4786384F25E3,
    0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65, 0x2DE92C6F592B0275,
    0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F,
    0xBF597FC7BEEF0EE4, 0xC6E00BF33DA88FC2, 0xD5A79147930AA725,
    0x06CA6351E003826F, 0x142929670A0E6E70, 0x27B70A8546D22FFC,
    0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6,
    0x92722C851482353B, 0xA2BFE8A14CF10364, 0xA81A664BBC423001,
    0xC24B8B70D0F89791, 0xC76C51A30654BE30, 0xD192E819D6EF5218,
    0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99,
    0x34B0BCB5E19B48A8, 0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB,
    0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3, 0x748F82EE5DEFB2FC,
    0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915,
    0xC67178F2E372532B, 0xCA273ECEEA26619C, 0xD186B8C721C0C207,
    0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178, 0x06F067AA72176FBA,
    0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC,
    0x431D67C49C100D4C, 0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A,
    0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
)

# The framework-facing init: 16 uint32 words, (hi, lo) per 64-bit word.
SHA512_INIT = tuple(
    w for v in SHA512_INIT64 for w in ((v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF)
)

_M64 = (1 << 64) - 1


def _rotr64(x: int, n: int) -> int:
    return ((x >> n) | (x << (64 - n))) & _M64


def py_compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    """Pure-Python SHA-512 block compression.

    ``state`` is the framework's 16-uint32 (hi, lo) representation; the
    arithmetic runs on reassembled 64-bit ints and splits back at the
    end, so this twin also documents the pairing convention the JAX
    compress emulates limb-wise.
    """
    assert len(block) == BLOCK_BYTES
    w = list(struct.unpack(">16Q", block))
    for i in range(16, 80):
        s0 = _rotr64(w[i - 15], 1) ^ _rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7)
        s1 = _rotr64(w[i - 2], 19) ^ _rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & _M64)
    hs = [
        (state[2 * i] << 32) | state[2 * i + 1] for i in range(8)
    ]
    a, b, c, d, e, f, g, h = hs
    for i in range(80):
        S1 = _rotr64(e, 14) ^ _rotr64(e, 18) ^ _rotr64(e, 41)
        ch = (e & f) ^ (~e & g)
        t1 = (h + S1 + ch + SHA512_K64[i] + w[i]) & _M64
        S0 = _rotr64(a, 28) ^ _rotr64(a, 34) ^ _rotr64(a, 39)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (S0 + maj) & _M64
        h, g, f, e = g, f, e, (d + t1) & _M64
        d, c, b, a = c, b, a, (t1 + t2) & _M64
    out64 = [
        (hv + nv) & _M64
        for hv, nv in zip(hs, (a, b, c, d, e, f, g, h))
    ]
    return tuple(
        w for v in out64 for w in ((v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF)
    )


def py_absorb(prefix: bytes, init=SHA512_INIT) -> Tuple[Tuple[int, ...], bytes, int]:
    """Absorb all complete 128-byte blocks of ``prefix``; same contract
    as the other models' ``py_absorb`` (the packing layer reads
    ``model.block_bytes``, so the different block size is transparent).
    ``init`` parameterizes the variant (sha384_jax passes its own)."""
    state = init
    n_full = len(prefix) // BLOCK_BYTES
    for i in range(n_full):
        state = py_compress(state, prefix[i * BLOCK_BYTES:(i + 1) * BLOCK_BYTES])
    return state, prefix[n_full * BLOCK_BYTES:], n_full * BLOCK_BYTES


def py_digest(message: bytes, init=SHA512_INIT, digest_words: int = 16) -> bytes:
    """Full SHA-512-family digest via the pure-Python compression
    (oracle): one copy of the padding rules for sha512 AND sha384
    (review r4 — the truncating sibling passes its init and 12)."""
    state, rem, _ = py_absorb(message, init)
    total = len(message)
    tail = rem + b"\x80"
    pad = (-len(tail) - LENGTH_BYTES) % BLOCK_BYTES
    tail += b"\x00" * pad + (total * 8).to_bytes(LENGTH_BYTES, "big")
    for i in range(0, len(tail), BLOCK_BYTES):
        state = py_compress(state, tail[i:i + BLOCK_BYTES])
    return b"".join(w.to_bytes(4, "big") for w in state[:digest_words])
