"""SHA-384 — SHA-512's truncated sibling (round 4, sixth registry model).

FIPS 180-4 section 5.3.4: identical compression and padding to SHA-512
with a different initial hash value, and the digest is the first six
64-bit words (48 bytes) of the final state.  Everything is shared with
``sha512_jax``/``sha512_py``; this module only contributes the init
constants and the truncation, which exercises a new interface case:
``digest_words`` (12) SMALLER than the state width (16).  The
difficulty-mask layer reads only digest words (``state[:digest_words]``
carry the digest; the mask fold slices the trailing ones), and
verification goes through hashlib, so truncation is free — but it is
the first model where ``len(init_state) != digest_words``, pinned by
tests so no layer silently assumes they match.
"""

from __future__ import annotations

from .sha512_jax import sha512_compress as sha384_compress  # noqa: F401
from .sha512_py import BLOCK_BYTES  # noqa: F401
from .sha512_py import LENGTH_BYTEORDER  # noqa: F401
from .sha512_py import LENGTH_BYTES  # noqa: F401
from .sha512_py import WORD_BYTEORDER  # noqa: F401
from .sha512_py import py_compress as _sha512_py_compress

DIGEST_WORDS = 12  # 6 x 64-bit = 48 bytes; state stays 16 uint32 words

# FIPS 180-4 section 5.3.4 initial hash value.
SHA384_INIT64 = (
    0xCBBB9D5DC1059ED8, 0x629A292A367CD507, 0x9159015A3070DD17,
    0x152FECD8F70E5939, 0x67332667FFC00B31, 0x8EB44A8768581511,
    0xDB0C2E0D64F98FA7, 0x47B5481DBEFA4FA4,
)
SHA384_INIT = tuple(
    w for v in SHA384_INIT64 for w in ((v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF)
)


def py_compress(state, block):
    return _sha512_py_compress(state, block)


def py_absorb(prefix: bytes):
    from . import sha512_py

    return sha512_py.py_absorb(prefix, init=SHA384_INIT)


def py_digest(message: bytes) -> bytes:
    # one copy of the padding rules (sha512_py), parameterized by init
    # and the truncated digest width (review r4)
    from . import sha512_py

    return sha512_py.py_digest(message, init=SHA384_INIT,
                               digest_words=DIGEST_WORDS)
