"""BLAKE2b-256 spec data + pure-Python twin (jax-free).

Eighth registry model (round 4) and the per-block-parameter proof: a
BLAKE2b compression takes, besides the state and message words, a byte
COUNTER ``t`` (total message bytes absorbed through this block) and a
FINALIZATION flag ``f0`` — inputs that are neither state nor message.
The first seven models never exercised that shape; here the packing
layer bakes them per block into extra constant template words
(``HashModel.block_param_words``, ops/packing.py) since for a fixed
search layout they are compile-time constants.

RFC 7693 parameters for BLAKE2b-256 (sequential mode, no key): 128-byte
blocks, 12 rounds, digest 32 bytes = the first 4 of 8 64-bit state
words, everything little-endian.  There is NO padding marker: the final
block is zero-filled and distinguished solely by ``f0`` and ``t`` —
``padding="blake2"`` writes nothing at all.

The framework carries the 8-lane state as 16 uint32 limbs lo-first
(little-endian serialization order, like sha3), so the digest is the
leading 8 uint32 words with ``word_byteorder="little"``.

Oracle: hashlib.blake2b(digest_size=32) (guaranteed in CPython).
"""

from __future__ import annotations

from typing import List, Tuple

BLOCK_BYTES = 128
DIGEST_WORDS = 8            # 32-byte digest as uint32 words
WORD_BYTEORDER = "little"
LENGTH_BYTEORDER = "little"  # unused (no length field in the padding)
STATE_WORDS = 16            # 8 lanes x 2 uint32 limbs, lo-first
ROUNDS = 12
# extra per-block template words appended by the packing layer:
# t_lo, t_hi (the 64-bit byte counter; t1 is always 0 for real message
# sizes), f_lo, f_hi (the finalization word: all-ones on the last
# block, else 0)
PARAM_WORDS = 4

MASK64 = (1 << 64) - 1

BLAKE2B_IV: Tuple[int, ...] = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B,
    0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

# h[0] ^= 0x01010000 | digest_length  (fanout 1, depth 1, no key)
_PARAM_XOR = 0x01010000 | 32

BLAKE2B_INIT64: Tuple[int, ...] = (
    (BLAKE2B_IV[0] ^ _PARAM_XOR),
) + BLAKE2B_IV[1:]

# lo-first uint32 limb serialization of the init state
BLAKE2B_INIT: Tuple[int, ...] = tuple(
    w for v in BLAKE2B_INIT64 for w in (v & 0xFFFFFFFF, (v >> 32) & 0xFFFFFFFF)
)

# message schedule permutations (RFC 7693 table; rounds 10, 11 reuse
# rows 0, 1)
BLAKE2B_SIGMA: Tuple[Tuple[int, ...], ...] = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
)


def _rotr64(v: int, n: int) -> int:
    return ((v >> n) | (v << (64 - n))) & MASK64


def _g(v: List[int], a: int, b: int, c: int, d: int, x: int, y: int) -> None:
    v[a] = (v[a] + v[b] + x) & MASK64
    v[d] = _rotr64(v[d] ^ v[a], 32)
    v[c] = (v[c] + v[d]) & MASK64
    v[b] = _rotr64(v[b] ^ v[c], 24)
    v[a] = (v[a] + v[b] + y) & MASK64
    v[d] = _rotr64(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & MASK64
    v[b] = _rotr64(v[b] ^ v[c], 63)


def blake2b_f(h: List[int], m: List[int], t: int, last: bool) -> List[int]:
    """One BLAKE2b compression: 8 uint64 state words, 16 message words,
    byte counter ``t``, finalization flag ``last``."""
    v = list(h) + list(BLAKE2B_IV)
    v[12] ^= t & MASK64
    v[13] ^= (t >> 64) & MASK64  # t1: always 0 for real message sizes
    if last:
        v[14] ^= MASK64
    for r in range(ROUNDS):
        s = BLAKE2B_SIGMA[r]
        _g(v, 0, 4, 8, 12, m[s[0]], m[s[1]])
        _g(v, 1, 5, 9, 13, m[s[2]], m[s[3]])
        _g(v, 2, 6, 10, 14, m[s[4]], m[s[5]])
        _g(v, 3, 7, 11, 15, m[s[6]], m[s[7]])
        _g(v, 0, 5, 10, 15, m[s[8]], m[s[9]])
        _g(v, 1, 6, 11, 12, m[s[10]], m[s[11]])
        _g(v, 2, 7, 8, 13, m[s[12]], m[s[13]])
        _g(v, 3, 4, 9, 14, m[s[14]], m[s[15]])
    return [h[i] ^ v[i] ^ v[i + 8] for i in range(8)]


def _limbs_to_lanes(state, n: int) -> List[int]:
    return [int(state[2 * i]) | (int(state[2 * i + 1]) << 32)
            for i in range(n)]


def _lanes_to_limbs(lanes) -> Tuple[int, ...]:
    out: List[int] = []
    for v in lanes:
        out.append(v & 0xFFFFFFFF)
        out.append((v >> 32) & 0xFFFFFFFF)
    return tuple(out)


def py_compress(state: Tuple[int, ...], block: bytes, *,
                t: int | None = None,
                last: bool | None = None) -> Tuple[int, ...]:
    """Absorb one block.  Two accepted shapes (advisor r4 — every other
    model's py_compress takes exactly BLOCK_BYTES, so a generic consumer
    must be able to pass a plain block here too):

    * ``BLOCK_BYTES + 4 * PARAM_WORDS`` bytes — the packing-template
      form, trailing bytes carrying the baked (t, f) parameter words;
      ``t``/``last`` kwargs must not also be given.
    * exactly ``BLOCK_BYTES`` — a plain block; ``t`` (total bytes
      absorbed INCLUDING this block) is REQUIRED, because unlike every
      other model blake2's compression is not a pure function of
      (state, block): a silently-defaulted counter would chain
      multi-block inputs into a wrong digest with no error (review
      r5).  ``last`` defaults to False (non-final block).
    """
    if len(block) == BLOCK_BYTES + 4 * PARAM_WORDS:
        if t is not None or last is not None:
            # TypeError (not assert): under python -O an assert would
            # silently drop the caller's explicit counter in favor of
            # the baked one — the silent-wrong-counter class the plain
            # path's guard below exists to prevent (review r5)
            raise TypeError(
                "template-shaped block already carries baked (t, f) "
                "parameter words; do not also pass t=/last="
            )
        t = int.from_bytes(block[128:136], "little")
        last = int.from_bytes(block[136:144], "little") != 0
    else:
        assert len(block) == BLOCK_BYTES, len(block)
        if t is None:
            raise TypeError(
                "blake2b py_compress needs t= (bytes absorbed including "
                "this block) for a plain 128-byte block — the byte "
                "counter is a compression input; use py_absorb for "
                "prefix absorption, or pass the template-shaped block "
                "(BLOCK_BYTES + 16) with baked parameters"
            )
        last = False if last is None else last
    h = _limbs_to_lanes(state, 8)
    m = [int.from_bytes(block[8 * i: 8 * i + 8], "little") for i in range(16)]
    return _lanes_to_limbs(blake2b_f(h, m, t, last))


def py_absorb(prefix: bytes) -> Tuple[Tuple[int, ...], bytes, int]:
    """Absorb the full 128-byte blocks of ``prefix`` that are safely
    non-final.  A block is only compressible once later data is KNOWN
    to exist; every search candidate appends >= 1 secret byte after the
    nonce, so all full prefix blocks qualify (t = bytes so far,
    last = False)."""
    state64 = list(BLAKE2B_INIT64)
    n_full = len(prefix) // BLOCK_BYTES
    for b in range(n_full):
        block = prefix[b * BLOCK_BYTES: (b + 1) * BLOCK_BYTES]
        m = [int.from_bytes(block[8 * i: 8 * i + 8], "little")
             for i in range(16)]
        state64 = blake2b_f(state64, m, (b + 1) * BLOCK_BYTES, False)
    absorbed = n_full * BLOCK_BYTES
    return _lanes_to_limbs(state64), prefix[absorbed:], absorbed


def block_param_words(absorbed: int, content: int, block_idx: int,
                      n_blocks: int) -> Tuple[int, int, int, int]:
    """The per-block parameter limbs the packing layer bakes into the
    template (``HashModel.block_param_words``): byte counter t through
    this block's MESSAGE bytes (zero-fill padding is not counted, so
    the final block uses the true message length), and the
    finalization word f0 (all-ones on the last block).  The search
    tail always contains the message end, so finality is static."""
    last = block_idx == n_blocks - 1
    t = absorbed + (content if last else (block_idx + 1) * BLOCK_BYTES)
    f = 0xFFFFFFFF if last else 0
    return (t & 0xFFFFFFFF, (t >> 32) & 0xFFFFFFFF, f, f)


def py_digest(message: bytes) -> bytes:
    """BLAKE2b-256 from the twin (oracle parity with hashlib.blake2b).

    Unlike ``py_absorb`` (whose callers always append more bytes), the
    whole message is in hand here, so the final block — even a FULL one
    when ``len % 128 == 0`` — must be compressed with ``last=True``:
    blake2 buffers one block precisely because finality is only known
    once the stream ends.
    """
    n_full_nonfinal = max(0, (len(message) - 1) // BLOCK_BYTES)
    h = list(BLAKE2B_INIT64)
    for b in range(n_full_nonfinal):
        block = message[b * BLOCK_BYTES: (b + 1) * BLOCK_BYTES]
        m = [int.from_bytes(block[8 * i: 8 * i + 8], "little")
             for i in range(16)]
        h = blake2b_f(h, m, (b + 1) * BLOCK_BYTES, False)
    rem = message[n_full_nonfinal * BLOCK_BYTES:]
    tail = bytearray(BLOCK_BYTES)
    tail[: len(rem)] = rem
    m = [int.from_bytes(bytes(tail[8 * i: 8 * i + 8]), "little")
         for i in range(16)]
    h = blake2b_f(h, m, len(message), True)
    return b"".join(int(w).to_bytes(8, "little") for w in h[:4])
