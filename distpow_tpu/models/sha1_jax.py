"""SHA-1 as a vectorized JAX computation over uint32 lanes.

Third hash model in the pluggable registry (``models/registry.py``; the
reference hard-codes MD5 at worker.go:5,353 and BASELINE.json's north
star names SHA-256) — included to pin the model abstraction: everything
below the registry (packing, difficulty masks, search step, backends,
the native miner) is hash-agnostic, so a new model is exactly one
compression function plus a registry entry.

Same interface as ``md5_jax``/``sha256_jax`` (16 broadcastable message
words in, state out) and the same platform-keyed compilation strategy
as SHA-256: the 80-round graph is fully unrolled on accelerators (the
message schedule stays a plain Python list, so entries fed only by
constant words remain scalars) and a ``lax.fori_loop`` with a rolling
16-word window on XLA:CPU, whose codegen blows up on big unrolled hash
graphs (see sha256_jax.py module docstring).  Correctness pinned
against ``hashlib`` in tests/test_hash_models.py.
"""

from __future__ import annotations

import struct
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SHA1_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)

# One constant per 20-round group (FIPS 180-4 §4.2.1).
SHA1_K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)

BLOCK_BYTES = 64
DIGEST_WORDS = 5
WORD_BYTEORDER = "big"
LENGTH_BYTEORDER = "big"


def _u32(x):
    return x if hasattr(x, "dtype") else jnp.uint32(np.uint32(x))


def _rotl(x, s):
    return (x << s) | (x >> (32 - s))


def _round(st, i, w_i):
    a, b, c, d, e = st
    if i < 20:
        f = (b & c) | (~b & d)
    elif i < 40:
        f = b ^ c ^ d
    elif i < 60:
        f = (b & c) | (b & d) | (c & d)
    else:
        f = b ^ c ^ d
    # (k + w) grouped: a scalar-unit add for constant/scalar message
    # words (XLA does not reassociate integer adds; same rationale as
    # sha256_jax._round)
    temp = _rotl(a, 5) + f + e + (jnp.uint32(SHA1_K[i // 20]) + w_i)
    return (temp, a, _rotl(b, 30), c, d)


def _compress_unrolled(state, words):
    """Fully unrolled 80-round form (accelerators): schedule entries fed
    only by constant words stay scalars through the recursion."""
    w = [_u32(m) for m in words]
    for i in range(16, 80):
        w.append(_rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1))
    st = tuple(_u32(s) for s in state)
    for i in range(80):
        st = _round(st, i, w[i])
    return tuple(_u32(s0) + s for s0, s in zip(state, st))


def _compress_loop(state, words):
    """fori_loop form (XLA:CPU): rounds 0-15 unrolled on the raw words,
    rounds 16-79 carry a rolling 16-word window.  The round function
    switches at fixed indices, so the loop runs as four 20-round spans
    (16-20 is finished inside the first span's unrolled prefix).

    The window is one stacked (16, *batch) array, not a tuple: under
    ``shard_map`` some message words vary across the mesh axis and some
    are replicated, and rotating a tuple would move a varying value
    into a replicated slot — a carry-type mismatch the stack avoids by
    unifying the axis-varying type at construction."""
    ws = [_u32(m) for m in words]
    # include the STATE shapes: a tail block can be all-constant (the
    # padding/length block of a 2-block tail whose variable bytes all
    # landed in block 0) while the incoming state is batch-shaped —
    # words alone would give shape () and broadcast_to would throw
    shape = jnp.broadcast_shapes(*(jnp.shape(w) for w in ws),
                                 *(jnp.shape(_u32(s)) for s in state))
    st = tuple(_u32(s) for s in state)
    for i in range(16):
        st = _round(st, i, ws[i])

    window = jnp.stack([jnp.broadcast_to(w, shape) for w in ws])
    # varying-typed zero: rows of the stacked window share the JOINT
    # axis-varying type, so adding it unifies each state word's type
    # too (a state word fed only by replicated message words would
    # otherwise flip to varying mid-loop as the rotation mixes them)
    vzero = window[0] & jnp.uint32(0)
    st = tuple(jnp.broadcast_to(s, shape) + vzero for s in st)

    def make_body(group):
        k = jnp.uint32(SHA1_K[group])

        def body(i, carry):
            st, win = carry
            w_new = _rotl(win[13] ^ win[8] ^ win[2] ^ win[0], 1)
            a, b, c, d, e = st
            if group == 0:
                f = (b & c) | (~b & d)
            elif group == 2:
                f = (b & c) | (b & d) | (c & d)
            else:
                f = b ^ c ^ d
            temp = _rotl(a, 5) + f + e + (k + w_new)
            return ((temp, a, _rotl(b, 30), c, d),
                    jnp.concatenate([win[1:], w_new[None]], axis=0))

        return body

    carry = (st, window)
    for group, (lo, hi) in enumerate(((16, 20), (20, 40), (40, 60), (60, 80))):
        carry = lax.fori_loop(lo, hi, make_body(group), carry, unroll=4)
    st, _ = carry
    return tuple(_u32(s0) + s for s0, s in zip(state, st))


@jax.jit
def _sha1_compress_jit(state, words):
    # platform-keyed like sha256: loop on XLA:CPU, unrolled elsewhere
    if jax.default_backend() == "cpu":
        return _compress_loop(state, words)
    return _compress_unrolled(state, words)


def sha1_compress(state, words: Sequence):
    """One SHA-1 block compression, vectorized over broadcastable words."""
    return _sha1_compress_jit(
        tuple(_u32(s) for s in state), tuple(_u32(w) for w in words)
    )


def sha1_digest_words(blocks: Sequence[Sequence]) -> Tuple:
    state = SHA1_INIT
    for words in blocks:
        state = sha1_compress(state, words)
    return state


# ---------------------------------------------------------------------------
# Pure-Python twin (host-side prefix absorption + oracle).
# ---------------------------------------------------------------------------

_MASK = 0xFFFFFFFF


def _py_rotl(x: int, s: int) -> int:
    return ((x << s) | (x >> (32 - s))) & _MASK


def py_compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    assert len(block) == BLOCK_BYTES
    w = list(struct.unpack(">16I", block))
    for i in range(16, 80):
        w.append(_py_rotl(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1))
    a, b, c, d, e = state
    for i in range(80):
        if i < 20:
            f = (b & c) | (~b & d & _MASK)
        elif i < 40:
            f = b ^ c ^ d
        elif i < 60:
            f = (b & c) | (b & d) | (c & d)
        else:
            f = b ^ c ^ d
        temp = (_py_rotl(a, 5) + f + e + SHA1_K[i // 20] + w[i]) & _MASK
        a, b, c, d, e = temp, a, _py_rotl(b, 30), c, d
    out = (a, b, c, d, e)
    return tuple((s0 + s) & _MASK for s0, s in zip(state, out))


def py_absorb(prefix: bytes):
    state = SHA1_INIT
    n_full = len(prefix) // BLOCK_BYTES
    for i in range(n_full):
        state = py_compress(state, prefix[i * BLOCK_BYTES : (i + 1) * BLOCK_BYTES])
    return state, prefix[n_full * BLOCK_BYTES :], n_full * BLOCK_BYTES


def py_digest(message: bytes) -> bytes:
    state, rem, _ = py_absorb(message)
    tail = rem + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % BLOCK_BYTES)
    tail += struct.pack(">Q", len(message) * 8)
    for i in range(0, len(tail), BLOCK_BYTES):
        state = py_compress(state, tail[i : i + BLOCK_BYTES])
    return b"".join(w.to_bytes(4, "big") for w in state)
