"""Pluggable hash-model registry.

SURVEY.md section 0 requires the hash to be "a pluggable kernel and default
to MD5 for behavioral/trace parity" (the reference hard-codes MD5 at
worker.go:5,353; BASELINE.json's north star speaks of SHA-256).  A hash
model bundles everything the packing/search layers need to stay
hash-agnostic.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Callable, Dict, Sequence, Tuple

from . import (
    blake2b_jax,
    md5_jax,
    ripemd160_jax,
    sha1_jax,
    sha3_jax,
    sha256_jax,
    sha256d_jax,
    sha384_jax,
    sha512_jax,
)


@dataclass(frozen=True)
class HashModel:
    name: str
    block_bytes: int
    digest_words: int          # number of uint32 words in the digest
    word_byteorder: str        # how digest words map to digest bytes
    length_byteorder: str      # byte order of the 8-byte bit-length field
    init_state: Tuple[int, ...]
    compress: Callable         # (state, words[16]) -> state, vectorized JAX
    # Pure-Python twin, for host-side absorption.  Contract: takes
    # (state, block) with block of exactly BLOCK_BYTES — except models
    # with block_param_words (blake2b), whose template-shaped blocks
    # widen to BLOCK_BYTES + 4*param_words; their py_compress also
    # accepts a plain BLOCK_BYTES block with an EXPLICIT t= byte
    # counter (required — a defaulted counter would silently chain
    # multi-block inputs wrong; advisor r4 + review r5).
    py_compress: Callable
    py_absorb: Callable        # prefix -> (state, remainder, absorbed_len)
    # Measured compute cost: XLA cost_analysis() op count per hash on
    # the optimized difficulty<=8-nibble serving program (mask-word DCE
    # included) — the method and per-model derivations are documented
    # in bench.py and docs/MODELS.md.  Consumed by the bench's
    # roofline-utilization lines and by the default per-dispatch launch
    # budget (scaled so one launch's wall-clock — the cancellation
    # granularity — is roughly model-independent).  REQUIRED, no
    # default: a new slow model silently inheriting md5's count would
    # reintroduce multi-second launch quantization (review r4).
    cost_ops: int
    # Size of the message-bit-length field in the padding (8 for every
    # 64-byte-block MD hash; 16 for SHA-384/512's 128-bit field).
    length_bytes: int = 8
    # Padding family, consumed by ops/packing.build_tail_spec:
    # "md"   — Merkle-Damgard strengthening: 0x80, zeros, bit-length
    #          field of length_bytes in length_byteorder (all six
    #          original models);
    # "sha3" — the sponge's pad10*1 with the SHA-3 domain bits: 0x06
    #          after the message, 0x80 into the LAST rate byte (the two
    #          merge to 0x86 when adjacent), no length field;
    # "blake2" — nothing at all: the final block is zero-filled and
    #          distinguished solely by the baked parameter words below.
    padding: str = "md"
    # Per-block compression PARAMETERS (beyond state and message):
    # blake2's byte counter and finalization flag.  For a fixed search
    # layout they are compile-time constants, so the packing layer
    # appends ``param_words`` extra uint32 template words to each
    # block's row, produced by ``block_param_words(absorbed_bytes,
    # tail_msg_len, block_idx, n_blocks)``; ``compress`` slices them
    # off the end of its words.  0/None for every hash whose
    # compression is purely (state, message).
    param_words: int = 0
    block_param_words: Callable = None
    # Hash COMPOSITION (sha256d): an optional state -> state stage the
    # search step applies after the last compress and before the
    # difficulty check — e.g. a second full compression over the first
    # digest.  Absorption/packing/partitioning never see it; the
    # difficulty masks and digest serialization apply to the FINALIZED
    # state.  ``py_finalize`` is the pure-Python twin for host oracles.
    finalize: Callable = None
    py_finalize: Callable = None

    @property
    def digest_bytes(self) -> int:
        return self.digest_words * 4

    @property
    def words_per_block(self) -> int:
        return self.block_bytes // 4

    @property
    def max_difficulty(self) -> int:
        """Digest nibble count — difficulties above this are unsatisfiable."""
        return self.digest_bytes * 2

    def hashlib_new(self):
        from . import puzzle

        return puzzle.new_hash(self.name)  # ripemd160 fallback included

    def state_to_digest(self, state: Sequence[int]) -> bytes:
        # truncating models (sha384) carry more state words than digest
        # words; the digest is always the leading digest_words
        return b"".join(
            int(w).to_bytes(4, self.word_byteorder)
            for w in state[: self.digest_words]
        )


MD5 = HashModel(
    name="md5",
    block_bytes=md5_jax.BLOCK_BYTES,
    digest_words=md5_jax.DIGEST_WORDS,
    word_byteorder=md5_jax.WORD_BYTEORDER,
    length_byteorder=md5_jax.LENGTH_BYTEORDER,
    init_state=md5_jax.MD5_INIT,
    compress=md5_jax.md5_compress,
    py_compress=md5_jax.py_compress,
    py_absorb=md5_jax.py_absorb,
    cost_ops=584,  # the launch-budget scale's reference point
)

SHA256 = HashModel(
    name="sha256",
    block_bytes=sha256_jax.BLOCK_BYTES,
    digest_words=sha256_jax.DIGEST_WORDS,
    word_byteorder=sha256_jax.WORD_BYTEORDER,
    length_byteorder=sha256_jax.LENGTH_BYTEORDER,
    init_state=sha256_jax.SHA256_INIT,
    compress=sha256_jax.sha256_compress,
    py_compress=sha256_jax.py_compress,
    py_absorb=sha256_jax.py_absorb,
    cost_ops=2909,
)

SHA1 = HashModel(
    name="sha1",
    block_bytes=sha1_jax.BLOCK_BYTES,
    digest_words=sha1_jax.DIGEST_WORDS,
    word_byteorder=sha1_jax.WORD_BYTEORDER,
    length_byteorder=sha1_jax.LENGTH_BYTEORDER,
    init_state=sha1_jax.SHA1_INIT,
    compress=sha1_jax.sha1_compress,
    py_compress=sha1_jax.py_compress,
    py_absorb=sha1_jax.py_absorb,
    cost_ops=1341,
)

RIPEMD160 = HashModel(
    name="ripemd160",
    block_bytes=ripemd160_jax.BLOCK_BYTES,
    digest_words=ripemd160_jax.DIGEST_WORDS,
    word_byteorder=ripemd160_jax.WORD_BYTEORDER,
    length_byteorder=ripemd160_jax.LENGTH_BYTEORDER,
    init_state=ripemd160_jax.RIPEMD160_INIT,
    compress=ripemd160_jax.ripemd160_compress,
    py_compress=ripemd160_jax.py_compress,
    py_absorb=ripemd160_jax.py_absorb,
    cost_ops=1854,
)

SHA512 = HashModel(
    name="sha512",
    block_bytes=sha512_jax.BLOCK_BYTES,
    digest_words=sha512_jax.DIGEST_WORDS,
    word_byteorder=sha512_jax.WORD_BYTEORDER,
    length_byteorder=sha512_jax.LENGTH_BYTEORDER,
    init_state=sha512_jax.SHA512_INIT,
    compress=sha512_jax.sha512_compress,
    py_compress=sha512_jax.py_compress,
    py_absorb=sha512_jax.py_absorb,
    length_bytes=sha512_jax.LENGTH_BYTES,
    cost_ops=9782,
)

SHA384 = HashModel(
    name="sha384",
    block_bytes=sha384_jax.BLOCK_BYTES,
    digest_words=sha384_jax.DIGEST_WORDS,  # 12 < 16 state words (truncated)
    word_byteorder=sha384_jax.WORD_BYTEORDER,
    length_byteorder=sha384_jax.LENGTH_BYTEORDER,
    init_state=sha384_jax.SHA384_INIT,
    compress=sha384_jax.sha384_compress,
    py_compress=sha384_jax.py_compress,
    py_absorb=sha384_jax.py_absorb,
    length_bytes=sha384_jax.LENGTH_BYTES,
    cost_ops=9782,
)

SHA3_256 = HashModel(
    name="sha3_256",
    block_bytes=sha3_jax.BLOCK_BYTES,      # the RATE (1088 bits)
    digest_words=sha3_jax.DIGEST_WORDS,    # 8 of the 50 carried limbs
    word_byteorder=sha3_jax.WORD_BYTEORDER,
    length_byteorder=sha3_jax.LENGTH_BYTEORDER,  # unused (sponge)
    init_state=sha3_jax.SHA3_INIT,
    compress=sha3_jax.sha3_256_compress,   # sponge absorb: XOR + permute
    py_compress=sha3_jax.py_compress,
    py_absorb=sha3_jax.py_absorb,
    padding="sha3",
    cost_ops=9900,
)

BLAKE2B_256 = HashModel(
    name="blake2b_256",
    block_bytes=blake2b_jax.BLOCK_BYTES,
    digest_words=blake2b_jax.DIGEST_WORDS,  # 8 of the 16 carried limbs
    word_byteorder=blake2b_jax.WORD_BYTEORDER,
    length_byteorder=blake2b_jax.LENGTH_BYTEORDER,  # unused (no field)
    init_state=blake2b_jax.BLAKE2B_INIT,
    compress=blake2b_jax.blake2b_256_compress,
    py_compress=blake2b_jax.py_compress,
    py_absorb=blake2b_jax.py_absorb,
    padding="blake2",                       # zero-fill, no markers
    param_words=blake2b_jax.PARAM_WORDS,    # t (2 limbs) + f0 (2 limbs)
    block_param_words=blake2b_jax.block_param_words,
    # cost_analysis of the unrolled tile at the serving mask bucket
    # (same convention as sha3_256 — no unrolled XLA serving form)
    cost_ops=5205,
)

SHA256D = HashModel(
    name="sha256d",
    block_bytes=sha256d_jax.BLOCK_BYTES,
    digest_words=sha256d_jax.DIGEST_WORDS,
    word_byteorder=sha256d_jax.WORD_BYTEORDER,
    length_byteorder=sha256d_jax.LENGTH_BYTEORDER,
    init_state=sha256d_jax.SHA256_INIT,
    compress=sha256_jax.sha256_compress,   # first stage = plain SHA-256
    py_compress=sha256d_jax.py_compress,
    py_absorb=sha256d_jax.py_absorb,
    finalize=sha256d_jax.sha256d_finalize,  # second stage (composition)
    py_finalize=sha256d_jax.py_finalize,
    # derived from sha256's measured cost_analysis figures (same op
    # counting as every model): first compression at FULL digest (3165
    # — every word feeds stage 2, no DCE) + second compression at the
    # serving mask bucket (2909)
    cost_ops=6074,
)

_REGISTRY: Dict[str, HashModel] = {
    "md5": MD5, "sha256": SHA256, "sha1": SHA1, "ripemd160": RIPEMD160,
    "sha512": SHA512, "sha384": SHA384, "sha3_256": SHA3_256,
    "blake2b_256": BLAKE2B_256, "sha256d": SHA256D,
}


def get_hash_model(name: str) -> HashModel:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown hash model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def register_hash_model(model: HashModel) -> None:
    _REGISTRY[model.name.lower()] = model
