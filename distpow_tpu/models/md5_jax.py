"""MD5 as a vectorized JAX computation over uint32 lanes.

This is the TPU-native replacement for the reference's hot-loop kernel
``md5.Sum`` (worker.go:353).  The reference hashes one candidate at a time
and then *hex-formats the digest per candidate* to count trailing zeros
(worker.go:354-355); here the whole pipeline — message-word construction,
compression, difficulty check — is expressed as elementwise uint32 ops over
large candidate batches, which XLA fuses into a handful of VPU kernels and
``jax.vmap``/``shard_map`` scale across lanes and cores.

Design notes:

* MD5 is byte-oriented but its compression function is pure uint32
  arithmetic (add, and, or, xor, not, rotate).  Only message *packing*
  touches bytes, and in this framework packing is arithmetic too
  (see ``distpow_tpu.ops.packing``), so no byte arrays ever exist on
  device.
* ``md5_compress`` takes the 16 message words as a *list* of arrays that
  need only be broadcast-compatible: constant words are passed as Python
  ints (weakly-typed scalars), variable words as batch-shaped arrays.
  XLA folds the constants into the fused kernel.
* The round loop is unrolled in Python (static, 64 steps) — there is no
  data-dependent control flow, so the whole thing jits to a single fused
  elementwise graph.

A minimal pure-Python implementation (``py_compress``, ``py_absorb``) is
included for host-side prefix absorption (long nonces) and as an
independent oracle; correctness of both is pinned against ``hashlib`` in
tests/test_md5.py.
"""

from __future__ import annotations

import math
import struct
from typing import Sequence, Tuple

import jax.numpy as jnp

MD5_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)

# K[i] = floor(abs(sin(i+1)) * 2^32)
MD5_K = tuple(int(abs(math.sin(i + 1)) * (1 << 32)) & 0xFFFFFFFF for i in range(64))

MD5_S = (
    7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
    5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20,
    4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
    6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
)

BLOCK_BYTES = 64
DIGEST_WORDS = 4
WORD_BYTEORDER = "little"  # digest = b"".join(w.to_bytes(4, "little"))
LENGTH_BYTEORDER = "little"  # 8-byte bit-length field in the final block


def _rotl(x, s: int):
    x = x.astype(jnp.uint32) if hasattr(x, "astype") else jnp.uint32(x)
    return (x << s) | (x >> (32 - s))


def md5_compress(state, words: Sequence):
    """One MD5 block compression, vectorized.

    ``state`` is a 4-tuple of uint32 arrays/scalars; ``words`` is a sequence
    of 16 broadcast-compatible uint32 arrays (or Python ints for constant
    words).  Returns the new 4-tuple state.
    """
    a0, b0, c0, d0 = (jnp.uint32(s) for s in state)
    a, b, c, d = a0, b0, c0, d0
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        m = words[g]
        if not hasattr(m, "dtype"):
            # compile-time constant word: fold the round constant in now
            f = f + a + jnp.uint32((MD5_K[i] + int(m)) & 0xFFFFFFFF)
        elif m.ndim == 0:
            # runtime scalar word (the dynamic serving regime's base-word
            # operands): group (K + m) so it is ONE scalar add hoisted
            # out of the batch dimension instead of two scalar-vector
            # adds — XLA does not reassociate this on its own, and the
            # ungrouped form costs the dynamic regime ~1 vector op in
            # each constant-word round vs the static regime
            f = f + a + (jnp.uint32(MD5_K[i]) + m)
        else:
            f = f + a + jnp.uint32(MD5_K[i]) + m
        a, d, c = d, c, b
        b = b + _rotl(f, MD5_S[i])
    return (a0 + a, b0 + b, c0 + c, d0 + d)


def md5_digest_words(blocks: Sequence[Sequence]) -> Tuple:
    """Digest (4 uint32 word arrays) of a padded message given as a sequence
    of 16-word blocks, starting from the standard init state."""
    state = MD5_INIT
    for words in blocks:
        state = md5_compress(state, words)
    return state


# ---------------------------------------------------------------------------
# Pure-Python twin: host-side prefix absorption + independent oracle.
# ---------------------------------------------------------------------------

_MASK = 0xFFFFFFFF


def py_compress(state: Tuple[int, int, int, int], block: bytes) -> Tuple[int, int, int, int]:
    """Pure-Python MD5 block compression on a 64-byte block."""
    assert len(block) == BLOCK_BYTES
    words = struct.unpack("<16I", block)
    a0, b0, c0, d0 = state
    a, b, c, d = a0, b0, c0, d0
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        f = (f + a + MD5_K[i] + words[g]) & _MASK
        a, d, c = d, c, b
        s = MD5_S[i]
        b = (b + (((f << s) | (f >> (32 - s))) & _MASK)) & _MASK
    return ((a0 + a) & _MASK, (b0 + b) & _MASK, (c0 + c) & _MASK, (d0 + d) & _MASK)


def py_absorb(prefix: bytes) -> Tuple[Tuple[int, int, int, int], bytes, int]:
    """Absorb all complete 64-byte blocks of ``prefix`` into an MD5 state.

    Returns ``(state, remainder_bytes, total_absorbed_len)``.  This lets the
    device kernel handle arbitrarily long constant nonces: the constant
    full blocks are compressed once on the host and only the final (tail)
    block(s), which contain the per-candidate bytes, run on device.
    """
    state = MD5_INIT
    n_full = len(prefix) // BLOCK_BYTES
    for i in range(n_full):
        state = py_compress(state, prefix[i * BLOCK_BYTES : (i + 1) * BLOCK_BYTES])
    return state, prefix[n_full * BLOCK_BYTES :], n_full * BLOCK_BYTES


def py_digest(message: bytes) -> bytes:
    """Full MD5 of ``message`` via the pure-Python compression (oracle)."""
    state, rem, absorbed = py_absorb(message)
    total = len(message)
    tail = rem + b"\x80"
    pad = (-len(tail) - 8) % BLOCK_BYTES
    tail += b"\x00" * pad + struct.pack("<Q", total * 8)
    for i in range(0, len(tail), BLOCK_BYTES):
        state = py_compress(state, tail[i : i + BLOCK_BYTES])
    return b"".join(w.to_bytes(4, "little") for w in state)
