"""RIPEMD-160 as a vectorized JAX computation over uint32 lanes.

Fourth registry model (round 4).  RIPEMD-160 is the classic Merkle-
Damgard sibling the registry abstraction was built for: MD5's exact
block/padding layout (64-byte blocks, little-endian 64-bit bit-length
field, little-endian digest words — worker.go:353's md5.Sum analogue)
with a different compression function, so every layer above the model —
packing (ops/packing.py), difficulty masks, search drivers, backends —
serves it unchanged.  It is also a real-world pick: RIPEMD-160 is the
second hash in Bitcoin's HASH160, so "mine a RIPEMD-160 puzzle" is not a
toy ask.

TPU shape: the compression runs two independent 80-round lines (left /
right) over the same 16 message words; both lines are pure uint32
add/xor/or/and/rot — VPU-native, and their independence gives the
scheduler explicit ILP the single-chain MD5/SHA rounds don't have.  The
whole 160-round graph is unrolled (static; no data-dependent control
flow) and XLA fuses it into one elementwise kernel, same as the other
models.

Spec tables and the pure-Python twin (host-side prefix absorption +
independent oracle + the hashlib fallback shim) live in the jax-free
``ripemd160_py`` and are re-exported here — one copy of the spec data
for this module, the Pallas tile, and puzzle.py's fallback.  Pinned
against ``hashlib.new("ripemd160")`` and the published spec vectors in
tests/test_hash_models.py.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .ripemd160_py import (  # noqa: F401  (shared spec data + py twin)
    BLOCK_BYTES,
    DIGEST_WORDS,
    LENGTH_BYTEORDER,
    RIPEMD160_INIT,
    WORD_BYTEORDER,
    _KL,
    _KR,
    _MASK,
    _RL,
    _RR,
    _SL,
    _SR,
    _f,
    py_absorb,
    py_compress,
    py_digest,
)


def _rotl(x, s: int):
    x = x.astype(jnp.uint32) if hasattr(x, "astype") else jnp.uint32(x)
    return (x << s) | (x >> (32 - s))


def ripemd160_compress(state, words: Sequence):
    """One RIPEMD-160 block compression, vectorized.

    ``state`` is a 5-tuple of uint32 arrays/scalars; ``words`` is a
    sequence of 16 broadcast-compatible uint32 arrays (or Python ints for
    constant words, which XLA folds together with the round constant —
    same convention as md5_jax.md5_compress).
    """
    h = tuple(jnp.uint32(s) for s in state)
    al, bl, cl, dl, el = h
    ar, br, cr, dr, er = h
    for j in range(80):
        # left line: functions in forward group order, constants _KL
        m = words[_RL[j]]
        fl = _f(j, bl, cl, dl) + al
        if not hasattr(m, "dtype"):
            fl = fl + jnp.uint32((_KL[j // 16] + int(m)) & _MASK)
        elif m.ndim == 0:
            fl = fl + (jnp.uint32(_KL[j // 16]) + m)
        else:
            fl = fl + jnp.uint32(_KL[j // 16]) + m
        t = _rotl(fl, _SL[j]) + el
        al, el, dl, cl, bl = el, dl, _rotl(cl, 10), bl, t
        # right line: functions in REVERSE group order, constants _KR
        m = words[_RR[j]]
        fr = _f(79 - j, br, cr, dr) + ar
        if not hasattr(m, "dtype"):
            fr = fr + jnp.uint32((_KR[j // 16] + int(m)) & _MASK)
        elif m.ndim == 0:
            fr = fr + (jnp.uint32(_KR[j // 16]) + m)
        else:
            fr = fr + jnp.uint32(_KR[j // 16]) + m
        t = _rotl(fr, _SR[j]) + er
        ar, er, dr, cr, br = er, dr, _rotl(cr, 10), br, t
    h0, h1, h2, h3, h4 = h
    return (
        h1 + cl + dr,
        h2 + dl + er,
        h3 + el + ar,
        h4 + al + br,
        h0 + bl + cr,
    )
