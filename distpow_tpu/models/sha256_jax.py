"""SHA-256 as a vectorized JAX computation over uint32 lanes.

The reference's kernel is MD5 (worker.go:5,353) but BASELINE.json's
north-star text describes the TPU backend as "a jax.vmap'd SHA-256 kernel";
this framework therefore treats the hash as a *pluggable model*
(``distpow_tpu.models.registry``) with MD5 as the behavioral-parity default
and SHA-256 available for the north-star configuration.

Same interface as ``md5_jax`` (16 broadcastable message words in, state
out), different compilation strategy: SHA-256's rounds are uniform, so they
are expressed as a ``lax.fori_loop`` (partially unrolled) instead of a
fully unrolled graph.  An unrolled SHA-256 triggers an exponential
compile/codegen blowup in XLA:CPU past ~56 rounds (the a/e state words fan
out ~6x per round and the message schedule is a 4-fan-in recursive DAG);
the loop form compiles in ~1s on CPU and maps to compiler-friendly static
control flow on TPU.  MD5 stays unrolled — its round chain is single-use
and fuses into one flat VPU kernel.  Correctness pinned against
``hashlib`` in tests/test_hash_models.py.
"""

from __future__ import annotations

import struct
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SHA256_INIT = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

SHA256_K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)

BLOCK_BYTES = 64
DIGEST_WORDS = 8
WORD_BYTEORDER = "big"
LENGTH_BYTEORDER = "big"


def _u32(x):
    return x if hasattr(x, "dtype") else jnp.uint32(np.uint32(x))


def _rotr(x, s):
    return (x >> s) | (x << (32 - s))


def _k_array():
    # built fresh per trace: caching the array in a module global would
    # leak a tracer when first created inside a jit trace
    return jnp.asarray(np.array(SHA256_K, np.uint32))


def sha256_compress(state, words: Sequence):
    """One SHA-256 block compression, vectorized over broadcastable words.

    Eager calls route through a module-level jit so the two fori_loops
    compile once per shape signature instead of re-tracing per call (the
    loop bodies are closures, which defeat eager fori_loop caching).
    Under an outer jit the nested jit is inlined.
    """
    # pre-convert: python ints above 2^31 would overflow the default int32
    # when parsed as jit arguments
    return _sha256_compress_jit(
        tuple(_u32(s) for s in state), tuple(_u32(w) for w in words)
    )


def _round(st, k_i, w_i):
    a, b, c, d, e, f, g, h = st
    S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    # (k + w) grouped: for constant/scalar message words this is a
    # scalar-unit add hoisted out of the batch dimension (XLA does not
    # reassociate integer adds on its own); for batch words the op count
    # is unchanged
    t1 = h + S1 + ch + (k_i + w_i)
    S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)


def _compress_loop(state, words):
    """fori_loop form: rounds 0-15 unrolled on the RAW words (constant
    message words stay scalars XLA folds); rounds 16-63 carry a rolling
    16-word schedule WINDOW.  Compiles in ~1s everywhere — but on TPU
    the window costs real HBM traffic, so the serving path prefers the
    unrolled form.

    The window is one stacked (16, *batch) array, not a tuple: under
    ``shard_map`` some message words vary across the mesh axis and some
    are replicated, and rotating a tuple would move a varying value
    into a replicated slot — a carry-type mismatch the stack avoids by
    unifying the axis-varying type at construction (the sha1 fix,
    latent here until sha256d's mesh leg hit a layout whose trailing
    window entries were all template constants, r5)."""
    ws = [_u32(m) for m in words]
    # include the STATE shapes: a tail block can be all-constant (the
    # padding/length block of a 2-block tail whose variable bytes all
    # landed in block 0) while the incoming state is batch-shaped —
    # words alone would give shape () and broadcast_to would throw
    shape = jnp.broadcast_shapes(*(jnp.shape(w) for w in ws),
                                 *(jnp.shape(_u32(s)) for s in state))
    st = tuple(_u32(s) for s in state)
    for i in range(16):
        st = _round(st, jnp.uint32(SHA256_K[i]), ws[i])

    K = _k_array()
    window = jnp.stack([jnp.broadcast_to(w, shape) for w in ws])
    # varying-typed zero: the stacked window rows share the JOINT
    # axis-varying type; adding it unifies the state words' types too
    # (a state word fed only by replicated message words would
    # otherwise flip to varying mid-loop as the rotation mixes them)
    vzero = window[0] & jnp.uint32(0)
    st = tuple(jnp.broadcast_to(s, shape) + vzero for s in st)

    def body(i, carry):
        st, win = carry
        w15, w7, w2 = win[1], win[9], win[14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        w_new = win[0] + s0 + w7 + s1
        st = _round(st, K[i], w_new)
        return st, jnp.concatenate([win[1:], w_new[None]], axis=0)

    st, _ = lax.fori_loop(16, 64, body, (st, window), unroll=4)
    return tuple(_u32(s0) + s for s0, s in zip(state, st))


def _compress_unrolled(state, words):
    """Fully unrolled form: the message schedule is a plain Python list,
    so schedule entries fed only by constant words stay SCALARS through
    the recursion and every value flows register-to-register in one
    fused graph — no rolling-window copies.  Measured 4.2x faster than
    the loop form on TPU v5e (1,360 vs 322 MH/s serving-shape batch,
    BENCH_r02) at ~13s compile."""
    w = [_u32(m) for m in words]
    for i in range(16, 64):
        w15, w7, w2 = w[i - 15], w[i - 7], w[i - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        w.append(w[i - 16] + s0 + w7 + s1)
    st = tuple(_u32(s) for s in state)
    for i in range(64):
        st = _round(st, jnp.uint32(SHA256_K[i]), w[i])
    return tuple(_u32(s0) + s for s0, s in zip(state, st))


@jax.jit
def _sha256_compress_jit(state, words):
    # Platform-keyed compilation strategy (the trace runs once per
    # backend): XLA:CPU's codegen blows up exponentially on the unrolled
    # 64-round graph (observed past ~56 rounds), while XLA:TPU compiles
    # it in ~13s and runs it 4.2x faster than the loop form — the
    # fori_loop's rolling window is HBM-traffic-bound on TPU.
    if jax.default_backend() == "cpu":
        return _compress_loop(state, words)
    return _compress_unrolled(state, words)


def sha256_digest_words(blocks: Sequence[Sequence]) -> Tuple:
    state = SHA256_INIT
    for words in blocks:
        state = sha256_compress(state, words)
    return state


# ---------------------------------------------------------------------------
# Pure-Python twin (host-side prefix absorption + oracle).
# ---------------------------------------------------------------------------

_MASK = 0xFFFFFFFF


def _py_rotr(x: int, s: int) -> int:
    return ((x >> s) | (x << (32 - s))) & _MASK


def py_compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    assert len(block) == BLOCK_BYTES
    w = list(struct.unpack(">16I", block))
    for i in range(16, 64):
        s0 = _py_rotr(w[i - 15], 7) ^ _py_rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _py_rotr(w[i - 2], 17) ^ _py_rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & _MASK)
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        S1 = _py_rotr(e, 6) ^ _py_rotr(e, 11) ^ _py_rotr(e, 25)
        ch = (e & f) ^ (~e & g & _MASK)
        t1 = (h + S1 + ch + SHA256_K[i] + w[i]) & _MASK
        S0 = _py_rotr(a, 2) ^ _py_rotr(a, 13) ^ _py_rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (S0 + maj) & _MASK
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _MASK, c, b, a, (t1 + t2) & _MASK
    out = (a, b, c, d, e, f, g, h)
    return tuple((s0 + s) & _MASK for s0, s in zip(state, out))


def py_absorb(prefix: bytes):
    state = SHA256_INIT
    n_full = len(prefix) // BLOCK_BYTES
    for i in range(n_full):
        state = py_compress(state, prefix[i * BLOCK_BYTES : (i + 1) * BLOCK_BYTES])
    return state, prefix[n_full * BLOCK_BYTES :], n_full * BLOCK_BYTES


def py_digest(message: bytes) -> bytes:
    state, rem, _ = py_absorb(message)
    total = len(message)
    tail = rem + b"\x80"
    pad = (-len(tail) - 8) % BLOCK_BYTES
    tail += b"\x00" * pad + struct.pack(">Q", total * 8)
    for i in range(0, len(tail), BLOCK_BYTES):
        state = py_compress(state, tail[i : i + BLOCK_BYTES])
    return b"".join(w.to_bytes(4, "big") for w in state)
