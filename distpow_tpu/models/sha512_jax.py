"""SHA-512 as a vectorized JAX computation over uint32 (hi, lo) pairs.

Fifth registry model (round 4) and the interface-generality proof: the
first model with 128-byte blocks, a 16-byte length field, and 64-bit
words.  A TPU VPU has no native uint64 lanes, so every 64-bit value is
carried as a (hi32, lo32) pair of uint32 lanes and the FIPS 180-4
operations are emulated limb-wise:

* ``add64``: lo-limb add, carry = (sum < either addend) via an unsigned
  compare, hi-limb add + carry — 4 VPU ops per 64-bit add.
* ``rotr64 by n``: two shifts + OR per limb, crossing limbs; n == 32 is
  a free limb swap, n > 32 swaps then rotates by n - 32.  XLA folds the
  constant shift amounts, so a rotation costs 6 ops (vs 3 for a 32-bit
  rotation).
* bitwise ops apply per limb at no overhead.

Everything else — packing (16 uint32 template words per *half* block
row, ``model.words_per_block`` = 32), trailing-nibble difficulty masks
over 16 uint32 digest words, the search drivers, warmup, backends —
consumes the standard uint32-word interface unchanged; only this module
knows the words pair up.  The pure-Python twin and spec constants live
in the jax-free ``sha512_py`` (same split as ripemd160).

The 80-round graph uses the fori_loop window form on EVERY platform —
the r4 hardware probe inverted the sha256-style "unroll for
accelerators" analogy on both axes (unrolled: 1681.7 s compile,
2.4 MH/s; loop: 12.1 s, 13.9 MH/s on the TPU v5e;
docs/artifacts/r4c/sha512_forms.json): the live set (8 x 2 working
limbs + a 16 x 2-limb schedule window) is the largest of the shipped
models and the unrolled form spills catastrophically.  Even the loop
form sits far below the VPU roofline, so a Pallas tile with an
explicit geometry is the known fix (docs/KERNELS.md); until one ships
the pallas backends fall back to this fused step transparently.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .sha512_py import (  # noqa: F401  (shared spec data + py twin)
    BLOCK_BYTES,
    DIGEST_WORDS,
    LENGTH_BYTEORDER,
    LENGTH_BYTES,
    SHA512_INIT,
    SHA512_INIT64,
    SHA512_K64,
    WORD_BYTEORDER,
    py_absorb,
    py_compress,
    py_digest,
)

U32 = jnp.uint32
Pair = Tuple  # (hi, lo) of broadcast-compatible uint32 values


def _u(x):
    return x if hasattr(x, "dtype") else jnp.uint32(int(x) & 0xFFFFFFFF)


def _add64(a: Pair, b: Pair) -> Pair:
    """(hi, lo) + (hi, lo) with carry via an unsigned compare."""
    ah, al = a
    bh, bl = b
    al, bl = _u(al), _u(bl)
    lo = al + bl
    carry = (lo < al).astype(U32) if hasattr(lo, "dtype") else U32(lo < al)
    return _u(ah) + _u(bh) + carry, lo


def _add64_many(*vals: Pair) -> Pair:
    acc = vals[0]
    for v in vals[1:]:
        acc = _add64(acc, v)
    return acc


def _rotr64(x: Pair, n: int) -> Pair:
    hi, lo = _u(x[0]), _u(x[1])
    if n == 0:
        return hi, lo
    if n == 32:
        return lo, hi
    if n > 32:
        hi, lo, n = lo, hi, n - 32
    return (
        (hi >> n) | (lo << (32 - n)),
        (lo >> n) | (hi << (32 - n)),
    )


def _shr64(x: Pair, n: int) -> Pair:
    hi, lo = _u(x[0]), _u(x[1])
    assert 0 < n < 32  # the only shifts SHA-512 needs (7 and 6)
    return hi >> n, (lo >> n) | (hi << (32 - n))


def _xor64(*vals: Pair) -> Pair:
    hi, lo = _u(vals[0][0]), _u(vals[0][1])
    for v in vals[1:]:
        hi = hi ^ _u(v[0])
        lo = lo ^ _u(v[1])
    return hi, lo


def _sigma0(w: Pair) -> Pair:
    return _xor64(_rotr64(w, 1), _rotr64(w, 8), _shr64(w, 7))


def _sigma1(w: Pair) -> Pair:
    return _xor64(_rotr64(w, 19), _rotr64(w, 61), _shr64(w, 6))


def _round64(st, k: Pair, w: Pair):
    """One SHA-512 round on a tuple of 8 (hi, lo) pairs."""
    a, b, c, d, e, f, g, h = st
    S1 = _xor64(_rotr64(e, 14), _rotr64(e, 18), _rotr64(e, 41))
    ch = ((e[0] & f[0]) ^ (~e[0] & g[0]),
          (e[1] & f[1]) ^ (~e[1] & g[1]))
    t1 = _add64_many(h, S1, ch, k, w)
    S0 = _xor64(_rotr64(a, 28), _rotr64(a, 34), _rotr64(a, 39))
    maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
           (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
    return (_add64(t1, _add64(S0, maj)), a, b, c, _add64(d, t1), e, f, g)


def _k_pair(i: int) -> Pair:
    k = SHA512_K64[i]
    return U32((k >> 32) & 0xFFFFFFFF), U32(k & 0xFFFFFFFF)


def _compress_unrolled(state, words):
    """Fully unrolled 80-round form (accelerators): schedule pairs fed
    only by constant words stay scalars XLA folds, and the whole graph
    fuses register-to-register — same rationale as sha256/sha1."""
    w = [(_u(words[2 * i]), _u(words[2 * i + 1])) for i in range(16)]
    for i in range(16, 80):
        w.append(_add64_many(w[i - 16], _sigma0(w[i - 15]), w[i - 7],
                             _sigma1(w[i - 2])))
    hs = [(_u(state[2 * i]), _u(state[2 * i + 1])) for i in range(8)]
    st = tuple(hs)
    for i in range(80):
        st = _round64(st, _k_pair(i), w[i])
    out = []
    for hv, nv in zip(hs, st):
        rh, rl = _add64(hv, nv)
        out.extend((rh, rl))
    return tuple(out)


def _compress_loop(state, words):
    """fori_loop form (XLA:CPU): rounds 0-15 unrolled on the raw word
    pairs, rounds 16-79 carry a rolling window.  The unrolled 80-round
    emulation graph (~2x sha256's width in uint32 ops) hits the same
    XLA:CPU codegen blowup sha256 did — observed >9 min with no result;
    this form compiles in seconds.

    The window is ONE stacked (32, *batch) uint32 array — rows 2i/2i+1
    are word i's (hi, lo) limbs — not a tuple, for the same shard_map
    carry-type reason as sha1_jax._compress_loop (rotating a tuple
    moves an axis-varying value into a replicated slot)."""
    ws = [_u(m) for m in words]
    # include the STATE shapes — same all-constant-block case as
    # sha256_jax._compress_loop (see comment there)
    shape = jnp.broadcast_shapes(*(jnp.shape(w) for w in ws),
                                 *(jnp.shape(_u(s)) for s in state))
    st = tuple(
        (_u(state[2 * i]), _u(state[2 * i + 1])) for i in range(8)
    )
    hs0 = st
    for i in range(16):
        st = _round64(st, _k_pair(i), (ws[2 * i], ws[2 * i + 1]))

    window = jnp.stack([jnp.broadcast_to(w, shape) for w in ws])
    vzero = window[0] & jnp.uint32(0)
    st = tuple(
        (jnp.broadcast_to(p[0], shape) + vzero,
         jnp.broadcast_to(p[1], shape) + vzero)
        for p in st
    )
    # round constants as (80,) hi/lo arrays, built per trace (a module-
    # level jnp array would leak a tracer on first in-jit construction)
    k_hi = jnp.asarray(np.array([k >> 32 for k in SHA512_K64], np.uint32))
    k_lo = jnp.asarray(
        np.array([k & 0xFFFFFFFF for k in SHA512_K64], np.uint32))

    def body(i, carry):
        st, win = carry
        w15 = (win[2], win[3])
        w7 = (win[18], win[19])
        w2 = (win[28], win[29])
        w16 = (win[0], win[1])
        nh, nl = _add64_many(w16, _sigma0(w15), w7, _sigma1(w2))
        st = _round64(st, (k_hi[i], k_lo[i]), (nh, nl))
        return st, jnp.concatenate([win[2:], nh[None], nl[None]], axis=0)

    st, _ = lax.fori_loop(16, 80, body, (st, window), unroll=2)
    out = []
    for hv, nv in zip(hs0, st):
        rh, rl = _add64(hv, nv)
        out.extend((rh, rl))
    return tuple(out)


@jax.jit
def _sha512_compress_jit(state, words):
    # The loop form wins EVERYWHERE, measured, not just on XLA:CPU: the
    # r4 hardware probe (scripts/probe_sha512_forms.py, TPU v5e via
    # tunnel, docs/artifacts/r4c/sha512_forms.json) clocked the
    # unrolled form at 1681.7 s compile / 2.4 MH/s vs the loop form's
    # 12.1 s / 13.9 MH/s — the 160-limb unrolled live set spills so
    # badly that the sha256-style "unroll for accelerators" analogy
    # inverts on both axes.  Keep _compress_unrolled for differential
    # tests; do not serve it.
    return _compress_loop(state, words)


def sha512_compress(state, words: Sequence):
    """One SHA-512 block compression, vectorized.

    ``state`` is 16 uint32 entries ((hi, lo) per 64-bit word); ``words``
    is 32 broadcast-compatible uint32 entries — the 16 message words of
    one 128-byte block as (hi, lo) pairs in order, exactly how the
    packing template serializes big-endian 64-bit words into uint32s.
    Eager calls route through a module-level jit (compile once per shape
    signature); under an outer jit the nested jit is inlined.
    """
    return _sha512_compress_jit(
        tuple(_u(s) for s in state), tuple(_u(w) for w in words)
    )
