"""Core proof-of-work puzzle semantics (pure Python, no JAX).

This module pins the behavioral contract of the reference system before any
performance work happens.  The contract (reference: worker.go:353-356,
worker.go:246-256):

    given ``nonce: bytes`` and ``num_trailing_zeros: int``, find
    ``secret: bytes`` such that the lowercase hex encoding of
    ``md5(nonce + secret)`` ends in at least ``num_trailing_zeros``
    ASCII ``'0'`` characters.

Notes on units: the difficulty counts trailing zero *hex digits* (nibbles,
4 bits each) of the digest, not bits.  A 16-byte MD5 digest has 32 nibbles,
so difficulties above 32 are unsatisfiable.

The secret search-space enumeration contract (reference: worker.go:234-244,
worker.go:301-319):

    secret = bytes([thread_byte]) + chunk

where ``chunk`` starts empty and advances via an append-carry counter
(``next_chunk``), and for each chunk value all of the worker's thread bytes
are tried in ascending order before the chunk advances.  The chunk counter
enumerates exactly the *minimal little-endian byte encodings* of the
integers 0, 1, 2, ... (0 is the empty chunk; value n >= 1 has
``ceil(bit_length(n)/8)`` bytes with a non-zero top byte).  This integer
<-> chunk bijection is what lets the TPU backend map a flat batch index to
a candidate arithmetically, with one kernel launch per chunk width.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Iterator, Optional, Sequence, Tuple

# An MD5 digest has 16 bytes = 32 hex nibbles.
MAX_DIFFICULTY_MD5 = 32


class _DoubleSha256:
    """hashlib-shaped sha256(sha256(.)) — Bitcoin's PoW digest."""

    name = "sha256d"
    digest_size = 32

    def __init__(self, data: bytes = b""):
        self._inner = hashlib.sha256(data)

    def update(self, data: bytes) -> None:
        self._inner.update(data)

    def digest(self) -> bytes:
        return hashlib.sha256(self._inner.digest()).digest()

    def hexdigest(self) -> str:
        return self.digest().hex()

    def copy(self) -> "_DoubleSha256":
        c = _DoubleSha256()
        c._inner = self._inner.copy()
        return c


def new_hash(algo: str):
    """``hashlib.new`` with a pure-Python fallback for ripemd160.

    ripemd160 (round 4's fourth registry model) is the only shipped
    model outside hashlib's guaranteed set: stock OpenSSL 3 builds
    without the legacy provider raise ``unsupported hash type`` for it.
    On such hosts every verification path (and the python parity
    backend) falls back to the spec-vector-pinned pure-Python
    implementation — slower, but correct and always available.  All
    puzzle hashing goes through here so the fallback cannot be
    bypassed.
    """
    if algo == "blake2b_256":
        # a PARAMETERIZED hashlib constructor, not a named algorithm:
        # blake2b's digest size is a compression input (it XORs into
        # h[0]), so ``hashlib.new`` has no name for this variant
        return hashlib.blake2b(digest_size=32)
    if algo == "sha256d":
        # a COMPOSED hash (sha256 of sha256 — Bitcoin's PoW digest):
        # hashlib has no name for it; this thin wrapper keeps the
        # update/digest/hexdigest surface every caller here uses
        return _DoubleSha256()
    try:
        return hashlib.new(algo)
    except ValueError:
        if algo == "ripemd160":
            from .ripemd160_py import Ripemd160

            return Ripemd160()
        raise


def hash_hex(nonce: bytes, secret: bytes, algo: str = "md5") -> str:
    """Lowercase hex digest of ``algo(nonce + secret)`` (worker.go:353-355)."""
    h = new_hash(algo)
    h.update(bytes(nonce) + bytes(secret))
    return h.hexdigest()


def count_trailing_zero_chars(s: str) -> int:
    """Number of trailing ``'0'`` characters of ``s`` (worker.go:246-256)."""
    n = 0
    for ch in reversed(s):
        if ch == "0":
            n += 1
        else:
            break
    return n


def count_trailing_zero_nibbles(digest: bytes) -> int:
    """Trailing zero nibbles of a raw digest.

    Equivalent to ``count_trailing_zero_chars(digest.hex())``: the hex string
    is written most-significant-nibble first per byte, so trailing characters
    are (low nibble of last byte, high nibble of last byte, low nibble of the
    second-to-last byte, ...).
    """
    n = 0
    for b in reversed(digest):
        if b == 0:
            n += 2
            continue
        if b & 0x0F == 0:
            n += 1
        break
    return n


def check_secret(
    nonce: bytes, secret: bytes, num_trailing_zeros: int, algo: str = "md5"
) -> bool:
    """True iff ``secret`` solves the puzzle (worker.go:353-356)."""
    h = new_hash(algo)
    h.update(bytes(nonce) + bytes(secret))
    return count_trailing_zero_nibbles(h.digest()) >= num_trailing_zeros


def next_chunk(chunk: bytearray) -> bytearray:
    """Advance the append-carry chunk counter in place (worker.go:234-244).

    Increments byte 0; a 0xFF byte wraps to 0 and carries into the next byte;
    if every byte wraps, a fresh ``1`` byte is appended (so ``[] -> [1]`` and
    ``[0xFF, 0xFF] -> [0, 0, 1]``).
    """
    for i in range(len(chunk)):
        if chunk[i] == 0xFF:
            chunk[i] = 0
        else:
            chunk[i] += 1
            return chunk
    chunk.append(1)
    return chunk


def chunk_to_int(chunk: bytes) -> int:
    """Little-endian integer value of a chunk."""
    return int.from_bytes(chunk, "little")


def int_to_chunk(n: int) -> bytes:
    """Minimal little-endian encoding of ``n`` (inverse of the counter walk).

    ``0`` maps to the empty chunk; otherwise the top byte is non-zero.
    """
    if n == 0:
        return b""
    return n.to_bytes((n.bit_length() + 7) // 8, "little")


def chunk_width(n: int) -> int:
    """Byte width of the chunk encoding ``int_to_chunk(n)``."""
    return 0 if n == 0 else (n.bit_length() + 7) // 8


def iter_candidates(
    thread_bytes: Sequence[int], start: int = 0
) -> Iterator[Tuple[int, int, bytes]]:
    """Yield ``(chunk_int, thread_byte, secret)`` in reference enumeration
    order: for each chunk value all thread bytes are tried before the chunk
    advances (worker.go:318-399).  ``start`` is the first chunk integer.
    """
    n = start
    while True:
        chunk = int_to_chunk(n)
        for tb in thread_bytes:
            yield n, tb, bytes([tb]) + chunk
        n += 1


def python_search(
    nonce: bytes,
    num_trailing_zeros: int,
    thread_bytes: Sequence[int],
    algo: str = "md5",
    start_chunk: int = 0,
    max_candidates: Optional[int] = None,
    cancel_check: Optional[Callable[[], bool]] = None,
    cancel_poll_interval: int = 4096,
    on_progress: Optional[Callable[[int], None]] = None,
    on_exit: Optional[Callable[[str], None]] = None,
) -> Optional[bytes]:
    """Reference-order brute force over ``iter_candidates`` using hashlib.

    This is the behavioral oracle for every accelerated backend and the
    compute path of the pure-Python worker backend (the analogue of the
    reference's ``miner`` hot loop, worker.go:318-400, minus the
    per-candidate hex formatting cost noted in BASELINE.md).

    Returns the first solving secret, or None if ``max_candidates`` is
    exhausted or ``cancel_check`` fires.  ``on_progress(n)`` is invoked
    with the total candidates hashed before every exit (an injection
    point for callers' accounting; this module stays side-effect-free).
    ``on_exit(reason)`` reports WHY the search returned — ``"found"``,
    ``"cancelled"`` or ``"exhausted"`` — so callers never have to
    re-evaluate ``cancel_check`` after the fact (the condition may have
    changed since the loop observed it, and re-invoking it re-triggers
    its side effects).
    """
    nonce = bytes(nonce)
    tried = 0

    def done(result, reason):
        if on_progress is not None:
            on_progress(tried)
        if on_exit is not None:
            on_exit(reason)
        return result

    for _, _, secret in iter_candidates(thread_bytes, start=start_chunk):
        if cancel_check is not None and tried % cancel_poll_interval == 0:
            if cancel_check():
                return done(None, "cancelled")
        if max_candidates is not None and tried >= max_candidates:
            return done(None, "exhausted")
        tried += 1
        h = new_hash(algo)
        h.update(nonce)
        h.update(secret)
        if count_trailing_zero_nibbles(h.digest()) >= num_trailing_zeros:
            return done(secret, "found")
    return done(None, "exhausted")
