"""SHA3-256 as a vectorized JAX computation over uint32 limb pairs.

Keccak-f[1600] on 25 lanes carried as (lo, hi) uint32 pairs in
little-endian serialization order (sha3_py.py module docstring; note
the limb order is the OPPOSITE of sha512's big-endian hi-first pairs).
``sha3_256_compress(state, words)`` implements the sponge absorb the
generic layers expect of a ``HashModel.compress``: XOR the 34 rate
words into the leading state limbs, then permute.

Form: ``lax.fori_loop`` over the 24 rounds — the per-round structure
(theta / rho+pi / chi / iota) is identical across rounds except the
round constant, which indexes a (24,)-shaped table, so the loop body
compiles once.  The carry is the 50-limb tuple with every limb
broadcast to one common shape up front: a sponge XORs batch-varying
message words into a zero state, leaving mixed scalar/batch limbs that
a fori_loop carry cannot hold (carry shapes must be invariant), and
theta spreads the batch shape everywhere after one round anyway.  No
unrolled form exists: sha512's hardware probe showed XLA's compile on
big unrolled limb graphs is pathological on EVERY backend
(docs/artifacts/r4c/sha512_forms.json), and keccak's ~100-limb live
set is worse — the Pallas tile (ops/md5_pallas.py `_sha3_tile`) is the
TPU serving path, exactly the sha512/sha384 playbook.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .sha3_py import (  # noqa: F401  (shared spec data + py twin)
    BLOCK_BYTES,
    DIGEST_WORDS,
    KECCAK_RC,
    KECCAK_ROT,
    LENGTH_BYTEORDER,
    RATE_LANES,
    SHA3_INIT,
    STATE_WORDS,
    WORD_BYTEORDER,
    py_absorb,
    py_compress,
    py_digest,
)

from .sha512_jax import _u  # same scalar-coercion helper, one home

U32 = jnp.uint32

_RC_LO = tuple(rc & 0xFFFFFFFF for rc in KECCAK_RC)
_RC_HI = tuple((rc >> 32) & 0xFFFFFFFF for rc in KECCAK_RC)


def _rotl64(p, n: int):
    """rotl of a (lo, hi) pair by a STATIC amount."""
    lo, hi = p
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n > 32:
        lo, hi, n = hi, lo, n - 32
    return (
        (lo << n) | (hi >> (32 - n)),
        (hi << n) | (lo >> (32 - n)),
    )


def _xor(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def keccak_f_pairs(lanes):
    """Keccak-f[1600] on 25 (lo, hi) pairs (lane index = x + 5y).

    The loop carry is ONE stacked (50, batch) array, not a tuple of 50:
    under ``shard_map`` some limbs arrive axis-varying (absorbed
    message words) and some replicated (the zero capacity limbs), and
    a tuple carry would change varying-ness across iterations — the
    same carry-type mismatch sha1's rolling window hit
    (models/sha1_jax.py `_compress_loop`); stacking forces one uniform
    varying-ness up front.
    """
    rc_lo = jnp.asarray(_RC_LO, U32)
    rc_hi = jnp.asarray(_RC_HI, U32)

    def round_body(r, st):
        A = [(st[2 * i], st[2 * i + 1]) for i in range(25)]
        C = [
            _xor(_xor(_xor(_xor(A[x], A[x + 5]), A[x + 10]), A[x + 15]),
                 A[x + 20])
            for x in range(5)
        ]
        D = [_xor(C[(x - 1) % 5], _rotl64(C[(x + 1) % 5], 1))
             for x in range(5)]
        A = [_xor(A[i], D[i % 5]) for i in range(25)]
        B = [None] * 25
        for x in range(5):
            for y in range(5):
                B[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64(
                    A[x + 5 * y], KECCAK_ROT[x][y]
                )
        A = [
            (
                B[x + 5 * y][0] ^ (~B[(x + 1) % 5 + 5 * y][0]
                                   & B[(x + 2) % 5 + 5 * y][0]),
                B[x + 5 * y][1] ^ (~B[(x + 1) % 5 + 5 * y][1]
                                   & B[(x + 2) % 5 + 5 * y][1]),
            )
            for y in range(5) for x in range(5)
        ]
        A[0] = (A[0][0] ^ rc_lo[r], A[0][1] ^ rc_hi[r])
        return jnp.stack([limb for pair in A for limb in pair])

    st0 = jnp.stack([limb for pair in lanes for limb in pair])
    out = lax.fori_loop(0, len(KECCAK_RC), round_body, st0)
    return [(out[2 * i], out[2 * i + 1]) for i in range(25)]


@jax.jit
def _sha3_compress_jit(state, words):
    # absorb: XOR the rate words into the leading limbs
    limbs = [_u(state[i]) for i in range(STATE_WORDS)]
    for i in range(2 * RATE_LANES):
        limbs[i] = limbs[i] ^ _u(words[i])
    # one common shape for every limb: fori_loop carries must be
    # shape-invariant, and a sponge state mixes batch-varying absorbed
    # limbs with still-scalar capacity limbs
    limbs = jnp.broadcast_arrays(*limbs)
    lanes = [(limbs[2 * i], limbs[2 * i + 1]) for i in range(25)]
    out = keccak_f_pairs(lanes)
    flat = []
    for lo, hi in out:
        flat.extend((lo, hi))
    return tuple(flat)


def sha3_256_compress(state, words: Sequence):
    """One SHA3-256 sponge absorb step, vectorized.

    ``state`` is 50 uint32 limbs (lo-first per lane); ``words`` is 34
    broadcast-compatible uint32 entries — one 136-byte rate block in
    little-endian serialization order, exactly how the packing template
    serializes it.  Eager calls route through a module-level jit; under
    an outer jit the nested jit is inlined.
    """
    # coerce python ints (e.g. raw template words) BEFORE the jit
    # boundary: a word whose top bit is set (the 0x80 pad byte) would
    # otherwise overflow the default int->int32 argument conversion
    return _sha3_compress_jit(
        tuple(_u(x) for x in state), tuple(_u(x) for x in words)
    )
