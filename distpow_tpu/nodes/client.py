"""Client wrapper binding config + tracer + powlib into a node object
(SURVEY.md section 2 component 2; reference: client.go).

``ChCapacity`` defaults to 10 (client.go:9).  ``initialize`` may only run
once per instance (client.go:44-46); ``mine`` delegates to powlib with
this client's tracer; ``close`` tears down tracer then powlib
(client.go:61-68).
"""

from __future__ import annotations

import queue
from typing import Optional

from ..runtime.config import ClientConfig
from ..runtime.tracing import make_tracer
from .powlib import POW, MineResult


class Client:
    def __init__(self, config: ClientConfig, pow_: Optional[POW] = None, sink=None):
        self.config = config
        self.pow = pow_ or POW()
        self.tracer = None
        self._sink = sink
        self.notify_queue: Optional["queue.Queue[MineResult]"] = None
        self._initialized = False

    def initialize(self) -> "queue.Queue[MineResult]":
        if self._initialized:
            raise RuntimeError("client has been initialized before")
        # coordinator-outage resilience knobs ride the config
        # (nodes/powlib.py module docstring; defaults in ClientConfig).
        # CoordAddrs (>= 2 entries) flips powlib into cluster mode —
        # consistent-hash routing over the coordinator pool
        # (docs/CLUSTER.md); otherwise the single CoordAddr keeps the
        # historical behavior byte-identical.
        # a one-entry pool still names a valid coordinator (powlib
        # collapses it to plain single mode) — falling through to a
        # possibly-empty CoordAddr would discard it (review PR 10)
        pool = list(getattr(self.config, "CoordAddrs", []) or [])
        self.notify_queue = self.pow.initialize(
            pool if pool else self.config.CoordAddr,
            self.config.ChCapacity,
            retries=getattr(self.config, "MineRetries", None),
            backoff_s=getattr(self.config, "MineBackoffS", None),
            backoff_max_s=getattr(self.config, "MineBackoffMaxS", None),
            attempt_timeout_s=getattr(self.config, "MineAttemptTimeoutS", None),
        )
        self.tracer = make_tracer(
            self.config.ClientID,
            self.config.TracerServerAddr,
            self.config.TracerSecret,
            sink=self._sink,
        )
        self._initialized = True
        return self.notify_queue

    def mine(self, nonce: bytes, num_trailing_zeros: int,
             hash_model: Optional[str] = None) -> None:
        """``hash_model`` (optional, docs/SERVING.md): request an
        off-default hash model end to end — powlib tags the Mine, the
        coordinator routes it cache-skipped to model-capable workers.
        None keeps the request wire-identical to every earlier
        version."""
        if not self._initialized:
            raise RuntimeError("client not initialized")
        self.pow.mine(self.tracer, nonce, num_trailing_zeros,
                      hash_model=hash_model)

    def close(self) -> None:
        # powlib first: it joins in-flight mine threads, which may still
        # record actions — the tracer's sink must outlive them
        self.pow.close()
        if self.tracer is not None:
            self.tracer.close()
        self._initialized = False
