from .client import Client
from .coordinator import CoordRPCHandler, Coordinator
from .powlib import POW, MineResult
from .worker import Worker, WorkerRPCHandler

__all__ = [
    "Client", "CoordRPCHandler", "Coordinator",
    "POW", "MineResult", "Worker", "WorkerRPCHandler",
]
