"""Worker node — the compute plane (SURVEY.md section 2 component 4).

Protocol parity with the reference worker (worker.go):

* ``Mine`` RPC (worker.go:169-185): registers a cancellable task *before*
  receiving the token, records ``WorkerMine``, then kicks off the miner
  asynchronously so the RPC returns immediately.
* ``Found`` RPC (worker.go:202-232) doubles as cancellation and cache
  install.  If the task is still active: install the winning secret into
  the worker cache, fire the cancel event, delete the task — the miner
  thread then emits its ``WorkerCancel`` + nil-secret ACK.  If no task is
  active (late re-broadcast, or a repeat ``Found``): record
  ``WorkerCancel`` here, install the cache entry, and ACK directly.
* ``Cancel`` RPC (worker.go:189-198): legacy plain cancellation, kept for
  API parity (the reference coordinator never calls it).
* The miner (worker.go:258-401): consult the dominance cache first; on a
  hit, replay the found-path (result -> wait for cancel -> ``WorkerCancel``
  -> nil ACK).  Otherwise expand the worker's thread-byte partition and
  run the configured compute backend.  The found-path *blocks on the
  cancel event after sending the result* so ``WorkerCancel`` is always the
  trace's final worker action — same ordering discipline the reference
  enforces by blocking on killChan (worker.go:375-379).  A cancelled miner
  sends TWO nil ACKs (worker.go:327-341): one for the in-flight round, one
  consumed by the coordinator's 2N-ack ledger.

Divergence from the reference (documented, SURVEY.md section 7): the
reference polls its cancel channel once per candidate; accelerator
backends poll between batches, so cancellation latency is one batch.

Results leave through a queue drained by a forwarder thread issuing async
``CoordRPCHandler.Result`` calls — the cmd/worker/main.go:27-36 loop.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, Optional, Tuple

from ..backends import get_backend
from ..parallel import partition
from ..runtime import actions as act
from ..runtime.cache import ResultCache
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.config import WorkerConfig
from ..runtime.rpc import RPCClient, RPCServer
from ..runtime.tracing import Tracer, decode_token, encode_token, make_tracer

log = logging.getLogger("distpow.worker")

TaskKey = Tuple[bytes, int, int]  # (nonce, num_trailing_zeros, worker_byte)


def maybe_init_distributed(coordinator: str, num_processes: int,
                           process_id: int) -> None:
    """Join a multi-host JAX cluster (no-op when ``coordinator`` is empty).

    The TPU-native analogue of an NCCL/MPI world bootstrap: XLA's own
    distributed runtime wires the hosts; all subsequent collectives (the
    ``lax.pmin`` found-index reduction, parallel/mesh_search.py) run over
    ICI within a host and DCN across hosts with no NCCL/MPI code.  Must
    run before any backend is built.
    """
    if not coordinator:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "joined jax cluster via %s: process %d/%d, %d global devices",
        coordinator, process_id, num_processes, len(jax.devices()),
    )


def _key(params) -> TaskKey:
    return (bytes(params["nonce"]), int(params["num_trailing_zeros"]),
            int(params["worker_byte"]))


class WorkerRPCHandler:
    """RPC service ``WorkerRPCHandler`` (Mine / Found / Cancel)."""

    def __init__(self, tracer: Tracer, result_queue: "queue.Queue", backend,
                 cache_file: Optional[str] = None):
        self.tracer = tracer
        self.result_queue = result_queue
        self.backend = backend
        self.result_cache = ResultCache(persist_path=cache_file or None)
        self._tasks: Dict[TaskKey, threading.Event] = {}
        self._tasks_lock = threading.Lock()

    # -- task table (worker.go:403-421) -----------------------------------
    def _task_set(self, key: TaskKey, ev: threading.Event) -> None:
        with self._tasks_lock:
            self._tasks[key] = ev

    def _task_pop(self, key: TaskKey) -> Optional[threading.Event]:
        with self._tasks_lock:
            return self._tasks.pop(key, None)

    def _task_get(self, key: TaskKey) -> Optional[threading.Event]:
        with self._tasks_lock:
            return self._tasks.get(key)

    # -- RPCs ---------------------------------------------------------------
    def Mine(self, params) -> dict:
        metrics.inc("worker.mine_rpcs")
        key = _key(params)
        cancel_ev = threading.Event()
        self._task_set(key, cancel_ev)

        trace = self.tracer.receive_token(decode_token(params["token"]))
        trace.record_action(
            act.WorkerMine(
                nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
            )
        )
        threading.Thread(
            target=self._mine,
            args=(key, int(params["worker_bits"]), cancel_ev, trace),
            daemon=True,
        ).start()
        return {}

    def Found(self, params) -> dict:
        metrics.inc("worker.found_rpcs")
        key = _key(params)
        secret = bytes(params["secret"])
        trace = self.tracer.receive_token(decode_token(params["token"]))
        ev = self._task_pop(key)
        if ev is not None:
            self.result_cache.add(key[0], key[1], secret, trace)
            ev.set()
        else:
            # no active task: cache-update-only round (late-result
            # re-broadcast or repeat Found), worker.go:212-230
            trace.record_action(
                act.WorkerCancel(
                    nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
                )
            )
            self.result_cache.add(key[0], key[1], secret, trace)
            self._send_result(key, None, trace)
        return {}

    def Cancel(self, params) -> dict:
        metrics.inc("worker.cancel_rpcs")
        key = _key(params)
        ev = self._task_pop(key)
        if ev is None:
            raise RuntimeError(f"no active task for cancel: {key}")
        ev.set()
        return {}

    def Ping(self, params) -> dict:
        """Liveness probe for the coordinator's failure detector
        (FailurePolicy="reassign"; no reference equivalent — the
        reference has no liveness checking, SURVEY.md section 5)."""
        return {"worker_tasks": len(self._tasks)}

    def Stats(self, params) -> dict:
        """Metrics snapshot (runtime/metrics.py; no reference
        equivalent).  ``python -m distpow_tpu.cli.stats`` prints it."""
        snap = metrics.snapshot()
        snap["role"] = "worker"
        snap["backend"] = type(self.backend).__name__
        snap["active_tasks"] = len(self._tasks)
        snap["cache_entries"] = len(self.result_cache)
        return snap

    # -- miner (worker.go:258-401) -----------------------------------------
    def _send_result(self, key: TaskKey, secret: Optional[bytes], trace) -> None:
        metrics.inc("worker.results_sent")
        self.result_queue.put(
            {
                "nonce": list(key[0]),
                "num_trailing_zeros": key[1],
                "worker_byte": key[2],
                "secret": list(secret) if secret is not None else None,
                "token": encode_token(trace.generate_token()),
            }
        )

    def _finish_found(self, key: TaskKey, secret: bytes, cancel_ev, trace) -> None:
        """Result -> block for Found -> WorkerCancel -> nil ACK ordering."""
        trace.record_action(
            act.WorkerResult(
                nonce=key[0], num_trailing_zeros=key[1],
                worker_byte=key[2], secret=secret,
            )
        )
        self._send_result(key, secret, trace)
        cancel_ev.wait()  # coordinator always sends Found (worker.go:375-379)
        trace.record_action(
            act.WorkerCancel(
                nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
            )
        )
        self._send_result(key, None, trace)

    def _mine(self, key: TaskKey, worker_bits: int, cancel_ev, trace) -> None:
        nonce, ntz, worker_byte = key
        cached = self.result_cache.get(nonce, ntz, trace)
        if cached is not None:
            self._finish_found(key, cached, cancel_ev, trace)
            return

        def cancel_check() -> bool:
            # also stop when a satisfying secret lands in the cache
            # mid-search (a Found for a sibling task, or one this
            # coordinator could no longer deliver to us) — a worker the
            # coordinator abandoned must not burn the device forever
            return (cancel_ev.is_set()
                    or self.result_cache.get(nonce, ntz, None) is not None)

        tbs = partition.thread_bytes(worker_byte, worker_bits)
        secret = self.backend.search(
            nonce, ntz, tbs, cancel_check=cancel_check
        )
        if secret is not None:
            self._finish_found(key, secret, cancel_ev, trace)
            return
        if not cancel_ev.is_set():
            cached = self.result_cache.get(nonce, ntz, None)
            if cached is not None:
                # cache-triggered stop: deliver the cached secret as this
                # task's result so the owning request's protocol still
                # sees a result, never a spurious first-message ACK
                self._finish_found(key, cached, cancel_ev, trace)
                return

        # cancelled mid-search: two nil ACKs (worker.go:320-345)
        trace.record_action(
            act.WorkerCancel(
                nonce=nonce, num_trailing_zeros=ntz, worker_byte=worker_byte
            )
        )
        self._send_result(key, None, trace)
        self._send_result(key, None, trace)


class Worker:
    """Worker process object: RPC server + result forwarder
    (NewWorker/InitializeWorkerRPCs, worker.go:116-165 +
    cmd/worker/main.go:27-36)."""

    def __init__(self, config: WorkerConfig, sink=None):
        self.config = config
        maybe_init_distributed(
            getattr(config, "JaxCoordinator", ""),
            getattr(config, "JaxNumProcesses", 1),
            getattr(config, "JaxProcessId", 0),
        )
        if getattr(config, "CompilationCacheDir", ""):
            # persist XLA compiles across boots (warmup becomes a cache
            # read after the first run on a machine)
            import jax

            jax.config.update(
                "jax_compilation_cache_dir", config.CompilationCacheDir
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        self.tracer = make_tracer(
            config.WorkerID, config.TracerServerAddr, config.TracerSecret,
            sink=sink,
        )
        self.coordinator = RPCClient(config.CoordAddr)
        self.result_queue: "queue.Queue" = queue.Queue()
        backend = get_backend(
            config.Backend,
            hash_model=config.HashModel,
            batch_size=config.BatchSize,
            mesh_devices=config.MeshDevices,
            max_launch=config.MaxLaunchCandidates or None,
        )
        self.handler = WorkerRPCHandler(
            self.tracer, self.result_queue, backend,
            cache_file=getattr(config, "CacheFile", "") or None,
        )
        self.server = RPCServer()
        self.server.register("WorkerRPCHandler", self.handler)
        self.bound_addr: Optional[str] = None
        self._forwarder: Optional[threading.Thread] = None
        self._start_warmup(backend)

    def _start_warmup(self, backend) -> None:
        """Background-compile the layout-keyed search programs at boot so
        the first Mine RPC is pure dispatch (the reference has no compile
        step to hide; XLA does — see WorkerConfig.WarmupNonceLens)."""
        lens = list(self.config.WarmupNonceLens or [])
        widths = list(self.config.WarmupWidths or [])
        if not lens or not widths or not hasattr(backend, "warmup"):
            return

        def warm():
            try:
                backend.warmup(lens, widths)
                log.info("%s: warmup done (%d layouts)",
                         self.config.WorkerID, len(lens) * len(widths))
            except Exception as exc:  # warmup is best-effort
                log.warning("%s: warmup failed: %s", self.config.WorkerID, exc)

        threading.Thread(target=warm, daemon=True).start()

    def initialize_rpcs(self) -> str:
        self.bound_addr = self.server.listen(self.config.ListenAddr)
        self.server.serve_in_background()
        log.info("serving %s RPCs on %s", self.config.WorkerID, self.bound_addr)
        return self.bound_addr

    def start_forwarder(self) -> None:
        def forward():
            while True:
                res = self.result_queue.get()
                if res is None:
                    return
                self.coordinator.go("CoordRPCHandler.Result", res)

        self._forwarder = threading.Thread(target=forward, daemon=True)
        self._forwarder.start()

    def run_forever(self) -> None:
        self.initialize_rpcs()
        self.start_forwarder()
        threading.Event().wait()

    def shutdown(self) -> None:
        self.result_queue.put(None)
        self.server.shutdown()
        self.coordinator.close()
        self.handler.result_cache.close()
        self.tracer.close()
