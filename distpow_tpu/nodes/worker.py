"""Worker node — the compute plane (SURVEY.md section 2 component 4).

Protocol parity with the reference worker (worker.go):

* ``Mine`` RPC (worker.go:169-185): registers a cancellable task *before*
  receiving the token, records ``WorkerMine``, then kicks off the miner
  asynchronously so the RPC returns immediately.
* ``Found`` RPC (worker.go:202-232) doubles as cancellation and cache
  install.  If the task is still active: install the winning secret into
  the worker cache, fire the cancel event, delete the task — the miner
  thread then emits its ``WorkerCancel`` + nil-secret ACK.  If no task is
  active (late re-broadcast, or a repeat ``Found``): record
  ``WorkerCancel`` here, install the cache entry, and ACK directly.
* ``Cancel`` RPC (worker.go:189-198): legacy plain cancellation, kept for
  API parity (the reference coordinator never calls it).
* The miner (worker.go:258-401): consult the dominance cache first; on a
  hit, replay the found-path (result -> wait for cancel -> ``WorkerCancel``
  -> nil ACK).  Otherwise expand the worker's thread-byte partition and
  run the configured compute backend.  The found-path *blocks on the
  cancel event after sending the result* so ``WorkerCancel`` is always the
  trace's final worker action — same ordering discipline the reference
  enforces by blocking on killChan (worker.go:375-379).  A cancelled miner
  sends TWO nil ACKs (worker.go:327-341): one for the in-flight round, one
  consumed by the coordinator's 2N-ack ledger.

Divergence from the reference (documented, SURVEY.md section 7): the
reference polls its cancel channel once per candidate; accelerator
backends poll between batches, so cancellation latency is one batch.

Results leave through a queue drained by a forwarder thread issuing async
``CoordRPCHandler.Result`` calls — the cmd/worker/main.go:27-36 loop.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import Dict, Optional, Tuple

from ..backends import get_backend
from ..parallel import partition
from ..runtime import actions as act
from ..runtime.cache import ResultCache
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.config import WorkerConfig
from ..runtime.health import SENTINELS
from ..runtime.rpc import RPCClient, RPCServer, StatsOnly
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from ..runtime.tracing import Tracer, decode_token, make_tracer, wire_token
from ..runtime.watchdog import WATCHDOG

log = logging.getLogger("distpow.worker")

TaskKey = Tuple[bytes, int, int]  # (nonce, num_trailing_zeros, worker_byte)


def maybe_init_distributed(coordinator: str, num_processes: int,
                           process_id: int) -> None:
    """Join a multi-host JAX cluster (no-op when ``coordinator`` is empty).

    The TPU-native analogue of an NCCL/MPI world bootstrap: XLA's own
    distributed runtime wires the hosts; all subsequent collectives (the
    ``lax.pmin`` found-index reduction, parallel/mesh_search.py) run over
    ICI within a host and DCN across hosts with no NCCL/MPI code.  Must
    run before any backend is built.
    """
    if not coordinator:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "joined jax cluster via %s: process %d/%d, %d global devices",
        coordinator, process_id, num_processes, len(jax.devices()),
    )


def _key(params) -> TaskKey:
    return (bytes(params["nonce"]), int(params["num_trailing_zeros"]),
            int(params["worker_byte"]))


def _backend_model_name(backend) -> str:
    """The hash model a backend was built to serve (every backend
    carries either a ``HashModel`` or its name)."""
    m = getattr(backend, "model", None)
    if m is not None:
        return m.name
    return getattr(backend, "hash_model", "md5")


def _rid_split(rid: str) -> Tuple[str, str]:
    """``(namespace, ordering_body)`` of a round id.

    Pooled coordinators prefix their ring member id
    (``"c1.<epoch><ns>"`` — nodes/coordinator.py new_round_id): the
    issue-order comparison zombie fencing relies on is only meaningful
    WITHIN one coordinator's id stream, so the namespace must be
    split off before any ordering.  Single-coordinator ids have no
    separator and land in the ``""`` namespace — every pre-cluster id
    keeps exactly its old ordering behavior.
    """
    ns, sep, body = rid.rpartition(".")
    return (ns, body) if sep else ("", rid)


def _rid_order(rid: str) -> str:
    """Round-id ordering key, robust to the id-format width change.

    Epoch-prefixed ids (nodes/coordinator.py new_round_id) are 24 hex
    chars; ids minted by the pre-epoch format (or by a coordinator
    running without a CacheFile before the epoch field existed) are the
    bare 16-char time_ns — exactly an epoch-0 id without its prefix.
    Left-padding with zeros makes the two formats compare correctly
    during a mixed-format window (worker outlives a coordinator
    upgrade); plain string comparison would order EVERY new-format id
    before every old-format one.  Callers comparing ids must first
    establish they share a namespace (``_rid_split``).
    """
    return _rid_split(rid)[1].rjust(24, "0")


class TaskRound:
    """One Mine round's cancellation state.

    ``superseded`` distinguishes a protocol cancel (Found/Cancel RPC —
    the miner must emit WorkerCancel + nil ACKs, worker.go:320-345) from
    replacement by a NEWER Mine for the same key: a superseded miner must
    exit silently, because anything it sent would be routed into the new
    round's coordinator queue (keyed by (nonce, ntz) only) and either
    trip the first-message-must-be-a-result protocol check or drain the
    new round's 2N-ack ledger early.

    ``round_id`` is the coordinator's fan-out-round tag (nodes/
    coordinator.py module docstring); it is echoed in every result this
    round sends so the coordinator can drop whatever a zombie does leak
    through the unavoidable check-then-send window.

    ``reply_to`` (coordinator pool, docs/CLUSTER.md): the worker-facing
    address of the coordinator that fanned this round out — shared
    workers route the round's Results back on it instead of the config
    default.  None outside cluster mode.
    """

    __slots__ = ("ev", "superseded", "round_id", "reply_to")

    def __init__(self, round_id=None, reply_to=None):
        self.ev = threading.Event()
        self.superseded = False
        self.round_id = round_id
        self.reply_to = reply_to


class WorkerRPCHandler:
    """RPC service ``WorkerRPCHandler`` (Mine / Found / Cancel)."""

    def __init__(self, tracer: Tracer, result_queue: "queue.Queue", backend,
                 cache_file: Optional[str] = None, scheduler=None):
        self.tracer = tracer
        self.result_queue = result_queue
        self.backend = backend
        # continuous-batching scheduler (sched/engine.py): when set,
        # miner threads submit slots to its shared device loop instead
        # of each owning backend.search — worker.active_searches then
        # stays at the loop's own concurrency (bounded), and the
        # pile-up signal moves to sched.active_slots/run_queue_depth
        self.scheduler = scheduler
        self.result_cache = ResultCache(persist_path=cache_file or None)
        self._tasks: Dict[TaskKey, TaskRound] = {}
        self._tasks_lock = threading.Lock()
        # miner threads currently inside backend.search — the
        # admission-control contention signal (VERDICT r5 weak #4:
        # measure the multi-request pile-up before designing the fix)
        self._active_searches = 0

    def _searches_delta(self, d: int) -> None:
        with self._tasks_lock:
            self._active_searches += d
            # gauge published under the same lock that computed it: two
            # threads publishing outside would let a stale count
            # overwrite a fresher one and stick (review PR 3)
            metrics.gauge("worker.active_searches", self._active_searches)

    # -- task table (worker.go:403-421) -----------------------------------
    # every mutation re-gauges worker.mine_queue_depth, so the reading
    # tracks the LIVE depth, not the high-water mark (review PR 3)
    def _task_set(self, key: TaskKey, round_: TaskRound) -> None:
        with self._tasks_lock:
            stale = self._tasks.get(key)
            if stale is not None:
                # a repeat Mine for a key whose previous round is still
                # running (coordinator retry after reassignment/timeouts):
                # mark the zombie superseded and wake it so it stops
                # burning the device — silently (see TaskRound)
                stale.superseded = True
                stale.ev.set()
            self._tasks[key] = round_
            metrics.gauge("worker.mine_queue_depth", len(self._tasks))

    def _task_pop(self, key: TaskKey) -> Optional[TaskRound]:
        with self._tasks_lock:
            out = self._tasks.pop(key, None)
            metrics.gauge("worker.mine_queue_depth", len(self._tasks))
            return out

    def _task_take(self, key: TaskKey, rid) -> Optional[TaskRound]:
        """Pop the active round for ``key`` given a Found tagged ``rid``.

        Matching round (or a None wildcard on either side): returned to
        the caller for the normal cancel path.  On a mismatch, round ids
        are ordered by issue time (nodes/coordinator.py new_round_id), so
        the worker can tell which side is stale:

        * Found NEWER than the entry: the entry is a zombie from a round
          whose cancel never reached us — pop it and wake it superseded
          (silent unwind) so its miner neither burns the device nor parks
          in ev.wait(), and Ping's liveness count stays honest.
        * Found OLDER than the entry (a delayed cancel from a previous
          round surfacing after a new Mine): the live round must NOT be
          touched — the caller treats the Found as cache-update-only, and
          the live miner stops on its own via the cache-aware cancel
          check, delivering the installed secret as its (current-round)
          result.
        * Found from a DIFFERENT round-id namespace (two pool members
          fanning to this shared worker — docs/CLUSTER.md): the two
          coordinators' clocks and epochs are unrelated, so neither
          "newer" verdict is sound.  Treated like "older": the live
          round is untouched (its own coordinator owes it a matching
          Found), the foreign Found is cache-update-only, and the live
          miner stops via the cache-aware cancel check if the installed
          secret satisfies it.
        """
        with self._tasks_lock:
            cur = self._tasks.get(key)
            if cur is None:
                return None
            if rid is None or cur.round_id is None or cur.round_id == rid:
                del self._tasks[key]
                metrics.gauge("worker.mine_queue_depth", len(self._tasks))
                return cur
            if _rid_split(rid)[0] != _rid_split(cur.round_id)[0]:
                return None
            if _rid_order(rid) > _rid_order(cur.round_id):
                del self._tasks[key]
                metrics.gauge("worker.mine_queue_depth", len(self._tasks))
                cur.superseded = True
                cur.ev.set()
            return None

    def _task_get(self, key: TaskKey) -> Optional[TaskRound]:
        with self._tasks_lock:
            return self._tasks.get(key)

    # -- RPCs ---------------------------------------------------------------
    def _default_model(self) -> str:
        return (self.scheduler.model.name if self.scheduler is not None
                else _backend_model_name(self.backend))

    def Mine(self, params) -> dict:
        metrics.inc("worker.mine_rpcs")
        key = _key(params)
        # optional per-request hash model (docs/SERVING.md mixed-hash
        # serving): requests off the worker's default model need the
        # batching scheduler's registry dispatch — without it the
        # single-model backend cannot honor the request, and failing
        # the RPC here (before the task registers) is the honest reply
        hash_model = params.get("hash_model") or None
        if hash_model is not None and hash_model != self._default_model():
            if self.scheduler is None:
                raise RuntimeError(
                    f"worker serves {self._default_model()!r} and has no "
                    f"batching scheduler for mixed-hash requests "
                    f"(got hash_model={hash_model!r})"
                )
            # validate the model HERE, not in the miner thread: an
            # unknown name (or a never-admitted model, engine._solo)
            # raising inside the daemon thread would produce no result,
            # no acks and no error reply — the caller would wait out
            # its full timeout instead of getting this honest refusal.
            # Lazy imports: registry pulls jax, which a scheduler
            # worker has necessarily already loaded.
            from ..models.registry import get_hash_model
            from ..ops.search_step import XLA_SERVING_COMPILE_IMPRACTICAL
            try:
                model = get_hash_model(hash_model)
            except (KeyError, ValueError) as exc:
                raise RuntimeError(
                    f"unknown hash_model {hash_model!r}"
                ) from exc
            if model.name in XLA_SERVING_COMPILE_IMPRACTICAL:
                raise RuntimeError(
                    f"hash_model {model.name!r} is never admitted to the "
                    f"XLA serving path (XLA_SERVING_COMPILE_IMPRACTICAL): "
                    f"serve it from a worker whose configured backend is "
                    f"its Pallas kernel"
                )
        # capability-weighted rounds (docs/FLEET.md) ship the shard's
        # byte range EXPLICITLY instead of the worker_byte/worker_bits
        # algebra; validate at the RPC so a malformed range is an
        # honest error reply, not a silent miner-thread death
        tb_range = None
        if params.get("tb_count") is not None:
            tb_lo = int(params.get("tb_lo") or 0)
            tb_count = int(params["tb_count"])
            if not (0 <= tb_lo <= 255 and 1 <= tb_count <= 256 - tb_lo):
                raise RuntimeError(
                    f"invalid weighted shard range tb_lo={tb_lo} "
                    f"tb_count={tb_count}"
                )
            tb_range = (tb_lo, tb_count)
        round_ = TaskRound(params.get("round"),
                           reply_to=params.get("coord_addr") or None)
        self._task_set(key, round_)

        trace = self.tracer.receive_token(decode_token(params["token"]))
        trace.record_action(
            act.WorkerMine(
                nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
            )
        )
        threading.Thread(
            target=self._mine,
            args=(key, int(params["worker_bits"]), round_, trace,
                  hash_model, tb_range),
            daemon=True,
        ).start()
        return {}

    def Found(self, params) -> dict:
        metrics.inc("worker.found_rpcs")
        key = _key(params)
        secret = bytes(params["secret"])
        trace = self.tracer.receive_token(decode_token(params["token"]))
        # the dominance cache is single-model (entries satisfy lookups
        # purely by (nonce, ntz)): a secret solving under an off-default
        # hash must never be installed where a default-model lookup
        # could replay it (docs/SERVING.md)
        cacheable = (params.get("hash_model") or None) in (
            None, self._default_model())
        round_ = self._task_take(key, params.get("round"))
        if round_ is not None:
            if cacheable:
                self.result_cache.add(key[0], key[1], secret, trace)
            round_.ev.set()
        else:
            # no active task for this round: cache-update-only round
            # (late-result re-broadcast or repeat Found), worker.go:212-230
            trace.record_action(
                act.WorkerCancel(
                    nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
                )
            )
            if cacheable:
                self.result_cache.add(key[0], key[1], secret, trace)
            self._send_result(key, None, trace, params.get("round"),
                              reply_to=params.get("coord_addr") or None)
        return {}

    def Cancel(self, params) -> dict:
        metrics.inc("worker.cancel_rpcs")
        key = _key(params)
        round_ = self._task_pop(key)
        if round_ is None:
            raise RuntimeError(f"no active task for cancel: {key}")
        round_.ev.set()
        return {}

    def Ping(self, params) -> dict:
        """Liveness probe for the coordinator's failure detector
        (FailurePolicy="reassign"; no reference equivalent — the
        reference has no liveness checking, SURVEY.md section 5)."""
        return {"worker_tasks": len(self._tasks)}

    def Stats(self, params) -> dict:
        """Metrics snapshot (runtime/metrics.py; no reference
        equivalent).  ``python -m distpow_tpu.cli.stats`` prints it."""
        # resource sentinels ride every Stats snapshot (runtime/health.py)
        SENTINELS.sample()
        snap = metrics.snapshot()
        snap["role"] = "worker"
        snap["backend"] = type(self.backend).__name__
        snap["scheduler"] = "batching" if self.scheduler is not None else "off"
        snap["active_tasks"] = len(self._tasks)
        snap["cache_entries"] = len(self.result_cache)
        snap["watchdog_armed"] = WATCHDOG.running
        return snap

    # -- miner (worker.go:258-401) -----------------------------------------
    def _send_result(self, key: TaskKey, secret: Optional[bytes], trace,
                     round_id=None, hash_model: Optional[str] = None,
                     reply_to: Optional[str] = None) -> None:
        metrics.inc("worker.results_sent")
        msg = {
            # bytes fields travel raw: wire v2 ships them verbatim,
            # the JSON codec renders them as the int arrays every
            # earlier version sent (runtime/rpc.py _json_default)
            "nonce": bytes(key[0]),
            "num_trailing_zeros": key[1],
            "worker_byte": key[2],
            "secret": bytes(secret) if secret is not None else None,
            "round": round_id,
            "token": wire_token(trace.generate_token()),
        }
        if hash_model is not None:
            # off-default-model result (docs/SERVING.md): tagged so the
            # coordinator's single-model dominance cache skips it — a
            # replayed off-model secret would fail default-model checks.
            # Absent for default-model results, keeping those frames
            # wire-identical to every earlier version on both codecs.
            msg["hash_model"] = hash_model
        if reply_to is not None:
            # pooled round (docs/CLUSTER.md): the forwarder pops this
            # and delivers to the round's OWN coordinator — the key
            # never reaches the wire, so Result frames stay identical
            msg["coord_addr"] = reply_to
        self.result_queue.put(msg)
        # forwarder backlog: grows when the coordinator is slow/away
        # (qsize is advisory under concurrency — a gauge, not a ledger)
        metrics.gauge("worker.forward_queue_depth", self.result_queue.qsize())

    def _finish_found(self, key: TaskKey, secret: bytes, round_: TaskRound,
                      trace, hash_model: Optional[str] = None) -> None:
        """Result -> block for Found -> WorkerCancel -> nil ACK ordering."""
        trace.record_action(
            act.WorkerResult(
                nonce=key[0], num_trailing_zeros=key[1],
                worker_byte=key[2], secret=secret,
            )
        )
        self._send_result(key, secret, trace, round_.round_id,
                          hash_model=hash_model, reply_to=round_.reply_to)
        round_.ev.wait()  # coordinator always sends Found (worker.go:375-379)
        if round_.superseded:
            # replaced by a newer Mine for this key while waiting: the
            # nil ACK belongs to the new round's miner, not us
            return
        trace.record_action(
            act.WorkerCancel(
                nonce=key[0], num_trailing_zeros=key[1], worker_byte=key[2]
            )
        )
        self._send_result(key, None, trace, round_.round_id,
                          reply_to=round_.reply_to)

    def _mine(self, key: TaskKey, worker_bits: int, round_: TaskRound,
              trace, hash_model=None, tb_range=None) -> None:
        # forensics binding (runtime/spans.py, docs/FORENSICS.md): the
        # miner thread carries its request's trace id so the layers
        # below the RPC surface — the search drivers' launch/poll spans
        # and the scheduler's slot spans — attribute to this Mine
        # without threading ids through every call
        with SPANS.bind(trace.trace_id, self.tracer.identity):
            self._mine_bound(key, worker_bits, round_, trace, hash_model,
                             tb_range)

    def _mine_bound(self, key: TaskKey, worker_bits: int, round_: TaskRound,
                    trace, hash_model=None, tb_range=None) -> None:
        nonce, ntz, worker_byte = key
        t0 = time.monotonic()
        # mixed-hash requests bypass the (single-model) dominance cache
        # entirely: its entries solve under the DEFAULT model, and a
        # replayed default-model secret would fail the requested hash
        off_model = (hash_model is not None
                     and hash_model != self._default_model())
        cached = None if off_model else self.result_cache.get(
            nonce, ntz, trace)
        if cached is not None:
            self._finish_found(key, cached, round_, trace)
            return

        def cancel_check() -> bool:
            # also stop when a satisfying secret lands in the cache
            # mid-search (a Found for a sibling task, or one this
            # coordinator could no longer deliver to us) — a worker the
            # coordinator abandoned must not burn the device forever.
            # satisfies() is the unmetered lookup: this polls every batch
            # and must not pollute the cache.hit/miss protocol counters
            if round_.ev.is_set():
                return True
            return (not off_model
                    and self.result_cache.satisfies(nonce, ntz) is not None)

        if tb_range is not None:
            # weighted shard (docs/FLEET.md): the coordinator already
            # sized this worker's slice by its advertised rate; the
            # contiguous run feeds every backend exactly like an
            # algebra-expanded one
            tbs = list(range(tb_range[0], tb_range[0] + tb_range[1]))
        else:
            tbs = partition.thread_bytes(worker_byte, worker_bits)
        # one "worker.solve" span per REAL device search (cache replays
        # returned above): the per-shard segment forensics attributes a
        # slow round to (docs/FORENSICS.md) — the context-manager form
        # records error outcomes too, so a dead miner thread still
        # leaves its span
        with SPANS.span("worker.solve", shard=worker_byte,
                        model=hash_model or self._default_model()) as sp:
            if self.scheduler is not None:
                # scheduler path: this thread only parks on the slot's
                # completion — the engine's single loop owns the device,
                # so the active_searches pile-up the contention stress
                # test recorded cannot form (docs/SCHEDULER.md).
                # Mixed-hash requests ride the same slot table: the
                # engine packs per-model sub-batches into one launch
                # (docs/SERVING.md)
                secret = self.scheduler.search(
                    nonce, ntz, tbs, cancel_check=cancel_check,
                    hash_model=hash_model,
                )
            else:
                self._searches_delta(+1)
                try:
                    secret = self.backend.search(
                        nonce, ntz, tbs, cancel_check=cancel_check
                    )
                finally:
                    self._searches_delta(-1)
            sp.annotate(outcome="found" if secret is not None
                        else "no-result")
        if round_.superseded:
            # a newer Mine owns this key now; anything we emit would be
            # mis-attributed to its round (see TaskRound) — exit silently
            return
        if secret is not None:
            # a REAL device solve (cache replays return above): this is
            # the worker-side latency distribution of the paper's race.
            # The per-model family feeds the cluster aggregation's
            # per-model breakdown and the per-model SLO objectives
            # (distpow_tpu/obs/, docs/SLO.md) — per-hash performance
            # spread is why serving targets cannot be global.
            solve_s = time.monotonic() - t0
            metrics.observe("worker.solve_s", solve_s,
                            trace_id=trace.trace_id)
            metrics.observe(
                f"worker.solve_s.{hash_model or self._default_model()}",
                solve_s, trace_id=trace.trace_id,
            )
            self._finish_found(key, secret, round_, trace,
                               hash_model=hash_model if off_model else None)
            return
        if round_.ev.is_set():
            # cancelled by a Found/Cancel RPC: Mine receipt -> honored
            # cancellation, the per-worker half of cancel propagation
            metrics.observe("worker.time_to_cancel_s",
                            time.monotonic() - t0)
        else:
            cached = None if off_model else self.result_cache.get(
                nonce, ntz, None)
            if cached is not None:
                # cache-triggered stop.  Our own round's Found is
                # usually microseconds behind the install that stopped
                # us — Found writes the cache BEFORE it fires the
                # cancel event, so a cancel_check can land exactly in
                # that window.  Give the in-flight Found a beat: if it
                # arrives, this is an ordinary cancellation (below),
                # not an abandonment — minting a late result here would
                # cost the coordinator a full Found-rebroadcast round
                # of traffic for a secret it already has.
                if not round_.ev.wait(0.05):
                    # genuinely abandoned (our Found never came):
                    # deliver the cached secret as this task's result
                    # so the owning request's protocol still sees a
                    # result, never a spurious first-message ACK
                    self._finish_found(key, cached, round_, trace)
                    return
                if round_.superseded:
                    # a newer Mine took the key while we waited
                    return
                metrics.observe("worker.time_to_cancel_s",
                                time.monotonic() - t0)

        # cancelled mid-search: two nil ACKs (worker.go:320-345)
        trace.record_action(
            act.WorkerCancel(
                nonce=nonce, num_trailing_zeros=ntz, worker_byte=worker_byte
            )
        )
        self._send_result(key, None, trace, round_.round_id,
                          reply_to=round_.reply_to)
        self._send_result(key, None, trace, round_.round_id,
                          reply_to=round_.reply_to)


class Worker:
    """Worker process object: RPC server + result forwarder
    (NewWorker/InitializeWorkerRPCs, worker.go:116-165 +
    cmd/worker/main.go:27-36)."""

    def __init__(self, config: WorkerConfig, sink=None):
        self.config = config
        self._armed_watchdog = False
        maybe_init_distributed(
            getattr(config, "JaxCoordinator", ""),
            getattr(config, "JaxNumProcesses", 1),
            getattr(config, "JaxProcessId", 0),
        )
        if getattr(config, "CompilationCacheDir", ""):
            # persist XLA compiles across boots (warmup becomes a cache
            # read after the first run on a machine)
            from ..runtime.compile_cache import enable as enable_compile_cache

            enable_compile_cache(config.CompilationCacheDir)
        tdir = getattr(config, "TelemetryDir", "") or ""
        if tdir:
            # flight-recorder journal + dump-on-fault directory
            # (runtime/telemetry.py; off by default — memory-only ring)
            RECORDER.configure(
                journal_path=os.path.join(
                    tdir, f"{config.WorkerID}.telemetry.jsonl"
                ),
                dump_dir=tdir,
            )
        self.tracer = make_tracer(
            config.WorkerID, config.TracerServerAddr, config.TracerSecret,
            sink=sink,
        )
        self.coordinator = RPCClient(config.CoordAddr)
        # distpow: ok bounded-queue -- the forwarder queue must never
        # drop or block the miner: every message is owed to the
        # coordinator's ack ledger (losing one wedges the round), depth
        # is bounded by in-flight rounds x2 in practice, and the
        # backlog is observable (worker.forward_queue_depth gauge)
        self.result_queue: "queue.Queue" = queue.Queue()
        backend = get_backend(
            config.Backend,
            hash_model=config.HashModel,
            batch_size=config.BatchSize,
            mesh_devices=config.MeshDevices,
            max_launch=config.MaxLaunchCandidates or None,
            interpret=getattr(config, "PallasInterpret", False),
            loop=getattr(config, "SearchLoop", "persistent") or "persistent",
        )
        self.scheduler = None
        if (getattr(config, "Scheduler", "off") or "off") == "batching":
            # continuous-batching serving plane (docs/SCHEDULER.md):
            # the engine owns the device; the configured backend stays
            # as the fallback for shapes the packed step can't express
            from ..sched.engine import BatchingScheduler

            self.scheduler = BatchingScheduler(
                hash_model=config.HashModel,
                batch_size=config.BatchSize,
                max_slots=getattr(config, "SchedMaxSlots", 8) or 8,
                fallback=backend,
                extra_models=tuple(
                    getattr(config, "SchedHashModels", ()) or ()),
                lane=getattr(config, "SchedLane", "auto") or "auto",
            )
        self.handler = WorkerRPCHandler(
            self.tracer, self.result_queue, backend,
            cache_file=getattr(config, "CacheFile", "") or None,
            scheduler=self.scheduler,
        )
        self.server = RPCServer()
        self.server.register("WorkerRPCHandler", self.handler)
        # role-agnostic Stats alias for error-free auto-role discovery
        # by the fleet scraper (runtime/rpc.py StatsOnly, docs/SLO.md)
        self.server.register("Node", StatsOnly(self.handler))
        self.bound_addr: Optional[str] = None
        self._forwarder: Optional[threading.Thread] = None
        # per-destination delivery queues for pooled rounds
        # (docs/CLUSTER.md): keyed by the round's stamped reply-to
        # address ("" = the config default); the forwarder demux
        # creates entries, delivery loops drain them
        self._forward_subqueues: Dict[str, "queue.Queue"] = {}
        self._stopping = threading.Event()
        # elastic membership (distpow_tpu/fleet/, docs/FLEET.md):
        # opt-in — a FleetRegister=false worker is a static config
        # entry and behaves byte-identically to every earlier version.
        # The agent is built lazily in start_fleet_agent() because the
        # registration must advertise the REAL bound address.
        self.fleet_agent = None
        self._backend = backend
        self._start_warmup(backend)
        hang_timeout = float(getattr(config, "DeviceHangTimeoutS", 0.0) or 0.0)
        if hang_timeout > 0:
            # a hung accelerator dispatch makes this worker a zombie the
            # coordinator's liveness probes cannot see through; the
            # watchdog converts it into a visible death (and shard
            # reassignment under FailurePolicy="reassign") —
            # runtime/watchdog.py.  Refcounted: in-process multi-worker
            # harnesses share one clock (first timeout wins), and it
            # stops when the last armed worker shuts down.  Armed LAST,
            # after every fallible constructor step INCLUDING
            # _start_warmup (advisor r3: a malformed WarmupNonceLens
            # raising after the acquire would leak the ref forever): an
            # init failure must not leak a ref the matching shutdown()
            # will never release.  The warmup thread racing ahead of the
            # acquire is covered because active() counts even while the
            # watchdog is stopped (watchdog.py active()).
            WATCHDOG.acquire(hang_timeout)
            self._armed_watchdog = True

    def _start_warmup(self, backend) -> None:
        """Background-compile the layout-keyed search programs at boot so
        the first Mine RPC is pure dispatch (the reference has no compile
        step to hide; XLA does — see WorkerConfig.WarmupNonceLens)."""
        lens = list(self.config.WarmupNonceLens or [])
        widths = list(self.config.WarmupWidths or [])
        if not lens or not widths or not hasattr(backend, "warmup"):
            return

        def warm():
            try:
                backend.warmup(lens, widths)
                log.info("%s: warmup done (%d layouts)",
                         self.config.WorkerID, len(lens) * len(widths))
            except Exception as exc:  # warmup is best-effort
                log.warning("%s: warmup failed: %s", self.config.WorkerID, exc)

        threading.Thread(target=warm, daemon=True).start()

    def initialize_rpcs(self) -> str:
        self.bound_addr = self.server.listen(self.config.ListenAddr)
        self.server.serve_in_background()
        log.info("serving %s RPCs on %s", self.config.WorkerID, self.bound_addr)
        return self.bound_addr

    def start_fleet_agent(self) -> None:
        """Join the coordinator's fleet (docs/FLEET.md): self-calibrate,
        register with the capability advertisement, keep the lease via
        heartbeats.  No-op unless ``FleetRegister`` is set — static
        config-file workers are pre-registered permanent leases on the
        coordinator side and must not double-register.  Requires
        ``initialize_rpcs`` (the advertisement carries the bound
        address)."""
        if self.fleet_agent is not None or \
                not getattr(self.config, "FleetRegister", False):
            return
        if self.bound_addr is None:
            raise RuntimeError("initialize_rpcs() before start_fleet_agent()")
        from ..fleet import Capability, FleetAgent, calibrate_mhs

        mhs = float(getattr(self.config, "FleetMHS", 0.0) or 0.0)
        if mhs <= 0:
            # calibrate through the SERVING path: with the batching
            # scheduler on, requests run through its lane planner
            # (sched/lanes.py — mesh/pallas launch lanes), so the
            # advertised rate must be measured through the same facade
            # or a multi-device worker under-advertises by n_dev x
            mhs = calibrate_mhs(
                self.scheduler or self._backend,
                budget_s=float(
                    getattr(self.config, "FleetCalibrationS", 0.2) or 0.0),
            )
        cap = Capability(
            backend=self.config.Backend,
            hash_models=tuple(dict.fromkeys(
                [self.config.HashModel]
                + list(getattr(self.config, "SchedHashModels", ()) or ()))),
            mhs=mhs,
            max_slots=(getattr(self.config, "SchedMaxSlots", 0)
                       if (getattr(self.config, "Scheduler", "off")
                           or "off") == "batching" else 0),
        )
        self.fleet_agent = FleetAgent(
            worker_id=self.config.WorkerID,
            coord_addr=self.config.CoordAddr,
            listen_addr=self.bound_addr,
            capability=cap,
            heartbeat_s=float(
                getattr(self.config, "FleetHeartbeatS", 0.0) or 0.0),
            drain_timeout_s=float(
                getattr(self.config, "FleetDrainTimeoutS", 20.0) or 20.0),
        )
        self.fleet_agent.start()

    def start_forwarder(self) -> None:
        """Drain the result queue into ``CoordRPCHandler.Result`` calls.

        The reference forwarder is fire-and-forget on a connection dialed
        once at boot (cmd/worker/main.go:27-36): a coordinator restart
        silently black-holes every subsequent result.  Here each delivery
        is confirmed (future result with a timeout) and a failure
        re-dials the coordinator with backoff, retrying the SAME message
        — a restarted coordinator receives the result, installs it in
        its (journal-backed) cache, and a client retry completes from
        that cache (VERDICT r1 weak #5).

        Coordinator pool (docs/CLUSTER.md): pooled rounds stamp their
        owner's worker-facing address as ``coord_addr``, and delivery
        runs PER DESTINATION — one delivery loop per coordinator, fed
        by a demux of the shared result queue — so a dead pool member's
        retry backoff can never head-of-line-block results owed to a
        live one (messages to the dead member park on ITS loop alone
        and flow the moment it restarts).  Single-coordinator workers
        see exactly one destination and keep the historical per-message
        behavior.
        """

        def _result_trace_id(res) -> int:
            """Trace id straight out of the message's (self-contained
            JSON) tracing token, WITHOUT a tracer side effect — the
            forwarder must not tick vector clocks."""
            try:
                return int(json.loads(
                    bytes(res.get("token") or b"").decode())["trace_id"])
            except (ValueError, KeyError, TypeError):
                return 0

        def _backlog() -> int:
            # total undelivered results across demux + every
            # destination: the signal the gauge existed for ("grows
            # when the coordinator is slow/away").  The values are
            # SNAPSHOTTED: delivery threads call this while the demux
            # may be inserting a new destination, and iterating the
            # live dict would RuntimeError the delivery thread dead
            # mid-message (review PR 10)
            return self.result_queue.qsize() + sum(
                q.qsize() for q in list(self._forward_subqueues.values()))

        def delivery_loop(src: "queue.Queue", addr: str) -> None:
            """Deliver ``src``'s messages in order to one destination.
            ``addr`` empty = the config-default coordinator (whose
            connection object doubles as the protocol client and is
            re-dialed in place); otherwise a pool member dialed
            lazily."""
            backoff = 0.2
            extra: Optional[RPCClient] = None
            while True:
                res = src.get()
                metrics.gauge("worker.forward_queue_depth", _backlog())
                if res is None:
                    if extra is not None:
                        try:
                            extra.close()
                        except OSError:
                            pass
                    return
                tid = _result_trace_id(res) if SPANS.enabled else 0
                # the delivery clock starts ONCE per message, outside
                # the retry loop: a delivery that burned attempts and
                # backoff against an unreachable coordinator must show
                # its full stall on the timeline, not just the final
                # (fast) successful attempt (review PR 9)
                fwd_ts = time.time()
                fwd_t0 = time.monotonic()
                attempts = 0
                while not self._stopping.is_set():
                    try:
                        attempts += 1
                        if addr:
                            if extra is None:
                                extra = RPCClient(addr)
                            client = extra
                        else:
                            client = self.coordinator
                        client.go(
                            "CoordRPCHandler.Result", res
                        ).result(timeout=10.0)
                        if tid:
                            # the delivery leg of the request timeline:
                            # a delayed/retried Result shows up HERE,
                            # not in worker.solve — exactly the segment
                            # that otherwise hides between two nodes'
                            # clocks (docs/FORENSICS.md)
                            SPANS.record(
                                "worker.result_forward", fwd_ts,
                                time.monotonic() - fwd_t0, trace_id=tid,
                                node=self.config.WorkerID,
                                worker_byte=int(res["worker_byte"]),
                                attempts=attempts,
                                kind=("result" if res.get("secret")
                                      is not None else "ack"),
                            )
                        backoff = 0.2
                        break
                    except Exception as exc:
                        metrics.inc("worker.forward_retries")
                        RECORDER.record(
                            "worker.forward_retry",
                            worker=self.config.WorkerID,
                            queue_depth=_backlog(),
                            error=str(exc),
                        )
                        log.warning(
                            "%s: result delivery to %s failed (%s); "
                            "re-dialing in %.1fs",
                            self.config.WorkerID,
                            addr or self.config.CoordAddr, exc, backoff,
                        )
                        if self._stopping.wait(backoff):
                            return
                        backoff = min(backoff * 2, 5.0)
                        # tear down exactly the connection that failed;
                        # other destinations' loops are independent
                        if addr:
                            if extra is not None:
                                try:
                                    extra.close()
                                except OSError:
                                    pass
                                extra = None
                        else:
                            try:
                                self.coordinator.close()
                            except OSError:
                                pass
                            try:
                                self.coordinator = RPCClient(
                                    self.config.CoordAddr)
                            except OSError:
                                continue

        def destination(addr: str) -> "queue.Queue":
            q = self._forward_subqueues.get(addr)
            if q is None:
                # distpow: ok bounded-queue -- protocol-bounded like
                # the result queue it demuxes: depth is the in-flight
                # rounds x2 owed to ONE coordinator, every message is
                # owed to that coordinator's ack ledger (dropping one
                # wedges its round), and the backlog is observable via
                # worker.forward_queue_depth
                q = self._forward_subqueues[addr] = queue.Queue()
                threading.Thread(
                    target=delivery_loop, args=(q, addr), daemon=True,
                    name=f"forward-{addr or 'default'}",
                ).start()
            return q

        def forward():
            # demux only — never blocks on a destination, so one dead
            # pool member cannot stall the others' deliveries
            while True:
                res = self.result_queue.get()
                if res is None:
                    for q in list(self._forward_subqueues.values()):
                        q.put(None)
                    return
                # pooled rounds stamp their owner's address; popped
                # HERE so the Result frame on the wire stays identical
                reply_to = res.pop("coord_addr", None) or ""
                if reply_to == self.config.CoordAddr:
                    reply_to = ""
                destination(reply_to).put(res)
                metrics.gauge("worker.forward_queue_depth", _backlog())

        self._forwarder = threading.Thread(target=forward, daemon=True)
        self._forwarder.start()

    def run_forever(self, stop: Optional[threading.Event] = None) -> None:
        """Boot the full serving surface and park.  ``stop`` lets a
        signal handler (cli/worker.py) request a graceful teardown —
        fleet drain first, then shutdown; without one this never
        returns (reference parity)."""
        self.initialize_rpcs()
        self.start_forwarder()
        self.start_fleet_agent()
        if stop is None:
            threading.Event().wait()
            return
        stop.wait()
        log.info("%s: stop requested; draining and shutting down",
                 self.config.WorkerID)
        self.shutdown()

    def shutdown(self) -> None:
        try:
            if self.fleet_agent is not None:
                # graceful leave FIRST, while the serving plane is still
                # up: Fleet.Drain blocks (bounded) until this worker's
                # in-flight rounds complete, so a drain mid-round
                # finishes the shard instead of orphaning it
                self.fleet_agent.stop(drain=True)
                self.fleet_agent = None
            self._stopping.set()
            if self.scheduler is not None:
                # first: parked miner threads unblock (their slots
                # finish as cancelled) before the forwarder drains
                self.scheduler.close()
            self.result_queue.put(None)
            self.server.shutdown()
            self.coordinator.close()
            self.handler.result_cache.close()
            self.tracer.close()
        finally:
            if self._armed_watchdog:
                # last armed worker out stops the clock, so it cannot
                # govern unrelated later searches in the process — nor
                # vanish while other armed workers still serve
                # (refcount).  In a finally: a close() failure above
                # must not leak the ref.
                WATCHDOG.release()
                self._armed_watchdog = False
