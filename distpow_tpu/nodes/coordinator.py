"""Coordinator node — the control plane (SURVEY.md section 2 component 3).

Implements the reference's orchestration spine (coordinator.go:139-298):

blocking ``Mine`` RPC:
  1. receive token, record ``CoordinatorMine``;
  2. dominance-cache lookup — on hit record ``CoordinatorSuccess`` and
     reply immediately (coordinator.go:150-166);
  3. on miss, ensure worker connections (dial-retry,
     coordinator.go:169-172,356-368), register a per-task result queue
     (capacity semantics of the 2N-buffered channel,
     coordinator.go:176-177);
  4. fan out ``WorkerRPCHandler.Mine`` to every worker with its partition
     byte (``CoordinatorWorkerMine`` per worker);
  5. block for the first result — first-result-wins;
  6. broadcast ``WorkerRPCHandler.Found`` with the winning secret to every
     worker (``CoordinatorWorkerCancel`` per worker) — cancellation and
     cache-install in one message;
  7. drain the 2N-ack ledger: every worker owes exactly two messages per
     round (finder: result + ACK; cancelled: ACK + ACK); late non-nil
     results are collected (coordinator.go:237-248);
  8. for each late result, re-broadcast ``Found`` (cache convergence) and
     drain N more ACKs (coordinator.go:250-280);
  9. delete the task, record ``CoordinatorSuccess``, reply with a fresh
     token.

``Result`` RPC (coordinator.go:302-320): non-nil secrets are recorded
(``CoordinatorWorkerResult``) and installed into the coordinator cache,
then the payload is routed to the owning task queue.

Documented fixes over the reference (SURVEY.md section 7 "hard parts"):

* late ``Result`` after task deletion: the reference sends on a nil
  channel and leaks the RPC goroutine forever (coordinator.go:318,
  370-374); here the message is logged and dropped.
* duplicate concurrent ``Mine`` for the same (nonce, zeros): the
  reference overwrites the task queue and strands the first request
  (coordinator.go:376-381); here a per-key mutex serializes the miss
  path — the duplicate blocks, then (re-)checks the cache and typically
  returns the first request's result as a hit.
* every fan-out round carries a fresh ``round`` id in its Mine/Found
  RPCs; workers echo it in their Results and the ``Result`` handler
  drops messages whose round doesn't match the live task entry.  The
  reference has no such tag, so a zombie miner from a superseded round
  (coordinator retry, worker falsely declared dead) can contaminate the
  new round's 2N-ack ledger — its queues are keyed by (nonce, zeros)
  only.  Dropped-not-counted closes that race end-to-end, including
  messages already in flight on the wire.

Fan-out concurrency (ISSUE 5; docs/RPC.md "Control-plane concurrency"):
the reference launches one goroutine per worker, and the rebuild used
to execute the same shape as N *sequential* blocking calls — round
start, the cancel storm, and abandoned-worker re-sync all cost
O(N x RTT), and one hung worker head-of-line-blocked the rest for a
full ``_call_timeout``.  ``_assign_shards`` and ``_broadcast_found``
now issue every worker RPC as a concurrent ``RPCClient.go()`` future
before awaiting any reply; under "reassign" the Mine acks are harvested
OFF the round's critical path (``_harvest_inflight``) so dead/hung
workers time out in parallel while live workers already mine, with the
orphan-reassignment and 2N-ack-ledger semantics unchanged — a shard
whose ack fails (or expires) is dropped from the ledgers and re-issued
exactly as a failed blocking call was.  The old serial loops survive
behind ``_serial_fanout`` ($DISTPOW_SERIAL_FANOUT) purely as the
measurable baseline for ``bench.py --control-plane``.
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import threading
import time
import zlib
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional, Tuple

from ..cluster import ClusterService, ClusterState, NotOwnerError, \
    Replicator, ring_from_peers
from ..fleet.membership import (FleetRegistry, FleetService, RoundPlan,
                                WorkerLease)
from ..parallel.partition import worker_bits as partition_worker_bits
from ..runtime import actions as act
from ..runtime.cache import ResultCache
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.config import CoordinatorConfig
from ..runtime.health import SENTINELS
from ..runtime.rpc import (
    RPCClient,
    RPCError,
    RPCServer,
    RPCTransportError,
    StatsOnly,
)
from ..runtime.spans import SPANS, SlowRequestTrigger
from ..runtime.telemetry import RECORDER
from ..runtime.tracing import Tracer, decode_token, make_tracer, wire_token
from ..sched.admission import AdmissionReject
from ..sched.coalesce import Coalescer

log = logging.getLogger("distpow.coordinator")

TaskKey = Tuple[bytes, int]

_last_round_ns = [0]
_round_id_lock = threading.Lock()


def _read_epoch_file(path: str) -> Optional[int]:
    """One epoch replica -> int, or None if missing/unreadable/corrupt.

    Format: ``<epoch> <crc32hex>`` — the checksum catches silent
    corruption (e.g. a truncated "17" parsing as a valid-but-tiny epoch,
    VERDICT r3 weak #6).  Legacy pre-r4 bare-int files are accepted so
    an upgrade doesn't discard the persisted counter.
    """
    try:
        with open(path) as fh:
            raw = fh.read().strip()
    except OSError:
        return None
    try:
        parts = raw.split()
        if len(parts) == 2:
            if zlib.crc32(parts[0].encode()) != int(parts[1], 16):
                raise ValueError("checksum mismatch")
            return int(parts[0])
        # legacy bare-int acceptance is BOUNDED: every pre-checksum epoch
        # was floored by int(time.time()) at write, so a bare value below
        # that scale can only be a checksummed file truncated past its
        # separator (e.g. "1784... crc" torn to "17") — corrupt, not
        # legacy (review r4: unbounded int(raw) silently re-admitted the
        # truncation class the checksum exists to catch)
        val = int(raw or "0")
        if val < 1_000_000_000:  # 2001-09-09; far below any real epoch
            raise ValueError(f"bare epoch {val} below the wall-clock "
                             f"floor every legacy write had")
        return val
    except ValueError as exc:
        log.warning("restart-epoch replica %s corrupt (%s): ignoring it",
                    path, exc)
        return None


def _write_epoch_file(path: str, epoch: int) -> None:
    body = f"{epoch} {zlib.crc32(str(epoch).encode()):08x}"
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        fh.write(body)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_restart_epoch(path: Optional[str]) -> int:
    """Read-increment-persist the coordinator's boot counter.

    Stored next to the cache journal (``<CacheFile>.epoch`` plus a
    ``.epoch.bak`` replica) so round-id ordering survives coordinator
    restarts REGARDLESS of wall-clock behavior (VERDICT r2 weak #6:
    ordering by wall clock alone inverts if NTP steps the clock back
    further than the restart downtime, and a zombie round then
    out-orders the live one at the worker).  No path (no CacheFile
    configured) -> epoch 0, the pure wall-clock ordering.

    Durability (VERDICT r3 item 9): each replica is checksummed
    (``_read_epoch_file``), writes are atomic, and recovery takes the
    max over both replicas — so one lost/corrupt file costs nothing,
    and corruption is *detected*, never silently parsed.  The next
    epoch is ``max(persisted + 1, unix seconds)``: the wall-clock floor
    means losing BOTH replicas still cannot regress the epoch below
    previously-issued ids (those were themselves floored by an earlier
    ``time()``); only the triple fault of both replicas lost AND a
    backward clock step reintroduces the pre-epoch behavior, and that
    is logged loudly.
    """
    if not path:
        return 0
    replicas = (path, f"{path}.bak")
    vals = [v for v in (_read_epoch_file(p) for p in replicas)
            if v is not None]
    prev = max(vals, default=None)
    if prev is None and any(os.path.exists(p) for p in replicas):
        log.warning(
            "restart-epoch file %s unreadable in every replica: falling "
            "back to the wall-clock floor; round ordering vs pre-crash "
            "rounds now rides the clock", path,
        )
    epoch = max((prev or 0) + 1, int(time.time()))
    # primary first; the replica only after the primary landed, so a
    # crash between the two leaves at least one good copy of SOME epoch
    _write_epoch_file(path, epoch)
    _write_epoch_file(f"{path}.bak", epoch)
    return epoch


def new_round_id(epoch: int = 0, namespace: str = "") -> str:
    """Fan-out-round id: fixed-width hex, LEXICOGRAPHICALLY ordered by
    issue order.  Workers rely on the order to resolve a round mismatch:
    a Found tagged newer than the task-table entry proves the entry is a
    zombie, while an older Found is itself stale — random ids (uuid)
    cannot make that call and either choice then kills a live round or
    leaks a zombie.

    Ordering guarantee: the leading ``epoch`` (a persisted boot counter,
    ``load_restart_epoch``) strictly orders ids across coordinator
    restarts; within a process ``max(time_ns, last+1)`` is strictly
    monotonic even if the wall clock steps backward (NTP).  Coordinators
    without a CacheFile run at epoch 0 — there ordering across restarts
    degrades to wall clock (restarts are seconds apart, so only a
    backward step larger than the downtime could invert it).

    ``namespace`` (the coordinator pool — docs/CLUSTER.md): a pooled
    coordinator prefixes its ring member id (``"c1."``), because
    issue-order comparison is only meaningful WITHIN one coordinator's
    id stream — two pool members' clocks and epochs are unrelated, so
    a worker receiving rounds from both must never let one member's id
    fence the other's (nodes/worker.py ``_rid_split``).  Empty (single
    coordinator) keeps the id byte-identical to every earlier version.
    """
    with _round_id_lock:
        ns = max(time.time_ns(), _last_round_ns[0] + 1)
        _last_round_ns[0] = ns
    rid = f"{epoch:08x}{ns:016x}"
    return f"{namespace}.{rid}" if namespace else rid


class WorkerRef:
    def __init__(self, addr: str, worker_byte: int):
        self.addr = addr
        self.worker_byte = worker_byte
        self.client: Optional[RPCClient] = None
        # membership state (distpow_tpu/fleet/): static config workers
        # get a permanent lease at registry construction; elastic
        # workers a heartbeat lease at Fleet.Register
        self.lease: Optional[WorkerLease] = None
        self.inflight_rounds: int = 0


class CoordRPCHandler:
    """RPC service ``CoordRPCHandler`` (Mine / Result)."""

    def __init__(self, tracer: Tracer, worker_addrs: List[str],
                 dial_retry_interval: float = 0.2,
                 cache_file: Optional[str] = None,
                 failure_policy: str = "error",
                 failure_probe_secs: float = 1.0,
                 sched_max_inflight: int = 0,
                 sched_retry_after_s: float = 0.5,
                 sched_coalesce: bool = True,
                 lease_ttl_s: float = 10.0,
                 hedge: bool = True,
                 hedge_multiple: float = 3.0,
                 forensics_slow_s: float = 0.0,
                 forensics_p99x: float = 0.0):
        self.tracer = tracer
        self.workers = [WorkerRef(a, i) for i, a in enumerate(worker_addrs)]
        # floor(log2(N)) with the reference's uint truncation
        # (coordinator.go:326); see parallel/partition.py for the
        # non-power-of-two coverage discussion.  A coordinator may now
        # boot with ZERO static workers (pure-elastic fleet): the
        # per-round plan recomputes this from the live member count.
        self.worker_bits = (partition_worker_bits(len(worker_addrs))
                            if worker_addrs else 0)
        # lease-based membership plane (distpow_tpu/fleet/,
        # docs/FLEET.md): owns self.workers (static refs become
        # permanent leases; Fleet.Register appends heartbeat leases),
        # retires expired leases through _mark_dead so a vanished
        # worker rides the same orphan-reassignment path a crashed one
        # does, and plans each round's (possibly capability-weighted)
        # shard layout
        self.fleet = FleetRegistry(
            self.workers, lease_ttl_s=lease_ttl_s, hedge=hedge,
            hedge_multiple=hedge_multiple, on_expire=self._mark_dead,
            make_ref=WorkerRef,
        )
        self.result_cache = ResultCache(persist_path=cache_file or None)
        # persisted boot counter prefixing round ids: zombie-vs-live round
        # resolution at workers survives backward clock steps across
        # restarts (load_restart_epoch; VERDICT r2 weak #6)
        self.restart_epoch = load_restart_epoch(
            f"{cache_file}.epoch" if cache_file else None
        )
        if failure_policy not in ("error", "reassign"):
            raise ValueError(f"unknown FailurePolicy {failure_policy!r}")
        self.failure_policy = failure_policy
        self.failure_probe_secs = failure_probe_secs
        # reassign mode bounds every worker RPC, so a hung-but-connected
        # worker is detected like a crashed one; error mode keeps the
        # reference's unbounded blocking calls
        self._call_timeout = 10.0 if failure_policy == "reassign" else None
        # key -> (round_id, queue); the round id tags one fan-out round's
        # RPCs so Result can drop stale messages (module docstring)
        self._tasks: Dict[TaskKey, Tuple[str, "queue.Queue"]] = {}
        self._tasks_lock = threading.Lock()
        self._key_locks: Dict[TaskKey, list] = {}
        self._dial_retry_interval = dial_retry_interval
        # scheduler plane (docs/SCHEDULER.md): in-flight coalescing of
        # identical keys + bounded-run-queue admission control.  The
        # admitted count is a reservation counter under _tasks_lock —
        # counting len(_tasks) instead would let concurrent leaders all
        # pass the check before any of them registers its task
        self._coalescer = Coalescer() if sched_coalesce else None
        self._sched_max_inflight = int(sched_max_inflight or 0)
        self._sched_retry_after_s = float(sched_retry_after_s)
        self._sched_inflight = 0
        # serial-baseline knob (module docstring): restores the
        # one-blocking-call-per-worker fan-out so bench.py
        # --control-plane can measure the parallel win as a number
        self._serial_fanout = os.environ.get("DISTPOW_SERIAL_FANOUT") == "1"
        # slow-request auto-capture (runtime/spans.py, docs/FORENSICS.md):
        # a completed miss past the fixed budget — or past the rolling
        # p99 exceedance — snapshots its span tree into the flight
        # recorder, so the forensic evidence exists by construction
        self._slow_trigger = SlowRequestTrigger(
            threshold_s=forensics_slow_s, p99_factor=forensics_p99x,
        )
        # coordinator pool membership (distpow_tpu/cluster/,
        # docs/CLUSTER.md): None = single coordinator, every code path
        # byte-identical to before.  Installed by set_cluster(); the
        # Mine handler then redirects misrouted keys (NOT_OWNER), round
        # ids gain this member's namespace, and Mine/Found frames carry
        # the reply-to address shared workers route Results back on.
        self.cluster: Optional[ClusterState] = None
        #: pool-mode replication engine (cluster/replication.py): every
        #: accepted cache install is offered for write-behind push to
        #: the key's ring successors.  None in single-coordinator mode
        #: — the Result path then runs byte-identical to before.
        self.replicator = None
        #: this coordinator's WORKER-facing address, stamped into
        #: cluster-mode Mine/Found params as ``coord_addr`` (set by
        #: Coordinator.initialize_rpcs once the listener is bound)
        self.reply_addr: str = ""

    def set_cluster(self, state: ClusterState) -> None:
        self.cluster = state

    # -- task table (coordinator.go:370-388) -------------------------------
    def _task_set(self, key: TaskKey, rid: str, q: "queue.Queue") -> None:
        with self._tasks_lock:
            self._tasks[key] = (rid, q)

    def _task_get(self, key: TaskKey) -> Optional[Tuple[str, "queue.Queue"]]:
        with self._tasks_lock:
            return self._tasks.get(key)

    def _task_delete(self, key: TaskKey) -> None:
        with self._tasks_lock:
            self._tasks.pop(key, None)

    @contextlib.contextmanager
    def _key_lock(self, key: TaskKey):
        """Hold the per-(nonce, zeros) mutex; entries are refcounted and
        pruned when the last waiter releases, so arbitrary client nonces
        can't grow the map without bound."""
        with self._tasks_lock:
            entry = self._key_locks.get(key)
            if entry is None:
                entry = self._key_locks[key] = [threading.Lock(), 0]
            entry[1] += 1
        try:
            with entry[0]:
                yield
        finally:
            with self._tasks_lock:
                entry[1] -= 1
                if entry[1] == 0 and self._key_locks.get(key) is entry:
                    del self._key_locks[key]

    # -- worker connections (coordinator.go:356-368) ------------------------
    def _initialize_workers(self) -> None:
        """Dial-retry until all workers reachable (reference parity).

        Under FailurePolicy="reassign" a permanently dead worker must not
        wedge every future request, so each missing worker gets one dial
        attempt and the protocol proceeds with the live subset.
        """
        reassign = self.failure_policy == "reassign"
        while True:
            # snapshot: Fleet.Register may append members concurrently;
            # draining/retired members are not (re-)dialed — they keep
            # whatever connection their in-flight rounds already hold
            pending = [w for w in list(self.workers)
                       if w.client is None and self.fleet.in_service(w)]
            if not pending:
                return
            for w in pending:
                try:
                    # reassign mode: a short connect timeout so one
                    # blackholed address can't stall every request for
                    # the 10s default
                    w.client = RPCClient(
                        w.addr, timeout=2.0 if reassign else 10.0
                    )
                except OSError as exc:
                    if reassign:
                        log.warning("worker %d unreachable: %s",
                                    w.worker_byte, exc)
                        continue
                    log.info("waiting for worker %d: %s", w.worker_byte, exc)
                    time.sleep(self._dial_retry_interval)
                    break
            else:
                return

    def _mark_dead(self, w: WorkerRef) -> None:
        """Drop a failed connection; the next request re-dials
        (recovered workers rejoin automatically)."""
        if w.client is not None:
            try:
                w.client.close()
            except OSError:
                pass
            w.client = None

    def _probe_dead(self, refs) -> List[WorkerRef]:
        """Liveness-check distinct workers; returns the dead ones."""
        dead = []
        for ref in {id(w): w for w in refs}.values():
            try:
                if ref.client is None:
                    raise OSError("not connected")
                # a hung worker counts as dead: bounded probe.
                # concurrent.futures.TimeoutError is caught explicitly —
                # it only aliases the OSError-derived builtin on 3.11+.
                # distpow: ok serial-rpc-fanout -- deliberately serial:
                # probes run only while the round is already parked in
                # results.get, each is bounded to 2 s, and serializing
                # them keeps the failure detector from stampeding a
                # cluster that is slow precisely because it is loaded
                ref.client.call("WorkerRPCHandler.Ping", {}, timeout=2.0)
            except (OSError, RPCError, RuntimeError, FutureTimeout) as exc:
                log.warning("worker %d failed probe: %s", ref.worker_byte, exc)
                self._mark_dead(ref)
                dead.append(ref)
        return dead

    def _reap_dead(self, tasks, ledgers):
        """Probe and prune dead workers' tasks; drops their entries from
        the given ledgers.  Returns (surviving_tasks, orphaned_shards)."""
        dead = self._probe_dead([w for w, _ in tasks])
        if not dead:
            return tasks, []
        dead_ids = {id(w) for w in dead}
        orphans = [s for w, s in tasks if id(w) in dead_ids]
        for ledger in ledgers:
            for s in orphans:
                ledger.pop(s, None)
        return [(w, s) for w, s in tasks if id(w) not in dead_ids], orphans

    def _issue_shards(self, trace, nonce: bytes, ntz: int, tasks, shards,
                      rid: str, model: Optional[str] = None,
                      plan: Optional[RoundPlan] = None):
        """Place each shard on some live worker; shards that cannot be
        placed right now stay pending for the next probe round (coverage
        is never silently dropped).  The plan supplies each shard's
        weighted byte range, so a reassigned shard covers the SAME
        space on its new owner."""
        pending: List[int] = []
        for i, shard in enumerate(shards):
            placed = False
            w = None
            while not placed:
                live = list({id(x): x for x, _ in tasks}.values())
                candidates = [x for x in live if x.client is not None]
                if not candidates:
                    break
                w = candidates[i % len(candidates)]
                placed = self._send_mine(trace, nonce, ntz, w, shard, rid,
                                         model, plan)
                # a failed send marked w dead; retry the rest
            if placed:
                tasks.append((w, shard))
                if w.worker_byte != shard:
                    metrics.inc("coord.reassigned_shards")
                    # reassignment marker on the request timeline: which
                    # shard moved where (docs/FORENSICS.md)
                    SPANS.event("coord.reassign", trace_id=trace.trace_id,
                                node=self.tracer.identity, round=rid,
                                shard=shard, to_byte=w.worker_byte)
            else:
                pending.append(shard)
        return tasks, pending

    # -- RPCs ---------------------------------------------------------------
    def Mine(self, params) -> dict:
        """Span-wrapped Mine (docs/FORENSICS.md): the whole RPC is one
        ``coord.mine`` span — path (hit/miss/coalesced-hit) and error
        outcomes included — keyed by the trace id the client's token
        already carries, so the forensics plane stitches this node's
        view into the request timeline with no new protocol state."""
        t0 = time.monotonic()
        ts0 = time.time()
        info: dict = {"path": "miss"}
        try:
            return self._mine_rpc(params, t0, info)
        except BaseException as exc:
            info.setdefault("outcome", f"error:{type(exc).__name__}")
            raise
        finally:
            tid = info.pop("trace_id", 0)
            if tid:
                SPANS.record("coord.mine", ts0, time.monotonic() - t0,
                             trace_id=tid, node=self.tracer.identity,
                             **info)

    def _mine_rpc(self, params, t0: float, info: dict) -> dict:
        metrics.inc("coord.mine_rpcs")
        nonce = bytes(params["nonce"])
        ntz = int(params["num_trailing_zeros"])
        # off-default hash model (docs/SERVING.md): forwarded to the
        # workers (whose Mine validates it against their serving set)
        # and excluded from the SINGLE-MODEL dominance cache on both
        # lookup and install — a cached default-model secret replayed
        # against another hash would fail verification.  None/"" keeps
        # every frame and every code path identical to plain traffic.
        model = params.get("hash_model") or None
        cl = self.cluster
        if cl is not None and not cl.owns(nonce):
            if params.get("no_redirect"):
                # a hedged sibling retry or a failover send (powlib,
                # docs/CLUSTER.md): serve the foreign key — the shared
                # worker fleet makes it correct; only the dominance
                # cache's locality pays
                metrics.inc("cluster.foreign_mines")
            else:
                # misrouted (stale client ring): the typed redirect
                # carries a fresh snapshot so the client re-routes in
                # one round trip.  Deliberately BEFORE the token is
                # received: the redirecting coordinator is not serving
                # this request, so it must not inject CoordinatorMine
                # into the trace the real owner will complete.
                owner = cl.ring.owner(nonce)
                metrics.inc("cluster.not_owner_redirects")
                RECORDER.record("cluster.not_owner", nonce=nonce.hex(),
                                ntz=ntz, owner=owner,
                                self_id=cl.self_id)
                info["outcome"] = "not_owner"
                raise NotOwnerError(owner, cl.ring.to_wire())
        trace = self.tracer.receive_token(decode_token(params["token"]))
        trace.record_action(
            act.CoordinatorMine(nonce=nonce, num_trailing_zeros=ntz)
        )
        tid = trace.trace_id
        info["trace_id"] = tid
        info["ntz"] = ntz

        cached = None if model else self.result_cache.get(nonce, ntz, trace)
        if cached is not None:
            info["path"] = "hit"
            metrics.observe("coord.mine_s.hit", time.monotonic() - t0,
                            trace_id=tid)
            return self._success_reply(trace, nonce, ntz, cached)

        key = (nonce, ntz)
        # attempts bound the waiter->leader promotion loop below; under
        # normal operation one pass suffices (the loop only re-enters
        # when a leader vanished without either a result or an error)
        for _ in range(4):
            handle = self._coalescer.join(key) if self._coalescer else None
            if handle is not None and not handle.leader:
                # in-flight coalescing (sched/coalesce.py): attach to
                # the live round as a waiter — one fan-out, N replies
                metrics.inc("sched.coalesced_requests")
                handle.wait()
                # an off-model waiter cannot be served from the leader's
                # (default-model) cache entry: skip the lookup and lead
                # its own round on the next pass.  The coalescer stays
                # keyed by (nonce, ntz) alone, so different-model
                # duplicates SERIALIZE rather than share a result.
                cached = None if model else self.result_cache.get(
                    nonce, ntz, trace)
                if cached is not None:
                    # same split rule as the key-lock era: a duplicate
                    # that waited out the leader's round is a hit
                    info["path"] = "hit"
                    info["coalesced"] = True
                    metrics.observe("coord.mine_s.hit",
                                    time.monotonic() - t0, trace_id=tid)
                    return self._success_reply(trace, nonce, ntz, cached)
                err = handle.error()
                if err is not None:
                    # the leader's typed failure applies to the whole
                    # round — fresh instances, so concurrent waiters
                    # never share one exception's traceback
                    if isinstance(err, AdmissionReject):
                        raise AdmissionReject(
                            err.retry_after_s, "coalesced round rejected"
                        )
                    raise RuntimeError(f"coalesced mine failed: {err}")
                continue  # leader vanished resultless: try leading
            err2: Optional[BaseException] = None
            try:
                # serialize concurrent identical requests (documented
                # fix; with coalescing on, only round leaders ever
                # contend here)
                with self._key_lock(key):
                    # distpow: ok transitive-blocking-under-lock -- the
                    # per-key lock exists precisely to serialize the
                    # whole miss path for one (nonce, ntz): concurrent
                    # identical requests MUST wait for the leader's
                    # result; other keys use other locks, so fanout
                    # stays concurrent across keys
                    cached = None if model else self.result_cache.get(
                        nonce, ntz, trace)
                    if cached is not None:
                        info["path"] = "hit"
                        metrics.observe("coord.mine_s.hit",
                                        time.monotonic() - t0,
                                        trace_id=tid)
                        # distpow: ok transitive-blocking-under-lock -- same
                        # per-key serialization invariant as the cache
                        # probe above; the reply's span bookkeeping is
                        # bounded work on the key's own critical path
                        return self._success_reply(trace, nonce, ntz, cached)
                    reserved = self._admit(nonce, ntz)
                    try:
                        # distpow: ok transitive-blocking-under-lock -- the
                        # miss itself runs under the per-key lock BY
                        # DESIGN (docs/COALESCING.md): followers for the
                        # same key block here until the leader finishes,
                        # then hit the cache; reconnect-dials inside are
                        # bounded by the RPC attempt timeout
                        return self._mine_miss(trace, nonce, ntz, model)
                    finally:
                        if reserved:
                            with self._tasks_lock:
                                self._sched_inflight -= 1
                        # errors included (the rpc.py dispatch-timing
                        # discipline): an all-workers-died RuntimeError
                        # after minutes of reassign probing is exactly
                        # the outage latency this split exists to show
                        miss_s = time.monotonic() - t0
                        metrics.observe("coord.mine_s.miss", miss_s,
                                        trace_id=tid)
                        self._maybe_capture_slow(tid, nonce, ntz, miss_s)
            except BaseException as exc:
                err2 = exc
                raise
            finally:
                if handle is not None:
                    # every leader exit path releases the waiters —
                    # success or failure — or they would park forever
                    handle.finish(error=err2)
        raise RuntimeError(
            f"mine for {nonce.hex()}/{ntz} made no progress after "
            f"repeated coalesced rounds"
        )

    def _maybe_capture_slow(self, tid: int, nonce: bytes, ntz: int,
                            dur_s: float) -> None:
        """Slow-request auto-capture (docs/FORENSICS.md): when the
        trigger fires, the request's span tree — everything this node's
        ring retains for the trace — is snapshotted into the flight
        recorder, so the evidence for the tail outlier is captured by
        construction (the PR 3 dump-on-fault discipline), not by
        whoever notices the p99 move."""
        if not self._slow_trigger.armed:
            return
        reason = self._slow_trigger.observe(dur_s)
        if reason is None:
            return
        metrics.inc("forensics.slow_captures")
        RECORDER.record(
            "forensics.slow_request", trace_id=tid, nonce=nonce.hex(),
            ntz=ntz, dur_s=round(dur_s, 6), reason=reason,
            threshold_s=self._slow_trigger.threshold_s,
            spans=SPANS.spans_for(tid),
        )

    def _admit(self, nonce: bytes, ntz: int) -> bool:
        """Bounded run queue (docs/SCHEDULER.md): shed the request with
        a typed RETRY_AFTER once the admitted-round count hits the
        configured bound, instead of queueing without limit.  Check and
        reservation are ONE critical section, so concurrent leaders
        cannot all pass at limit-1; returns True when the caller holds
        a reservation it must release when its round ends."""
        limit = self._sched_max_inflight
        if not limit:
            return False
        with self._tasks_lock:
            inflight = self._sched_inflight
            if inflight < limit:
                self._sched_inflight = inflight + 1
                return True
        metrics.inc("sched.admission_rejected")
        RECORDER.record("sched.admission_reject", nonce=nonce.hex(),
                        ntz=ntz, inflight=inflight, limit=limit,
                        retry_after_s=self._sched_retry_after_s)
        raise AdmissionReject(
            self._sched_retry_after_s,
            f"coordinator run queue full ({inflight}/{limit})",
        )

    # -- fan-out plumbing (module docstring "Fan-out concurrency") ----------
    def _go_worker(self, w: WorkerRef, method: str, params: dict) -> Future:
        """Issue one async worker RPC; a worker with no live client
        yields an already-failed future so callers treat 'never dialed'
        exactly like 'send failed'."""
        if w.client is None:
            fut: Future = Future()
            fut.set_exception(
                RPCTransportError(f"worker {w.worker_byte} not connected")
            )
            return fut
        return w.client.go(method, params)

    def _mine_params(self, trace, nonce: bytes, ntz: int, worker_byte: int,
                     rid: str, model: Optional[str] = None,
                     plan: Optional[RoundPlan] = None) -> dict:
        out = {
            "nonce": bytes(nonce),
            "num_trailing_zeros": ntz,
            "worker_byte": worker_byte,
            "worker_bits": plan.worker_bits if plan is not None
            else self.worker_bits,
            "round": rid,
            "token": wire_token(trace.generate_token()),
        }
        if model:
            # off-default model rides only when requested: default
            # rounds stay wire-identical to every earlier version
            out["hash_model"] = model
        if self.cluster is not None and self.reply_addr:
            # pooled rounds carry their owner's worker-facing address:
            # a SHARED worker routes this round's Results back to the
            # coordinator that fanned it out, not its config default
            # (docs/CLUSTER.md).  Absent outside cluster mode — single-
            # coordinator frames stay wire-identical.
            out["coord_addr"] = self.reply_addr
        if plan is not None:
            # capability-weighted rounds carry the shard's explicit
            # (tb_lo, tb_count) byte range; equal-weight rounds attach
            # nothing and the worker expands the reference algebra —
            # frames stay wire-identical to every earlier version
            out.update(plan.mine_extra(worker_byte))
        return out

    def _found_params(self, trace, nonce: bytes, ntz: int, worker_byte: int,
                      secret: bytes, rid: str,
                      model: Optional[str] = None) -> dict:
        out = {
            "nonce": bytes(nonce),
            "num_trailing_zeros": ntz,
            "worker_byte": worker_byte,
            "secret": bytes(secret),
            "round": rid,
            "token": wire_token(trace.generate_token()),
        }
        if model:
            out["hash_model"] = model
        if self.cluster is not None and self.reply_addr:
            # the Found's cache-update-only ACK must route home too
            out["coord_addr"] = self.reply_addr
        return out

    def _mine_send_failure(self, w: WorkerRef, shard: int, rid: str,
                           exc: BaseException) -> None:
        log.warning("worker %d failed Mine for shard %d: %s",
                    w.worker_byte, shard, exc)
        metrics.inc("coord.worker_failures")
        RECORDER.record("coord.worker_failure",
                        worker_byte=w.worker_byte, shard=shard,
                        round=rid, error=str(exc))
        self._mark_dead(w)

    def _send_mine(self, trace, nonce: bytes, ntz: int, w: WorkerRef,
                   worker_byte: int, rid: str,
                   model: Optional[str] = None,
                   plan: Optional[RoundPlan] = None) -> bool:
        """Issue one worker Mine and BLOCK for its ack (the reissue,
        hedge and serial-baseline paths); under "reassign" a failure
        marks the worker dead and returns False instead of raising."""
        trace.record_action(
            act.CoordinatorWorkerMine(
                nonce=nonce, num_trailing_zeros=ntz, worker_byte=worker_byte,
            )
        )
        fut = self._go_worker(
            w, "WorkerRPCHandler.Mine",
            self._mine_params(trace, nonce, ntz, worker_byte, rid, model,
                              plan),
        )
        try:
            fut.result(timeout=self._call_timeout)
            return True
        except (OSError, RPCError, RuntimeError, FutureTimeout) as exc:
            if self.failure_policy != "reassign":
                raise
            self._mine_send_failure(w, worker_byte, rid, exc)
            return False

    def _harvest_inflight(self, inflight: List[tuple], tasks, ledgers,
                          rid: str):
        """Resolve the parallel fan-out's outstanding Mine futures off
        the round's critical path.  A confirmed ack just leaves the
        in-flight list; a failed future — or one still pending past its
        deadline (the hung-worker case the serial path paid
        ``_call_timeout`` for, per worker, before the round even
        started) — marks the worker dead, drops the shard from the
        given ack ledgers, and returns it for re-issue.  Returns
        (surviving_tasks, orphaned_shards)."""
        if not inflight:
            return tasks, []
        orphans: List[int] = []
        now = time.monotonic()
        for entry in list(inflight):
            w, shard, fut, deadline = entry
            exc: Optional[BaseException] = None
            if fut.done():
                try:
                    fut.result()
                    inflight.remove(entry)
                    continue  # ack confirmed
                except (OSError, RPCError, RuntimeError, FutureTimeout) as e:
                    exc = e
            elif now < deadline:
                continue  # still within its (parallel) timeout window
            else:
                exc = FutureTimeout(
                    f"Mine ack from worker {w.worker_byte} still pending "
                    f"after {self._call_timeout}s"
                )
            inflight.remove(entry)
            if (w, shard) not in tasks:
                # _reap_dead already killed this worker in an earlier
                # probe cycle (its ping timed out, closing the client —
                # which is exactly what failed this future) and the
                # shard was reassigned then.  Re-orphaning it here would
                # duplicate the (worker, shard) task entry and owe the
                # 2N-ack ledger acks the worker can never send — a
                # forever-spinning drain loop (review PR 5, reproduced
                # with a fully-hung worker and a >2s round).  The shard
                # number may still key a LIVE reassigned entry, so the
                # ledgers must not be touched either.
                continue
            self._mine_send_failure(w, shard, rid, exc)
            tasks = [t for t in tasks if t != (w, shard)]
            for ledger in ledgers:
                ledger.pop(shard, None)
            orphans.append(shard)
        return tasks, orphans

    def _assign_shards(self, trace, nonce: bytes, ntz: int, rid: str,
                       model: Optional[str] = None,
                       plan: Optional[RoundPlan] = None):
        """Fan the shard per worker (coordinator.go:179-199) — every
        Mine issued as a concurrent ``go()`` future before any reply is
        awaited; under "reassign", shards of dead workers go to live
        ones (a worker can mine a foreign worker_byte — the partition
        travels in the RPC).  The worker set is the round plan's
        membership snapshot (fleet.round_plan): static configs yield
        the reference layout, an elastic fleet whatever is live and not
        draining right now.  Returns (tasks, pending_unplaced_shards,
        inflight_mine_acks)."""
        if plan is None:
            plan = self.fleet.round_plan()
        if not plan.entries:
            raise RuntimeError("no live workers to mine on")
        reassign = self.failure_policy == "reassign"
        if self._serial_fanout:
            # serial baseline (bench.py --control-plane): the old
            # one-blocking-call-per-worker loop, kept measurable
            tasks: List[Tuple[WorkerRef, int]] = []
            orphans: List[int] = []
            for w, shard in plan.entries:
                if self._send_mine(trace, nonce, ntz, w, shard,
                                   rid, model, plan):
                    tasks.append((w, shard))
                else:
                    orphans.append(shard)
            tasks, pending = self._issue_shards(
                trace, nonce, ntz, tasks, orphans, rid, model, plan
            )
            if not tasks:
                raise RuntimeError("no live workers to mine on")
            return tasks, pending, []
        futs = []
        for w, shard in plan.entries:
            trace.record_action(
                act.CoordinatorWorkerMine(
                    nonce=nonce, num_trailing_zeros=ntz,
                    worker_byte=shard,
                )
            )
            futs.append((w, shard, self._go_worker(
                w, "WorkerRPCHandler.Mine",
                self._mine_params(trace, nonce, ntz, shard, rid,
                                  model, plan),
            )))
        if not reassign:
            # reference parity ("error"): every worker must take
            # delivery before the round proceeds — but the N sends
            # already overlapped, so N round trips cost ~one RTT
            tasks = []
            for w, shard, fut in futs:
                fut.result()  # any failure fails the Mine RPC, as before
                tasks.append((w, shard))
            return tasks, [], []
        tasks, orphans, inflight = [], [], []
        deadline = time.monotonic() + (self._call_timeout or 10.0)
        for w, shard, fut in futs:
            if fut.done():
                # resolved at issue time: either a send-path transport
                # failure (dead TCP fails inside go()) or an already-
                # arrived ack
                try:
                    fut.result()
                    tasks.append((w, shard))
                except (OSError, RPCError, RuntimeError, FutureTimeout) as exc:
                    self._mine_send_failure(w, shard, rid, exc)
                    orphans.append(shard)
            else:
                # optimistic placement: the frame is written, only the
                # ack is outstanding.  The round starts NOW; the ack is
                # confirmed (or timed out, in parallel with its peers)
                # by _harvest_inflight during the result waits — a hung
                # worker no longer adds _call_timeout to
                # fanout->first-result for the live ones
                tasks.append((w, shard))
                inflight.append((w, shard, fut, deadline))
        tasks, pending = self._issue_shards(
            trace, nonce, ntz, tasks, orphans, rid, model, plan
        )
        if not tasks:
            raise RuntimeError("no live workers to mine on")
        return tasks, pending, inflight

    def _mine_miss(self, trace, nonce: bytes, ntz: int,
                   model: Optional[str] = None) -> dict:
        self._initialize_workers()
        key = (nonce, ntz)
        # distpow: ok bounded-queue -- protocol-bounded: one round's
        # queue holds at most 2 messages per live worker (the 2N-ack
        # ledger) plus one ack per re-broadcast, and the Result handler
        # drops stale-round messages before they are enqueued; a hard
        # maxsize that ever blocked the Result dispatch thread would
        # wedge the whole round instead
        results: "queue.Queue" = queue.Queue()
        rid = new_round_id(
            self.restart_epoch,
            self.cluster.self_id if self.cluster is not None else "",
        )
        self._task_set(key, rid, results)
        reassign = self.failure_policy == "reassign"
        probe_t = self.failure_probe_secs if reassign else None
        # the round's membership snapshot (docs/FLEET.md): who gets a
        # shard, at which worker_bits, over which (weighted) byte
        # ranges.  Hedging appends duplicate placements to it, so the
        # closing track_round(-1) releases every ref the round touched
        # — the drain RPC waits on exactly this accounting.
        plan = self.fleet.round_plan()
        self.fleet.track_round([w for w, _ in plan.entries], +1)
        try:
            return self._mine_miss_locked(
                trace, nonce, ntz, results, reassign, probe_t, rid, model,
                plan,
            )
        finally:
            # every exit path (success, protocol violation, all-workers-
            # dead, error-policy RPC failure) must release the task entry,
            # or retries leak queues and late Results route to a zombie
            self._task_delete(key)
            self.fleet.track_round([w for w, _ in plan.entries], -1)

    def _mine_miss_locked(self, trace, nonce: bytes, ntz: int, results,
                          reassign: bool, probe_t, rid: str,
                          model: Optional[str] = None,
                          plan: Optional[RoundPlan] = None) -> dict:
        metrics.inc("coord.fanouts")
        # the fan-out instant anchors this round's two latency
        # distributions: fanout->first-result (the race the paper's
        # contract is about) and fanout->last-ack (cancel propagation)
        fanout_t0 = time.monotonic()
        fanout_ts = time.time()
        RECORDER.record("coord.fanout", round=rid, nonce=nonce.hex(),
                        ntz=ntz)
        if plan is None:
            plan = self.fleet.round_plan()
        tasks, pending, inflight = self._assign_shards(trace, nonce, ntz, rid,
                                                       model, plan)
        # forensics span (docs/FORENSICS.md): the shard-issue phase,
        # carved out of timestamps the round takes anyway — spans are
        # derived observers, never new trace actions
        SPANS.record("coord.fanout", fanout_ts,
                     time.monotonic() - fanout_t0,
                     trace_id=trace.trace_id, node=self.tracer.identity,
                     round=rid, nonce=nonce.hex(), ntz=ntz,
                     shards=len(tasks))

        # first-result-wins (coordinator.go:202-206); under "reassign",
        # waiting is interleaved with liveness probes, the harvest of
        # the parallel fan-out's outstanding Mine acks AND the straggler
        # hedge (docs/FLEET.md: a shard whose heartbeat-lease owner has
        # gone silent past the fleet's hedge threshold gets a duplicate
        # on the least-loaded live worker — first result still wins);
        # orphaned and not-yet-placed shards are re-issued every round
        # so coverage is never silently lost
        hedged: set = set()
        while True:
            try:
                first = results.get(timeout=probe_t)
                break
            except queue.Empty:
                tasks, hung = self._harvest_inflight(inflight, tasks, (), rid)
                tasks, orphans = self._reap_dead(tasks, ())
                if not tasks:
                    raise RuntimeError("all workers died while mining")
                tasks, pending = self._issue_shards(
                    trace, nonce, ntz, tasks, pending + hung + orphans, rid,
                    model, plan
                )
                tasks = self._maybe_hedge(trace, nonce, ntz, tasks, rid,
                                          model, plan, hedged)
        first_result_s = time.monotonic() - fanout_t0
        metrics.observe("coord.first_result_s", first_result_s,
                        trace_id=trace.trace_id)
        RECORDER.record("coord.first_result", round=rid,
                        nonce=nonce.hex(), ntz=ntz,
                        worker_byte=int(first["worker_byte"]),
                        latency_s=round(first_result_s, 6))
        SPANS.record("coord.first_result", fanout_ts, first_result_s,
                     trace_id=trace.trace_id, node=self.tracer.identity,
                     round=rid, nonce=nonce.hex(), ntz=ntz,
                     winner_byte=int(first["worker_byte"]))
        if first["secret"] is None:
            raise RuntimeError(
                "protocol violation: first worker message was a cancellation "
                f"ACK from worker_byte={first['worker_byte']}"
            )
        winner = bytes(first["secret"])

        tasks = self._broadcast_found(trace, nonce, ntz, winner, tasks, rid,
                                      model)

        # the 2-messages-per-task ack ledger (coordinator.go:237-248): the
        # finder already delivered 1 message; every surviving task owes 2
        remaining: Dict[int, int] = {}
        for _, shard in tasks:
            remaining[shard] = remaining.get(shard, 0) + 2
        fb = int(first["worker_byte"])
        if fb in remaining:
            remaining[fb] -= 1
        late: List[dict] = []
        while any(v > 0 for v in remaining.values()):
            try:
                msg = results.get(timeout=probe_t)
            except queue.Empty:
                tasks, _ = self._harvest_inflight(
                    inflight, tasks, (remaining,), rid
                )
                tasks, _ = self._reap_dead(tasks, (remaining,))
                continue
            if msg["secret"] is not None:
                late.append(msg)
                metrics.inc("coord.late_results")
                log.info("late worker result: %s", msg["worker_byte"])
            b = int(msg["worker_byte"])
            if b in remaining:
                remaining[b] -= 1
        # the 2N-ack ledger just drained: every surviving worker has
        # acknowledged the cancellation — fanout->last-ack is the
        # cancel-propagation latency the ISSUE-3 plane measures
        cancel_s = time.monotonic() - fanout_t0
        metrics.observe("coord.cancel_propagation_s", cancel_s,
                        trace_id=trace.trace_id)
        RECORDER.record("coord.cancel_complete", round=rid,
                        nonce=nonce.hex(), ntz=ntz,
                        late_results=len(late),
                        latency_s=round(cancel_s, 6))
        # the cancel-storm span starts where first_result ended, so the
        # two tile the round on the stitched timeline instead of
        # double-counting the race
        SPANS.record("coord.cancel_storm", fanout_ts + first_result_s,
                     cancel_s - first_result_s,
                     trace_id=trace.trace_id, node=self.tracer.identity,
                     round=rid, nonce=nonce.hex(), ntz=ntz,
                     late_results=len(late))

        # late-result cache propagation (coordinator.go:250-280): each
        # rebroadcast is acked once per task (cache-update-only round)
        for msg in late:
            tasks = self._broadcast_found(
                trace, nonce, ntz, bytes(msg["secret"]), tasks, rid, model
            )
            owed = {shard: 1 for _, shard in tasks}
            while any(v > 0 for v in owed.values()):
                try:
                    m = results.get(timeout=probe_t)
                except queue.Empty:
                    tasks, _ = self._harvest_inflight(
                        inflight, tasks, (owed,), rid
                    )
                    tasks, _ = self._reap_dead(tasks, (owed,))
                    continue
                b = int(m["worker_byte"])
                if b in owed:
                    owed[b] -= 1

        if reassign:
            alive = {id(w) for w, _ in tasks}
            # only workers THIS round touched (the plan, hedges
            # included) need the re-sync: a member that joined after
            # fan-out has no orphaned miners to unblock, and Found-ing
            # it would just mint unknown-task noise at its forwarder
            abandoned = [w for w in
                         {id(x): x for x, _ in plan.entries}.values()
                         if id(w) not in alive]
            if abandoned:
                # OFF the success-reply critical path (ISSUE 5 satellite:
                # the inline re-dial used to sit between the drained
                # ledger and the client's reply): bounded background
                # best-effort re-sync, one flight-recorder event per
                # outcome
                threading.Thread(
                    target=self._resync_abandoned,
                    args=(trace, nonce, ntz, winner, abandoned, rid, model),
                    daemon=True, name=f"resync-{rid[-8:]}",
                ).start()
        return self._success_reply(trace, nonce, ntz, winner)

    def _maybe_hedge(self, trace, nonce: bytes, ntz: int, tasks, rid: str,
                     model: Optional[str], plan: RoundPlan,
                     hedged: set):
        """Straggler hedging (docs/FLEET.md "Hedging policy"): while the
        round waits for its first result, any shard whose owner's
        heartbeat lease has gone silent for longer than
        ``hedge_multiple x`` the fleet's median heartbeat interval gets
        ONE duplicate Mine on the least-loaded live worker.  First
        result still wins; the straggler is neither killed nor
        abandoned — if it wakes and answers first, its result counts.
        Static (permanent-lease) workers never trip this: they have no
        heartbeats, and their failure detection stays the probe path.
        The PR 5 SIGSTOP machinery is exactly the scenario this makes
        first-class: a frozen worker's beats stop long before its TCP
        shows anything wrong."""
        if not self.fleet.hedge_enabled or self.failure_policy != "reassign":
            return tasks
        threshold = self.fleet.hedge_after_s()
        loads: Dict[int, int] = {}
        for x, _s in tasks:
            loads[id(x)] = loads.get(id(x), 0) + 1
        for w, shard in list(tasks):
            if shard in hedged or not self.fleet.is_stale(w, threshold):
                continue
            candidates = [
                x for x in {id(x): x for x, _ in tasks}.values()
                if x is not w and x.client is not None
                and not self.fleet.is_stale(x, threshold)
                and self.fleet.in_service(x)
            ]
            if not candidates:
                continue
            target = min(candidates, key=lambda x: loads.get(id(x), 0))
            if not self._send_mine(trace, nonce, ntz, target, shard, rid,
                                   model, plan):
                continue
            hedged.add(shard)
            tasks.append((target, shard))
            # the duplicate placement joins the plan so the round's
            # closing track_round(-1) and the re-sync sweep see it
            plan.entries.append((target, shard))
            loads[id(target)] = loads.get(id(target), 0) + 1
            metrics.inc("fleet.hedged_shards")
            RECORDER.record(
                "fleet.hedge", round=rid, shard=shard,
                owner_byte=w.worker_byte, target_byte=target.worker_byte,
                threshold_s=round(threshold, 3),
            )
            SPANS.event("fleet.hedge", trace_id=trace.trace_id,
                        node=self.tracer.identity, round=rid, shard=shard,
                        owner_byte=w.worker_byte,
                        target_byte=target.worker_byte)
            log.info("hedged shard %d of silent worker %d onto worker %d",
                     shard, w.worker_byte, target.worker_byte)
        return tasks

    #: total wall-clock budget for one round's abandoned-worker re-sync
    #: (dials + Found calls share it); generous vs the 2 s dial timeout
    #: yet small enough that a stack teardown never waits on stragglers
    RESYNC_CAP_S = 8.0
    RESYNC_DIAL_TIMEOUT_S = 2.0

    #: Found-ack patience for a member whose heartbeat lease is already
    #: hedge-stale: it is almost certainly frozen, and the full shared
    #: ``_call_timeout`` would gate the round's reply on a worker the
    #: fleet has stopped believing in.  Never applied to permanent
    #: (static) leases — they cannot be stale.
    STALE_ACK_TIMEOUT_S = 1.0

    def _resync_abandoned(self, trace, nonce: bytes, ntz: int,
                          secret: bytes, workers: List[WorkerRef],
                          rid: str, model: Optional[str] = None) -> None:
        """Best-effort Found to every worker not among the surviving
        tasks.  A worker falsely marked dead on a transient failure still
        has miner threads running (and a finder may be blocked waiting for
        its Found); once the blip heals, this installs the winning secret
        — which also self-cancels its orphaned miners via the worker's
        cache-aware cancel check — and unblocks any waiting finder.
        Failures are ignored: a truly dead worker has nothing running.

        Runs on a background thread, one sub-thread per worker, all
        capped by RESYNC_CAP_S: the re-dial of a black-holed address can
        no longer add its connect timeout to the Mine reply, and total
        re-sync time is bounded no matter how many workers are down.
        Dials are THROWAWAY clients — installing one on the WorkerRef
        here would race the next round's ``_initialize_workers``."""
        deadline = time.monotonic() + self.RESYNC_CAP_S

        def resync_one(w: WorkerRef) -> None:
            t0 = time.monotonic()
            outcome = "resynced"
            client = temp = None
            try:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    outcome = "deadline"
                    return
                client, temp = w.client, None
                if client is None:
                    temp = client = RPCClient(
                        w.addr,
                        timeout=min(self.RESYNC_DIAL_TIMEOUT_S, remaining),
                    )
                try:
                    client.call(
                        "WorkerRPCHandler.Found",
                        self._found_params(trace, nonce, ntz, w.worker_byte,
                                           secret, rid, model),
                        timeout=max(0.1, deadline - time.monotonic()),
                    )
                finally:
                    if temp is not None:
                        temp.close()
                log.info("abandoned worker %d cancelled and re-synced",
                         w.worker_byte)
            except (OSError, RPCError, RuntimeError, FutureTimeout) as exc:
                outcome = "unreachable"
                log.info("abandoned worker %d still unreachable: %s",
                         w.worker_byte, exc)
                # only tear down the client THIS thread observed failing:
                # the next round's _initialize_workers may have installed
                # a fresh healthy connection while this (post-reply,
                # seconds-long) background attempt was in flight, and
                # _mark_dead-ing that one would spuriously fail a live
                # worker's round (review PR 5)
                if temp is None and client is not None and \
                        w.client is client:
                    self._mark_dead(w)
            finally:
                metrics.inc("coord.abandoned_resyncs")
                RECORDER.record(
                    "coord.abandoned_resync", worker_byte=w.worker_byte,
                    round=rid, outcome=outcome,
                    latency_s=round(time.monotonic() - t0, 6),
                )

        for w in workers:
            # distpow: ok unbounded-thread-spawn -- bounded: one spawn
            # per abandoned worker of ONE round (<= fleet size), and
            # every thread self-terminates within RESYNC_CAP_S via the
            # shared deadline — the per-item spawn is the point (the
            # serial alternative re-serializes dial timeouts)
            threading.Thread(target=resync_one, args=(w,), daemon=True,
                             name=f"resync-{rid[-8:]}-w{w.worker_byte}"
                             ).start()

    def _await_found(self, w: WorkerRef, shard: int, fut: Future,
                     timeout: Optional[float]) -> bool:
        """Confirm one Found delivery; under "reassign" a failure (or a
        deadline expiry) marks the worker dead and returns False."""
        try:
            fut.result(timeout=timeout)
            return True
        except (OSError, RPCError, RuntimeError, FutureTimeout) as exc:
            if self.failure_policy != "reassign":
                raise
            log.warning("worker %d failed Found for shard %d: %s",
                        w.worker_byte, shard, exc)
            self._mark_dead(w)
            return False

    def _broadcast_found(
        self,
        trace,
        nonce: bytes,
        ntz: int,
        secret: bytes,
        tasks: List[Tuple[WorkerRef, int]],
        rid: str,
        model: Optional[str] = None,
    ) -> List[Tuple[WorkerRef, int]]:
        """Found-as-cancel+cache-install per task (coordinator.go:210-230);
        returns the tasks whose worker took delivery.  All Founds are
        issued before any reply is awaited, so the cancel storm costs
        ~one RTT instead of N, and every straggler shares ONE deadline
        instead of timing out head-of-line, one after another."""
        issued: List[Tuple[WorkerRef, int, Future]] = []
        delivered: List[Tuple[WorkerRef, int]] = []
        for w, shard in tasks:
            trace.record_action(
                act.CoordinatorWorkerCancel(
                    nonce=nonce, num_trailing_zeros=ntz, worker_byte=shard,
                )
            )
            fut = self._go_worker(
                w, "WorkerRPCHandler.Found",
                self._found_params(trace, nonce, ntz, shard, secret, rid,
                                   model),
            )
            if self._serial_fanout:
                # serial baseline: confirm before the next Found goes out
                if self._await_found(w, shard, fut, self._call_timeout):
                    delivered.append((w, shard))
            else:
                issued.append((w, shard, fut))
        deadline = (None if self._call_timeout is None
                    else time.monotonic() + self._call_timeout)
        for w, shard, fut in issued:
            timeout = (None if deadline is None
                       else max(0.0, deadline - time.monotonic()))
            if timeout is not None and self.fleet.is_stale(w):
                # hedge-stale member: clamp its ack patience so a
                # frozen straggler cannot gate the winner's reply on
                # the full shared deadline (STALE_ACK_TIMEOUT_S)
                timeout = min(timeout, self.STALE_ACK_TIMEOUT_S)
            if self._await_found(w, shard, fut, timeout):
                delivered.append((w, shard))
        return delivered

    def _success_reply(self, trace, nonce: bytes, ntz: int, secret: bytes) -> dict:
        trace.record_action(
            act.CoordinatorSuccess(
                nonce=nonce, num_trailing_zeros=ntz, secret=secret
            )
        )
        return {
            "nonce": bytes(nonce),
            "num_trailing_zeros": ntz,
            "secret": bytes(secret),
            "token": wire_token(trace.generate_token()),
        }

    def Result(self, params) -> dict:
        nonce = bytes(params["nonce"])
        ntz = int(params["num_trailing_zeros"])
        trace = self.tracer.receive_token(decode_token(params["token"]))
        if params.get("secret") is not None:
            trace.record_action(
                act.CoordinatorWorkerResult(
                    nonce=nonce,
                    num_trailing_zeros=ntz,
                    worker_byte=int(params["worker_byte"]),
                    secret=bytes(params["secret"]),
                )
            )
            if not params.get("hash_model"):
                # the dominance cache is single-model: an off-default
                # result (tagged by the worker, docs/SERVING.md) must
                # never be installed where a default-model lookup could
                # replay it — same invariant the worker's Found handler
                # enforces one hop down
                installed = self.result_cache.add(
                    nonce, ntz, bytes(params["secret"]), trace)
                if installed and self.replicator is not None:
                    # write-behind replication (cluster/replication.py):
                    # non-blocking enqueue — a full queue drops and
                    # counts, never stalls the Result handler
                    self.replicator.offer(nonce, ntz,
                                          bytes(params["secret"]))
        entry = self._task_get((nonce, ntz))
        if entry is None:
            # documented fix: the reference blocks forever on a nil channel
            # here (coordinator.go:318); we log and drop instead.
            log.warning("result for unknown task %s/%d dropped", nonce.hex(), ntz)
            return {}
        rid, q = entry
        msg_rid = params.get("round")
        if msg_rid is not None and msg_rid != rid:
            # a zombie miner from a superseded round: its message must
            # not count against the live round's 2N-ack ledger (module
            # docstring).  The cache add above already happened for
            # non-nil secrets — a valid secret is valid whatever round
            # found it (late-result semantics, coordinator.go:250-280).
            metrics.inc("coord.stale_results_dropped")
            log.info("stale-round result for %s/%d dropped", nonce.hex(), ntz)
            return {}
        q.put(params)
        return {}

    def Stats(self, params) -> dict:
        """Metrics snapshot (runtime/metrics.py; no reference
        equivalent).  ``python -m distpow_tpu.cli.stats`` prints it."""
        # resource sentinels ride every Stats snapshot (runtime/health.py,
        # docs/SOAK.md): proc.* self-telemetry plus the depth of every
        # bounded ring, refreshed before the registry is read
        repl_view = (self.replicator.stats_view()
                     if self.replicator is not None else None)
        if repl_view is not None:
            metrics.gauge("ring.repl_queue_depth",
                          float(repl_view.get("queue_depth", 0)))
        SENTINELS.sample()
        snap = metrics.snapshot()
        snap["role"] = "coordinator"
        snap["workers"] = [
            {"worker_byte": w.worker_byte, "addr": w.addr,
             "connected": w.client is not None}
            for w in list(self.workers)
        ]
        # live membership table (docs/FLEET.md): what `stats --discover`
        # and Fleet.Members render — leases, capabilities, drain state
        snap["fleet"] = {
            "members": self.fleet.members(),
            "lease_ttl_s": self.fleet.lease_ttl_s,
            "hedge": self.fleet.hedge_enabled,
        }
        snap["active_tasks"] = len(self._tasks)
        snap["cache_entries"] = len(self.result_cache)
        snap["failure_policy"] = self.failure_policy
        if self.cluster is not None:
            # pool membership view (docs/CLUSTER.md): which shard this
            # is and the ring it routes by — what `stats --discover`
            # walks to cover the whole pool
            snap["cluster"] = {"self": self.cluster.self_id,
                               "ring": self.cluster.ring.to_wire()}
        if repl_view is not None:
            snap["replication"] = repl_view
        snap["sched"] = {
            "max_inflight": self._sched_max_inflight,
            "coalesce": self._coalescer is not None,
        }
        return snap


class Coordinator:
    """Coordinator process object (NewCoordinator/InitializeRPCs,
    coordinator.go:115-136, 322-354)."""

    def __init__(self, config: CoordinatorConfig, sink=None):
        self.config = config
        tdir = getattr(config, "TelemetryDir", "") or ""
        if tdir:
            # flight-recorder journal + dump-on-fault directory
            # (runtime/telemetry.py; off by default — memory-only ring)
            RECORDER.configure(
                journal_path=os.path.join(
                    tdir, "coordinator.telemetry.jsonl"
                ),
                dump_dir=tdir,
            )
        # pooled coordinators trace under DISTINCT identities: two
        # processes sharing one vector-clock stream would interleave
        # its components and trip every monotonicity invariant
        # trace_check holds (docs/CLUSTER.md).  Shard 0 — and every
        # single-coordinator config — keeps the historical
        # "coordinator", so golden traces stay byte-identical.
        shard = int(getattr(config, "ClusterSelf", -1))
        identity = (f"coordinator{shard}"
                    if getattr(config, "ClusterPeers", None) and shard > 0
                    else "coordinator")
        self.tracer = make_tracer(
            identity, config.TracerServerAddr, config.TracerSecret,
            sink=sink,
        )
        self.handler = CoordRPCHandler(
            self.tracer, list(config.Workers),
            cache_file=getattr(config, "CacheFile", "") or None,
            failure_policy=getattr(config, "FailurePolicy", "error") or "error",
            failure_probe_secs=getattr(config, "FailureProbeSecs", 1.0),
            sched_max_inflight=getattr(config, "SchedMaxInflight", 0),
            sched_retry_after_s=getattr(config, "SchedRetryAfterS", 0.5),
            sched_coalesce=getattr(config, "SchedCoalesce", True),
            lease_ttl_s=getattr(config, "FleetLeaseTTLS", 10.0) or 10.0,
            hedge=bool(getattr(config, "FleetHedge", True)),
            hedge_multiple=getattr(config, "FleetHedgeMultiple", 3.0) or 3.0,
            forensics_slow_s=getattr(config, "ForensicsSlowS", 0.0) or 0.0,
            forensics_p99x=getattr(config, "ForensicsSlowP99X", 0.0) or 0.0,
        )
        self.server = RPCServer()
        self.server.register("CoordRPCHandler", self.handler)
        # lease-based membership RPCs (distpow_tpu/fleet/, docs/FLEET.md):
        # elastic workers Register/Heartbeat/Drain against either
        # listener; Members feeds `stats --discover`
        self.server.register(
            "Fleet",
            FleetService(self.handler.fleet,
                         drain_timeout_s=getattr(
                             config, "FleetDrainTimeoutS", 20.0) or 20.0),
        )
        # role-agnostic Stats alias (distpow_tpu/obs/, docs/SLO.md):
        # lets the fleet scraper's auto-role discovery resolve ANY
        # current node without the unknown-service error a wrong-role
        # probe earns — which would otherwise tick rpc.handler_errors
        # on the very node being observed (the watcher-perturbation
        # class the stats CLI's JSON pin already guards against).
        # Stats-only view: the protocol surface stays single-named.
        self.server.register("Node", StatsOnly(self.handler))
        self.client_addr: Optional[str] = None
        self.worker_addr: Optional[str] = None
        # cache replication knobs (cluster/replication.py) — only read
        # when set_cluster_peers actually runs, so single-coordinator
        # configs never construct a Replicator and stay byte-identical
        self._repl_replicas = int(getattr(config, "ClusterCacheReplicas", 1))
        self._repl_queue_depth = int(
            getattr(config, "ClusterReplQueueDepth", 1024))
        self._repl_antientropy_s = float(
            getattr(config, "ClusterAntiEntropyS", 5.0))
        self._repl_handoff_deadline_s = float(
            getattr(config, "ClusterHandoffDeadlineS", 5.0))
        self._replicator: Optional[Replicator] = None
        # coordinator pool (distpow_tpu/cluster/, docs/CLUSTER.md):
        # config-driven membership installs here; ':0'-bound harnesses
        # call set_cluster_peers() once the real addresses exist
        peers = list(getattr(config, "ClusterPeers", []) or [])
        if peers:
            self.set_cluster_peers(
                peers, int(getattr(config, "ClusterSelf", -1)))

    def set_cluster_peers(self, peers: List[str], self_index: int) -> None:
        """Join (or rewire) the coordinator pool: build the canonical
        ring from the peer list, adopt member id ``c<self_index>``,
        register the ``Cluster`` RPC service, and advertise the ring in
        every ``rpc.hello`` ack.  Call before the first Mine; harnesses
        binding on ':0' call it after ``initialize_rpcs`` when the real
        peer addresses exist (the set_worker_addrs discipline).

        Rewiring an already-pooled coordinator is a MEMBERSHIP CHANGE:
        the ring version bumps (clients adopt strictly newer rings) and
        the warm shard handoff (cluster/replication.py, docs/CLUSTER.md
        "Replication & HA") pushes the remapped ranges' entries to
        their new owners BEFORE the new ring is installed or served —
        the handoff-before-ack ordering that keeps a grown pool warm.
        The handoff is deadline-bounded (ClusterHandoffDeadlineS), so a
        frozen recipient delays the ring change by at most the
        deadline; anti-entropy heals whatever was cut off."""
        if not (0 <= self_index < len(peers)):
            raise ValueError(
                f"ClusterSelf={self_index} is not an index into the "
                f"{len(peers)}-entry ClusterPeers list"
            )
        old = self.handler.cluster
        version = old.ring.version + 1 if old is not None else 0
        ring = ring_from_peers(peers, version=version)
        if self._replicator is None:
            # lazily constructed on first pool join — single-coordinator
            # processes never reach here, so they carry no replication
            # threads, queues, or RPCs (byte-identity pin,
            # tests/test_cluster.py)
            self._replicator = Replicator(
                self.handler.result_cache,
                replicas=self._repl_replicas,
                queue_depth=self._repl_queue_depth,
                antientropy_s=self._repl_antientropy_s,
                handoff_deadline_s=self._repl_handoff_deadline_s,
            )
            self.handler.replicator = self._replicator
        if old is not None and ring != old.ring:
            # handoff BEFORE install: until this returns (or hits its
            # deadline) we keep serving and replicating on the old ring
            self._replicator.handoff(old.ring, ring)
        state = ClusterState(ring, f"c{self_index}")
        self._replicator.set_state(state)
        self.handler.set_cluster(state)
        self.server.register(
            "Cluster", ClusterService(state, replicator=self._replicator))
        self.server.hello_extra = state.hello_extra

    def set_worker_addrs(self, addrs: List[str]) -> None:
        """Rebind worker addresses after construction.

        The reference fixes the worker list in static config
        (config/coordinator_config.json:4-9) and dials lazily with retry
        (coordinator.go:169-172, 356-368).  We keep the lazy dial but also
        support ':0'-bound workers whose real ports are only known after
        they listen; call this before the first Mine.
        """
        if len(addrs) != len(self.handler.workers):
            raise ValueError(
                f"expected {len(self.handler.workers)} worker addrs, "
                f"got {len(addrs)}"
            )
        for ref, addr in zip(self.handler.workers, addrs):
            if ref.client is not None and ref.addr != addr:
                raise RuntimeError(f"worker {ref.worker_byte} already dialed")
            ref.addr = addr

    def initialize_rpcs(self) -> Tuple[str, str]:
        """Bind the segregated worker-facing and client-facing listeners."""
        self.worker_addr = self.server.listen(self.config.WorkerAPIListenAddr)
        self.client_addr = self.server.listen(self.config.ClientAPIListenAddr)
        # cluster-mode rounds stamp this as their reply-to so shared
        # workers deliver Results to the round's owner (docs/CLUSTER.md)
        self.handler.reply_addr = self.worker_addr
        self.server.serve_in_background()
        log.info(
            "coordinator serving clients on %s, workers on %s",
            self.client_addr, self.worker_addr,
        )
        return self.client_addr, self.worker_addr

    def run_forever(self) -> None:
        self.initialize_rpcs()
        threading.Event().wait()

    def shutdown(self) -> None:
        self.handler.fleet.close()  # stop the lease reaper
        if self._replicator is not None:
            self._replicator.close()
        self.server.shutdown()
        for w in list(self.handler.workers):
            if w.client is not None:
                w.client.close()
        self.handler.result_cache.close()
        self.tracer.close()
