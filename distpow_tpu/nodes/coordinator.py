"""Coordinator node — the control plane (SURVEY.md section 2 component 3).

Implements the reference's orchestration spine (coordinator.go:139-298):

blocking ``Mine`` RPC:
  1. receive token, record ``CoordinatorMine``;
  2. dominance-cache lookup — on hit record ``CoordinatorSuccess`` and
     reply immediately (coordinator.go:150-166);
  3. on miss, ensure worker connections (dial-retry,
     coordinator.go:169-172,356-368), register a per-task result queue
     (capacity semantics of the 2N-buffered channel,
     coordinator.go:176-177);
  4. fan out ``WorkerRPCHandler.Mine`` to every worker with its partition
     byte (``CoordinatorWorkerMine`` per worker);
  5. block for the first result — first-result-wins;
  6. broadcast ``WorkerRPCHandler.Found`` with the winning secret to every
     worker (``CoordinatorWorkerCancel`` per worker) — cancellation and
     cache-install in one message;
  7. drain the 2N-ack ledger: every worker owes exactly two messages per
     round (finder: result + ACK; cancelled: ACK + ACK); late non-nil
     results are collected (coordinator.go:237-248);
  8. for each late result, re-broadcast ``Found`` (cache convergence) and
     drain N more ACKs (coordinator.go:250-280);
  9. delete the task, record ``CoordinatorSuccess``, reply with a fresh
     token.

``Result`` RPC (coordinator.go:302-320): non-nil secrets are recorded
(``CoordinatorWorkerResult``) and installed into the coordinator cache,
then the payload is routed to the owning task queue.

Documented fixes over the reference (SURVEY.md section 7 "hard parts"):

* late ``Result`` after task deletion: the reference sends on a nil
  channel and leaks the RPC goroutine forever (coordinator.go:318,
  370-374); here the message is logged and dropped.
* duplicate concurrent ``Mine`` for the same (nonce, zeros): the
  reference overwrites the task queue and strands the first request
  (coordinator.go:376-381); here a per-key mutex serializes the miss
  path — the duplicate blocks, then (re-)checks the cache and typically
  returns the first request's result as a hit.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..parallel.partition import worker_bits as partition_worker_bits
from ..runtime import actions as act
from ..runtime.cache import ResultCache
from ..runtime.config import CoordinatorConfig
from ..runtime.rpc import RPCClient, RPCServer
from ..runtime.tracing import Tracer, decode_token, encode_token, make_tracer

log = logging.getLogger("distpow.coordinator")

TaskKey = Tuple[bytes, int]


class WorkerRef:
    def __init__(self, addr: str, worker_byte: int):
        self.addr = addr
        self.worker_byte = worker_byte
        self.client: Optional[RPCClient] = None


class CoordRPCHandler:
    """RPC service ``CoordRPCHandler`` (Mine / Result)."""

    def __init__(self, tracer: Tracer, worker_addrs: List[str],
                 dial_retry_interval: float = 0.2):
        self.tracer = tracer
        self.workers = [WorkerRef(a, i) for i, a in enumerate(worker_addrs)]
        # floor(log2(N)) with the reference's uint truncation
        # (coordinator.go:326); see parallel/partition.py for the
        # non-power-of-two coverage discussion.
        self.worker_bits = partition_worker_bits(len(worker_addrs))
        self.result_cache = ResultCache()
        self._tasks: Dict[TaskKey, "queue.Queue"] = {}
        self._tasks_lock = threading.Lock()
        self._key_locks: Dict[TaskKey, list] = {}
        self._dial_retry_interval = dial_retry_interval

    # -- task table (coordinator.go:370-388) -------------------------------
    def _task_set(self, key: TaskKey, q: "queue.Queue") -> None:
        with self._tasks_lock:
            self._tasks[key] = q

    def _task_get(self, key: TaskKey) -> Optional["queue.Queue"]:
        with self._tasks_lock:
            return self._tasks.get(key)

    def _task_delete(self, key: TaskKey) -> None:
        with self._tasks_lock:
            self._tasks.pop(key, None)

    @contextlib.contextmanager
    def _key_lock(self, key: TaskKey):
        """Hold the per-(nonce, zeros) mutex; entries are refcounted and
        pruned when the last waiter releases, so arbitrary client nonces
        can't grow the map without bound."""
        with self._tasks_lock:
            entry = self._key_locks.get(key)
            if entry is None:
                entry = self._key_locks[key] = [threading.Lock(), 0]
            entry[1] += 1
        try:
            with entry[0]:
                yield
        finally:
            with self._tasks_lock:
                entry[1] -= 1
                if entry[1] == 0 and self._key_locks.get(key) is entry:
                    del self._key_locks[key]

    # -- worker connections (coordinator.go:356-368) ------------------------
    def _initialize_workers(self) -> None:
        while True:
            pending = [w for w in self.workers if w.client is None]
            if not pending:
                return
            for w in pending:
                try:
                    w.client = RPCClient(w.addr)
                except OSError as exc:
                    log.info("waiting for worker %d: %s", w.worker_byte, exc)
                    time.sleep(self._dial_retry_interval)
                    break

    # -- RPCs ---------------------------------------------------------------
    def Mine(self, params) -> dict:
        nonce = bytes(params["nonce"])
        ntz = int(params["num_trailing_zeros"])
        trace = self.tracer.receive_token(decode_token(params["token"]))
        trace.record_action(
            act.CoordinatorMine(nonce=nonce, num_trailing_zeros=ntz)
        )

        cached = self.result_cache.get(nonce, ntz, trace)
        if cached is not None:
            return self._success_reply(trace, nonce, ntz, cached)

        # serialize concurrent identical requests (documented fix; the
        # second request re-checks the cache once the first completes)
        with self._key_lock((nonce, ntz)):
            cached = self.result_cache.get(nonce, ntz, trace)
            if cached is not None:
                return self._success_reply(trace, nonce, ntz, cached)
            return self._mine_miss(trace, nonce, ntz)

    def _mine_miss(self, trace, nonce: bytes, ntz: int) -> dict:
        self._initialize_workers()
        n = len(self.workers)
        key = (nonce, ntz)
        results: "queue.Queue" = queue.Queue(maxsize=2 * n)
        self._task_set(key, results)

        for w in self.workers:
            trace.record_action(
                act.CoordinatorWorkerMine(
                    nonce=nonce, num_trailing_zeros=ntz,
                    worker_byte=w.worker_byte,
                )
            )
            w.client.call(
                "WorkerRPCHandler.Mine",
                {
                    "nonce": list(nonce),
                    "num_trailing_zeros": ntz,
                    "worker_byte": w.worker_byte,
                    "worker_bits": self.worker_bits,
                    "token": encode_token(trace.generate_token()),
                },
            )

        # first-result-wins (coordinator.go:202-206)
        first = results.get()
        if first["secret"] is None:
            raise RuntimeError(
                "protocol violation: first worker message was a cancellation "
                f"ACK from worker_byte={first['worker_byte']}"
            )
        winner = bytes(first["secret"])

        self._broadcast_found(trace, nonce, ntz, winner)

        # 2N-ack ledger (coordinator.go:237-248)
        seen = 1
        late: List[dict] = []
        while seen < 2 * n:
            msg = results.get()
            if msg["secret"] is not None:
                late.append(msg)
                log.info("late worker result: %s", msg["worker_byte"])
            seen += 1

        # late-result cache propagation (coordinator.go:250-280)
        for msg in late:
            self._broadcast_found(trace, nonce, ntz, bytes(msg["secret"]))
            for _ in range(n):
                results.get()

        self._task_delete(key)
        return self._success_reply(trace, nonce, ntz, winner)

    def _broadcast_found(self, trace, nonce: bytes, ntz: int, secret: bytes) -> None:
        for w in self.workers:
            trace.record_action(
                act.CoordinatorWorkerCancel(
                    nonce=nonce, num_trailing_zeros=ntz,
                    worker_byte=w.worker_byte,
                )
            )
            w.client.call(
                "WorkerRPCHandler.Found",
                {
                    "nonce": list(nonce),
                    "num_trailing_zeros": ntz,
                    "worker_byte": w.worker_byte,
                    "secret": list(secret),
                    "token": encode_token(trace.generate_token()),
                },
            )

    def _success_reply(self, trace, nonce: bytes, ntz: int, secret: bytes) -> dict:
        trace.record_action(
            act.CoordinatorSuccess(
                nonce=nonce, num_trailing_zeros=ntz, secret=secret
            )
        )
        return {
            "nonce": list(nonce),
            "num_trailing_zeros": ntz,
            "secret": list(secret),
            "token": encode_token(trace.generate_token()),
        }

    def Result(self, params) -> dict:
        nonce = bytes(params["nonce"])
        ntz = int(params["num_trailing_zeros"])
        trace = self.tracer.receive_token(decode_token(params["token"]))
        if params.get("secret") is not None:
            trace.record_action(
                act.CoordinatorWorkerResult(
                    nonce=nonce,
                    num_trailing_zeros=ntz,
                    worker_byte=int(params["worker_byte"]),
                    secret=bytes(params["secret"]),
                )
            )
            self.result_cache.add(nonce, ntz, bytes(params["secret"]), trace)
        q = self._task_get((nonce, ntz))
        if q is None:
            # documented fix: the reference blocks forever on a nil channel
            # here (coordinator.go:318); we log and drop instead.
            log.warning("result for unknown task %s/%d dropped", nonce.hex(), ntz)
            return {}
        q.put(params)
        return {}


class Coordinator:
    """Coordinator process object (NewCoordinator/InitializeRPCs,
    coordinator.go:115-136, 322-354)."""

    def __init__(self, config: CoordinatorConfig, sink=None):
        self.config = config
        self.tracer = make_tracer(
            "coordinator", config.TracerServerAddr, config.TracerSecret,
            sink=sink,
        )
        self.handler = CoordRPCHandler(self.tracer, list(config.Workers))
        self.server = RPCServer()
        self.server.register("CoordRPCHandler", self.handler)
        self.client_addr: Optional[str] = None
        self.worker_addr: Optional[str] = None

    def set_worker_addrs(self, addrs: List[str]) -> None:
        """Rebind worker addresses after construction.

        The reference fixes the worker list in static config
        (config/coordinator_config.json:4-9) and dials lazily with retry
        (coordinator.go:169-172, 356-368).  We keep the lazy dial but also
        support ':0'-bound workers whose real ports are only known after
        they listen; call this before the first Mine.
        """
        if len(addrs) != len(self.handler.workers):
            raise ValueError(
                f"expected {len(self.handler.workers)} worker addrs, "
                f"got {len(addrs)}"
            )
        for ref, addr in zip(self.handler.workers, addrs):
            if ref.client is not None and ref.addr != addr:
                raise RuntimeError(f"worker {ref.worker_byte} already dialed")
            ref.addr = addr

    def initialize_rpcs(self) -> Tuple[str, str]:
        """Bind the segregated worker-facing and client-facing listeners."""
        self.worker_addr = self.server.listen(self.config.WorkerAPIListenAddr)
        self.client_addr = self.server.listen(self.config.ClientAPIListenAddr)
        self.server.serve_in_background()
        log.info(
            "coordinator serving clients on %s, workers on %s",
            self.client_addr, self.worker_addr,
        )
        return self.client_addr, self.worker_addr

    def run_forever(self) -> None:
        self.initialize_rpcs()
        threading.Event().wait()

    def shutdown(self) -> None:
        self.server.shutdown()
        for w in self.handler.workers:
            if w.client is not None:
                w.client.close()
        self.tracer.close()
