"""powlib — the asynchronous client mining library
(SURVEY.md section 2 component 1; reference: powlib/powlib.go).

API parity:

* ``initialize(coord_addr, ch_capacity)`` connects to the coordinator and
  returns the bounded notify queue solutions are delivered on
  (powlib.go:76-93).
* ``mine(tracer, nonce, num_trailing_zeros)`` is non-blocking
  (powlib.go:102-113): it creates a fresh trace, records
  ``PowlibMiningBegin``, and hands off to a request thread which records
  ``PowlibMine``, embeds a token in the RPC args, and issues the async
  ``CoordRPCHandler.Mine`` call (powlib.go:137-156).
* On completion the response token is received back into the tracer and
  ``PowlibSuccess`` + ``PowlibMiningComplete`` are recorded before the
  result lands on the notify queue (powlib.go:164-176).
* ``close()`` stops delivery: in-flight request threads abandon their
  calls (powlib.go:119-135, 179-182) and the connection closes.

Documented divergences from the reference:

* **RPC failure surfaces as an error result.**  The reference
  ``log.Fatal``s the whole client process on a mine-RPC error
  (powlib.go:161-162).  Here the notify queue delivers a ``MineResult``
  with ``secret=None`` and ``error`` set, so a caller blocked on
  ``get()`` observes the failure (a coordinator outage) and can retry —
  it neither crashes nor hangs forever (VERDICT r1 weak #6).
* **Close handshake.**  The reference re-sends the close token so
  ``Close()`` rendezvouses with every in-flight goroutine
  (powlib.go:179-182) — a mechanism its tracing library needs to keep
  the token chain linear.  This tracer's tokens are self-contained
  (runtime/tracing.py), so ``close()`` instead sets an event that makes
  in-flight threads abandon their calls, then joins them with a bounded
  timeout.  Observable behavior matches: after close, no further
  results are delivered and the process can exit.
"""

from __future__ import annotations

import logging
import queue
import threading
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass
from typing import Optional

from ..runtime import actions as act
from ..runtime.rpc import RPCClient, RPCError
from ..runtime.tracing import Tracer, decode_token, encode_token

log = logging.getLogger("distpow.powlib")


@dataclass
class MineResult:
    nonce: bytes
    num_trailing_zeros: int
    secret: Optional[bytes]
    token: Optional[bytes] = None
    # set (with secret=None) when the mine RPC failed — e.g. the
    # coordinator went down mid-request; see module docstring
    error: Optional[str] = None


class POW:
    def __init__(self):
        self.coordinator: Optional[RPCClient] = None
        self.notify_queue: Optional["queue.Queue[MineResult]"] = None
        self._close_ev = threading.Event()
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()

    def initialize(self, coord_addr: str, ch_capacity: int) -> "queue.Queue[MineResult]":
        log.info("dialing coordinator at %s", coord_addr)
        self.coordinator = RPCClient(coord_addr)
        self.notify_queue = queue.Queue(maxsize=ch_capacity)
        self._close_ev.clear()
        return self.notify_queue

    def mine(self, tracer: Tracer, nonce: bytes, num_trailing_zeros: int) -> None:
        if self.coordinator is None:
            raise RuntimeError("powlib not initialized")
        nonce = bytes(nonce)
        trace = tracer.create_trace()
        trace.record_action(
            act.PowlibMiningBegin(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
        )
        t = threading.Thread(
            target=self._call_mine,
            args=(tracer, nonce, num_trailing_zeros, trace),
            daemon=True,
        )
        with self._inflight_lock:
            self._inflight.add(t)
        t.start()

    def _call_mine(self, tracer, nonce, num_trailing_zeros, trace) -> None:
        try:
            trace.record_action(
                act.PowlibMine(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
            )
            fut = self.coordinator.go(
                "CoordRPCHandler.Mine",
                {
                    "nonce": list(nonce),
                    "num_trailing_zeros": num_trailing_zeros,
                    "token": encode_token(trace.generate_token()),
                },
            )
            while True:
                if self._close_ev.is_set():
                    log.info("mine call abandoned on close")
                    return
                try:
                    result = fut.result(timeout=0.05)
                    break
                except (TimeoutError, FutureTimeoutError):
                    # both spellings: concurrent.futures.TimeoutError is
                    # only an alias of the builtin since Python 3.11
                    continue
                except CancelledError:
                    return
                except RPCError as exc:
                    log.error("mine RPC failed: %s", exc)
                    if not self._close_ev.is_set():
                        # deliver the failure: a silent drop would leave
                        # the client blocked on the notify queue forever
                        self.notify_queue.put(MineResult(
                            nonce=nonce,
                            num_trailing_zeros=num_trailing_zeros,
                            secret=None,
                            error=str(exc),
                        ))
                    return
            token = decode_token(result["token"])
            result_trace = tracer.receive_token(token)
            mr = MineResult(
                nonce=bytes(result["nonce"]),
                num_trailing_zeros=int(result["num_trailing_zeros"]),
                secret=bytes(result["secret"]),
                token=token,
            )
            result_trace.record_action(
                act.PowlibSuccess(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            result_trace.record_action(
                act.PowlibMiningComplete(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            if not self._close_ev.is_set():
                self.notify_queue.put(mr)
        finally:
            with self._inflight_lock:
                self._inflight.discard(threading.current_thread())

    def close(self) -> None:
        self._close_ev.set()
        with self._inflight_lock:
            threads = list(self._inflight)
        for t in threads:
            t.join(timeout=5)
        if self.coordinator is not None:
            self.coordinator.close()
            self.coordinator = None
        log.info("powlib closed")
