"""powlib — the asynchronous client mining library
(SURVEY.md section 2 component 1; reference: powlib/powlib.go).

API parity:

* ``initialize(coord_addr, ch_capacity)`` connects to the coordinator and
  returns the bounded notify queue solutions are delivered on
  (powlib.go:76-93).
* ``mine(tracer, nonce, num_trailing_zeros)`` is non-blocking
  (powlib.go:102-113): it creates a fresh trace, records
  ``PowlibMiningBegin``, and hands off to a request thread which records
  ``PowlibMine``, embeds a token in the RPC args, and issues the async
  ``CoordRPCHandler.Mine`` call (powlib.go:137-156).
* On completion the response token is received back into the tracer and
  ``PowlibSuccess`` + ``PowlibMiningComplete`` are recorded before the
  result lands on the notify queue (powlib.go:164-176).
* ``close()`` stops delivery: in-flight request threads abandon their
  calls (powlib.go:119-135, 179-182) and the connection closes.

Documented divergences from the reference:

* **Coordinator outages are retried, then surfaced — never fatal.**
  The reference ``log.Fatal``s the whole client process on a mine-RPC
  error (powlib.go:161-162).  Here a *transport* failure (connection
  reset/refused, truncated frame, attempt timeout —
  ``rpc.RPCTransportError``) triggers automatic recovery: exponential
  backoff with jitter (``backoff_delay``), a shared re-dial of the
  coordinator connection, and a re-issue of the Mine call — safe
  because Mine is idempotent (the coordinator's dominance cache and
  per-key mutex absorb repeats).  A connection that is still healthy
  (the failure was an attempt timeout or a silently dropped frame) is
  kept and re-issued on; only a dead transport is re-dialed — one slow
  mine hitting its attempt timeout never tears the shared connection
  out from under sibling in-flight mines.  The retry budget is bounded
  (``ClientConfig.MineRetries``); each failed attempt consumes one
  unit, and a *successful* re-dial restores the full budget (an outage
  is charged for its reconnect, not forever) — under an overall
  attempts ceiling (10x the budget, min 8) so a flapping coordinator
  still terminates.  Only when the budget or ceiling is
  exhausted does the notify queue deliver a terminal ``MineResult``
  with ``secret=None`` and ``error="degraded: ..."`` — a caller
  blocked on ``get()`` observes the failure and can escalate; it
  neither crashes nor hangs forever (VERDICT r1 weak #6).  An error
  *returned by* the coordinator's handler (plain ``RPCError``) is not
  retried — re-issuing would just re-earn it — and surfaces as an
  error result immediately.  Counters: ``powlib.retries``,
  ``powlib.reconnects``, ``powlib.degraded`` (runtime/metrics.py).
* **Server-paced backpressure is retried without burning budget.**
  A typed RETRY_AFTER rejection (``rpc.RPCRetryAfter``, minted by the
  coordinator's admission control — sched/admission.py) waits the
  server's own hint and re-issues as a NON-COUNTING attempt: load
  shedding is the server working as designed, so it never consumes the
  transport retry budget nor interacts with the reconnect machinery
  (the connection is healthy).  Only the overall attempts ceiling
  bounds it, so a permanently saturated coordinator still terminates
  in a ``degraded:`` error instead of a hang.  Counter:
  ``powlib.retry_after``.
* **Close handshake.**  The reference re-sends the close token so
  ``Close()`` rendezvouses with every in-flight goroutine
  (powlib.go:179-182) — a mechanism its tracing library needs to keep
  the token chain linear.  This tracer's tokens are self-contained
  (runtime/tracing.py), so ``close()`` instead sets an event that makes
  in-flight threads abandon their calls, then joins them with a bounded
  timeout.  Observable behavior matches: after close, no further
  results are delivered and the process can exit.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass
from typing import Optional

from ..cluster.ring import HashRing, ring_from_peers
from ..runtime import actions as act
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import (
    RPCClient,
    RPCError,
    RPCNotOwner,
    RPCRetryAfter,
    RPCTransportError,
)
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from ..runtime.tracing import Tracer, decode_token, wire_token

log = logging.getLogger("distpow.powlib")

# Retry defaults (ClientConfig.Mine* fields override per client).
DEFAULT_RETRIES = 4
DEFAULT_BACKOFF_S = 0.2
DEFAULT_BACKOFF_MAX_S = 2.0
# Bounds on the server's RETRY_AFTER hint (sched/admission.py): the
# floor keeps a zero/garbage hint from spinning; the cap keeps a
# misconfigured server from parking a mine for minutes per attempt.
RETRY_AFTER_MIN_S = 0.01
RETRY_AFTER_MAX_S = 30.0


def backoff_delay(attempt: int, base: float, cap: float,
                  rng: random.Random) -> float:
    """Jittered exponential backoff: uniform in ``[u/2, u]`` where
    ``u = min(cap, base * 2**attempt)`` — so every delay is positive,
    never exceeds ``cap``, and the halved floor keeps reconnect storms
    from synchronizing without ever collapsing the wait to zero."""
    upper = min(cap, base * (2.0 ** attempt))
    return upper * (0.5 + 0.5 * rng.random())


class _CoordLink:
    """One pool member's connection state (cluster mode,
    docs/CLUSTER.md): the PR 1 reconnect/generation machinery, per
    shard.  Dials LAZILY — a dead shard at ``initialize`` time must
    not fail the whole pool — and mirrors ``POW._reconnect``'s
    discipline exactly: one dialer at a time, backoff under the lock so
    concurrent failed attempts queue instead of dial-storming, healthy
    transports kept, budget-restoring True only for a genuinely fresh
    connection."""

    def __init__(self, member_id: str, addr: str):
        self.member_id = member_id
        self.addr = addr
        self._lock = threading.Lock()
        self._client: Optional[RPCClient] = None
        self._gen = 0
        self._hello: dict = {}

    def conn(self):
        """``(client, gen)``, dialing if needed; a failed dial raises
        ``RPCTransportError`` so callers treat 'never dialed' exactly
        like 'send failed'."""
        with self._lock:
            if self._client is None:
                try:
                    # distpow: ok no-blocking-under-lock -- exactly-one-
                    # dialer per shard, like POW._reconnect: the lock
                    # exists to make the dial exclusive; RPCClient has
                    # its default bounded dial timeout
                    self._client = RPCClient(self.addr)
                except OSError as exc:
                    raise RPCTransportError(
                        f"shard {self.member_id} ({self.addr}): {exc}"
                    ) from exc
                self._gen += 1
                self._hello = dict(
                    getattr(self._client, "hello_info", {}) or {})
            return self._client, self._gen

    def reconnect(self, stale_gen: Optional[int], attempt: int,
                  pow_: "POW") -> bool:
        """Replace this shard's connection after a transport failure on
        generation ``stale_gen`` (None = the dial itself failed).
        Returns True when the connection is fresh — the caller's cue to
        restore its retry budget (POW._reconnect semantics)."""
        with self._lock:
            if stale_gen is not None and self._gen != stale_gen:
                return True  # a sibling attempt already replaced it
            delay = backoff_delay(
                attempt, pow_.backoff_s, pow_.backoff_max_s, pow_._rng
            )
            # distpow: ok no-blocking-under-lock -- same single-dialer
            # design as POW._reconnect: failed attempts queue behind
            # the one re-dialer; the wait is close()-interruptible
            if pow_._close_ev.wait(delay):
                return False
            if self._client is not None and \
                    not getattr(self._client, "dead", True):
                return False  # healthy transport: re-issue on it
            try:
                # distpow: ok no-blocking-under-lock -- exactly-one-
                # dialer (see above); bounded by the default dial timeout
                fresh = RPCClient(self.addr)
            except OSError as exc:
                log.warning("shard %s re-dial failed: %s",
                            self.member_id, exc)
                return False
            old, self._client = self._client, fresh
            self._gen += 1
            self._hello = dict(getattr(fresh, "hello_info", {}) or {})
            metrics.inc("powlib.reconnects")
            RECORDER.record("powlib.reconnect", addr=self.addr,
                            shard=self.member_id, gen=self._gen)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        return True

    def alive(self) -> bool:
        """True while this shard's transport looks healthy — the
        failure the caller just saw was an attempt timeout or a
        dropped frame, not a dead connection, so the right move is
        re-issuing on it (the single-coordinator semantics), never a
        failover."""
        with self._lock:
            return self._client is not None and \
                not getattr(self._client, "dead", True)

    def take_hello(self) -> dict:
        """The hello-ack extras of the most recent FRESH dial, consumed
        once: the ring a pooled coordinator advertises in exchange zero
        (docs/CLUSTER.md) reaches ``POW._adopt_ring`` through this."""
        with self._lock:
            info, self._hello = self._hello, {}
            return info

    def close(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except OSError:
                pass


class _Closed(Exception):
    """Internal: close() was called while an attempt was in flight."""


class _MineFailed(Exception):
    """Internal: the attempt loop concluded with a client-visible error."""


@dataclass
class MineResult:
    nonce: bytes
    num_trailing_zeros: int
    secret: Optional[bytes]
    token: Optional[bytes] = None
    # set (with secret=None) when the mine RPC failed terminally — a
    # coordinator handler error, or a coordinator outage that outlived
    # the retry budget ("degraded: ..."); see module docstring
    error: Optional[str] = None


class POW:
    def __init__(self):
        self.coordinator: Optional[RPCClient] = None
        self.notify_queue: Optional["queue.Queue[MineResult]"] = None
        self.coord_addr: Optional[str] = None
        self.retries = DEFAULT_RETRIES
        self.backoff_s = DEFAULT_BACKOFF_S
        self.backoff_max_s = DEFAULT_BACKOFF_MAX_S
        # per-attempt bound on waiting for the Mine response; None waits
        # forever (a legitimate mine can run arbitrarily long, so only
        # chaos/ops configs should set this)
        self.attempt_timeout_s: Optional[float] = None
        self._close_ev = threading.Event()
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()
        # connection generation: in-flight threads that all hit the same
        # outage coordinate through this so exactly one re-dials and the
        # rest reuse the fresh connection
        self._conn_lock = threading.Lock()
        self._conn_gen = 0
        self._rng = random.Random()  # jitter only — never correctness
        # cluster mode (docs/CLUSTER.md): a cached consistent-hash ring
        # + one _CoordLink per pool member.  None/_links empty in
        # single-coordinator mode, where every code path above stays
        # byte-identical to earlier versions.
        self._ring: Optional[HashRing] = None
        self._ring_lock = threading.Lock()
        self._links: dict = {}

    def initialize(self, coord_addr, ch_capacity: int, *,
                   retries: Optional[int] = None,
                   backoff_s: Optional[float] = None,
                   backoff_max_s: Optional[float] = None,
                   attempt_timeout_s: Optional[float] = None,
                   ) -> "queue.Queue[MineResult]":
        """``coord_addr``: one address (the historical single-
        coordinator mode, behavior byte-identical to every earlier
        version) — or a list/comma-joined string of the POOL's
        client-facing addresses in shard order, which flips this client
        into cluster mode (docs/CLUSTER.md): consistent-hash owner
        routing, hedged sibling retry on RETRY_AFTER, NOT_OWNER ring
        adoption, and ring-guided failover when a shard dies."""
        addrs = (list(coord_addr)
                 if isinstance(coord_addr, (list, tuple))
                 else [a.strip() for a in str(coord_addr).split(",")
                       if a.strip()])
        if len(addrs) > 1:
            return self._initialize_cluster(
                addrs, ch_capacity, retries=retries, backoff_s=backoff_s,
                backoff_max_s=backoff_max_s,
                attempt_timeout_s=attempt_timeout_s,
            )
        coord_addr = addrs[0]
        log.info("dialing coordinator at %s", coord_addr)
        self.coord_addr = coord_addr
        if retries is not None:
            self.retries = int(retries)
        if backoff_s is not None:
            self.backoff_s = float(backoff_s)
        if backoff_max_s is not None:
            self.backoff_max_s = float(backoff_max_s)
        if attempt_timeout_s:  # 0/None both mean "wait forever"
            self.attempt_timeout_s = float(attempt_timeout_s)
        # distpow: ok unguarded-shared-write -- write-once before any
        # reader thread exists: initialize() runs before the notify
        # pump starts, so no thread can observe the handoff; later
        # swaps (in _reconnect) do take _conn_lock
        self.coordinator = RPCClient(coord_addr)
        self.notify_queue = queue.Queue(maxsize=ch_capacity)
        self._close_ev.clear()
        return self.notify_queue

    def _initialize_cluster(self, addrs, ch_capacity: int, *,
                            retries=None, backoff_s=None,
                            backoff_max_s=None, attempt_timeout_s=None,
                            ) -> "queue.Queue[MineResult]":
        """Cluster mode: the seed list IS the pool, so the canonical
        ring (cluster/ring.py ring_from_peers) is computed locally —
        the same pure function every coordinator runs over its
        ClusterPeers — and refreshed thereafter from NOT_OWNER
        redirects and every fresh dial's hello ack (``Cluster.Ring``
        serves CLIs and ops tooling the same snapshot on demand).  No
        connection is dialed here: links dial lazily per shard, so a
        dead seed cannot fail client boot (the chaos contract: clients
        ride out a shard death)."""
        log.info("powlib cluster mode: %d-coordinator pool %s",
                 len(addrs), addrs)
        self.coord_addr = addrs[0]
        if retries is not None:
            self.retries = int(retries)
        if backoff_s is not None:
            self.backoff_s = float(backoff_s)
        if backoff_max_s is not None:
            self.backoff_max_s = float(backoff_max_s)
        if attempt_timeout_s:
            self.attempt_timeout_s = float(attempt_timeout_s)
        with self._ring_lock:
            self._ring = ring_from_peers(addrs)
            self._links = {}
        self.notify_queue = queue.Queue(maxsize=ch_capacity)
        self._close_ev.clear()
        return self.notify_queue

    def mine(self, tracer: Tracer, nonce: bytes, num_trailing_zeros: int,
             hash_model: Optional[str] = None) -> None:
        if self.coordinator is None and self._ring is None:
            raise RuntimeError("powlib not initialized")
        nonce = bytes(nonce)
        trace = tracer.create_trace()
        trace.record_action(
            act.PowlibMiningBegin(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
        )
        t = threading.Thread(
            target=self._call_mine,
            args=(tracer, nonce, num_trailing_zeros, trace,
                  hash_model or None),
            daemon=True,
        )
        with self._inflight_lock:
            self._inflight.add(t)
        t.start()

    # -- the retry machinery ------------------------------------------------
    def _conn(self):
        with self._conn_lock:
            return self.coordinator, self._conn_gen

    def _await_attempt(self, fut):
        """Poll the future, honoring close() and the per-attempt bound."""
        deadline = (
            time.monotonic() + self.attempt_timeout_s
            if self.attempt_timeout_s else None
        )
        while True:
            if self._close_ev.is_set():
                raise _Closed
            try:
                return fut.result(timeout=0.05)
            except (TimeoutError, FutureTimeoutError):
                # both spellings: concurrent.futures.TimeoutError is
                # only an alias of the builtin since Python 3.11
                if deadline is not None and time.monotonic() > deadline:
                    # the frame (or its response) vanished on a healthy
                    # connection — retryable like any transport fault;
                    # the abandoned future is simply never read again
                    raise RPCTransportError(
                        f"mine attempt timed out after "
                        f"{self.attempt_timeout_s:.1f}s"
                    )
                continue
            except CancelledError:
                raise _Closed

    def _issue_attempt(self, client, trace, nonce: bytes, ntz: int,
                       hash_model: Optional[str] = None,
                       no_redirect: bool = False) -> dict:
        """One Mine RPC attempt on ``client`` (fresh token per attempt).
        ``hash_model`` rides as an extra param only when set, keeping
        default-model frames wire-identical to every earlier version.
        ``no_redirect`` (cluster mode, docs/CLUSTER.md) marks a
        deliberate off-owner send — a hedged sibling retry or a
        failover — so the receiving coordinator serves the foreign key
        instead of answering NOT_OWNER."""
        params = {
            "nonce": bytes(nonce),
            "num_trailing_zeros": ntz,
            "token": wire_token(trace.generate_token()),
        }
        if hash_model:
            params["hash_model"] = hash_model
        if no_redirect:
            params["no_redirect"] = True
        fut = client.go("CoordRPCHandler.Mine", params)
        return self._await_attempt(fut)

    def _reconnect(self, stale_gen: int, attempt: int) -> bool:
        """Replace the shared coordinator connection after a transport
        failure observed on generation ``stale_gen``.  Returns True when
        the connection is fresh (this thread re-dialed successfully, or
        a sibling already had) — the caller's cue to restore its retry
        budget.  A connection that is still HEALTHY (``RPCClient.dead``
        false — the failure was an attempt timeout or a dropped frame,
        not a dead transport) is kept: tearing it down would fail every
        sibling mine's pending future mid-flight; the caller simply
        re-issues on it after the backoff.  Holding the lock across the
        backoff sleep is deliberate: concurrent failed attempts queue up
        behind the one re-dialer instead of hammering the coordinator
        with parallel dials."""
        with self._conn_lock:
            if self.coordinator is None:
                return False  # closing
            if self._conn_gen != stale_gen:
                return True  # a sibling attempt already replaced it
            delay = backoff_delay(
                attempt, self.backoff_s, self.backoff_max_s, self._rng
            )
            # distpow: ok no-blocking-under-lock -- holding _conn_lock
            # across the backoff is the design (docstring above): failed
            # attempts queue behind the one re-dialer instead of dial-
            # storming the coordinator; the wait is close()-interruptible
            if self._close_ev.wait(delay):
                return False
            if not getattr(self.coordinator, "dead", True):
                return False  # healthy transport: re-issue on it
            try:
                # distpow: ok no-blocking-under-lock -- exactly-one-dialer:
                # the lock exists to make this dial exclusive (see above);
                # the connect has the RPCClient default dial timeout
                fresh = RPCClient(self.coord_addr)
            except OSError as exc:
                log.warning("coordinator re-dial failed: %s", exc)
                return False
            old, self.coordinator = self.coordinator, fresh
            self._conn_gen += 1
            metrics.inc("powlib.reconnects")
            RECORDER.record("powlib.reconnect", addr=self.coord_addr,
                            gen=self._conn_gen)
            log.info("reconnected to coordinator at %s (gen %d)",
                     self.coord_addr, self._conn_gen)
        try:
            old.close()
        except OSError:
            pass
        return True

    def _mine_with_retry(self, trace, nonce: bytes, ntz: int,
                         hash_model: Optional[str] = None) -> Optional[dict]:
        """Issue Mine until success, terminal failure (_MineFailed), or
        close (returns None).  See the module docstring for semantics.

        Liveness bound: budget resets on a successful re-dial mean a
        FLAPPING coordinator (dial accepts, call dies, repeat) would
        otherwise loop forever — the overall attempts ceiling keeps the
        "terminal error, never a hang" contract true regardless of how
        the outage flaps."""
        if self._ring is not None:
            # cluster mode routes per key (docs/CLUSTER.md); the single-
            # coordinator loop below stays byte-identical to before
            return self._mine_cluster(trace, nonce, ntz, hash_model)
        budget = self.retries
        attempt = 0
        attempts_cap = max(8, self.retries * 10)
        while True:
            client, gen = self._conn()
            if client is None:
                return None
            try:
                # default-model mines keep the historical 4-arg call
                # shape (chaos tests stub _issue_attempt with it)
                if hash_model:
                    return self._issue_attempt(client, trace, nonce, ntz,
                                               hash_model)
                return self._issue_attempt(client, trace, nonce, ntz)
            except _Closed:
                log.info("mine call abandoned on close")
                return None
            except RPCTransportError as exc:
                attempt += 1
                if budget <= 0 or attempt >= attempts_cap:
                    metrics.inc("powlib.degraded")
                    RECORDER.record("powlib.degraded", nonce=nonce.hex(),
                                    ntz=ntz, attempts=attempt,
                                    error=str(exc))
                    raise _MineFailed(
                        f"degraded: mine RPC failed after {attempt} "
                        f"attempt(s) ({self.retries}-retry budget): {exc}"
                    )
                budget -= 1
                metrics.inc("powlib.retries")
                log.warning(
                    "mine RPC transport failure (%s); %d/%d retries left",
                    exc, budget, self.retries,
                )
                if self._reconnect(gen, attempt - 1):
                    budget = self.retries
            except RPCRetryAfter as exc:
                # server-paced backpressure (the coordinator's bounded
                # run queue, sched/admission.py): wait exactly as long
                # as the server asked and re-issue.  NON-COUNTING: the
                # transport-failure budget stays untouched — shedding
                # load is the server working as designed, not an
                # outage, so it must never walk a client toward the
                # terminal "degraded:" error.  The overall attempts
                # ceiling still applies, keeping the never-hangs
                # contract true against a permanently saturated server.
                attempt += 1
                if attempt >= attempts_cap:
                    metrics.inc("powlib.degraded")
                    RECORDER.record("powlib.degraded", nonce=nonce.hex(),
                                    ntz=ntz, attempts=attempt,
                                    error=str(exc))
                    raise _MineFailed(
                        f"degraded: mine RPC backpressured after "
                        f"{attempt} attempt(s): {exc}"
                    )
                metrics.inc("powlib.retry_after")
                delay = min(max(exc.delay_s, RETRY_AFTER_MIN_S),
                            RETRY_AFTER_MAX_S)
                log.info("mine backpressured (%s); retrying in %.3fs "
                         "(server-paced, budget untouched)", exc, delay)
                if self._close_ev.wait(delay):
                    return None
            except RPCError as exc:
                # the coordinator's handler returned an error: re-issuing
                # would re-earn it — surface immediately (module docstring)
                raise _MineFailed(str(exc))

    # -- cluster routing (docs/CLUSTER.md) ----------------------------------
    def _link_for(self, member_id: str) -> Optional[_CoordLink]:
        with self._ring_lock:
            link = self._links.get(member_id)
            if link is None and self._ring is not None:
                addr = self._ring.addr_of(member_id)
                if addr is None:
                    return None  # stale member id: ring moved under us
                link = self._links[member_id] = _CoordLink(member_id, addr)
            return link

    def _adopt_ring(self, wire_dict: dict) -> None:
        """Adopt a ring snapshot (a NOT_OWNER redirect's payload, or a
        hello/Cluster.Ring reply).  Versions order snapshots; equal
        versions adopt too — the redirecting coordinator is
        authoritative about its own membership."""
        try:
            fresh = HashRing.from_wire(wire_dict)
        except (TypeError, ValueError) as exc:
            log.warning("ignoring malformed ring snapshot: %s", exc)
            return
        stale = []
        with self._ring_lock:
            if self._ring is not None and fresh.version < self._ring.version:
                return
            self._ring = fresh
            # a link whose member id now resolves to a DIFFERENT
            # address must leave the table, or every future route to
            # that member would keep hitting the old address and
            # redirect-loop; it is not closed here — in-flight mines
            # on it drain naturally and re-resolve on their next error
            for member_id, link in list(self._links.items()):
                if fresh.addr_of(member_id) != link.addr:
                    stale.append(self._links.pop(member_id))
        if stale:
            log.info("ring adoption invalidated %d link(s): %s",
                     len(stale), [link.member_id for link in stale])

    def _degraded(self, nonce: bytes, ntz: int, attempt: int,
                  exc: BaseException, what: str) -> "_MineFailed":
        metrics.inc("powlib.degraded")
        RECORDER.record("powlib.degraded", nonce=nonce.hex(), ntz=ntz,
                        attempts=attempt, error=str(exc))
        return _MineFailed(
            f"degraded: mine RPC {what} after {attempt} attempt(s) "
            f"({self.retries}-retry budget): {exc}"
        )

    def _mine_cluster(self, trace, nonce: bytes, ntz: int,
                      hash_model: Optional[str] = None) -> Optional[dict]:
        """Cluster-mode Mine: route to the ring owner of the NONCE and
        ride out everything the pool can throw back (docs/CLUSTER.md):

        * ``NOT_OWNER`` — stale client ring: adopt the carried
          snapshot, re-route.  Non-counting (the server did its job);
          only the attempts ceiling bounds a pathological ping-pong.
        * ``RETRY_AFTER`` from the owner — hedged sibling retry: the
          next distinct member on the key's ring walk absorbs the mine
          (``no_redirect``) instead of the client parking on the
          owner's hint.  NON-COUNTING, budget untouched — identical
          semantics to the single-coordinator server-paced retry, the
          wait just becomes useful work on a sibling.  If the sibling
          is saturated too, honor the pacing hint and return to the
          owner.
        * transport failure — PR 1 machinery per shard: backoff +
          re-dial under the link's generation lock (budget-counting,
          budget restored on a successful re-dial).  When the re-dial
          fails the shard is presumed dead and the mine FAILS OVER
          along the ring walk — the sibling serves the foreign key
          over the shared worker fleet; ``cluster.failover_s`` records
          what the death cost this request.  With cache replication on
          (cluster/replication.py, docs/CLUSTER.md "Replication & HA")
          the sibling IS the dead owner's ring successor, so a repeat
          key lands as a dominance-cache hit there — failover serves
          warm, not a re-mine (scripts/ha_smoke.py pins the trace
          shape).
        """
        budget = self.retries
        attempt = 0
        attempts_cap = max(8, self.retries * 10)
        target: Optional[str] = None  # explicit off-owner routing
        dead: set = set()  # members whose re-dial failed this mine
        failover_t0: Optional[float] = None
        while True:
            if self._close_ev.is_set():
                return None
            with self._ring_lock:
                ring = self._ring
            if ring is None:
                return None  # closed
            owner = ring.owner(nonce)
            member = target if target is not None else owner
            foreign = member != owner
            link = self._link_for(member)
            if link is None:
                target = None  # stale target after a ring refresh
                continue
            gen: Optional[int] = None
            try:
                client, gen = link.conn()
                hello = link.take_hello()
                if isinstance(hello.get("ring"), dict):
                    # a FRESH dial's hello ack advertised the pool's
                    # ring (docs/CLUSTER.md): adopt it, and when it
                    # re-routes this key — or moved this member's
                    # address, invalidating the link — re-resolve
                    # BEFORE issuing instead of paying a NOT_OWNER
                    # round trip.  At most one re-resolve per dial
                    # (the hello is consumed), so this cannot spin.
                    self._adopt_ring(hello["ring"])
                    with self._ring_lock:
                        moved = (self._links.get(member) is not link
                                 or (target is None and self._ring
                                     is not None
                                     and self._ring.owner(nonce)
                                     != member))
                    if moved:
                        target = None
                        continue
                result = self._issue_attempt(client, trace, nonce, ntz,
                                             hash_model,
                                             no_redirect=foreign)
                if failover_t0 is not None and foreign:
                    # the observable cost of riding out a shard death:
                    # first owner failure -> successful foreign reply
                    metrics.observe("cluster.failover_s",
                                    time.monotonic() - failover_t0,
                                    trace_id=trace.trace_id)
                    # whether the sibling served from its replicated
                    # cache (warm, the replication plane's promise) or
                    # re-mined is visible one hop down in the trace;
                    # mark the serve so ha_smoke/forensics can join on it
                    RECORDER.record("cluster.failover_served",
                                    member=member,
                                    trace_id=trace.trace_id)
                return result
            except _Closed:
                log.info("mine call abandoned on close")
                return None
            except RPCNotOwner as exc:
                attempt += 1
                if attempt >= attempts_cap:
                    raise self._degraded(nonce, ntz, attempt, exc,
                                         "redirect-looped")
                metrics.inc("cluster.reroutes")
                log.info("mine for %s misrouted to shard %s: adopting "
                         "ring and re-routing", nonce.hex(), member)
                self._adopt_ring(exc.ring)
                target = None
            except RPCTransportError as exc:
                attempt += 1
                if budget <= 0 or attempt >= attempts_cap:
                    raise self._degraded(nonce, ntz, attempt, exc, "failed")
                budget -= 1
                metrics.inc("powlib.retries")
                if failover_t0 is None:
                    failover_t0 = time.monotonic()
                log.warning(
                    "mine RPC transport failure on shard %s (%s); "
                    "%d/%d retries left", member, exc, budget, self.retries,
                )
                if link.reconnect(gen, attempt - 1, self):
                    budget = self.retries
                    dead.discard(member)
                elif link.alive():
                    # the transport is HEALTHY — the failure was an
                    # attempt timeout or a dropped frame, exactly the
                    # case single-coordinator mode re-issues on the
                    # same connection.  No failover: marking a live
                    # owner dead would mis-report a shard death and
                    # sacrifice its dominance-cache locality for the
                    # rest of this mine (review PR 10).
                    dead.discard(member)
                else:
                    # the shard stays unreachable: fail over along the
                    # key's ring walk to the first member not already
                    # found dead this mine (all dead -> start the walk
                    # over; the budget/ceiling still terminate)
                    dead.add(member)
                    nxt = next((m for m in ring.ordered(nonce)
                                if m not in dead), None)
                    if nxt is None:
                        dead = {member}
                        nxt = next((m for m in ring.ordered(nonce)
                                    if m not in dead), None)
                    if nxt is not None and nxt != member:
                        metrics.inc("cluster.failovers")
                        RECORDER.record("cluster.failover",
                                        nonce=nonce.hex(), ntz=ntz,
                                        from_shard=member, to_shard=nxt)
                        log.warning("failing over mine for %s: shard %s "
                                    "-> %s", nonce.hex(), member, nxt)
                    target = nxt
            except RPCRetryAfter as exc:
                attempt += 1
                if attempt >= attempts_cap:
                    raise self._degraded(nonce, ntz, attempt, exc,
                                         "backpressured")
                metrics.inc("powlib.retry_after")
                sibling = next((m for m in ring.ordered(nonce)
                                if m != member), None)
                if not foreign and sibling is not None:
                    # hedged sibling retry: the owner is shedding load,
                    # a sibling may have headroom RIGHT NOW — budget
                    # untouched, no wait (docs/CLUSTER.md)
                    metrics.inc("cluster.sibling_hedges")
                    log.info("mine backpressured by owner %s; hedging "
                             "to sibling %s (non-counting)",
                             member, sibling)
                    target = sibling
                else:
                    # the sibling is saturated too (or the pool is one
                    # shard wide): server-paced wait, then back to the
                    # owner — UNLESS the owner is the member whose
                    # re-dial already failed this mine, in which case
                    # the retry stays on the current (live, merely
                    # busy) member: bouncing to a known-dead owner
                    # would burn one transport-budget unit per pacing
                    # hint and walk a chaos-under-load client into the
                    # terminal degraded error (review PR 10)
                    delay = min(max(exc.delay_s, RETRY_AFTER_MIN_S),
                                RETRY_AFTER_MAX_S)
                    log.info("mine backpressured (%s); retrying in "
                             "%.3fs (server-paced, budget untouched)",
                             exc, delay)
                    if self._close_ev.wait(delay):
                        return None
                    target = member if owner in dead else None
            except RPCError as exc:
                # a handler error from whichever shard served the key:
                # re-issuing would re-earn it (module docstring)
                raise _MineFailed(str(exc))

    def _call_mine(self, tracer, nonce, num_trailing_zeros, trace,
                   hash_model=None) -> None:
        t0 = time.monotonic()
        ts0 = time.time()
        try:
            trace.record_action(
                act.PowlibMine(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
            )
            try:
                result = self._mine_with_retry(trace, nonce,
                                               num_trailing_zeros, hash_model)
            except _MineFailed as exc:
                log.error("mine RPC failed: %s", exc)
                # the client half of the request timeline records its
                # failures too — a degraded mine is forensics evidence,
                # not just a log line (docs/FORENSICS.md)
                SPANS.record("powlib.mine", ts0, time.monotonic() - t0,
                             trace_id=trace.trace_id,
                             node=tracer.identity, ntz=num_trailing_zeros,
                             outcome="error")
                if not self._close_ev.is_set():
                    # deliver the failure: a silent drop would leave
                    # the client blocked on the notify queue forever
                    self.notify_queue.put(MineResult(
                        nonce=nonce,
                        num_trailing_zeros=num_trailing_zeros,
                        secret=None,
                        error=str(exc),
                    ))
                return
            if result is None:  # closed mid-call
                return
            # client-observed mine round-trip, retries and backoff
            # included — the end-to-end latency a caller actually waits.
            # The trace id rides as the histogram's bucket exemplar and
            # keys the client-side span of the request timeline.
            mine_s = time.monotonic() - t0
            metrics.observe("powlib.mine_s", mine_s,
                            trace_id=trace.trace_id)
            SPANS.record("powlib.mine", ts0, mine_s,
                         trace_id=trace.trace_id, node=tracer.identity,
                         ntz=num_trailing_zeros, outcome="ok")
            token = decode_token(result["token"])
            result_trace = tracer.receive_token(token)
            mr = MineResult(
                nonce=bytes(result["nonce"]),
                num_trailing_zeros=int(result["num_trailing_zeros"]),
                secret=bytes(result["secret"]),
                token=token,
            )
            result_trace.record_action(
                act.PowlibSuccess(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            result_trace.record_action(
                act.PowlibMiningComplete(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            if not self._close_ev.is_set():
                self.notify_queue.put(mr)
        finally:
            with self._inflight_lock:
                self._inflight.discard(threading.current_thread())

    def close(self) -> None:
        self._close_ev.set()
        with self._inflight_lock:
            threads = list(self._inflight)
        for t in threads:
            t.join(timeout=5)
        with self._conn_lock:
            client, self.coordinator = self.coordinator, None
        if client is not None:
            client.close()
        with self._ring_lock:
            links, self._links = list(self._links.values()), {}
            self._ring = None
        for link in links:
            link.close()
        log.info("powlib closed")
