"""powlib — the asynchronous client mining library
(SURVEY.md section 2 component 1; reference: powlib/powlib.go).

API parity:

* ``initialize(coord_addr, ch_capacity)`` connects to the coordinator and
  returns the bounded notify queue solutions are delivered on
  (powlib.go:76-93).
* ``mine(tracer, nonce, num_trailing_zeros)`` is non-blocking
  (powlib.go:102-113): it creates a fresh trace, records
  ``PowlibMiningBegin``, and hands off to a request thread which records
  ``PowlibMine``, embeds a token in the RPC args, and issues the async
  ``CoordRPCHandler.Mine`` call (powlib.go:137-156).
* On completion the response token is received back into the tracer and
  ``PowlibSuccess`` + ``PowlibMiningComplete`` are recorded before the
  result lands on the notify queue (powlib.go:164-176).
* ``close()`` stops delivery: in-flight request threads abandon their
  calls (powlib.go:119-135, 179-182) and the connection closes.

Documented divergences from the reference:

* **Coordinator outages are retried, then surfaced — never fatal.**
  The reference ``log.Fatal``s the whole client process on a mine-RPC
  error (powlib.go:161-162).  Here a *transport* failure (connection
  reset/refused, truncated frame, attempt timeout —
  ``rpc.RPCTransportError``) triggers automatic recovery: exponential
  backoff with jitter (``backoff_delay``), a shared re-dial of the
  coordinator connection, and a re-issue of the Mine call — safe
  because Mine is idempotent (the coordinator's dominance cache and
  per-key mutex absorb repeats).  A connection that is still healthy
  (the failure was an attempt timeout or a silently dropped frame) is
  kept and re-issued on; only a dead transport is re-dialed — one slow
  mine hitting its attempt timeout never tears the shared connection
  out from under sibling in-flight mines.  The retry budget is bounded
  (``ClientConfig.MineRetries``); each failed attempt consumes one
  unit, and a *successful* re-dial restores the full budget (an outage
  is charged for its reconnect, not forever) — under an overall
  attempts ceiling (10x the budget, min 8) so a flapping coordinator
  still terminates.  Only when the budget or ceiling is
  exhausted does the notify queue deliver a terminal ``MineResult``
  with ``secret=None`` and ``error="degraded: ..."`` — a caller
  blocked on ``get()`` observes the failure and can escalate; it
  neither crashes nor hangs forever (VERDICT r1 weak #6).  An error
  *returned by* the coordinator's handler (plain ``RPCError``) is not
  retried — re-issuing would just re-earn it — and surfaces as an
  error result immediately.  Counters: ``powlib.retries``,
  ``powlib.reconnects``, ``powlib.degraded`` (runtime/metrics.py).
* **Server-paced backpressure is retried without burning budget.**
  A typed RETRY_AFTER rejection (``rpc.RPCRetryAfter``, minted by the
  coordinator's admission control — sched/admission.py) waits the
  server's own hint and re-issues as a NON-COUNTING attempt: load
  shedding is the server working as designed, so it never consumes the
  transport retry budget nor interacts with the reconnect machinery
  (the connection is healthy).  Only the overall attempts ceiling
  bounds it, so a permanently saturated coordinator still terminates
  in a ``degraded:`` error instead of a hang.  Counter:
  ``powlib.retry_after``.
* **Close handshake.**  The reference re-sends the close token so
  ``Close()`` rendezvouses with every in-flight goroutine
  (powlib.go:179-182) — a mechanism its tracing library needs to keep
  the token chain linear.  This tracer's tokens are self-contained
  (runtime/tracing.py), so ``close()`` instead sets an event that makes
  in-flight threads abandon their calls, then joins them with a bounded
  timeout.  Observable behavior matches: after close, no further
  results are delivered and the process can exit.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass
from typing import Optional

from ..runtime import actions as act
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import RPCClient, RPCError, RPCRetryAfter, RPCTransportError
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from ..runtime.tracing import Tracer, decode_token, wire_token

log = logging.getLogger("distpow.powlib")

# Retry defaults (ClientConfig.Mine* fields override per client).
DEFAULT_RETRIES = 4
DEFAULT_BACKOFF_S = 0.2
DEFAULT_BACKOFF_MAX_S = 2.0
# Bounds on the server's RETRY_AFTER hint (sched/admission.py): the
# floor keeps a zero/garbage hint from spinning; the cap keeps a
# misconfigured server from parking a mine for minutes per attempt.
RETRY_AFTER_MIN_S = 0.01
RETRY_AFTER_MAX_S = 30.0


def backoff_delay(attempt: int, base: float, cap: float,
                  rng: random.Random) -> float:
    """Jittered exponential backoff: uniform in ``[u/2, u]`` where
    ``u = min(cap, base * 2**attempt)`` — so every delay is positive,
    never exceeds ``cap``, and the halved floor keeps reconnect storms
    from synchronizing without ever collapsing the wait to zero."""
    upper = min(cap, base * (2.0 ** attempt))
    return upper * (0.5 + 0.5 * rng.random())


class _Closed(Exception):
    """Internal: close() was called while an attempt was in flight."""


class _MineFailed(Exception):
    """Internal: the attempt loop concluded with a client-visible error."""


@dataclass
class MineResult:
    nonce: bytes
    num_trailing_zeros: int
    secret: Optional[bytes]
    token: Optional[bytes] = None
    # set (with secret=None) when the mine RPC failed terminally — a
    # coordinator handler error, or a coordinator outage that outlived
    # the retry budget ("degraded: ..."); see module docstring
    error: Optional[str] = None


class POW:
    def __init__(self):
        self.coordinator: Optional[RPCClient] = None
        self.notify_queue: Optional["queue.Queue[MineResult]"] = None
        self.coord_addr: Optional[str] = None
        self.retries = DEFAULT_RETRIES
        self.backoff_s = DEFAULT_BACKOFF_S
        self.backoff_max_s = DEFAULT_BACKOFF_MAX_S
        # per-attempt bound on waiting for the Mine response; None waits
        # forever (a legitimate mine can run arbitrarily long, so only
        # chaos/ops configs should set this)
        self.attempt_timeout_s: Optional[float] = None
        self._close_ev = threading.Event()
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()
        # connection generation: in-flight threads that all hit the same
        # outage coordinate through this so exactly one re-dials and the
        # rest reuse the fresh connection
        self._conn_lock = threading.Lock()
        self._conn_gen = 0
        self._rng = random.Random()  # jitter only — never correctness

    def initialize(self, coord_addr: str, ch_capacity: int, *,
                   retries: Optional[int] = None,
                   backoff_s: Optional[float] = None,
                   backoff_max_s: Optional[float] = None,
                   attempt_timeout_s: Optional[float] = None,
                   ) -> "queue.Queue[MineResult]":
        log.info("dialing coordinator at %s", coord_addr)
        self.coord_addr = coord_addr
        if retries is not None:
            self.retries = int(retries)
        if backoff_s is not None:
            self.backoff_s = float(backoff_s)
        if backoff_max_s is not None:
            self.backoff_max_s = float(backoff_max_s)
        if attempt_timeout_s:  # 0/None both mean "wait forever"
            self.attempt_timeout_s = float(attempt_timeout_s)
        self.coordinator = RPCClient(coord_addr)
        self.notify_queue = queue.Queue(maxsize=ch_capacity)
        self._close_ev.clear()
        return self.notify_queue

    def mine(self, tracer: Tracer, nonce: bytes, num_trailing_zeros: int,
             hash_model: Optional[str] = None) -> None:
        if self.coordinator is None:
            raise RuntimeError("powlib not initialized")
        nonce = bytes(nonce)
        trace = tracer.create_trace()
        trace.record_action(
            act.PowlibMiningBegin(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
        )
        t = threading.Thread(
            target=self._call_mine,
            args=(tracer, nonce, num_trailing_zeros, trace,
                  hash_model or None),
            daemon=True,
        )
        with self._inflight_lock:
            self._inflight.add(t)
        t.start()

    # -- the retry machinery ------------------------------------------------
    def _conn(self):
        with self._conn_lock:
            return self.coordinator, self._conn_gen

    def _await_attempt(self, fut):
        """Poll the future, honoring close() and the per-attempt bound."""
        deadline = (
            time.monotonic() + self.attempt_timeout_s
            if self.attempt_timeout_s else None
        )
        while True:
            if self._close_ev.is_set():
                raise _Closed
            try:
                return fut.result(timeout=0.05)
            except (TimeoutError, FutureTimeoutError):
                # both spellings: concurrent.futures.TimeoutError is
                # only an alias of the builtin since Python 3.11
                if deadline is not None and time.monotonic() > deadline:
                    # the frame (or its response) vanished on a healthy
                    # connection — retryable like any transport fault;
                    # the abandoned future is simply never read again
                    raise RPCTransportError(
                        f"mine attempt timed out after "
                        f"{self.attempt_timeout_s:.1f}s"
                    )
                continue
            except CancelledError:
                raise _Closed

    def _issue_attempt(self, client, trace, nonce: bytes, ntz: int,
                       hash_model: Optional[str] = None) -> dict:
        """One Mine RPC attempt on ``client`` (fresh token per attempt).
        ``hash_model`` rides as an extra param only when set, keeping
        default-model frames wire-identical to every earlier version."""
        params = {
            "nonce": bytes(nonce),
            "num_trailing_zeros": ntz,
            "token": wire_token(trace.generate_token()),
        }
        if hash_model:
            params["hash_model"] = hash_model
        fut = client.go("CoordRPCHandler.Mine", params)
        return self._await_attempt(fut)

    def _reconnect(self, stale_gen: int, attempt: int) -> bool:
        """Replace the shared coordinator connection after a transport
        failure observed on generation ``stale_gen``.  Returns True when
        the connection is fresh (this thread re-dialed successfully, or
        a sibling already had) — the caller's cue to restore its retry
        budget.  A connection that is still HEALTHY (``RPCClient.dead``
        false — the failure was an attempt timeout or a dropped frame,
        not a dead transport) is kept: tearing it down would fail every
        sibling mine's pending future mid-flight; the caller simply
        re-issues on it after the backoff.  Holding the lock across the
        backoff sleep is deliberate: concurrent failed attempts queue up
        behind the one re-dialer instead of hammering the coordinator
        with parallel dials."""
        with self._conn_lock:
            if self.coordinator is None:
                return False  # closing
            if self._conn_gen != stale_gen:
                return True  # a sibling attempt already replaced it
            delay = backoff_delay(
                attempt, self.backoff_s, self.backoff_max_s, self._rng
            )
            # distpow: ok no-blocking-under-lock -- holding _conn_lock
            # across the backoff is the design (docstring above): failed
            # attempts queue behind the one re-dialer instead of dial-
            # storming the coordinator; the wait is close()-interruptible
            if self._close_ev.wait(delay):
                return False
            if not getattr(self.coordinator, "dead", True):
                return False  # healthy transport: re-issue on it
            try:
                # distpow: ok no-blocking-under-lock -- exactly-one-dialer:
                # the lock exists to make this dial exclusive (see above);
                # the connect has the RPCClient default dial timeout
                fresh = RPCClient(self.coord_addr)
            except OSError as exc:
                log.warning("coordinator re-dial failed: %s", exc)
                return False
            old, self.coordinator = self.coordinator, fresh
            self._conn_gen += 1
            metrics.inc("powlib.reconnects")
            RECORDER.record("powlib.reconnect", addr=self.coord_addr,
                            gen=self._conn_gen)
            log.info("reconnected to coordinator at %s (gen %d)",
                     self.coord_addr, self._conn_gen)
        try:
            old.close()
        except OSError:
            pass
        return True

    def _mine_with_retry(self, trace, nonce: bytes, ntz: int,
                         hash_model: Optional[str] = None) -> Optional[dict]:
        """Issue Mine until success, terminal failure (_MineFailed), or
        close (returns None).  See the module docstring for semantics.

        Liveness bound: budget resets on a successful re-dial mean a
        FLAPPING coordinator (dial accepts, call dies, repeat) would
        otherwise loop forever — the overall attempts ceiling keeps the
        "terminal error, never a hang" contract true regardless of how
        the outage flaps."""
        budget = self.retries
        attempt = 0
        attempts_cap = max(8, self.retries * 10)
        while True:
            client, gen = self._conn()
            if client is None:
                return None
            try:
                # default-model mines keep the historical 4-arg call
                # shape (chaos tests stub _issue_attempt with it)
                if hash_model:
                    return self._issue_attempt(client, trace, nonce, ntz,
                                               hash_model)
                return self._issue_attempt(client, trace, nonce, ntz)
            except _Closed:
                log.info("mine call abandoned on close")
                return None
            except RPCTransportError as exc:
                attempt += 1
                if budget <= 0 or attempt >= attempts_cap:
                    metrics.inc("powlib.degraded")
                    RECORDER.record("powlib.degraded", nonce=nonce.hex(),
                                    ntz=ntz, attempts=attempt,
                                    error=str(exc))
                    raise _MineFailed(
                        f"degraded: mine RPC failed after {attempt} "
                        f"attempt(s) ({self.retries}-retry budget): {exc}"
                    )
                budget -= 1
                metrics.inc("powlib.retries")
                log.warning(
                    "mine RPC transport failure (%s); %d/%d retries left",
                    exc, budget, self.retries,
                )
                if self._reconnect(gen, attempt - 1):
                    budget = self.retries
            except RPCRetryAfter as exc:
                # server-paced backpressure (the coordinator's bounded
                # run queue, sched/admission.py): wait exactly as long
                # as the server asked and re-issue.  NON-COUNTING: the
                # transport-failure budget stays untouched — shedding
                # load is the server working as designed, not an
                # outage, so it must never walk a client toward the
                # terminal "degraded:" error.  The overall attempts
                # ceiling still applies, keeping the never-hangs
                # contract true against a permanently saturated server.
                attempt += 1
                if attempt >= attempts_cap:
                    metrics.inc("powlib.degraded")
                    RECORDER.record("powlib.degraded", nonce=nonce.hex(),
                                    ntz=ntz, attempts=attempt,
                                    error=str(exc))
                    raise _MineFailed(
                        f"degraded: mine RPC backpressured after "
                        f"{attempt} attempt(s): {exc}"
                    )
                metrics.inc("powlib.retry_after")
                delay = min(max(exc.delay_s, RETRY_AFTER_MIN_S),
                            RETRY_AFTER_MAX_S)
                log.info("mine backpressured (%s); retrying in %.3fs "
                         "(server-paced, budget untouched)", exc, delay)
                if self._close_ev.wait(delay):
                    return None
            except RPCError as exc:
                # the coordinator's handler returned an error: re-issuing
                # would re-earn it — surface immediately (module docstring)
                raise _MineFailed(str(exc))

    def _call_mine(self, tracer, nonce, num_trailing_zeros, trace,
                   hash_model=None) -> None:
        t0 = time.monotonic()
        ts0 = time.time()
        try:
            trace.record_action(
                act.PowlibMine(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
            )
            try:
                result = self._mine_with_retry(trace, nonce,
                                               num_trailing_zeros, hash_model)
            except _MineFailed as exc:
                log.error("mine RPC failed: %s", exc)
                # the client half of the request timeline records its
                # failures too — a degraded mine is forensics evidence,
                # not just a log line (docs/FORENSICS.md)
                SPANS.record("powlib.mine", ts0, time.monotonic() - t0,
                             trace_id=trace.trace_id,
                             node=tracer.identity, ntz=num_trailing_zeros,
                             outcome="error")
                if not self._close_ev.is_set():
                    # deliver the failure: a silent drop would leave
                    # the client blocked on the notify queue forever
                    self.notify_queue.put(MineResult(
                        nonce=nonce,
                        num_trailing_zeros=num_trailing_zeros,
                        secret=None,
                        error=str(exc),
                    ))
                return
            if result is None:  # closed mid-call
                return
            # client-observed mine round-trip, retries and backoff
            # included — the end-to-end latency a caller actually waits.
            # The trace id rides as the histogram's bucket exemplar and
            # keys the client-side span of the request timeline.
            mine_s = time.monotonic() - t0
            metrics.observe("powlib.mine_s", mine_s,
                            trace_id=trace.trace_id)
            SPANS.record("powlib.mine", ts0, mine_s,
                         trace_id=trace.trace_id, node=tracer.identity,
                         ntz=num_trailing_zeros, outcome="ok")
            token = decode_token(result["token"])
            result_trace = tracer.receive_token(token)
            mr = MineResult(
                nonce=bytes(result["nonce"]),
                num_trailing_zeros=int(result["num_trailing_zeros"]),
                secret=bytes(result["secret"]),
                token=token,
            )
            result_trace.record_action(
                act.PowlibSuccess(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            result_trace.record_action(
                act.PowlibMiningComplete(
                    nonce=mr.nonce,
                    num_trailing_zeros=mr.num_trailing_zeros,
                    secret=mr.secret,
                )
            )
            if not self._close_ev.is_set():
                self.notify_queue.put(mr)
        finally:
            with self._inflight_lock:
                self._inflight.discard(threading.current_thread())

    def close(self) -> None:
        self._close_ev.set()
        with self._inflight_lock:
            threads = list(self._inflight)
        for t in threads:
            t.join(timeout=5)
        with self._conn_lock:
            client, self.coordinator = self.coordinator, None
        if client is not None:
            client.close()
        log.info("powlib closed")
