"""Elastic worker-fleet membership plane (docs/FLEET.md).

The reference fixes its worker set in a config file: the coordinator
dials the list at boot and a worker can leave only by crashing.  This
package adds lease-based membership on top of the existing RPC layer —

* :mod:`.capability` — the capability advertisement a worker registers
  with (backend, hash models, measured MH/s from a short
  self-calibration, scheduler slot width);
* :mod:`.membership` — the coordinator-side lease registry + the
  ``Fleet`` RPC service (Register / Heartbeat / Drain / Members) and
  the per-round capability-weighted shard plan;
* :mod:`.agent` — the worker-side agent: self-calibrate, register,
  heartbeat, re-register after a lease loss, drain on shutdown.

Static config-file workers remain first-class: they boot as
pre-registered PERMANENT leases, so existing configs, tests and golden
traces see byte-identical behavior.
"""

from .capability import Capability, calibrate_mhs
from .membership import FleetRegistry, FleetService, RoundPlan, WorkerLease
from .agent import FleetAgent

__all__ = [
    "Capability",
    "calibrate_mhs",
    "FleetAgent",
    "FleetRegistry",
    "FleetService",
    "RoundPlan",
    "WorkerLease",
]
