"""Worker capability advertisement (docs/FLEET.md "Capability").

What a worker tells the coordinator at registration time so shard
assignment can stop pretending the fleet is homogeneous (ROADMAP item
4; HashCore in PAPERS.md motivates capability-aware scheduling across
heterogeneous provers): the compute backend, the hash models it can
serve, a MEASURED hash rate from a short boot-time self-calibration,
and the batching scheduler's slot width.  The measured MH/s feeds the
capability-weighted prefix split (parallel/partition.py
``weighted_ranges``); the rest is operator-facing (``Fleet.Members``,
``stats --discover``) and reserved for future placement policy.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Optional, Tuple

log = logging.getLogger("distpow.fleet")


@dataclass(frozen=True)
class Capability:
    """One worker's advertisement; travels as a plain dict on the wire
    (both codecs encode dicts natively, so no schema machinery)."""

    backend: str = "python"
    hash_models: Tuple[str, ...] = ("md5",)
    #: measured hash rate in MH/s; 0.0 = unknown (calibration skipped
    #: or failed) — an unknown rate makes the whole plan fall back to
    #: the reference equal split (membership.py round_plan)
    mhs: float = 0.0
    #: batching-scheduler slot width (WorkerConfig.SchedMaxSlots); 0 =
    #: no batching scheduler
    max_slots: int = 0

    def to_wire(self) -> dict:
        return {
            "backend": self.backend,
            "hash_models": list(self.hash_models),
            "mhs": float(self.mhs),
            "max_slots": int(self.max_slots),
        }

    @classmethod
    def from_wire(cls, d: Optional[dict]) -> "Capability":
        d = d or {}
        return cls(
            backend=str(d.get("backend") or "unknown"),
            hash_models=tuple(str(m) for m in (d.get("hash_models") or ())),
            mhs=max(0.0, float(d.get("mhs") or 0.0)),
            max_slots=int(d.get("max_slots") or 0),
        )


def calibrate_mhs(backend: object, budget_s: float = 0.2,
                  nonce: bytes = b"\xfc\x01", difficulty: int = 8) -> float:
    """Measure the backend's hash rate with a short budgeted search.

    Runs ``backend.search`` over the full first-byte space at a
    satisfiable-but-hard difficulty (md5 at ntz=8 is ~16^-8 per
    candidate — statistically unreachable inside the budget, but every
    candidate is hashed and counted, unlike an UNSATISFIABLE difficulty
    which the serving path parks without hashing) and reads the
    ``search.hashes`` counter delta around it.  The counter is
    process-global, so a calibration racing live traffic reads high —
    acceptable for an ADVERTISEMENT (this runs once at boot, before the
    worker registers), and the weighted split degrades gracefully:
    weights shift shares, they never drop coverage.

    Best-effort by contract: any failure (a backend without the counter
    discipline, a compile error, a zero-length budget) returns 0.0 —
    "unknown", which keeps the fleet on the reference equal split
    rather than poisoning it with a garbage weight.
    """
    if budget_s <= 0:
        return 0.0
    from ..runtime.metrics import REGISTRY as metrics

    deadline = time.monotonic() + budget_s
    try:
        before = metrics.get("search.hashes")
        t0 = time.monotonic()
        backend.search(
            bytes(nonce), int(difficulty), list(range(256)),
            cancel_check=lambda: time.monotonic() >= deadline,
        )
        elapsed = time.monotonic() - t0
        hashed = metrics.get("search.hashes") - before
        if elapsed <= 0 or hashed <= 0:
            return 0.0
        return round(hashed / elapsed / 1e6, 4)
    except Exception as exc:  # calibration must never kill worker boot
        log.warning("self-calibration failed (%s); advertising unknown "
                    "rate", exc)
        return 0.0
