"""Coordinator-side lease registry + the ``Fleet`` RPC service.

Membership model (docs/FLEET.md "Lease protocol"):

* **Static workers** (the config-file ``Workers`` list) boot as
  pre-registered PERMANENT leases — no heartbeats, never expired, never
  hedge-stale.  A static-only fleet behaves byte-identically to every
  earlier version of this repo.
* **Elastic workers** call ``Fleet.Register`` with their reachable RPC
  address and a :class:`..fleet.capability.Capability`; the reply
  carries a lease id, the lease TTL and a heartbeat-interval hint.
  They renew via ``Fleet.Heartbeat`` — whose ARRIVAL CADENCE is the
  progress signal straggler hedging keys off (no payload beyond the
  lease id) — leave via ``Fleet.Drain`` (the
  lease is released only once their in-flight rounds complete), and a
  lease that misses its TTL expires: the registry's reaper closes the
  worker's connection and removes it from membership, which drops it
  into the coordinator's existing ``_mark_dead``/``_reap_dead``
  orphan-reassignment path — a vanished worker is indistinguishable
  from a crashed one.
* A worker that lost its lease (SIGSTOP'd past the TTL, network
  partition) re-registers under the SAME worker id: the stale entry is
  retired first, so recovery cannot double-assign shards to a zombie
  twin of itself.

Every transition emits a flight-recorder event and ticks the declared
``fleet.*`` metrics (runtime/metrics.py; docs/METRICS.md).

Shard planning: :meth:`FleetRegistry.round_plan` snapshots the
in-service refs and — when every member advertises a measured rate and
the rates differ — attaches the capability-weighted prefix split
(parallel/partition.py ``weighted_ranges``) as per-shard explicit
``(tb_lo, tb_count)`` ranges; otherwise the plan is the reference
``worker_byte``/``worker_bits`` algebra, wire-identical to before.
"""

from __future__ import annotations

import secrets
import statistics
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Tuple

from ..parallel import partition
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from .capability import Capability

if TYPE_CHECKING:  # runtime import would be circular (nodes -> fleet)
    from ..nodes.coordinator import WorkerRef


class WorkerLease:
    """One member's lease state (guarded by the registry lock — the
    mutable fields below carry ``# guarded-by`` declarations, so
    distpow-lint enforces what this docstring used to merely say:
    docs/CONCURRENCY.md)."""

    __slots__ = ("lease_id", "worker_id", "ttl_s", "permanent", "state",
                 "last_beat", "registered_at", "beat_ema_s", "capability")

    def __init__(self, worker_id: str, ttl_s: float, permanent: bool,
                 capability: Optional[Capability] = None) -> None:
        self.lease_id = secrets.token_hex(8)
        self.worker_id = worker_id
        self.ttl_s = float(ttl_s)
        self.permanent = bool(permanent)
        self.state = "live"  # live | draining; guarded-by: registry._lock
        self.last_beat = time.monotonic()  # guarded-by: registry._lock
        self.registered_at = self.last_beat
        #: observed heartbeat cadence (EMA); None until two beats landed
        self.beat_ema_s: Optional[float] = None  # guarded-by: registry._lock
        self.capability = capability

    def beat(self) -> None:
        now = time.monotonic()
        interval = now - self.last_beat
        if interval > 0:
            self.beat_ema_s = (interval if self.beat_ema_s is None
                               else 0.7 * self.beat_ema_s + 0.3 * interval)
        self.last_beat = now

    def expired(self, now: float) -> bool:
        # a DRAINING lease never expires: the agent stops heartbeating
        # before it issues Fleet.Drain, so a drain outlasting the TTL
        # would otherwise be expired mid-drain — crashing out the exact
        # worker the graceful path is finishing (and double-counting
        # the departure).  Safe from leaks: "draining" is only ever set
        # by drain(), whose bounded server-side wait ALWAYS releases
        # the lease within its timeout, worker fate notwithstanding.
        return (not self.permanent and self.state != "draining"
                and (now - self.last_beat) > self.ttl_s)

    def beat_age(self, now: float) -> Optional[float]:
        return None if self.permanent else now - self.last_beat

    def to_wire(self, now: float) -> dict:
        out = {
            "worker_id": self.worker_id,
            "state": self.state,
            "permanent": self.permanent,
            "ttl_s": self.ttl_s,
            "age_s": round(now - self.registered_at, 3),
        }
        if not self.permanent:
            out["beat_age_s"] = round(now - self.last_beat, 3)
        if self.capability is not None:
            out["capability"] = self.capability.to_wire()
        return out


class RoundPlan:
    """One fan-out round's shard layout: a snapshot of the in-service
    workers plus (optionally) explicit weighted byte ranges per shard.
    Round-local and mutable — hedging appends duplicate placements."""

    __slots__ = ("entries", "worker_bits", "ranges")

    def __init__(self, entries: List[tuple], worker_bits: int,
                 ranges: Optional[Dict[int, Tuple[int, int]]]) -> None:
        #: ``[(WorkerRef, shard_id), ...]`` — shard_id doubles as the
        #: wire ``worker_byte`` (the partition travels in the RPC, so a
        #: foreign shard on a reassigned/hedged worker is routine)
        self.entries = entries
        self.worker_bits = worker_bits
        #: shard_id -> (tb_lo, tb_count); None = reference algebra
        self.ranges = ranges

    def mine_extra(self, shard: int) -> dict:
        """Per-shard Mine params beyond the reference set: the explicit
        weighted byte range, when this plan carries one."""
        if self.ranges is None:
            return {}
        rng = self.ranges.get(shard)
        if rng is None:
            return {}
        return {"tb_lo": rng[0], "tb_count": rng[1]}


class FleetRegistry:
    """Lease table + round planner.  Owns the coordinator's mutable
    ``WorkerRef`` list (the handler's ``self.workers`` IS this list);
    every mutation happens under the registry lock, and round-scoped
    consumers always work from snapshots."""

    #: reaper cadence = max(ttl/4, floor); one bounded daemon thread
    REAP_FLOOR_S = 0.25

    def __init__(self, refs: List[object], lease_ttl_s: float = 10.0,
                 hedge: bool = True, hedge_multiple: float = 3.0,
                 on_expire: Optional[Callable[[object], None]] = None,
                 make_ref: Optional[Callable[[str, int], object]] = None) -> None:
        self._lock = threading.Lock()
        #: shared with CoordRPCHandler.workers
        self.refs: List["WorkerRef"] = refs
        self.lease_ttl_s = float(lease_ttl_s)
        self.hedge_enabled = bool(hedge)
        self.hedge_multiple = float(hedge_multiple)
        self._on_expire = on_expire
        self._make_ref = make_ref
        self._by_lease: Dict[str, "WorkerRef"] = {}
        self._next_byte = len(refs)
        self._reaper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # static config workers: pre-registered permanent leases
        # (indexed like any other lease, so a misdirected Drain against
        # one earns the typed static-workers-cannot-drain rejection,
        # not an unknown-lease error)
        with self._lock:
            for ref in refs:
                ref.lease = WorkerLease(
                    worker_id=f"static{ref.worker_byte}",
                    ttl_s=self.lease_ttl_s, permanent=True,
                )
                ref.inflight_rounds = 0
                self._by_lease[ref.lease.lease_id] = ref
            self._publish_gauge_locked()

    # -- gauges / helpers ---------------------------------------------------
    def _publish_gauge_locked(self) -> None:
        live = sum(1 for r in self.refs
                   if r.lease is not None and r.lease.state == "live")
        metrics.gauge("fleet.live_workers", live)

    def _in_service(self, ref: "WorkerRef") -> bool:
        lease = getattr(ref, "lease", None)
        return lease is not None and lease.state == "live"

    def in_service(self, ref: "WorkerRef") -> bool:
        with self._lock:
            return self._in_service(ref)

    # -- registration / heartbeat / drain -----------------------------------
    def register(self, worker_id: str, addr: str,
                 capability: Capability) -> dict:
        """Admit (or re-admit) one elastic worker; returns the lease
        grant.  A stale entry under the same worker id is retired first
        — SIGSTOP recovery must not leave a zombie twin that still owns
        (and double-assigns) first-byte space."""
        if not worker_id or not addr:
            raise ValueError("Register needs worker_id and addr")
        retired = None
        with self._lock:
            for ref in list(self.refs):
                lease = getattr(ref, "lease", None)
                if lease is not None and not lease.permanent and \
                        lease.worker_id == worker_id:
                    retired = ref
                    self.refs.remove(ref)
                    self._by_lease.pop(lease.lease_id, None)
            ref = self._make_ref(addr, self._next_byte)
            self._next_byte += 1
            lease = WorkerLease(worker_id=worker_id, ttl_s=self.lease_ttl_s,
                                permanent=False, capability=capability)
            ref.lease = lease
            ref.inflight_rounds = 0
            self.refs.append(ref)
            self._by_lease[lease.lease_id] = ref
            self._publish_gauge_locked()
        if retired is not None and self._on_expire is not None:
            # the replaced entry's connection must not linger half-dead
            self._on_expire(retired)
        metrics.inc("fleet.joins")
        RECORDER.record("fleet.join", worker_id=worker_id, addr=addr,
                        rejoin=retired is not None,
                        mhs=capability.mhs, backend=capability.backend,
                        lease_ttl_s=self.lease_ttl_s)
        self._ensure_reaper()
        return {
            "lease_id": lease.lease_id,
            "ttl_s": self.lease_ttl_s,
            # the hint elastic workers without an explicit config beat
            # at: 3 beats per TTL keeps one lost heartbeat survivable
            "heartbeat_s": round(self.lease_ttl_s / 3.0, 3),
        }

    def heartbeat(self, lease_id: str) -> dict:
        with self._lock:
            ref = self._by_lease.get(lease_id)
            if ref is None or ref.lease is None or \
                    ref.lease.lease_id != lease_id:
                # the agent treats this as "lease lost: re-register" —
                # the SIGSTOP-recovery path (module docstring)
                raise KeyError(f"unknown lease {lease_id!r}")
            ref.lease.beat()
            state = ref.lease.state
        return {"ok": True, "state": state, "ttl_s": self.lease_ttl_s}

    def drain(self, lease_id: str, timeout_s: float = 20.0) -> dict:
        """Graceful leave: mark the member draining (no new shards, no
        hedge duplicates land on it), wait — bounded — for its in-flight
        rounds to finish, then release the lease.  The worker keeps
        serving its current shards throughout, so a drain mid-round
        completes the shard instead of orphaning it."""
        with self._lock:
            ref = self._by_lease.get(lease_id)
            if ref is None or ref.lease is None:
                raise KeyError(f"unknown lease {lease_id!r}")
            if ref.lease.permanent:
                raise ValueError("static workers cannot drain "
                                 "(remove them from the config instead)")
            ref.lease.state = "draining"
            self._publish_gauge_locked()
        RECORDER.record("fleet.drain_begin",
                        worker_id=ref.lease.worker_id,
                        inflight_rounds=ref.inflight_rounds)
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while time.monotonic() < deadline:
            with self._lock:
                pending = ref.inflight_rounds
            if pending <= 0:
                break
            time.sleep(0.05)
        with self._lock:
            pending = ref.inflight_rounds
            if ref in self.refs:
                self.refs.remove(ref)
            self._by_lease.pop(lease_id, None)
            self._publish_gauge_locked()
        if self._on_expire is not None:
            self._on_expire(ref)
        metrics.inc("fleet.drains")
        RECORDER.record("fleet.drain", worker_id=ref.lease.worker_id,
                        drained=pending <= 0, pending_rounds=pending)
        return {"drained": pending <= 0, "pending_rounds": pending}

    # -- expiry -------------------------------------------------------------
    def _ensure_reaper(self) -> None:
        with self._lock:
            if self._reaper is not None and self._reaper.is_alive():
                return
            interval = max(self.REAP_FLOOR_S, self.lease_ttl_s / 4.0)
            self._reaper = threading.Thread(
                target=self._reap_loop, args=(interval,), daemon=True,
                name="fleet-reaper",
            )
            self._reaper.start()

    def _reap_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.expire_stale()

    def expire_stale(self, now: Optional[float] = None) -> List[object]:
        """Retire every lease past its TTL; feeds each retired ref to
        ``on_expire`` (the coordinator's ``_mark_dead``) so a vanished
        worker joins the same orphan-reassignment path a crashed one
        does.  Returns the retired refs (tests and the bench poll it)."""
        now = time.monotonic() if now is None else now
        # beat ages snapshot INSIDE the lock with the expiry decision:
        # last_beat is written by heartbeat() on RPC handler threads,
        # and the old bare reads below the critical section raced it
        # (unguarded-shared-write sweep, ISSUE 17)
        expired: List[Tuple[object, float]] = []
        with self._lock:
            for ref in list(self.refs):
                lease = getattr(ref, "lease", None)
                if lease is not None and lease.expired(now):
                    expired.append((ref, round(now - lease.last_beat, 3)))
                    self.refs.remove(ref)
                    self._by_lease.pop(lease.lease_id, None)
            if expired:
                self._publish_gauge_locked()
        for ref, beat_age_s in expired:
            metrics.inc("fleet.lease_expiries")
            RECORDER.record("fleet.lease_expiry",
                            worker_id=ref.lease.worker_id,
                            beat_age_s=beat_age_s,
                            ttl_s=ref.lease.ttl_s)
            # fleet-scoped forensics marker (docs/FORENSICS.md): no
            # request in scope on the reaper thread, so this records
            # under trace 0 — visible in the ring and in dumps, and the
            # orphaned shards' reassignment shows up per-trace via the
            # coord.reassign spans the next probe cycle mints
            SPANS.event("fleet.lease_expiry", trace_id=0,
                        worker_id=ref.lease.worker_id,
                        worker_byte=getattr(ref, "worker_byte", None),
                        beat_age_s=beat_age_s)
            if self._on_expire is not None:
                self._on_expire(ref)
        return [ref for ref, _ in expired]

    # -- round planning -----------------------------------------------------
    def round_plan(self) -> RoundPlan:
        """Snapshot the in-service members into one round's shard plan.

        Weighted ranges attach only when EVERY member advertises a
        measured rate and the rates differ — any unknown (static
        workers advertise none) keeps the whole round on the reference
        equal split, because mixing measured MH/s with guesses would
        skew shares by an uncalibrated constant.
        """
        with self._lock:
            refs = [r for r in self.refs if self._in_service(r)]
        n = len(refs)
        if n == 0:
            return RoundPlan([], 0, None)
        bits = partition.worker_bits(n)
        weights = []
        for r in refs:
            cap = r.lease.capability if r.lease is not None else None
            weights.append(cap.mhs if cap is not None and cap.mhs > 0
                           else None)
        ranges = None
        if n <= 256 and all(w is not None for w in weights) and \
                len(set(weights)) > 1:
            ranges = {i: rng
                      for i, rng in enumerate(partition.weighted_ranges(
                          [float(w) for w in weights]))}
        return RoundPlan([(r, i) for i, r in enumerate(refs)], bits, ranges)

    def track_round(self, refs: List[object], delta: int) -> None:
        """Round-level in-flight accounting (drain waits on it): +1 per
        distinct ref at fan-out, -1 when the round ends."""
        with self._lock:
            for ref in {id(r): r for r in refs}.values():
                ref.inflight_rounds = max(
                    0, getattr(ref, "inflight_rounds", 0) + delta)

    # -- straggler signals --------------------------------------------------
    def median_beat_interval(self) -> float:
        """Median observed heartbeat cadence across heartbeat leases —
        the fleet's "progress interval" straggler hedging multiplies.
        Falls back to the TTL-derived hint while cadences are still
        unobserved."""
        with self._lock:
            obs = [r.lease.beat_ema_s for r in self.refs
                   if r.lease is not None and not r.lease.permanent
                   and r.lease.beat_ema_s is not None]
        if not obs:
            return self.lease_ttl_s / 3.0
        return statistics.median(obs)

    def hedge_after_s(self) -> float:
        return self.hedge_multiple * self.median_beat_interval()

    def is_stale(self, ref: "WorkerRef",
                 threshold_s: Optional[float] = None) -> bool:
        """True when a HEARTBEAT member has not reported for longer
        than ``threshold_s`` (default: the hedge threshold).  Permanent
        leases never heartbeat, so they are never stale — static fleets
        keep their probe-based failure detection unchanged.

        The beat clock is read under the registry lock: ``last_beat``
        is written by ``heartbeat()`` on RPC handler threads, and the
        bare read here raced it (found by distpow-lint's
        unguarded-shared-write sweep, ISSUE 17 — ``test_is_stale_
        reads_beat_clock_under_registry_lock`` pins the discipline).
        ``hedge_after_s()`` re-takes the lock, so it must stay outside
        the critical section."""
        with self._lock:
            lease = getattr(ref, "lease", None)
            if lease is None or lease.permanent:
                return False
            age = lease.beat_age(time.monotonic())
        t = self.hedge_after_s() if threshold_s is None else threshold_s
        return age is not None and age > t

    # -- views --------------------------------------------------------------
    def members(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            out = []
            for ref in self.refs:
                lease = getattr(ref, "lease", None)
                row = {"addr": ref.addr, "worker_byte": ref.worker_byte,
                       "connected": ref.client is not None,
                       "inflight_rounds": getattr(ref, "inflight_rounds", 0)}
                if lease is not None:
                    row.update(lease.to_wire(now))
                out.append(row)
        return out

    def close(self) -> None:
        self._stop.set()


class FleetService:
    """The ``Fleet`` RPC service the coordinator registers on both its
    listeners (runtime/rpc.py dispatch): thin translation between wire
    params and the registry."""

    def __init__(self, registry: FleetRegistry,
                 drain_timeout_s: float = 20.0) -> None:
        self._registry = registry
        self._drain_timeout_s = float(drain_timeout_s)

    def Register(self, params: dict) -> dict:
        cap = Capability.from_wire(params.get("capability"))
        return self._registry.register(
            str(params.get("worker_id") or ""),
            str(params.get("addr") or ""),
            cap,
        )

    def Heartbeat(self, params: dict) -> dict:
        return self._registry.heartbeat(str(params.get("lease_id") or ""))

    def Drain(self, params: dict) -> dict:
        # the wait bound is CLAMPED by the coordinator's own configured
        # ceiling: the TTL exemption for draining leases (expired())
        # holds only because this wait provably releases — a
        # client-supplied timeout must not be able to pin a lease and a
        # dispatch thread for a mistyped day
        timeout = params.get("timeout_s")
        if timeout is None:
            timeout = self._drain_timeout_s
        else:
            timeout = min(float(timeout), self._drain_timeout_s)
        return self._registry.drain(
            str(params.get("lease_id") or ""), timeout_s=timeout,
        )

    def Members(self, params: dict) -> dict:
        return {"workers": self._registry.members(),
                "lease_ttl_s": self._registry.lease_ttl_s,
                "hedge": self._registry.hedge_enabled}
