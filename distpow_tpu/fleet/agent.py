"""Worker-side fleet agent: calibrate, register, heartbeat, drain.

Lifecycle (docs/FLEET.md "Joining and leaving"):

1. **Calibrate** — a short budgeted search measures the backend's MH/s
   (capability.py ``calibrate_mhs``; best-effort, 0.0 = unknown).
2. **Register** — ``Fleet.Register`` with the worker's reachable RPC
   address and capability; the reply's lease id + TTL + heartbeat hint
   arm the heartbeat loop.  Registration retries with backoff on its
   own daemon thread, so a worker booted before its coordinator still
   joins once the coordinator is up.
3. **Heartbeat** — one persistent loop thread renews the lease every
   interval; the observed round trip feeds ``fleet.heartbeat_rtt_s``.
   An "unknown lease" error means the lease was lost (SIGSTOP past the
   TTL, coordinator restart, partition) — the agent RE-REGISTERS with
   the same worker id and carries on with the fresh lease; transport
   failures re-dial with backoff.
4. **Drain** — ``stop(drain=True)`` (the worker's shutdown path) issues
   a bounded ``Fleet.Drain`` so in-flight shards finish before the
   lease is released; only then does shutdown proceed.  A dead
   coordinator cannot block shutdown: the drain call is bounded and
   best-effort.

The agent is a pure client of the PR 5 RPC layer — heartbeats ride
wire v2 when the coordinator speaks it, and the fault plane can
refuse/delay/drop them like any other frame (chaos tests).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..runtime.metrics import REGISTRY as metrics
from ..runtime.rpc import RPCClient, RPCError, RPCTransportError
from ..runtime.telemetry import RECORDER
from .capability import Capability

log = logging.getLogger("distpow.fleet")


class FleetAgent:
    """One worker's membership client (module docstring)."""

    #: registration retry backoff bounds (jitter-free: one worker, one
    #: coordinator — the powlib thundering-herd concern does not apply)
    REGISTER_BACKOFF_S = 0.2
    REGISTER_BACKOFF_MAX_S = 5.0

    def __init__(self, worker_id: str, coord_addr: str, listen_addr: str,
                 capability: Capability, heartbeat_s: float = 0.0,
                 drain_timeout_s: float = 20.0) -> None:
        self.worker_id = worker_id
        self.coord_addr = coord_addr
        self.listen_addr = listen_addr
        self.capability = capability
        #: 0 = use the coordinator's hint from the Register reply
        self._heartbeat_s = float(heartbeat_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._client: Optional[RPCClient] = None
        self._lease_id: Optional[str] = None
        self._interval = 1.0
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registered = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Arm the register+heartbeat loop (one persistent daemon
        thread; never spawned per beat)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-agent-{self.worker_id}",
        )
        self._thread.start()

    def wait_registered(self, timeout: float = 10.0) -> bool:
        """Block until the first successful registration (tests, smoke
        scripts); True on success within ``timeout``."""
        return self._registered.wait(timeout)

    def pause(self) -> None:
        """Suspend heartbeats WITHOUT releasing the lease — the
        in-process stand-in for a frozen worker (bench --membership's
        straggler; the real-SIGSTOP variant lives in the subprocess
        tests)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def stop(self, drain: bool = True) -> dict:
        """Stop the loop; optionally drain first (bounded).  Returns
        the drain reply (or a marker dict when no drain happened)."""
        self._stop.set()
        out: dict = {"drained": False, "skipped": True}
        client, lease = self._client, self._lease_id
        if drain and client is not None and lease is not None:
            try:
                out = client.call(
                    "Fleet.Drain",
                    {"lease_id": lease, "timeout_s": self._drain_timeout_s},
                    timeout=self._drain_timeout_s + 5.0,
                )
                out["skipped"] = False
                RECORDER.record("fleet.drained", worker_id=self.worker_id,
                                drained=bool(out.get("drained")))
            except Exception as exc:  # best-effort by contract
                log.info("%s: drain failed (%s); leaving by lease expiry",
                         self.worker_id, exc)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None
        return out

    # -- the loop -----------------------------------------------------------
    def _dial(self) -> RPCClient:
        if self._client is None or self._client.dead:
            if self._client is not None:
                try:
                    self._client.close()
                except OSError:
                    pass
            self._client = RPCClient(self.coord_addr, timeout=5.0)
        return self._client

    def _register_once(self) -> None:
        client = self._dial()
        reply = client.call("Fleet.Register", {
            "worker_id": self.worker_id,
            "addr": self.listen_addr,
            "capability": self.capability.to_wire(),
        }, timeout=10.0)
        self._lease_id = str(reply["lease_id"])
        hint = float(reply.get("heartbeat_s") or 1.0)
        self._interval = self._heartbeat_s if self._heartbeat_s > 0 else hint
        self._registered.set()
        log.info("%s: joined fleet (lease %s, ttl %.1fs, beating every "
                 "%.2fs)", self.worker_id, self._lease_id,
                 float(reply.get("ttl_s") or 0.0), self._interval)

    def _run(self) -> None:
        backoff = self.REGISTER_BACKOFF_S
        while not self._stop.is_set():
            try:
                if self._lease_id is None:
                    self._register_once()
                    backoff = self.REGISTER_BACKOFF_S
                    # registration itself proved liveness: wait a full
                    # interval before the first heartbeat, so the
                    # registry's cadence EMA never sees a near-zero
                    # register->beat gap (a tiny first sample would
                    # drag the fleet's median — and with it the hedge
                    # threshold — low enough to flag HEALTHY members
                    # as stale between ordinary beats)
                    if self._stop.wait(self._interval):
                        return
                    continue
                if self._paused.is_set():
                    if self._stop.wait(0.05):
                        return
                    continue
                t0 = time.monotonic()
                client = self._dial()
                client.call("Fleet.Heartbeat",
                            {"lease_id": self._lease_id},
                            timeout=min(10.0, self._interval * 4 + 1.0))
                metrics.observe("fleet.heartbeat_rtt_s",
                                time.monotonic() - t0)
                backoff = self.REGISTER_BACKOFF_S  # healthy again
                if self._stop.wait(self._interval):
                    return
            except (RPCTransportError, OSError) as exc:
                # coordinator away: keep the lease id (it may still be
                # valid when the coordinator returns) and re-dial.
                # OSError belongs HERE, not below — a refused re-dial
                # raises it raw from the RPCClient constructor, and
                # misreading that as a lost lease would re-register and
                # retire a perfectly valid lease mid-round (review
                # PR 8: register's twin-retirement closes the
                # coordinator's healthy connection to this worker).
                log.info("%s: heartbeat transport failure (%s); retrying "
                         "in %.1fs", self.worker_id, exc, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, self.REGISTER_BACKOFF_MAX_S)
            except RPCError as exc:
                # handler-level rejection — almost always "unknown
                # lease": the lease was lost while we were gone
                # (SIGSTOP past the TTL).  Re-register FRESH: the
                # registry retires any stale twin under our worker id,
                # so recovery cannot double-own first-byte space.
                log.info("%s: lease lost (%s); re-registering",
                         self.worker_id, exc)
                RECORDER.record("fleet.lease_lost",
                                worker_id=self.worker_id, error=str(exc))
                self._lease_id = None
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, self.REGISTER_BACKOFF_MAX_S)
