"""The distpow-lint rule engine.

Walks every ``.py`` module under the scanned roots, parses each once,
and hands the parse to every registered rule (``rules/`` — one module
per rule).  Rules yield :class:`Finding`s; the engine then applies the
suppression protocol and the exit-code contract:

Suppression protocol
    A finding is suppressed by a ``# distpow: ok <rule-id>`` comment
    either trailing the finding's own line, or in the comment block
    directly above it (the suppression covers the first code line after
    its comment block, so a multi-line justification reads naturally).
    A suppression MUST carry a justification after ``--`` (``# distpow:
    ok no-blocking-under-lock -- the write lock IS the frame
    serializer``); a bare suppression is itself reported (rule id
    ``bare-suppression``), and a suppression that matches no finding is
    reported as ``unused-suppression`` — stale suppressions must not
    rot in the tree.  Several ids may be listed comma-separated.

Exit-code contract (scripts/lint.py)
    0 — no active findings (suppressed ones are counted, not fatal)
    1 — at least one active finding
    2 — usage or internal error

The engine is deliberately stdlib-only: it must run in environments
where jax cannot import (CI sandboxes, pre-commit hooks) and must never
import the code it scans.  Project facts rules need — the declared
action vocabulary, the metrics counter registry, the config dataclass
fields — are parsed out of the package's own source by
:func:`build_context`, so the linter and the runtime can never disagree
about where the truth lives.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*distpow:\s*ok\s+(?P<rules>[a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)"
    r"(?:\s+--\s*(?P<why>\S.*))?"
)

BARE_SUPPRESSION = "bare-suppression"
UNUSED_SUPPRESSION = "unused-suppression"


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # relative to the scan invocation's cwd
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]
    justification: str
    used: bool = False


@dataclass
class Module:
    """One parsed source file as rules see it."""

    path: str
    tree: ast.Module
    source: str
    suppressions: List[Suppression] = field(default_factory=list)

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) else node
        return Finding(rule=rule, path=self.path, line=line, message=message)


@dataclass
class ProjectContext:
    """Cross-module facts parsed from the package's own declarations.

    Every field has a usable default so the engine can lint loose files
    (the fixture corpus) without a package root; :func:`build_context`
    fills them from ``runtime/actions.py``, ``runtime/metrics.py`` and
    ``runtime/config.py`` when scanning the real tree.
    """

    action_names: Set[str] = field(default_factory=set)
    counters: Set[str] = field(default_factory=set)
    counter_prefixes: Tuple[str, ...] = ()
    histograms: Set[str] = field(default_factory=set)
    histogram_prefixes: Tuple[str, ...] = ()
    gauges: Set[str] = field(default_factory=set)
    gauge_prefixes: Tuple[str, ...] = ()
    config_fields: Set[str] = field(default_factory=set)


def _parse_file(path: str) -> Optional[ast.Module]:
    with open(path, "rb") as fh:
        src = fh.read()
    try:
        return ast.parse(src, filename=path)
    except SyntaxError:
        return None


def _collect_suppressions(path: str) -> List[Suppression]:
    """Find ``# distpow: ok`` comments; a justification continues across
    the following comment-only lines of the same block, so a multi-line
    rationale counts in full."""
    out: List[Suppression] = []
    comments: Dict[int, str] = {}
    try:
        # tokenize from the real readline so token line numbers are the
        # interpreter's own physical lines; split the source on "\n"
        # only (NOT splitlines(), which also splits on \x0b/\x0c/\x85
        # inside string literals) so comment_only() shares that
        # numbering (review: a NEL in a literal shifted every following
        # suppression by one line)
        with tokenize.open(path) as fh:
            for tok in tokenize.generate_tokens(fh.readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        with tokenize.open(path) as fh:
            src_lines = fh.read().split("\n")
    except (OSError, tokenize.TokenError, SyntaxError,
            IndentationError, ValueError):
        return out

    def comment_only(line: int) -> bool:
        return 1 <= line <= len(src_lines) and \
            src_lines[line - 1].lstrip().startswith("#")

    for line in sorted(comments):
        m = SUPPRESS_RE.search(comments[line])
        if m is None:
            continue
        rules = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        why = (m.group("why") or "").strip()
        cont = line + 1
        while why and comment_only(cont) and cont in comments and \
                SUPPRESS_RE.search(comments[cont]) is None:
            why += " " + comments[cont].lstrip("# ").strip()
            cont += 1
        out.append(Suppression(line=line, rules=rules, justification=why))
    return out


def load_module(path: str, rel: Optional[str] = None) -> Optional[Module]:
    tree = _parse_file(path)
    if tree is None:
        return None
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    return Module(
        path=rel or path,
        tree=tree,
        source=source,
        suppressions=_collect_suppressions(path),
    )


# -- context extraction ------------------------------------------------------

def _actions_from_ast(tree: ast.Module) -> Set[str]:
    """Action vocabulary = classes deriving (transitively, within the
    file) from ``Action`` in runtime/actions.py."""
    names: Set[str] = set()
    bases_of: Dict[str, List[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            bases_of[node.name] = [
                b.id for b in node.bases if isinstance(b, ast.Name)
            ]

    def derives(name: str, seen: Set[str]) -> bool:
        if name == "Action":
            return True
        if name in seen:
            return False
        seen.add(name)
        return any(derives(b, seen) for b in bases_of.get(name, ()))

    for cls in bases_of:
        if cls != "Action" and derives(cls, set()):
            names.add(cls)
    return names


def _string_set_from_assign(tree: ast.Module, target: str) -> Set[str]:
    """Read a module-level ``TARGET = frozenset({...})`` / set / tuple /
    list of string literals."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == target
                   for t in node.targets):
            continue
        value = node.value
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            return {
                e.value for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return set()


def _config_fields_from_ast(tree: ast.Module) -> Set[str]:
    """Union of annotated field names over every dataclass in
    runtime/config.py."""
    fields: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                fields.add(stmt.target.id)
    return fields


def build_context(package_root: str) -> ProjectContext:
    """Parse the declared vocabularies out of the scanned package.

    ``package_root`` is the ``distpow_tpu`` directory.  Missing files
    leave the corresponding context empty, which disables the dependent
    checks rather than erroring — the engine must degrade gracefully on
    partial trees (fixtures, future package splits).
    """
    ctx = ProjectContext()
    actions_py = os.path.join(package_root, "runtime", "actions.py")
    metrics_py = os.path.join(package_root, "runtime", "metrics.py")
    config_py = os.path.join(package_root, "runtime", "config.py")
    if os.path.exists(actions_py):
        tree = _parse_file(actions_py)
        if tree is not None:
            ctx.action_names = _actions_from_ast(tree)
    if os.path.exists(metrics_py):
        tree = _parse_file(metrics_py)
        if tree is not None:
            ctx.counters = _string_set_from_assign(tree, "KNOWN_COUNTERS")
            ctx.counter_prefixes = tuple(sorted(
                _string_set_from_assign(tree, "KNOWN_COUNTER_PREFIXES")
            ))
            ctx.histograms = _string_set_from_assign(
                tree, "KNOWN_HISTOGRAMS"
            )
            ctx.histogram_prefixes = tuple(sorted(
                _string_set_from_assign(tree, "KNOWN_HISTOGRAM_PREFIXES")
            ))
            ctx.gauges = _string_set_from_assign(tree, "KNOWN_GAUGES")
            ctx.gauge_prefixes = tuple(sorted(
                _string_set_from_assign(tree, "KNOWN_GAUGE_PREFIXES")
            ))
    if os.path.exists(config_py):
        tree = _parse_file(config_py)
        if tree is not None:
            ctx.config_fields = _config_fields_from_ast(tree)
    return ctx


# -- walking -----------------------------------------------------------------

SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv"}


def iter_py_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


@dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    checked_files: int

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "checked_files": self.checked_files,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [
                {**f.to_json(), "justification": s.justification}
                for f, s in self.suppressed
            ],
        }


def _stmt_starts(module: Module) -> Dict[int, int]:
    """Physical line -> first line of the smallest enclosing SIMPLE
    statement.  Lets a trailing suppression on the continuation line of
    a wrapped call cover the finding anchored at the statement's first
    line.  Compound statements (With/If/def...) are excluded — mapping a
    body line to the header would over-suppress a whole block."""
    starts: Dict[int, int] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.stmt) or hasattr(node, "body"):
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        for ln in range(node.lineno, end + 1):
            prev = starts.get(ln)
            if prev is None or node.lineno > prev:  # smallest wins
                starts[ln] = node.lineno
    return starts


def _suppression_target(module: Module, s: Suppression,
                        stmt_starts: Dict[int, int]) -> int:
    """The code line a suppression covers: its statement's first line
    when the comment trails code (so a black-style wrapped call is
    covered from its anchor line), else the first non-blank,
    non-comment line below its comment block."""
    # split on "\n" only — physical-line numbering (see
    # _collect_suppressions)
    lines = module.source.split("\n")
    if s.line <= len(lines) and not lines[s.line - 1].lstrip().startswith("#"):
        return stmt_starts.get(s.line, s.line)  # trailing comment
    for ln in range(s.line + 1, len(lines) + 1):
        stripped = lines[ln - 1].strip()
        if stripped and not stripped.startswith("#"):
            return ln
    return s.line


def _apply_suppressions(
    module: Module, findings: List[Finding], executed_rules: Set[str]
) -> Tuple[List[Finding], List[Tuple[Finding, Suppression]]]:
    """Split one module's findings into (active, suppressed) and append
    the suppression-protocol findings (bare / unused)."""
    active: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    stmt_starts = _stmt_starts(module)
    by_line: Dict[int, List[Suppression]] = {}
    for s in module.suppressions:
        by_line.setdefault(
            _suppression_target(module, s, stmt_starts), []
        ).append(s)

    for f in findings:
        hit = None
        for s in by_line.get(f.line, ()):
            if f.rule in s.rules:
                hit = s
                break
        if hit is None:
            active.append(f)
            continue
        hit.used = True
        if not hit.justification:
            active.append(Finding(
                rule=BARE_SUPPRESSION, path=module.path, line=hit.line,
                message=(
                    f"suppression of [{f.rule}] carries no justification — "
                    f"append ' -- <why this is safe>'"
                ),
            ))
        else:
            suppressed.append((f, hit))

    for s in module.suppressions:
        if not s.used and set(s.rules) & executed_rules:
            # only rules that actually ran this invocation can prove a
            # suppression stale — a --rule subset run must not flag the
            # other rules' justified holds as unused
            active.append(Finding(
                rule=UNUSED_SUPPRESSION, path=module.path, line=s.line,
                message=(
                    f"suppression for {', '.join(s.rules)} matches no "
                    f"finding on its statement — delete it"
                ),
            ))
    return active, suppressed


def run_analysis(
    roots: Sequence[str],
    context: Optional[ProjectContext] = None,
    rule_ids: Optional[Sequence[str]] = None,
    rel_to: Optional[str] = None,
) -> Report:
    """Run every (or the selected) rule over every module under
    ``roots``.  ``context`` defaults to :func:`build_context` on the
    first root that looks like the package (contains ``runtime/``)."""
    from .rules import ALL_RULES

    rules = [r for r in ALL_RULES
             if rule_ids is None or r.RULE_ID in rule_ids]
    if context is None:
        context = ProjectContext()
        for root in roots:
            if os.path.isdir(os.path.join(root, "runtime")):
                context = build_context(root)
                break

    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    executed = {r.RULE_ID for r in rules}
    checked = 0
    for root in roots:
        # directory-level rules (dead-package) see the root, not files
        for rule in rules:
            scan_tree = getattr(rule, "scan_tree", None)
            if scan_tree is not None and os.path.isdir(root):
                findings.extend(scan_tree(root, rel_to or ".", context))
        # load EVERY module under the root first: project-level rules
        # (``check_project(modules, context)`` — the interprocedural
        # concurrency passes, docs/CONCURRENCY.md) need the whole tree
        # to resolve helper calls across files, and their findings must
        # still land in the owning module's suppression pass
        modules: List[Module] = []
        for path in iter_py_files(root):
            rel = os.path.relpath(path, rel_to) if rel_to else path
            module = load_module(path, rel)
            if module is None:
                findings.append(Finding(
                    rule="syntax-error", path=rel, line=1,
                    message="file does not parse; nothing was checked",
                ))
                continue
            checked += 1
            modules.append(module)
        per_module: Dict[str, List[Finding]] = {m.path: [] for m in modules}
        for module in modules:
            for rule in rules:
                check = getattr(rule, "check", None)
                if check is not None:
                    per_module[module.path].extend(check(module, context))
        for rule in rules:
            check_project = getattr(rule, "check_project", None)
            if check_project is None:
                continue
            for f in check_project(modules, context):
                if f.path in per_module:
                    per_module[f.path].append(f)
                else:  # finding on a path outside the scan: keep it raw
                    findings.append(f)
        for module in modules:
            act, sup = _apply_suppressions(
                module, per_module[module.path], executed
            )
            findings.extend(act)
            suppressed.extend(sup)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=findings, suppressed=suppressed,
                  checked_files=checked)


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    """A committed baseline grandfathers specific findings (rule, path,
    message) — line numbers excluded so unrelated edits don't churn it.
    The shipped baseline is empty and should stay that way."""
    with open(path) as fh:
        data = json.load(fh)
    return {
        (f["rule"], f["path"], f["message"])
        for f in data.get("findings", ())
    }
