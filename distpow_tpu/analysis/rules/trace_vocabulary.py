"""trace-vocabulary — the 16-action trace vocabulary stays closed.

Trace parity with the reference (worker.go / coordinator.go /
powlib.go / cache.go recorded actions) is this repo's correctness
oracle; it holds only while every action the code constructs is one of
the classes declared in ``runtime/actions.py``.  Two drift vectors are
checked mechanically:

* a constructed action must be declared: any call through an actions-
  module alias (``act.WorkerResult(...)``, or a name imported from
  ``runtime.actions``) whose target is CamelCase but not in the parsed
  vocabulary is flagged — a typo'd or invented action name would
  otherwise surface only when that protocol path executes;
* the vocabulary must stay centralized: an ``Action`` subclass defined
  in any module other than ``runtime/actions.py`` is flagged — a
  scattered vocabulary cannot be diffed against the reference's four
  action files.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ._util import is_module

RULE_ID = "trace-vocabulary"
DESCRIPTION = (
    "every constructed trace action must be declared in "
    "runtime/actions.py; no Action subclasses elsewhere"
)

ACTIONS_MODULE = "actions"


def _actions_aliases(tree: ast.Module) -> Set[str]:
    """Names this module binds to the actions module itself
    (``from ..runtime import actions as act``)."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == ACTIONS_MODULE:
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] != ACTIONS_MODULE:
                    continue
                if a.asname:
                    aliases.add(a.asname)
                elif "." not in a.name:
                    # plain `import actions` binds the module name; a
                    # dotted `import pkg.runtime.actions` binds only the
                    # TOP package — construction goes through an
                    # Attribute chain this Name-based check cannot (and
                    # must not pretend to) track
                    aliases.add(a.name)
    return aliases


def _imported_action_names(tree: ast.Module) -> Set[str]:
    """Names imported FROM the actions module
    (``from .actions import CacheAdd``)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".")[-1] == ACTIONS_MODULE:
            for a in node.names:
                names.add(a.asname or a.name)
    return names


def check(module, context) -> Iterator:
    if not context.action_names:
        return  # no vocabulary parsed (fixture tree without actions.py)
    if is_module(module.path, "runtime/actions.py"):
        return

    aliases = _actions_aliases(module.tree)
    imported = _imported_action_names(module.tree)

    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            for base in node.bases:
                base_name = base.attr if isinstance(base, ast.Attribute) \
                    else getattr(base, "id", None)
                if base_name == "Action" or (
                        base_name in context.action_names):
                    yield module.finding(
                        RULE_ID, node,
                        f"Action subclass {node.name!r} defined outside "
                        f"runtime/actions.py — the trace vocabulary must "
                        f"stay centralized for reference parity",
                    )
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id in aliases:
            name = func.attr
        elif isinstance(func, ast.Name) and func.id in imported:
            name = func.id
        if name is None or not name[:1].isupper():
            continue
        if name not in context.action_names:
            yield module.finding(
                RULE_ID, node,
                f"action {name!r} is not declared in runtime/actions.py "
                f"(declared vocabulary: {len(context.action_names)} "
                f"types) — a recorded unknown action breaks trace parity",
            )
