"""silent-except — broad catches in the protocol planes must account.

``nodes/`` and ``runtime/`` are the protocol: a swallowed ``except
Exception`` there converts an invariant violation into a silent
behavioral drift (the round-5 silently-capped-watcher class).  Narrow
catches (``except OSError``) are the normal idiom and exempt; a broad
handler — bare ``except:`` or one whose matched types include
``Exception``/``BaseException`` — must do at least one of:

* re-raise (``raise``),
* log through a logging receiver (``log.warning(...)``, ``logger.*``,
  ``logging.*``),
* count a metric (``metrics.inc``/``REGISTRY.inc``).

Handlers that genuinely must stay silent (the compile-cache hook that
runs INSIDE the warnings/logging machinery it instruments) carry a
suppression with that justification.  Nested defs inside the handler
don't count — they run later, if ever.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import in_dirs, receiver_name, walk_same_scope

RULE_ID = "silent-except"
DESCRIPTION = (
    "except Exception in nodes//runtime/ must log, count a metric, "
    "or re-raise"
)

LOG_RECEIVERS = frozenset({"log", "logger", "logging"})
LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
})
METRIC_RECEIVERS = frozenset({"metrics", "REGISTRY"})


def _in_scope(path: str) -> bool:
    return in_dirs(path, "nodes", "runtime")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", None)) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", None))]
    return any(n in ("Exception", "BaseException") for n in names)


def _accounts(handler: ast.ExceptHandler) -> bool:
    for node in walk_same_scope(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            recv = receiver_name(node.func)
            if recv in LOG_RECEIVERS and node.func.attr in LOG_METHODS:
                return True
            if recv in METRIC_RECEIVERS and node.func.attr == "inc":
                return True
    return False


def check(module, context) -> Iterator:
    if not _in_scope(module.path):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _is_broad(node) and not _accounts(node):
            what = "bare except:" if node.type is None else "except Exception"
            yield module.finding(
                RULE_ID, node,
                f"{what} swallows errors in the protocol plane without "
                f"logging, counting a metric, or re-raising — narrow the "
                f"exception, account for it, or suppress with why "
                f"silence is required here",
            )
