"""no-blocking-under-lock — no blocking I/O while a mutex is held.

The coordinator's per-key miss lock, the worker task table, the RPC
client's write lock and the tracer's clock lock are all contended from
RPC handler threads; a blocking call (RPC, socket send, device search,
sleep, event wait, subprocess) under any of them turns one slow peer
into a process-wide stall — the Python analogue of the interleaving
bugs the reference's Go race detector existed to catch.

A "lock" is any ``with`` context whose expression's terminal name
contains ``lock`` or ``mutex`` (``self._lock``, ``wlock``,
``self._key_lock(key)``); blocking calls are the project's known set:
socket ops (``sendall``/``recv``/``accept``/``connect``/
``create_connection``), blocking RPC (``.call``), device work
(``.search`` — ``re.search`` excluded), ``sleep``, event ``wait``,
``subprocess`` calls, ``RPCClient(...)`` construction (it dials), and
the tracing emit path (``emit``/``_emit``/``record_action``/
``record_actions`` — sinks send over TCP).

Lexical only: indirection (a helper that itself sends) is not tracked;
a deliberate hold (e.g. the tracer's emit-inside-lock ordering
invariant) is suppressed with a justification, which is the point —
the invariant becomes visible at the call site.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import dotted_name, receiver_name, terminal_name, walk_same_scope

RULE_ID = "no-blocking-under-lock"
DESCRIPTION = (
    "no RPC call, socket send, device search, sleep, event wait, or "
    "subprocess while a threading lock is held"
)

BLOCKING_ATTRS = frozenset({
    "sendall", "recv", "accept", "connect", "create_connection",
    "call", "search", "sleep", "wait",
    "emit", "_emit", "record_action", "record_actions",
})
SUBPROCESS_ATTRS = frozenset({
    "run", "call", "check_call", "check_output", "communicate",
})
BLOCKING_CONSTRUCTORS = frozenset({"RPCClient", "create_connection"})
# receivers whose .search/.call etc. are not I/O
BENIGN_RECEIVERS = frozenset({"re", "regex", "pattern"})


def _is_lock_context(expr: ast.AST) -> bool:
    node = expr.func if isinstance(expr, ast.Call) else expr
    name = terminal_name(node)
    if name is None:
        return False
    lowered = name.lower()
    return "lock" in lowered or "mutex" in lowered


def _blocking_reason(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in BLOCKING_CONSTRUCTORS:
            return f"{func.id}(...) dials/blocks"
        return ""
    if not isinstance(func, ast.Attribute):
        return ""
    recv = receiver_name(func)
    if recv in BENIGN_RECEIVERS:
        return ""
    if recv == "subprocess" and func.attr in SUBPROCESS_ATTRS:
        return f"subprocess.{func.attr}(...) blocks on a child process"
    if func.attr in BLOCKING_ATTRS:
        return f".{func.attr}(...) can block"
    return ""


def check(module, context) -> Iterator:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.With):
            continue
        lock_items = [i for i in node.items
                      if _is_lock_context(i.context_expr)]
        if not lock_items:
            continue
        held = dotted_name(
            lock_items[0].context_expr.func
            if isinstance(lock_items[0].context_expr, ast.Call)
            else lock_items[0].context_expr
        ) or "lock"
        for child in walk_same_scope(node):
            if not isinstance(child, ast.Call):
                continue
            reason = _blocking_reason(child)
            if reason:
                yield module.finding(
                    RULE_ID, child,
                    f"{reason} while holding {held} (acquired line "
                    f"{node.lineno}); move it outside the critical "
                    f"section or suppress with the invariant that makes "
                    f"the hold safe",
                )
