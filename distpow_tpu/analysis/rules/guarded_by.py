"""unguarded-shared-write — lock-guarded attributes must not be
written bare.

Every cross-thread capability in the tree — the worker task table,
the fleet lease map, the replicator queue set — is a plain attribute
whose only memory model is "hold the lock".  An attribute written
under ``with self._mu`` in one method and bare in another is a data
race waiting for a scheduler interleaving (the bug class most PR
review passes here have caught by hand: docs/CONCURRENCY.md).

Two tiers, both interprocedural (``analysis/concur.py``):

* **Discovered discipline**: an attribute written at least once with a
  lock held AND at least once bare (outside ``__init__``-like methods
  and freshly-constructed receivers) is flagged at each bare write.
  Reads are not flagged at this tier — too noisy for idioms like
  snapshot-read-then-act.
* **Declared discipline**: a ``# guarded-by: self._mu`` comment on the
  attribute's assignment or class-body annotation makes EVERY bare
  access — reads included — a hard finding.  Matching is by the
  lock's terminal name, so ``# guarded-by: registry._lock`` declares a
  cross-object guard.

"Under a lock" includes helper methods: a private method only ever
called with the lock held inherits it (entry-lock credit), and lock
aliases (``wlock = self._wlock``) count.  Deliberate invariants
(write-once before thread start, monotonic flags read locklessly) are
suppressed at the bare site with ``# distpow: ok
unguarded-shared-write -- <invariant>``.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from .. import concur

RULE_ID = "unguarded-shared-write"
DESCRIPTION = (
    "attributes written under a lock (or declared # guarded-by) must "
    "not be accessed bare from other methods"
)


def check_project(modules, context) -> Iterator:
    model = concur.get_model(modules)
    by_mod = {m.path: m for m in modules}

    # aggregate accesses per (owner class, attr)
    groups: Dict[Tuple[str, str], List[concur.Access]] = {}
    for info in model.methods.values():
        for a in info.accesses:
            if a.fresh or a.method.name in concur.INIT_METHODS:
                continue
            groups.setdefault((a.owner, a.attr), []).append(a)

    for (owner, attr), accesses in sorted(groups.items()):
        guard = model.guard_for(owner, attr)
        cls_name = owner.split("::")[-1]
        if guard is not None:
            lock_name, decl_line = guard
            for a in accesses:
                held = model.held_effective(a)
                if any(lid[1].rstrip("()") == lock_name for lid in held):
                    continue
                mod = by_mod.get(a.method.module.path)
                if mod is None:
                    continue
                yield mod.finding(
                    RULE_ID, a.node,
                    f"{cls_name}.{attr} is declared guarded-by "
                    f"{lock_name} ({mod.path.rsplit('/', 1)[-1]}:"
                    f"{decl_line}) but is "
                    f"{'written' if a.write else 'read'} here with no "
                    f"matching lock held",
                )
            continue
        locked = [a for a in accesses
                  if a.write and model.held_effective(a)]
        bare = [a for a in accesses
                if a.write and not model.held_effective(a)]
        if not locked or not bare:
            continue
        sample = locked[0]
        lock = sorted(model.held_effective(sample))[0]
        for a in bare:
            mod = by_mod.get(a.method.module.path)
            if mod is None:
                continue
            yield mod.finding(
                RULE_ID, a.node,
                f"{cls_name}.{attr} is written under "
                f"{concur.fmt_lock(lock)} in {sample.method.short} "
                f"(line {sample.node.lineno}) but bare here in "
                f"{a.method.short}; hold the lock, or suppress with "
                f"the invariant that makes the lock-free write safe",
            )
