"""modulo-routing — no ``hash(key) % len(members)`` shard routing.

The scale-out plane's cache-locality law (docs/CLUSTER.md): routing a
key by reducing its hash modulo the member count remaps ~every key
whenever membership changes — ``N -> N+1`` moves a fraction
``N/(N+1)`` of the keyspace — so every scale event cold-starts every
shard's dominance cache at once.  The sanctioned shape is the
consistent-hash ring (``distpow_tpu/cluster/ring.py``): adding one
member remaps only ~``1/(N+1)`` of the keyspace, and the ring is a
pure function of the member list so every party computes it
identically.  This rule freezes that invariant in ``nodes/``,
``cluster/`` and ``fleet/``: a modulo-over-membership expression
reintroduced there is a lint failure, not a cache-hit-rate regression
someone has to notice on a dashboard three scale events later.

Detection is lexical, like the sibling rules: a ``%`` BinOp whose
RIGHT side is ``len(<members-ish>)`` (any identifier containing
``member``/``worker``/``peer``/``node``/``coordinator``/``shard``/
``ring``/``replica``/``addr``/``server``) and whose LEFT side mentions
a hash — the ``hash()``/``crc32()``/``adler32()`` builtins, a
``.digest()``/``.hexdigest()`` call, or any identifier containing
``hash``/``digest``/``crc``.  Round-robin index arithmetic
(``i % len(candidates)`` — the coordinator's reassignment rotation) is
hash-free on the left and deliberately NOT flagged: rotating
placements is load balancing, not key routing, and has no cache
locality to lose.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ._util import in_dirs

RULE_ID = "modulo-routing"
DESCRIPTION = (
    "no hash(...) % len(members) shard routing in nodes/, cluster/ or "
    "fleet/ — membership changes remap ~every key; use the consistent-"
    "hash ring (cluster/ring.py)"
)

#: identifiers that mark a ``len(...)`` operand as a member collection
MEMBER_HINTS = ("member", "worker", "peer", "node", "coordinator",
                "shard", "ring", "replica", "addr", "server")

#: callables whose result is a hash value
HASH_CALLS = frozenset({"hash", "crc32", "adler32"})
HASH_METHOD_CALLS = frozenset({"digest", "hexdigest", "intdigest"})


def _names_in(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _is_member_len(expr: ast.AST) -> bool:
    """True for ``len(X)`` where X mentions a member-collection name."""
    if not (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name)
            and expr.func.id == "len" and expr.args):
        return False
    lowered = {n.lower() for n in _names_in(expr.args[0])}
    return any(h in n for n in lowered for h in MEMBER_HINTS)


def _mentions_hash(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id in HASH_CALLS:
                return True
            if isinstance(func, ast.Attribute) and \
                    func.attr in HASH_METHOD_CALLS:
                return True
    lowered = {n.lower() for n in _names_in(expr)}
    return any(h in n for n in lowered for h in ("hash", "digest", "crc"))


def check(module, context) -> Iterator:
    if not in_dirs(module.path, "nodes", "cluster", "fleet"):
        return
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Mod)):
            continue
        if not _is_member_len(node.right):
            continue
        if not _mentions_hash(node.left):
            continue
        yield module.finding(
            RULE_ID, node,
            "hash % len(members) routing remaps ~every key on any "
            "membership change, cold-starting every shard's dominance "
            "cache at once — route through the consistent-hash ring "
            "(cluster/ring.py HashRing.owner, ~1/N churn per member "
            "change), or suppress with the invariant that makes modulo "
            "reshuffling safe here",
        )
