"""config-key-sync — config keys read anywhere must be declared fields.

Node configs are CamelCase-keyed JSON deserialized into the
``runtime/config.py`` dataclasses; ``from_dict`` silently ignores
unknown keys (deliberate forward compatibility on the WIRE), so a
consumer reading a key the dataclasses don't declare —
``config.BatchSzie``, ``getattr(config, "CacheFiIe", "")`` — gets an
AttributeError at that code path's first execution, or worse, the
getattr default forever.  This rule closes the loop statically: any
CamelCase attribute read/write on a config-shaped receiver (a name
``config``/``cfg``, or an attribute chain ending in ``.config``), and
any ``getattr(config, "Key", ...)`` string key, must be a declared
field of one of the config dataclasses.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import is_module, terminal_name

RULE_ID = "config-key-sync"
DESCRIPTION = (
    "CamelCase attributes on config objects must exist as fields on "
    "the runtime/config.py dataclasses"
)

CONFIG_RECEIVERS = frozenset({"config", "cfg"})


def _is_config_receiver(node: ast.AST) -> bool:
    name = terminal_name(node)
    return name in CONFIG_RECEIVERS


def check(module, context) -> Iterator:
    if not context.config_fields:
        return  # no dataclasses parsed (fixture tree without config.py)
    if is_module(module.path, "runtime/config.py"):
        return
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute) and \
                _is_config_receiver(node.value):
            key = node.attr
            if key[:1].isupper() and key not in context.config_fields:
                yield module.finding(
                    RULE_ID, node,
                    f"config key {key!r} is not a field on any "
                    f"runtime/config.py dataclass — typo, or declare it "
                    f"there (from_dict ignores unknown JSON keys, so an "
                    f"undeclared read can never be satisfied)",
                )
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2 and \
                _is_config_receiver(node.args[0]) and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            key = node.args[1].value
            if key[:1].isupper() and key not in context.config_fields:
                yield module.finding(
                    RULE_ID, node,
                    f"getattr config key {key!r} is not a field on any "
                    f"runtime/config.py dataclass — the default would be "
                    f"returned forever",
                )
