"""unclosed-span — span begin sites must be context-managed or justified.

The forensics plane (runtime/spans.py, docs/FORENSICS.md) records a
span only when its handle FINISHES: a ``SPANS.begin(...)`` whose
``finish()`` is skipped on some exit path is a span that silently
never happened — the request timeline shows a hole exactly where the
interesting (slow, failed, preempted) work was, which is the
worst-possible failure mode for a forensics layer.  The sanctioned
begin-site form is therefore the context manager::

    with SPANS.span("worker.solve", shard=b) as sp:
        ...

which cannot leak (error exits record too, tagged with an ``outcome``).
``SPANS.begin`` exists only for spans that genuinely cross a thread
boundary — a scheduler slot is submitted on the miner thread and
finished by the device loop — and every such call site must carry a
justified suppression naming its single finish point, so the leak
analysis lives AT the call site instead of in reviewer memory.
One-shot recorders (``SPANS.record`` / ``SPANS.event``) take explicit
timings and have no open state to leak; they are not begin sites.

Detection is lexical, like the sibling rules: any ``.begin(...)`` call
on a ``SPANS``/``spans`` receiver.  Scope: ``runtime/``, ``nodes/``,
``sched/``, ``parallel/`` and ``fleet/`` — the layers the span
vocabulary instruments (runtime/spans.py itself, which defines the
API, is exempt).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import in_dirs, is_module, receiver_name

RULE_ID = "unclosed-span"
DESCRIPTION = (
    "SPANS.begin call sites in runtime//nodes//sched//parallel//fleet/ "
    "must use the context-manager form (SPANS.span) or carry a "
    "justified suppression naming their single finish point"
)

_RECEIVERS = frozenset({"SPANS", "spans"})


def check(module, context) -> Iterator:
    if not in_dirs(module.path, "runtime", "nodes", "sched", "parallel",
                   "fleet"):
        return
    if is_module(module.path, "runtime/spans.py"):
        return
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "begin" and \
                receiver_name(node.func) in _RECEIVERS:
            yield module.finding(
                RULE_ID, node,
                "SPANS.begin opens a span some other scope must "
                "finish() — a missed exit path is a silent hole in the "
                "request timeline; use the `with SPANS.span(...)` form, "
                "or suppress with the single finish point that makes "
                "this cross-thread handle safe",
            )
