"""serial-rpc-fanout — no blocking per-peer RPC inside a fan-out loop.

The control plane's scaling law (ISSUE 5, docs/RPC.md "Control-plane
concurrency"): a blocking ``.call(...)`` issued once per worker inside a
loop makes round start, the cancel storm, and every broadcast cost
O(N x RTT) — and one hung peer head-of-line-blocks the rest for its
full timeout.  The sanctioned shape is issue-then-await: fan the
``RPCClient.go()`` futures out first, then collect replies under one
shared deadline (nodes/coordinator.py ``_assign_shards`` /
``_broadcast_found``).  This rule freezes that invariant: a serial
``.call`` loop reintroduced in ``nodes/`` is a lint failure, not a
latency regression someone has to re-measure on hardware.

The fleet scraper (``distpow_tpu/obs/``, ISSUE 8) is the same bug
class one layer up: a sweep that Stats-polls N nodes one after another
serializes the cluster view on round trips and lets one SIGSTOP'd node
stall the whole sweep for its timeout — exactly what the shared-
deadline concurrent poll exists to prevent (docs/SLO.md).  The rule
therefore covers ``obs/`` with the same detection and the same
suppression protocol.

The replication plane (``distpow_tpu/cluster/``, ISSUE 16) is the
third habitat: write-behind pushes, anti-entropy digest exchanges, and
warm-handoff sends all loop over peer collections with an RPC inside.
Some of those loops are DELIBERATELY serial — the single background
pusher thread is the design, not an accident — but the rule still
covers ``cluster/`` so every such loop carries its bound in a
suppression (queue depth, successor count, sweep cadence, deadline)
instead of being invisibly exempt.

Detection is lexical, like the sibling rules: a ``for`` loop whose
iterated expression mentions a worker/peer-collection name (any
identifier containing ``worker``, ``peer``, ``task``, ``ref``,
``client`` or ``addr``) and whose body — nested loops included, nested
function bodies excluded — contains an attribute call named ``call``.
``subprocess.call`` is a different hazard (no-blocking-under-lock
territory) and is excluded.  Deliberately-serial remaining cases (the
failure detector's bounded 2 s probes in ``_probe_dead``) carry
justified suppressions at the call site, which is the point — the
invariant that makes serial acceptable becomes visible where it holds.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ._util import in_dirs, receiver_name, walk_same_scope

RULE_ID = "serial-rpc-fanout"
DESCRIPTION = (
    "no blocking .call() per peer inside a loop over worker/peer/node "
    "collections in nodes/, obs/ or cluster/ — issue go() futures, "
    "then await"
)

#: identifiers that mark a loop as iterating a peer collection
#: (``target``/``node``/``state`` cover the obs/ scraper's vocabulary)
COLLECTION_HINTS = ("worker", "peer", "task", "ref", "client", "addr",
                    "target", "node", "state")

#: receivers whose .call is not an RPC
EXCLUDED_RECEIVERS = frozenset({"subprocess"})


def _iter_mentions_peers(iter_expr: ast.AST) -> bool:
    names: Set[str] = set()
    for node in ast.walk(iter_expr):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
    lowered = {n.lower() for n in names}
    return any(h in n for n in lowered for h in COLLECTION_HINTS)


def check(module, context) -> Iterator:
    if not in_dirs(module.path, "nodes", "obs", "cluster"):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.For):
            continue
        if not _iter_mentions_peers(node.iter):
            continue
        for child in walk_same_scope(node):
            if not isinstance(child, ast.Call):
                continue
            func = child.func
            if not isinstance(func, ast.Attribute) or func.attr != "call":
                continue
            if receiver_name(func) in EXCLUDED_RECEIVERS:
                continue
            yield module.finding(
                RULE_ID, child,
                f"blocking .call() per peer inside the loop over "
                f"worker/peer collection (line {node.lineno}) serializes "
                f"the fan-out on round trips — issue RPCClient.go() "
                f"futures for every peer first, then await them under "
                f"one shared deadline, or suppress with the invariant "
                f"that makes serial dispatch safe here",
            )
