"""Shared AST and path-scope helpers for the rule modules."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def norm_path(path: str) -> str:
    """Forward-slash form of a module path (one normalization for every
    rule's scope check)."""
    return path.replace("\\", "/")


def in_dirs(path: str, *dirs: str) -> bool:
    """True when the module lives under any of the named directories
    (``in_dirs(p, "ops")`` matches ``distpow_tpu/ops/x.py`` and a
    scan rooted at ``ops/`` itself)."""
    p = norm_path(path)
    return any(f"/{d}/" in p or p.startswith(f"{d}/") for d in dirs)


def is_module(path: str, suffix: str) -> bool:
    """True when the module IS the named file (``runtime/actions.py``)."""
    return norm_path(path).endswith(suffix)

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last segment of a Name/Attribute chain (``self._conn_lock``
    -> ``_conn_lock``); None for anything else (calls, subscripts)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def receiver_name(call_func: ast.AST) -> Optional[str]:
    """For an Attribute callee ``recv.meth(...)``, the terminal name of
    ``recv``; None for plain Name calls."""
    if isinstance(call_func, ast.Attribute):
        return terminal_name(call_func.value)
    return None


def walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Yield descendants WITHOUT entering nested function/class/lambda
    bodies — code in those executes later, outside the enclosing
    block's dynamic extent (a callback defined under a lock does not
    run under the lock)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(child))


def resolve_str_constant(tree: ast.Module, name: str) -> Optional[str]:
    """Value of a module-level ``NAME = "literal"`` assignment."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value.value
    return None
