"""unbounded-thread-spawn — no ``threading.Thread`` creation in loops.

The membership plane (ISSUE 12) multiplies the places the control plane
reacts to per-member events — heartbeats, lease expiries, drains,
hedges — and the tempting shape for each is "spawn a thread per item in
the loop".  A thread is ~8 MB of stack and a scheduler entity; a loop
that mints one per member (or per request, per retry, per beat) scales
its resource cost with an UNBOUNDED external quantity and has produced
real fork-bomb-shaped incidents elsewhere.  The sanctioned shapes are:

* one persistent loop thread created OUTSIDE the loop (the fleet
  agent's heartbeat loop, the registry's single reaper);
* a pool/executor whose width is fixed up front (``submit`` inside the
  loop is fine — the pool bounds concurrency);
* a deliberately-bounded per-item spawn carrying a justified
  suppression naming the bound (the coordinator's
  ``_resync_abandoned`` workers are capped by the abandoned count AND
  the shared ``RESYNC_CAP_S`` deadline; the RPC server's
  thread-per-connection/request dispatch is the documented Go
  ``net/rpc`` goroutine-parity semantics).

Detection is lexical, like the sibling rules: any ``threading.Thread``
/ ``Thread`` constructor call inside a ``for`` or ``while`` loop body —
nested loops included, nested function/class bodies excluded (a
callback DEFINED in a loop is not SPAWNED by it).  Scope: ``nodes/``,
``runtime/``, ``fleet/`` and ``cluster/`` — the replication plane
(ISSUE 16) spawns one warm-handoff sender per new owner, which is
exactly the per-item-spawn shape this rule exists to make justify its
bound (pool-size cap + shared handoff deadline, carried in the
suppression at the spawn site).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import dotted_name, in_dirs

RULE_ID = "unbounded-thread-spawn"
DESCRIPTION = (
    "no threading.Thread creation inside loops in nodes//runtime//"
    "fleet//cluster/ — use one persistent thread, a bounded pool, or "
    "suppress with the bound that makes the per-item spawn safe"
)

_THREAD_NAMES = frozenset({"threading.Thread", "Thread"})
_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _loop_body_calls(loop: ast.AST) -> Iterator[ast.Call]:
    """Thread-constructor calls in THIS loop's direct dynamic extent:
    nested function/class bodies are excluded (defined, not spawned,
    by the loop) and nested loops are pruned — their spawns anchor to
    the innermost loop so one call never reports twice."""
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        child = stack.pop()
        if isinstance(child, (_SCOPE_NODES, ast.For, ast.While)):
            continue
        if isinstance(child, ast.Call) and \
                dotted_name(child.func) in _THREAD_NAMES:
            yield child
        stack.extend(ast.iter_child_nodes(child))


def check(module, context) -> Iterator:
    if not in_dirs(module.path, "nodes", "runtime", "fleet", "cluster"):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for call in _loop_body_calls(node):
            yield module.finding(
                RULE_ID, call,
                f"threading.Thread created inside the loop at line "
                f"{node.lineno}: thread count now scales with the loop's "
                f"trip count — hoist one persistent thread out of the "
                f"loop, submit to a bounded pool, or suppress with the "
                f"bound that keeps this spawn finite",
            )
