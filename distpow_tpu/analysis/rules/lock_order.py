"""lock-order-inversion — cycles in the static lock acquisition graph.

Two threads acquiring the same pair of locks in opposite orders
deadlock the process the day the scheduler interleaves them — the
failure mode the Replicator pusher / anti-entropy / handoff triangle
and the coordinator's per-key locks vs the FleetRegistry lock could
reach as more subsystems take locks while calling each other.

The graph (``analysis/concur.py``) has an edge ``A -> B`` when B is
acquired while A is held, either lexically (nested ``with``) or
through a call made under A that transitively acquires B
(bounded-depth call summaries, ≤3 hops).  Lock identity aggregates by
``(declaring class, attribute)`` so per-instance locks map onto the
class-level discipline; unresolvable locals stay unique per function
and cannot fabricate cross-function cycles.  Reentrant self-edges are
skipped (RLock reentry is a different discipline, not an inversion).

Any cycle is reported ONCE, anchored at the participating edge with
the smallest source location; a justified suppression there (a trylock
fallback, a documented global order) silences the cycle.  The runtime
twin, ``runtime/lockcheck.py``, catches the orders this static
over-approximation cannot see.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from .. import concur

RULE_ID = "lock-order-inversion"
DESCRIPTION = (
    "no cycles in the lock acquisition order graph (nested with "
    "blocks + calls made while a lock is held)"
)

# edge: (src lock, dst lock) -> list of (module path, line, how)
Edge = Tuple[concur.LockId, concur.LockId]


def _edges(model: concur.Model) -> Dict[Edge, List[Tuple[str, int, str]]]:
    edges: Dict[Edge, List[Tuple[str, int, str]]] = {}

    def add(src, dst, node, info, how):
        if src == dst:
            return
        edges.setdefault((src, dst), []).append(
            (info.module.path, getattr(node, "lineno", 0), how))

    for info in model.methods.values():
        for a in info.acquisitions:
            for held in a.held_before:
                add(held, a.lock, a.node, info,
                    f"nested with in {info.short}")
        for c in info.calls:
            closure = model.acq_closure.get(c.callee, {})
            for lock, chain in closure.items():
                for held in c.held:
                    names = " -> ".join(
                        q.split("::")[-1] for q in chain)
                    add(held, lock, c.node, info,
                        f"call {info.short} -> {names}")
    return edges


def _sccs(nodes, succ) -> List[List]:
    """Tarjan, iterative (the graph is tiny but recursion depth must
    not depend on scanned code)."""
    index: Dict = {}
    low: Dict = {}
    on_stack = set()
    stack: List = []
    out: List[List] = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def check_project(modules, context) -> Iterator:
    model = concur.get_model(modules)
    by_mod = {m.path: m for m in modules}
    edges = _edges(model)
    succ: Dict[concur.LockId, List[concur.LockId]] = {}
    nodes = set()
    for (src, dst) in edges:
        succ.setdefault(src, []).append(dst)
        nodes.add(src)
        nodes.add(dst)
    for comp in _sccs(sorted(nodes), succ):
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        in_cycle = sorted(
            (e, sites) for e, sites in edges.items()
            if e[0] in comp_set and e[1] in comp_set
        )
        legs = []
        anchor = None  # (path, line)
        for (src, dst), sites in in_cycle:
            path, line, how = min(sites)
            legs.append(f"{concur.fmt_lock(src)} -> "
                        f"{concur.fmt_lock(dst)} "
                        f"({path.rsplit('/', 1)[-1]}:{line}, {how})")
            if anchor is None or (path, line) < anchor:
                anchor = (path, line)
        if anchor is None:
            continue
        mod = by_mod.get(anchor[0])
        if mod is None:
            continue
        yield mod.finding(
            RULE_ID, anchor[1],
            "lock acquisition cycle (potential deadlock): "
            + "; ".join(legs)
            + " — impose one global order, or suppress with the "
              "invariant that rules the interleaving out",
        )
