"""hot-path-host-sync — no host synchronization inside the device path.

The pipelined driver (``parallel/search.py``) exists to keep the
device busy: the host prepares launch N+1 while the device crunches
launch N, and the ONLY sanctioned sync point is the FIFO drain
(``int(res)`` in ``drain_one``).  A stray ``.item()``,
``np.asarray``/``np.array`` on a device value, ``jax.device_get`` or
``.block_until_ready()`` inside ``ops/`` or the driver serializes the
pipeline — one launch in flight instead of ``pipeline_depth`` — which
is invisible to every correctness test and only shows up as a silent
2x serving-rate regression on hardware.  Deliberate sync points (a
warmup that *wants* to block) are suppressed with the justification
inline.

Scope: ``distpow_tpu/ops/`` and ``distpow_tpu/parallel/search.py``.
``jnp.asarray`` is device-side and exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import dotted_name, in_dirs, is_module, receiver_name

RULE_ID = "hot-path-host-sync"
DESCRIPTION = (
    "no .item()/np.asarray/jax.device_get/block_until_ready inside "
    "ops/ or the pipelined driver"
)

SYNC_ATTRS = frozenset({"item", "block_until_ready"})
NUMPY_RECEIVERS = frozenset({"np", "numpy"})
NUMPY_SYNC_FNS = frozenset({"asarray", "array"})


def _in_scope(path: str) -> bool:
    return in_dirs(path, "ops") or is_module(path, "parallel/search.py")


def check(module, context) -> Iterator:
    if not _in_scope(module.path):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        recv = receiver_name(func)
        full = dotted_name(func)
        if func.attr in SYNC_ATTRS:
            yield module.finding(
                RULE_ID, node,
                f".{func.attr}() forces a host sync inside the device "
                f"hot path — it serializes the launch pipeline; drain "
                f"through the driver's FIFO instead, or suppress with "
                f"why this sync is intended",
            )
        elif recv in NUMPY_RECEIVERS and func.attr in NUMPY_SYNC_FNS:
            yield module.finding(
                RULE_ID, node,
                f"{full}(...) copies device values to host inside the "
                f"hot path — use jnp (device-side) or move the copy "
                f"out of the dispatch loop",
            )
        elif full == "jax.device_get":
            yield module.finding(
                RULE_ID, node,
                "jax.device_get(...) blocks on device results inside "
                "the hot path — drain through the driver's FIFO",
            )
