"""transitive-blocking-under-lock — a helper that blocks is a
blocking call.

``no-blocking-under-lock`` is deliberately lexical: ``with self._lock:
self._flush()`` passes it even when ``_flush`` ends in ``sendall``.
This rule closes the indirection hole with the shared call-graph
summaries (``analysis/concur.py``): a call made while a lock is held
whose callee reaches a known blocking operation — RPC ``.call``,
socket send, ``sleep``, event/condition ``wait``, fsync, subprocess —
within ≤3 call hops is flagged at the call site, naming the chain.

Two deliberate seams with the direct rule:

* a call the direct rule already flags (the call itself IS a blocking
  name) is skipped here — one finding per site, never two;
* a DIRECT blocking call under a lock the direct rule cannot see
  (held via a discovered lock whose name is not lock-ish, e.g. a
  ``Condition`` named ``_cond``) is flagged here instead.

Fix by moving the call outside the critical section (snapshot under
the lock, act after release — the tree's standard shape), or suppress
at the call site with the invariant that bounds the hold.
"""

from __future__ import annotations

from typing import Iterator

from .. import concur
from .blocking_under_lock import _blocking_reason

RULE_ID = "transitive-blocking-under-lock"
DESCRIPTION = (
    "no call chain (≤3 hops) that reaches blocking I/O, sleep, or "
    "subprocess work while a threading lock is held"
)


def check_project(modules, context) -> Iterator:
    model = concur.get_model(modules)
    by_mod = {m.path: m for m in modules}
    for info in model.methods.values():
        for c in info.calls:
            if not c.held:
                continue
            if _blocking_reason(c.node):
                continue  # the direct rule's finding, not ours
            hit = model.block_depth.get(c.callee)
            if hit is None:
                continue
            hops, chain, reason = hit
            if hops > concur.CALL_DEPTH:
                continue
            held = sorted(c.held)
            chain_s = " -> ".join(q.split("::")[-1] for q in chain)
            mod = by_mod.get(info.module.path)
            if mod is None:
                continue
            yield mod.finding(
                RULE_ID, c.node,
                f"call to {chain[0].split('::')[-1]} while holding "
                f"{concur.fmt_lock(held[0])} reaches blocking work in "
                f"{hops} hop{'s' if hops > 1 else ''} "
                f"({chain_s}: {reason}); move it outside the critical "
                f"section or suppress with the bounding invariant",
            )
        for b in info.blocking:
            if not b.held or b.lock_named_hold or b.self_wait:
                continue  # bare, direct-rule territory, or cond-wait
            held = sorted(b.held)
            mod = by_mod.get(info.module.path)
            if mod is None:
                continue
            yield mod.finding(
                RULE_ID, b.node,
                f"{b.reason} while holding {concur.fmt_lock(held[0])} "
                f"(a discovered lock the lexical rule cannot name); "
                f"move it outside the critical section or suppress "
                f"with the bounding invariant",
            )
