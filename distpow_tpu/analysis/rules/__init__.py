"""Rule registry — one module per rule, each exposing ``RULE_ID``,
``DESCRIPTION`` and ``check(module, context)`` (and optionally
``scan_tree(root, rel_to, context)`` for directory-level rules).
Catalog with rationale and examples: docs/LINT.md."""

from . import (
    blocking_under_lock,
    bounded_queue,
    config_key_sync,
    dead_package,
    guarded_by,
    hot_path_host_sync,
    lock_order,
    metrics_registry,
    modulo_routing,
    relaunch_loop_sync,
    serial_rpc_fanout,
    silent_except,
    trace_vocabulary,
    transitive_blocking,
    unbounded_thread_spawn,
    unclosed_span,
    wall_clock_duration,
)

ALL_RULES = (
    blocking_under_lock,
    transitive_blocking,
    guarded_by,
    lock_order,
    bounded_queue,
    serial_rpc_fanout,
    unbounded_thread_spawn,
    modulo_routing,
    trace_vocabulary,
    metrics_registry,
    config_key_sync,
    hot_path_host_sync,
    relaunch_loop_sync,
    unclosed_span,
    wall_clock_duration,
    silent_except,
    dead_package,
)
