"""wall-clock-duration — durations must come from the monotonic clock.

``time.time()`` is the WALL clock: NTP slews it, admins step it, leap
smears stretch it.  A duration computed as the difference of two wall
readings silently goes negative or jumps by seconds when that happens —
and every consumer downstream (latency histograms, rate estimators, the
soak plane's lag budget, retry backoff) misjudges.  The stdlib grew
``time.monotonic()`` for exactly this; the rule makes the split
mechanical inside the planes that compute durations for a living
(``runtime/``, ``obs/``, ``load/``, ``nodes/``):

* any ``a - b`` where an operand is a ``time.time()`` call, a local
  name assigned from one in the same scope, or an attribute assigned
  from one anywhere in the module, is flagged;
* wall time IS the point in a few places — cross-process timestamps
  (one node's ``time.time()`` judged against another's, where no shared
  monotonic epoch exists), spool/journal record stamps, staleness ages
  against scraped snapshots.  Those carry ``# distpow: ok
  wall-clock-duration -- <why>`` suppressions; the justification is the
  documentation.

The rule is deliberately syntactic (no cross-module dataflow): a
wall-clock reading that escapes through a return value or a container
is not traced.  That bounds false negatives, not false positives —
everything it DOES flag is a wall-minus-something delta.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ._util import dotted_name, in_dirs

RULE_ID = "wall-clock-duration"
DESCRIPTION = (
    "time.time() deltas used as durations in runtime//obs//load//nodes/ "
    "must be time.monotonic() (wall clock slews; suppress where wall "
    "time is the point)"
)

_SCOPES = ("runtime", "obs", "load", "nodes")


def _is_wall_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) == "time.time")


def _assigned_names(body_node: ast.AST, *, attrs: bool) -> Set[str]:
    """Names (or attribute terminal names, with ``attrs=True``) assigned
    from a bare ``time.time()`` call anywhere under ``body_node``."""
    out: Set[str] = set()
    for node in ast.walk(body_node):
        if not (isinstance(node, ast.Assign) and _is_wall_call(node.value)):
            continue
        for t in node.targets:
            if not attrs and isinstance(t, ast.Name):
                out.add(t.id)
            elif attrs and isinstance(t, ast.Attribute):
                out.add(t.attr)
    return out


def check(module, context) -> Iterator:
    if not in_dirs(module.path, *_SCOPES):
        return
    # attributes carry wall readings across method boundaries
    # (``self._t0 = time.time()`` ... ``time.time() - self._t0``), so
    # their taint is module-wide; plain names are scoped to their
    # function (a ``now`` in one helper says nothing about another's)
    wall_attrs = _assigned_names(module.tree, attrs=True)

    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    scopes = [(module.tree, _assigned_names(module.tree, attrs=False)
               - {n for f in funcs
                  for n in _assigned_names(f, attrs=False)})]
    scopes += [(f, _assigned_names(f, attrs=False)) for f in funcs]

    seen: Set[int] = set()
    for scope, wall_names in scopes:
        for node in ast.walk(scope):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            if id(node) in seen:
                continue
            for side in (node.left, node.right):
                tainted = (
                    _is_wall_call(side)
                    or (isinstance(side, ast.Name)
                        and side.id in wall_names)
                    or (isinstance(side, ast.Attribute)
                        and side.attr in wall_attrs)
                )
                if tainted:
                    seen.add(id(node))
                    yield module.finding(
                        RULE_ID, node,
                        "wall-clock delta: time.time() readings are not "
                        "monotonic (NTP slew/step) — compute durations "
                        "from time.monotonic(), or suppress with a "
                        "justification where wall time is the point",
                    )
                    break
