"""relaunch-loop-sync — no blocking result conversions in dispatch loops.

The persistent serving loop (docs/SERVING.md) exists because the old
launch/fetch/relaunch loop paid one BLOCKING host sync per launch: an
``int(res)`` on an in-flight device value inside the dispatch loop
stalls the host until the device drains, serializing the pipeline and
putting the host round trip back on the critical path — the exact
regression BENCH_r05 measured as a 30-60x serving gap on the slower
hashes.  The sanctioned patterns are (a) the solo drivers' dedicated
drain helpers (``drain_one`` — a conversion OUTSIDE any dispatch loop,
and in the persistent driver one that polls ``is_ready()`` first) and
(b) the scheduler's single ``jax.device_get`` per batched launch.

This rule flags ``int(<name>)`` / ``int(<name>[...])`` calls that sit
lexically inside a ``for``/``while`` loop (or a comprehension) in the
driver and scheduler packages — the shape every relaunch-loop sync in
this repo's history has taken.  A conversion that is genuinely
host-side (an already-fetched array) is suppressed with the
justification inline; anything else should drain through the FIFO or
poll readiness first.

Scope: ``distpow_tpu/parallel/`` and ``distpow_tpu/sched/``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import in_dirs

RULE_ID = "relaunch-loop-sync"
DESCRIPTION = (
    "no blocking int(<device value>) conversions inside dispatch loops "
    "in parallel/ or sched/ — drain through the FIFO or poll is_ready()"
)

_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _in_scope(path: str) -> bool:
    return in_dirs(path, "parallel", "sched")


def _flaggable_arg(node: ast.Call) -> bool:
    """``int(name)`` or ``int(name[...])`` — the conversion shapes a
    device value takes in this codebase.  Calls, attributes and
    constants as the argument are host-side arithmetic, not syncs."""
    if len(node.args) != 1 or node.keywords:
        return False
    arg = node.args[0]
    if isinstance(arg, ast.Name):
        return True
    return isinstance(arg, ast.Subscript) and \
        isinstance(arg.value, ast.Name)


def _int_calls_in_loops(root: ast.AST) -> Iterator[ast.Call]:
    """Yield flaggable int() calls lexically inside a loop, without
    crossing into nested function/lambda bodies (those run outside the
    loop's dynamic extent — e.g. a drain helper *defined* near a loop
    but called once per launch boundary)."""
    stack = [(child, False) for child in ast.iter_child_nodes(root)]
    while stack:
        node, in_loop = stack.pop()
        if isinstance(node, ast.Call) and in_loop and \
                isinstance(node.func, ast.Name) and node.func.id == "int" \
                and _flaggable_arg(node):
            yield node
        entered = in_loop or isinstance(node, _LOOPS)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPES):
                # nested scope: restart the loop tracking inside it
                stack.extend(
                    (c, False) for c in ast.iter_child_nodes(child)
                )
            else:
                stack.append((child, entered))


def check(module, context) -> Iterator:
    if not _in_scope(module.path):
        return
    for node in _int_calls_in_loops(module.tree):
        yield module.finding(
            RULE_ID, node,
            "int() on a (potential) device value inside a dispatch loop "
            "blocks the host per launch and serializes the pipeline — "
            "drain through the driver's FIFO / poll is_ready() first, "
            "or suppress with why this conversion cannot block",
        )
