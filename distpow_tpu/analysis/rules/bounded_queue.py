"""bounded-queue — no unbounded queue construction in the serving planes.

The scheduler subsystem exists because unbounded buffering is how a
serving stack dies under load: memory grows until the OOM killer picks
a victim, and every queued request ages instead of being shed with a
typed RETRY_AFTER (sched/admission.py).  ``nodes/``, ``runtime/`` and
``sched/`` are the planes where a ``queue.Queue()`` sits between RPC
threads, so an unbounded one there must be a *decision*, not a default:

* ``queue.Queue()`` with no capacity, or an explicit ``maxsize`` that
  is a non-positive literal, is flagged;
* ``queue.SimpleQueue()`` is always unbounded and always flagged;
* a positive-literal or variable capacity passes (a variable is assumed
  to be a configured bound — the linter cannot prove otherwise and must
  not cry wolf on ``Queue(maxsize=ch_capacity)``).

Queues that are genuinely protocol-bounded (the coordinator's per-round
result queue: at most two messages per live worker) or must never drop
(the worker's result forwarder) carry a suppression stating exactly
that invariant — which is the point: the bound, or the reason none is
safe, becomes visible at the construction site.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ._util import dotted_name, in_dirs

RULE_ID = "bounded-queue"
DESCRIPTION = (
    "queue.Queue()/SimpleQueue() without a positive capacity in "
    "nodes//runtime//sched/"
)

_QUEUE_CTORS = frozenset({"Queue", "LifoQueue", "PriorityQueue"})


def _in_scope(path: str) -> bool:
    return in_dirs(path, "nodes", "runtime", "sched")


def _queue_ctor(call: ast.Call) -> str:
    """'Queue'/'SimpleQueue' for a queue-module constructor call, else ''.

    Matches both ``queue.Queue(...)`` and a bare imported ``Queue(...)``
    — the import style must not decide whether the bound is checked.
    """
    name = dotted_name(call.func)
    if name is None:
        return ""
    parts = name.split(".")
    last = parts[-1]
    if last == "SimpleQueue":
        return last
    if last in _QUEUE_CTORS and (len(parts) == 1 or parts[-2] == "queue"):
        return last
    return ""


def _capacity_ok(call: ast.Call) -> bool:
    """True when the construction carries a usable bound."""
    args = list(call.args)
    for kw in call.keywords:
        if kw.arg == "maxsize":
            args.append(kw.value)
    if not args:
        return False
    cap = args[0]
    if isinstance(cap, ast.Constant):
        return isinstance(cap.value, (int, float)) and cap.value > 0
    # non-literal capacity: assume a configured bound
    return True


def check(module, context) -> Iterator:
    if not _in_scope(module.path):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        ctor = _queue_ctor(node)
        if not ctor:
            continue
        if ctor == "SimpleQueue":
            yield module.finding(
                RULE_ID, node,
                "queue.SimpleQueue() is always unbounded — use "
                "queue.Queue(maxsize=N), or suppress with the invariant "
                "that bounds it",
            )
        elif not _capacity_ok(node):
            yield module.finding(
                RULE_ID, node,
                f"unbounded queue.{ctor}() in a serving plane — pass a "
                f"positive maxsize, or suppress with the invariant that "
                f"bounds the depth (protocol ledger, gauged backlog, ...)",
            )
