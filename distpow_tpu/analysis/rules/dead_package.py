"""dead-package — no hollow directories in the tree.

VERDICT r5 item 8: ``distpow_tpu/utils/`` shipped as an empty package
(a 0-line ``__init__.py``, no modules) for five rounds because nothing
mechanically objected.  A package directory whose only content is an
``__init__.py`` with no executable statements (docstrings and comments
don't count) and no sibling modules or subpackages is dead weight that
invites drive-by dumping-ground imports; delete it, or give it content.

This is a directory-level rule (``scan_tree``): it sees the scanned
root, not individual modules, so per-file suppression does not apply —
the fix is structural.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from ..engine import SKIP_DIRS, Finding

RULE_ID = "dead-package"
DESCRIPTION = (
    "package directories must contain more than an empty __init__.py"
)


def _init_is_empty(path: str) -> bool:
    try:
        with open(path, "rb") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return False
    for node in tree.body:
        if isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            continue  # docstring
        return False
    return True


def scan_tree(root: str, rel_to: str, context) -> Iterator[Finding]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        if "__init__.py" not in filenames:
            continue
        substance = [f for f in filenames
                     if f != "__init__.py" and not f.endswith(".pyc")]
        if substance or dirnames:
            continue
        init = os.path.join(dirpath, "__init__.py")
        if _init_is_empty(init):
            yield Finding(
                rule=RULE_ID,
                path=os.path.relpath(init, rel_to),
                line=1,
                message=(
                    f"package {os.path.basename(dirpath)!r} contains "
                    f"nothing but an empty __init__.py — delete the "
                    f"directory or give it real modules"
                ),
            )
