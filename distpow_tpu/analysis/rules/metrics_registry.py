"""metrics-registry — every counter AND histogram name must be declared.

``Metrics.inc``/``Metrics.observe`` create series on first touch, so a
typo'd name (``coord.fanout`` for ``coord.fanouts``; ``worker.solve``
for ``worker.solve_s``) silently splits a series into two and every
dashboard/asserting test reading the real name sees frozen zeros —
exactly the hand-transcribed-counts drift class VERDICT r5 called out.
The registry is declared in ``runtime/metrics.py``:

* ``KNOWN_COUNTERS`` / ``KNOWN_COUNTER_PREFIXES`` gate
  ``metrics.inc(...)`` / ``REGISTRY.inc(...)`` call sites;
* ``KNOWN_HISTOGRAMS`` / ``KNOWN_HISTOGRAM_PREFIXES`` gate
  ``metrics.observe(...)`` and ``metrics.time(...)`` call sites (the
  ISSUE-3 latency telemetry plane);
* ``KNOWN_GAUGES`` / ``KNOWN_GAUGE_PREFIXES`` gate
  ``metrics.gauge(...)`` call sites (the ISSUE-18 resource sentinels —
  a typo'd gauge name is a leak detector watching nothing).

Resolution, per call site:

* a string literal must be in the exact-name set;
* an f-string's leading literal text must match a declared prefix;
* a bare name is resolved through same-module string constants
  (``REGISTRY.inc(ERRORS_TOTAL)``); anything still dynamic is skipped
  (documented limitation — the registry cannot be checked through
  arbitrary dataflow).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

from ._util import is_module, receiver_name, resolve_str_constant

RULE_ID = "metrics-registry"
DESCRIPTION = (
    "metrics.inc()/observe()/time()/gauge() series names must be declared "
    "in runtime/metrics.py KNOWN_COUNTERS / KNOWN_HISTOGRAMS / "
    "KNOWN_GAUGES (+ prefixes)"
)

RECEIVERS = frozenset({"metrics", "REGISTRY"})
COUNTER_METHODS = frozenset({"inc"})
HISTOGRAM_METHODS = frozenset({"observe", "time"})
GAUGE_METHODS = frozenset({"gauge"})


def _series_arg(call: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """(family, name-arg) for a registry call site, else None.
    ``family`` is "counter" or "histogram"."""
    if not (isinstance(call.func, ast.Attribute)
            and receiver_name(call.func) in RECEIVERS and call.args):
        return None
    if call.func.attr in COUNTER_METHODS:
        return "counter", call.args[0]
    if call.func.attr in HISTOGRAM_METHODS:
        return "histogram", call.args[0]
    if call.func.attr in GAUGE_METHODS:
        return "gauge", call.args[0]
    return None


def check(module, context) -> Iterator:
    if not context.counters and not context.histograms:
        return  # registry not parsed (fixture tree without metrics.py)
    if is_module(module.path, "runtime/metrics.py"):
        return
    declared = {
        "counter": (context.counters, context.counter_prefixes,
                    "KNOWN_COUNTERS", "KNOWN_COUNTER_PREFIXES"),
        "histogram": (context.histograms, context.histogram_prefixes,
                      "KNOWN_HISTOGRAMS", "KNOWN_HISTOGRAM_PREFIXES"),
        "gauge": (context.gauges, context.gauge_prefixes,
                  "KNOWN_GAUGES", "KNOWN_GAUGE_PREFIXES"),
    }
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = _series_arg(node)
        if hit is None:
            continue
        family, arg = hit
        names, prefixes, names_decl, prefixes_decl = declared[family]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name: Optional[str] = arg.value
        elif isinstance(arg, ast.Name):
            name = resolve_str_constant(module.tree, arg.id)
            if name is None:
                continue  # dynamic: not checkable
        elif isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            if not (isinstance(head, ast.Constant) and
                    isinstance(head.value, str)):
                continue  # leading formatted value: fully dynamic, skip
            prefix = head.value
            if not any(prefix.startswith(p) for p in prefixes):
                yield module.finding(
                    RULE_ID, node,
                    f"f-string {family} prefix {prefix!r} matches no "
                    f"declared prefix in {prefixes_decl} "
                    f"({', '.join(prefixes) or 'none'})",
                )
            continue
        else:
            continue
        if name not in names:
            yield module.finding(
                RULE_ID, node,
                f"{family} {name!r} is not declared in "
                f"runtime/metrics.py {names_decl} — declare it (and "
                f"its docstring entry) or fix the typo",
            )
