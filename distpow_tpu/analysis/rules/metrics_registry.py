"""metrics-registry — every counter name must be declared.

``Metrics.inc`` creates counters on first touch, so a typo'd name
(``coord.fanout`` for ``coord.fanouts``) silently splits a counter into
two and every dashboard/asserting test reading the real name sees
frozen zeros — exactly the hand-transcribed-counts drift class VERDICT
r5 called out.  The registry is declared in ``runtime/metrics.py``
(``KNOWN_COUNTERS`` exact names, ``KNOWN_COUNTER_PREFIXES`` for
families minted from runtime values like ``faults.injected.<kind>``);
this rule checks every ``metrics.inc(...)`` / ``REGISTRY.inc(...)``
call site against it:

* a string literal must be in ``KNOWN_COUNTERS``;
* an f-string's leading literal text must match a declared prefix;
* a bare name is resolved through same-module string constants
  (``REGISTRY.inc(ERRORS_TOTAL)``); anything still dynamic is skipped
  (documented limitation — the registry cannot be checked through
  arbitrary dataflow).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ._util import is_module, receiver_name, resolve_str_constant

RULE_ID = "metrics-registry"
DESCRIPTION = (
    "metrics.inc() counter names must be declared in "
    "runtime/metrics.py KNOWN_COUNTERS / KNOWN_COUNTER_PREFIXES"
)

RECEIVERS = frozenset({"metrics", "REGISTRY"})


def _counter_arg(call: ast.Call) -> Optional[ast.AST]:
    if isinstance(call.func, ast.Attribute) and call.func.attr == "inc" \
            and receiver_name(call.func) in RECEIVERS and call.args:
        return call.args[0]
    return None


def check(module, context) -> Iterator:
    if not context.counters:
        return  # registry not parsed (fixture tree without metrics.py)
    if is_module(module.path, "runtime/metrics.py"):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        arg = _counter_arg(node)
        if arg is None:
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name: Optional[str] = arg.value
        elif isinstance(arg, ast.Name):
            name = resolve_str_constant(module.tree, arg.id)
            if name is None:
                continue  # dynamic: not checkable
        elif isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            if not (isinstance(head, ast.Constant) and
                    isinstance(head.value, str)):
                continue  # leading formatted value: fully dynamic, skip
            prefix = head.value
            if not any(
                    prefix.startswith(p)
                    for p in context.counter_prefixes):
                yield module.finding(
                    RULE_ID, node,
                    f"f-string counter prefix {prefix!r} matches no "
                    f"declared prefix in KNOWN_COUNTER_PREFIXES "
                    f"({', '.join(context.counter_prefixes) or 'none'})",
                )
            continue
        else:
            continue
        if name not in context.counters:
            yield module.finding(
                RULE_ID, node,
                f"counter {name!r} is not declared in "
                f"runtime/metrics.py KNOWN_COUNTERS — declare it (and "
                f"its docstring entry) or fix the typo",
            )
