"""The shared static call/lock model behind the concurrency rules.

Three interprocedural passes (docs/CONCURRENCY.md) — guarded-by
analysis, lock-order-inversion, transitive blocking-under-lock — all
need the same facts about the scanned tree:

* which attributes of each class ARE locks (assigned
  ``threading.Lock/RLock/Condition``, or annotated as one),
* which lock is held at every shared-attribute access and call site
  (lexical ``with`` nesting, local aliases like ``wlock =
  self._wlock``, and entry-lock credit for private helpers only ever
  called with a lock held),
* a bounded-depth call graph (``self.meth()``, attribute-typed
  cross-class calls like ``self.fleet.round_plan()``, same-module
  functions, and constructor calls) with per-method summaries of lock
  acquisitions and blocking operations.

:func:`build_model` computes all of it in one walk over the engine's
already-parsed :class:`~.engine.Module` list; :func:`get_model` caches
the result so the three rules share one build per ``run_analysis``.

The ``# guarded-by: self._mu`` annotation protocol is parsed here too:
a trailing comment on an attribute's assignment (or class-body
annotation) declares the lock that must be held at EVERY access, and
turns violations into hard findings (rules/guarded_by.py).  Matching
is by the lock's terminal name — ``# guarded-by: registry._lock``
declares a cross-object guard that any held ``._lock`` satisfies; the
model is a linter, not a verifier, and docs/CONCURRENCY.md says so.

Deliberately lexical+summaries only, stdlib only, like the engine:
no imports of scanned code, no dataflow through containers beyond
``Dict[K, V]``-style annotations, explicit ``.acquire()`` calls
untracked (the tree uses ``with`` everywhere).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

GUARD_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w.]*)")


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last segment of a Name/Attribute chain — duplicated from
    rules/_util.py because importing the rules package from here would
    be circular (the rule modules import this model)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None

LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

# receiver methods that mutate the container they are called on — a
# bare `self._threads.append(t)` is a WRITE to the shared list
MUTATORS = frozenset({
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft", "popleft",
})
# receiver methods whose result has the container's ELEMENT type
ELEM_CALLS = frozenset({"values", "get", "pop", "setdefault", "popleft"})

CONTAINER_GENERICS = frozenset({
    "Dict", "dict", "Mapping", "MutableMapping", "DefaultDict",
    "OrderedDict", "List", "list", "Sequence", "Set", "set",
    "FrozenSet", "Iterable", "Iterator", "Deque", "deque", "Optional",
    "Tuple", "tuple",
})

INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

# LockId = (owner, name): owner is a class qual ("path::Class"), a
# module path (module-level locks), or "local:<method qual>" for
# unresolvable locals (unique per method, so they can never fabricate
# cross-function cycles)
LockId = Tuple[str, str]


def is_lockish(name: str) -> bool:
    lowered = name.lower()
    return "lock" in lowered or "mutex" in lowered


def fmt_lock(lid: LockId) -> str:
    owner, name = lid
    if owner.startswith("local:"):
        return name
    return f"{owner.split('::')[-1]}.{name}"


@dataclass
class Access:
    """One read/write of a (resolved) shared attribute."""

    owner: str  # class qual the attribute belongs to
    attr: str
    write: bool
    held: FrozenSet[LockId]  # lexical locks at the access
    node: ast.AST
    method: "MethodInfo"
    fresh: bool = False  # receiver constructed in this same function


@dataclass
class CallSite:
    callee: str  # method qual
    held: FrozenSet[LockId]
    node: ast.AST
    method: "MethodInfo"
    # True when an enclosing lexical `with` is lock-NAMED (the direct
    # no-blocking-under-lock rule already polices this extent)
    lock_named_hold: bool = False


@dataclass
class BlockingSite:
    reason: str
    held: FrozenSet[LockId]
    node: ast.AST
    method: "MethodInfo"
    lock_named_hold: bool = False
    # `self._cond.wait()` with self._cond itself held: wait() RELEASES
    # the lock — the canonical condition-variable shape, not a hold
    self_wait: bool = False


@dataclass
class Acquisition:
    lock: LockId
    held_before: FrozenSet[LockId]
    node: ast.AST
    method: "MethodInfo"


@dataclass
class MethodInfo:
    qual: str  # "path::Class.meth", "path::func", nested "...meth.inner"
    name: str
    cls: Optional[str]  # owning class qual ('self' binds to it)
    module: "object"  # engine.Module
    node: ast.AST
    acquisitions: List[Acquisition] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    accesses: List[Access] = field(default_factory=list)
    blocking: List[BlockingSite] = field(default_factory=list)

    @property
    def short(self) -> str:
        return self.qual.split("::")[-1]


@dataclass
class ClassInfo:
    name: str
    qual: str  # "path::Name"
    module_path: str
    bases: List[str] = field(default_factory=list)  # raw base names
    lock_attrs: Set[str] = field(default_factory=set)
    attr_types: Dict[str, str] = field(default_factory=dict)  # raw names
    attr_elem_types: Dict[str, str] = field(default_factory=dict)
    guards: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    declared_attrs: Set[str] = field(default_factory=set)
    method_names: Set[str] = field(default_factory=set)


@dataclass
class Model:
    classes: Dict[str, ClassInfo] = field(default_factory=dict)  # by qual
    classes_by_name: Dict[str, List[str]] = field(default_factory=dict)
    methods: Dict[str, MethodInfo] = field(default_factory=dict)
    module_locks: Set[LockId] = field(default_factory=set)
    # method quals referenced as bare attributes (thread targets,
    # callbacks): their call sites are NOT all visible, so they earn
    # no entry-lock credit
    escaped_methods: Set[str] = field(default_factory=set)
    entry_locks: Dict[str, FrozenSet[LockId]] = field(default_factory=dict)
    # qual -> (hops, chain of quals, leaf reason)
    block_depth: Dict[str, Tuple[int, Tuple[str, ...], str]] = field(
        default_factory=dict)
    # qual -> {LockId: chain of quals from the method to the acquirer}
    acq_closure: Dict[str, Dict[LockId, Tuple[str, ...]]] = field(
        default_factory=dict)

    # -- resolution helpers --------------------------------------------------

    def resolve_class(self, name: str, module_path: str) -> Optional[str]:
        quals = self.classes_by_name.get(name, ())
        same = [q for q in quals
                if self.classes[q].module_path == module_path]
        if same:
            return same[0]
        if len(quals) == 1:
            return quals[0]
        return None  # ambiguous across modules: refuse to guess

    def mro(self, qual: str, depth: int = 4) -> List[ClassInfo]:
        """The class and its resolvable bases, bounded."""
        out, seen, frontier = [], set(), [qual]
        while frontier and depth >= 0:
            nxt: List[str] = []
            for q in frontier:
                if q in seen or q not in self.classes:
                    continue
                seen.add(q)
                ci = self.classes[q]
                out.append(ci)
                for b in ci.bases:
                    bq = self.resolve_class(b, ci.module_path)
                    if bq:
                        nxt.append(bq)
            frontier, depth = nxt, depth - 1
        return out

    def owner_of(self, cls_qual: str, attr: str) -> str:
        """The class (self or base) that declares ``attr`` — subclass
        accesses aggregate with the declaring class's discipline."""
        for ci in self.mro(cls_qual):
            if attr in ci.declared_attrs or attr in ci.lock_attrs \
                    or attr in ci.guards:
                return ci.qual
        return cls_qual

    def find_method(self, cls_qual: str, name: str) -> Optional[str]:
        for ci in self.mro(cls_qual):
            if name in ci.method_names:
                return f"{ci.qual}.{name}"
        return None

    def is_lock_attr(self, cls_qual: str, attr: str) -> bool:
        return any(attr in ci.lock_attrs for ci in self.mro(cls_qual))

    def is_method_name(self, cls_qual: str, attr: str) -> bool:
        return any(attr in ci.method_names for ci in self.mro(cls_qual))

    def guard_for(self, cls_qual: str, attr: str
                  ) -> Optional[Tuple[str, int]]:
        for ci in self.mro(cls_qual):
            if attr in ci.guards:
                return ci.guards[attr]
        return None

    def held_effective(self, acc_or_site) -> FrozenSet[LockId]:
        return acc_or_site.held | self.entry_locks.get(
            acc_or_site.method.qual, frozenset())


# -- annotation helpers ------------------------------------------------------

def _guard_lines(source: str) -> Dict[int, str]:
    """line -> guard lock terminal name, from ``# guarded-by:``
    comments (trailing an assignment or annotation)."""
    out: Dict[int, str] = {}
    for i, line in enumerate(source.split("\n"), start=1):
        m = GUARD_RE.search(line)
        if m:
            out[i] = m.group("lock").split(".")[-1]
    return out


def _annotation_types(ann: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(direct class name, container element class name) out of a type
    annotation — enough to chase ``self._leases: Dict[str, Lease]``
    lookups to ``Lease``.  String annotations are re-parsed."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None, None
    if isinstance(ann, (ast.Name, ast.Attribute)):
        name = terminal_name(ann)
        if name and name[:1].isupper() and name not in CONTAINER_GENERICS:
            return name, None
        return None, None
    if isinstance(ann, ast.Subscript):
        head = terminal_name(ann.value)
        if head not in CONTAINER_GENERICS:
            return None, None
        slc = ann.slice
        elts = list(slc.elts) if isinstance(slc, ast.Tuple) else [slc]
        # Optional[T] is T-with-None, not a container of T
        if head == "Optional":
            return _annotation_types(elts[0])
        # Dict[K, V] -> V; List[T]/... -> T
        pick = elts[-1] if head in ("Dict", "dict", "Mapping",
                                    "MutableMapping", "DefaultDict",
                                    "OrderedDict") else elts[0]
        direct, _ = _annotation_types(pick)
        return None, direct
    return None, None


def _queue_fsync_reason(call: ast.Call) -> str:
    """Blocking leaves the lexical rule's set leaves out but the
    transitive closure must see: ``q.get/put(..., timeout=...)`` (or
    an explicit ``block=``) and ``os.fsync`` — a journal fsync under a
    lock stalls every waiter for a disk flush."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return ""
    if fn.attr == "fsync":
        return "fsync(...) blocks on a disk flush"
    if fn.attr in ("get", "put") and any(
            k.arg in ("timeout", "block") for k in call.keywords):
        return f".{fn.attr}(timeout=...) parks the thread"
    return ""


def _is_lock_factory(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    name = terminal_name(call.func)
    return name in LOCK_FACTORIES


# -- class collection (pass 1) -----------------------------------------------

def _collect_class(node: ast.ClassDef, module, guards: Dict[int, str],
                   ) -> ClassInfo:
    ci = ClassInfo(
        name=node.name,
        qual=f"{module.path}::{node.name}",
        module_path=module.path,
        bases=[terminal_name(b) or "" for b in node.bases],
    )
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            attr = stmt.target.id
            ci.declared_attrs.add(attr)
            direct, elem = _annotation_types(stmt.annotation)
            t = terminal_name(stmt.annotation)
            if t in LOCK_FACTORIES or (
                    stmt.value is not None and _is_lock_factory(stmt.value)):
                ci.lock_attrs.add(attr)
            elif isinstance(stmt.value, ast.Call) and \
                    terminal_name(stmt.value.func) == "field" and \
                    any(k.arg == "default_factory"
                        and terminal_name(k.value) in LOCK_FACTORIES
                        for k in stmt.value.keywords):
                ci.lock_attrs.add(attr)  # dataclass lock field
            if direct:
                ci.attr_types[attr] = direct
            if elem:
                ci.attr_elem_types[attr] = elem
            g = guards.get(stmt.lineno) or guards.get(
                getattr(stmt, "end_lineno", stmt.lineno))
            if g:
                ci.guards[attr] = (g, stmt.lineno)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.method_names.add(stmt.name)
    # every `self.X = ...` in every method (nested too) declares X
    for fn in ast.walk(node):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(fn):
            targets: List[ast.AST] = []
            value = None
            if isinstance(sub, ast.Assign):
                targets, value = list(sub.targets), sub.value
            elif isinstance(sub, ast.AnnAssign):
                targets, value = [sub.target], sub.value
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                ci.declared_attrs.add(t.attr)
                if value is not None and _is_lock_factory(value):
                    ci.lock_attrs.add(t.attr)
                if isinstance(sub, ast.AnnAssign):
                    direct, elem = _annotation_types(sub.annotation)
                    if direct:
                        ci.attr_types.setdefault(t.attr, direct)
                    if elem:
                        ci.attr_elem_types.setdefault(t.attr, elem)
                elif isinstance(value, ast.Call):
                    vn = terminal_name(value.func)
                    if vn and vn[:1].isupper() and \
                            vn not in LOCK_FACTORIES:
                        ci.attr_types.setdefault(t.attr, vn)
                g = guards.get(sub.lineno) or guards.get(
                    getattr(sub, "end_lineno", sub.lineno))
                if g:
                    ci.guards.setdefault(t.attr, (g, sub.lineno))
    return ci


# -- per-function summaries (pass 2) -----------------------------------------

class _FuncVisitor:
    """One walk over one function body, tracking the lexical lock-held
    stack, a tiny local type/alias environment, and recording the
    method's accesses, calls, acquisitions and blocking sites."""

    def __init__(self, model: Model, info: MethodInfo):
        self.model = model
        self.info = info
        self.held: List[LockId] = []
        self.lock_named: List[bool] = []  # parallel: with-name lockish?
        self.locals_types: Dict[str, str] = {}   # name -> class qual
        self.locals_elem: Dict[str, str] = {}    # name -> elem class qual
        self.locals_locks: Dict[str, LockId] = {}  # lock aliases
        self.fresh: Set[str] = set()  # locals constructed here
        self.nested: List[ast.AST] = []
        node = info.node
        args = getattr(node, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.annotation is not None:
                    direct, elem = _annotation_types(a.annotation)
                    q = direct and self._class_qual(direct)
                    if q:
                        self.locals_types[a.arg] = q
                    eq = elem and self._class_qual(elem)
                    if eq:
                        self.locals_elem[a.arg] = eq

    # -- small resolution utilities ------------------------------------------

    def _class_qual(self, name: Optional[str]) -> Optional[str]:
        if not name:
            return None
        return self.model.resolve_class(name, self.info.module.path)

    def _snapshot(self) -> FrozenSet[LockId]:
        return frozenset(self.held)

    def _lock_named_now(self) -> bool:
        return any(self.lock_named)

    def expr_type(self, e: ast.AST) -> Optional[str]:
        """Class qual of an expression, chasing locals, self attrs,
        annotated-container element lookups, and constructors."""
        if isinstance(e, ast.Name):
            return self.locals_types.get(e.id)
        if isinstance(e, ast.Attribute):
            base_cls = None
            if isinstance(e.value, ast.Name) and e.value.id == "self":
                base_cls = self.info.cls
            else:
                base_cls = self.expr_type(e.value)
            if base_cls:
                for ci in self.model.mro(base_cls):
                    if e.attr in ci.attr_types:
                        return self._class_qual(ci.attr_types[e.attr])
            return None
        if isinstance(e, ast.Subscript):
            return self._elem_type(e.value)
        if isinstance(e, ast.Call):
            fn = e.func
            if isinstance(fn, ast.Name):
                if fn.id == "getattr" and len(e.args) >= 2 and \
                        isinstance(e.args[1], ast.Constant) and \
                        isinstance(e.args[1].value, str):
                    # getattr(ref, "lease", None) — the tree's
                    # duck-typing idiom; chase it like ref.lease
                    fake = ast.Attribute(value=e.args[0],
                                         attr=e.args[1].value,
                                         ctx=ast.Load())
                    return self.expr_type(fake)
                return self._class_qual(fn.id)
            if isinstance(fn, ast.Attribute) and fn.attr in ELEM_CALLS:
                return self._elem_type(fn.value)
        return None

    def _elem_type(self, container: ast.AST) -> Optional[str]:
        if isinstance(container, ast.Call):
            fn = container.func
            # list(self.refs) / sorted(...) snapshots keep the elem type
            if isinstance(fn, ast.Name) and container.args and fn.id in (
                    "list", "sorted", "tuple", "set", "iter", "reversed"):
                return self._elem_type(container.args[0])
            if isinstance(fn, ast.Attribute) and fn.attr in ELEM_CALLS:
                return self._elem_type(fn.value)
            return None
        if isinstance(container, ast.Name):
            return self.locals_elem.get(container.id)
        if isinstance(container, ast.Attribute):
            base_cls = None
            if isinstance(container.value, ast.Name) and \
                    container.value.id == "self":
                base_cls = self.info.cls
            else:
                base_cls = self.expr_type(container.value)
            if base_cls:
                for ci in self.model.mro(base_cls):
                    if container.attr in ci.attr_elem_types:
                        return self._class_qual(
                            ci.attr_elem_types[container.attr])
        return None

    def resolve_lock(self, expr: ast.AST) -> Optional[LockId]:
        """LockId of a `with` item, or None when it is not a lock.
        Recognition: a lock-ish terminal NAME, or an identity that maps
        to a discovered lock (class attr, module lock, local alias)."""
        node = expr.func if isinstance(expr, ast.Call) else expr
        name = terminal_name(node)
        if name is None:
            return None
        suffix = "()" if isinstance(expr, ast.Call) else ""
        if isinstance(node, ast.Attribute):
            base_cls = None
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                base_cls = self.info.cls
            else:
                base_cls = self.expr_type(node.value)
            if base_cls:
                if self.model.is_lock_attr(base_cls, name) or \
                        is_lockish(name):
                    owner = self.model.owner_of(base_cls, name)
                    return (owner, name + suffix)
                return None
            return (f"local:{self.info.qual}", name + suffix) \
                if is_lockish(name) else None
        if isinstance(node, ast.Name):
            if node.id in self.locals_locks:
                return self.locals_locks[node.id]
            mid = (self.info.module.path, name)
            if mid in self.model.module_locks:
                return mid
            return (f"local:{self.info.qual}", name + suffix) \
                if is_lockish(name) else None
        return None

    # -- recording -----------------------------------------------------------

    def record_access(self, attr_node: ast.Attribute, write: bool) -> None:
        base = attr_node.value
        if isinstance(base, ast.Name) and base.id == "self":
            cls = self.info.cls
            fresh = False
        else:
            cls = self.expr_type(base)
            fresh = isinstance(base, ast.Name) and base.id in self.fresh
        if cls is None:
            return
        attr = attr_node.attr
        if attr.startswith("__") or self.model.is_lock_attr(cls, attr):
            return
        if self.model.is_method_name(cls, attr):
            # a bare method reference escapes (thread target, callback):
            # its call sites are no longer all visible
            mq = self.model.find_method(cls, attr)
            if mq:
                self.model.escaped_methods.add(mq)
            return
        self.info.accesses.append(Access(
            owner=self.model.owner_of(cls, attr), attr=attr, write=write,
            held=self._snapshot(), node=attr_node, method=self.info,
            fresh=fresh,
        ))

    def record_call(self, call: ast.Call) -> None:
        from .rules.blocking_under_lock import _blocking_reason
        reason = _blocking_reason(call) or _queue_fsync_reason(call)
        if reason:
            self_wait = False
            fn = call.func
            if isinstance(fn, ast.Attribute) and fn.attr == "wait":
                recv_lock = self.resolve_lock(fn.value)
                self_wait = recv_lock is not None and recv_lock in self.held
            self.info.blocking.append(BlockingSite(
                reason=reason, held=self._snapshot(), node=call,
                method=self.info, lock_named_hold=self._lock_named_now(),
                self_wait=self_wait,
            ))
        callee: Optional[str] = None
        fn = call.func
        if isinstance(fn, ast.Attribute):
            base_cls = None
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                base_cls = self.info.cls
            else:
                base_cls = self.expr_type(fn.value)
            if base_cls:
                callee = self.model.find_method(base_cls, fn.attr)
        elif isinstance(fn, ast.Name):
            q = f"{self.info.module.path}::{fn.id}"
            if q in self.model.methods:
                callee = q
            else:
                cq = self._class_qual(fn.id)
                if cq and f"{cq}.__init__" in self.model.methods:
                    callee = f"{cq}.__init__"
        if callee:
            self.info.calls.append(CallSite(
                callee=callee, held=self._snapshot(), node=call,
                method=self.info, lock_named_hold=self._lock_named_now(),
            ))

    # -- the walk ------------------------------------------------------------

    def visit_body(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self.visit_stmt(s)

    def visit_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append(s)  # analyzed as its own method
            return
        if isinstance(s, ast.ClassDef):
            return  # nested classes: out of scope
        if isinstance(s, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in s.items:
                self.visit_expr(item.context_expr)
                lock = self.resolve_lock(item.context_expr)
                if lock is not None:
                    self.info.acquisitions.append(Acquisition(
                        lock=lock, held_before=self._snapshot(),
                        node=item.context_expr, method=self.info,
                    ))
                    self.held.append(lock)
                    self.lock_named.append(is_lockish(lock[1]))
                    pushed += 1
                if item.optional_vars is not None:
                    self.visit_target(item.optional_vars)
            self.visit_body(s.body)
            for _ in range(pushed):
                self.held.pop()
                self.lock_named.pop()
            return
        if isinstance(s, ast.Assign):
            self.visit_expr(s.value)
            for t in s.targets:
                self.visit_target(t)
            if len(s.targets) == 1 and isinstance(s.targets[0], ast.Name):
                self._bind_local(s.targets[0].id, s.value)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.visit_expr(s.value)
            self.visit_target(s.target)
            if isinstance(s.target, ast.Name):
                direct, elem = _annotation_types(s.annotation)
                q = direct and self._class_qual(direct)
                if q:
                    self.locals_types[s.target.id] = q
                eq = elem and self._class_qual(elem)
                if eq:
                    self.locals_elem[s.target.id] = eq
                if s.value is not None:
                    self._bind_local(s.target.id, s.value)
            return
        if isinstance(s, ast.AugAssign):
            self.visit_expr(s.value)
            if isinstance(s.target, ast.Attribute):
                self.visit_expr(s.target.value)
                self.record_access(s.target, write=True)
            else:
                self.visit_target(s.target)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                self.visit_target(t)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self.visit_expr(s.iter)
            if isinstance(s.target, ast.Name):
                eq = self._elem_type(s.iter) or (
                    self._elem_type(s.iter.func.value)
                    if isinstance(s.iter, ast.Call)
                    and isinstance(s.iter.func, ast.Attribute) else None)
                if eq:
                    self.locals_types[s.target.id] = eq
            self.visit_target(s.target)
            self.visit_body(s.body)
            self.visit_body(s.orelse)
            return
        # default: expressions in the statement, then nested bodies,
        # all under the current held set
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self.visit_expr(child)
            elif isinstance(child, ast.stmt):
                self.visit_stmt(child)
            elif isinstance(child, (ast.excepthandler,)):
                self.visit_body(child.body)
            elif isinstance(child, ast.withitem):
                self.visit_expr(child.context_expr)
        return

    def _bind_local(self, name: str, value: ast.expr) -> None:
        if isinstance(value, ast.Attribute):
            lock = self.resolve_lock(value)
            if lock is not None:
                self.locals_locks[name] = lock
                return
        t = self.expr_type(value)
        if t:
            self.locals_types[name] = t
            if isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Name) and \
                    self._class_qual(value.func.id) == t:
                self.fresh.add(name)  # constructed here, not yet shared
            else:
                self.fresh.discard(name)
        elem = self._elem_type(value) if not isinstance(value, ast.Call) \
            else None
        if elem:
            self.locals_elem[name] = elem

    def visit_target(self, t: ast.expr) -> None:
        """Assignment/delete targets: attribute and subscript stores
        are WRITES to the underlying shared attribute."""
        if isinstance(t, ast.Attribute):
            self.visit_expr(t.value)
            self.record_access(t, write=True)
        elif isinstance(t, ast.Subscript):
            # self._tasks[k] = v mutates self._tasks
            if isinstance(t.value, ast.Attribute):
                self.visit_expr(t.value.value)
                self.record_access(t.value, write=True)
            else:
                self.visit_expr(t.value)
            self.visit_expr(t.slice)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.visit_target(e)
        elif isinstance(t, ast.Starred):
            self.visit_target(t.value)

    def visit_expr(self, e: ast.expr) -> None:
        if isinstance(e, ast.Call):
            self.record_call(e)
            fn = e.func
            if isinstance(fn, ast.Attribute):
                # receiver read (or container mutation) — but a method
                # call's receiver chain below the method name
                if isinstance(fn.value, ast.Attribute):
                    self.record_access(
                        fn.value, write=fn.attr in MUTATORS)
                    self.visit_expr(fn.value.value)
                else:
                    self.visit_expr(fn.value)
            elif not isinstance(fn, ast.Name):
                self.visit_expr(fn)
            for a in e.args:
                self.visit_expr(a.value if isinstance(a, ast.Starred)
                                else a)
            for k in e.keywords:
                self.visit_expr(k.value)
            return
        if isinstance(e, ast.Attribute):
            self.record_access(e, write=False)
            self.visit_expr(e.value)
            return
        if isinstance(e, ast.Lambda):
            return  # runs later, outside this dynamic extent
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            # comprehensions execute inline (genexps mostly do too in
            # this tree — consumed immediately); walk them under the
            # current held set
            for gen in e.generators:
                self.visit_expr(gen.iter)
                for cond in gen.ifs:
                    self.visit_expr(cond)
            if isinstance(e, ast.DictComp):
                self.visit_expr(e.key)
                self.visit_expr(e.value)
            else:
                self.visit_expr(e.elt)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.visit_expr(child)


# -- model assembly ----------------------------------------------------------

CALL_DEPTH = 3  # bounded call-graph summaries: ≤3 hops


def _walk_functions(model: Model, module, cls: Optional[str],
                    prefix: str, fns: Sequence[ast.AST]) -> None:
    """Register + summarize each function, then its nested functions
    (which run later: their held stack starts empty, but ``self``
    still binds to the enclosing class through the closure)."""
    for fn in fns:
        qual = f"{prefix}{fn.name}"
        info = MethodInfo(qual=qual, name=fn.name, cls=cls,
                          module=module, node=fn)
        model.methods[qual] = info
        v = _FuncVisitor(model, info)
        v.visit_body(fn.body)
        _walk_functions(model, module, cls, qual + ".", v.nested)


def build_model(modules: Sequence) -> Model:
    model = Model()
    guard_maps = {}
    # pass 1: classes, module-level locks, guard annotations
    for m in modules:
        guards = _guard_lines(m.source)
        guard_maps[m.path] = guards
        for node in m.tree.body:
            if isinstance(node, ast.ClassDef):
                ci = _collect_class(node, m, guards)
                model.classes[ci.qual] = ci
                model.classes_by_name.setdefault(ci.name, []).append(
                    ci.qual)
            elif isinstance(node, ast.Assign) and _is_lock_factory(
                    node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        model.module_locks.add((m.path, t.id))
    # pass 2: register every function first (Name-call resolution needs
    # the full registry), then summarize
    pending: List[Tuple[object, Optional[str], str, List[ast.AST]]] = []
    for m in modules:
        top = [n for n in m.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        pending.append((m, None, f"{m.path}::", top))
        for node in m.tree.body:
            if isinstance(node, ast.ClassDef):
                methods = [n for n in node.body if isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef))]
                pending.append((m, f"{m.path}::{node.name}",
                                f"{m.path}::{node.name}.", methods))
    for m, cls, prefix, fns in pending:
        for fn in fns:  # pre-register names for cross-function calls
            model.methods.setdefault(
                f"{prefix}{fn.name}",
                MethodInfo(qual=f"{prefix}{fn.name}", name=fn.name,
                           cls=cls, module=m, node=fn))
    for m, cls, prefix, fns in pending:
        _walk_functions(model, m, cls, prefix, fns)
    _compute_entry_locks(model)
    _compute_block_depth(model)
    _compute_acq_closure(model)
    return model


def _compute_entry_locks(model: Model) -> None:
    """A method called at every visible call site with lock L held
    runs under L — its accesses classify as under-lock ('through
    helper methods', docs/CONCURRENCY.md).  Dunder methods and escaped
    methods (thread targets, callbacks, bare references — dispatched
    by machinery the model cannot see) earn no credit; a method with
    NO visible call sites (RPC handlers entered by name) earns none
    either, because ``callers`` is empty."""
    sites: Dict[str, List[CallSite]] = {}
    for info in model.methods.values():
        for c in info.calls:
            sites.setdefault(c.callee, []).append(c)
    entry: Dict[str, FrozenSet[LockId]] = {
        q: frozenset() for q in model.methods}
    for _ in range(CALL_DEPTH):
        nxt = dict(entry)
        for q, info in model.methods.items():
            if info.name.startswith("__") or \
                    q in model.escaped_methods:
                continue
            callers = sites.get(q)
            if not callers:
                continue
            held = None
            for c in callers:
                at_site = c.held | entry[c.method.qual]
                held = at_site if held is None else (held & at_site)
            nxt[q] = held or frozenset()
        if nxt == entry:
            break
        entry = nxt
    model.entry_locks = entry


def _compute_block_depth(model: Model) -> None:
    """qual -> (hops, chain, reason): fewest call hops from entering
    the method to a known blocking operation, bounded at CALL_DEPTH."""
    depth: Dict[str, Tuple[int, Tuple[str, ...], str]] = {}
    for q, info in model.methods.items():
        if info.blocking:
            b = info.blocking[0]
            depth[q] = (1, (q,), b.reason)
    for _ in range(CALL_DEPTH - 1):
        changed = False
        for q, info in model.methods.items():
            best = depth.get(q)
            for c in info.calls:
                sub = depth.get(c.callee)
                if sub is None or c.callee == q:
                    continue
                cand = (sub[0] + 1, (q,) + sub[1], sub[2])
                if cand[0] <= CALL_DEPTH and (
                        best is None or cand[0] < best[0]):
                    best = cand
            if best is not None and depth.get(q) != best:
                depth[q] = best
                changed = True
        if not changed:
            break
    model.block_depth = depth


def _compute_acq_closure(model: Model) -> None:
    """qual -> {lock: call chain to its acquirer}: every lock a call
    into the method can end up acquiring, bounded at CALL_DEPTH."""
    closure: Dict[str, Dict[LockId, Tuple[str, ...]]] = {}
    for q, info in model.methods.items():
        own: Dict[LockId, Tuple[str, ...]] = {}
        for a in info.acquisitions:
            own.setdefault(a.lock, (q,))
        closure[q] = own
    for _ in range(CALL_DEPTH):
        changed = False
        for q, info in model.methods.items():
            mine = closure[q]
            for c in info.calls:
                if c.callee == q:
                    continue
                for lock, chain in closure.get(c.callee, {}).items():
                    if lock not in mine and len(chain) < CALL_DEPTH + 1:
                        mine[lock] = (q,) + chain
                        changed = True
        if not changed:
            break
    model.acq_closure = closure


# -- shared-build cache ------------------------------------------------------

_CACHE: Tuple[Optional[tuple], Optional[Model]] = (None, None)


def get_model(modules: Sequence) -> Model:
    """One model build per ``run_analysis`` pass: the three concurrency
    rules receive the same module list object in sequence."""
    global _CACHE
    key = (id(modules), tuple((m.path, len(m.source)) for m in modules))
    if _CACHE[0] == key and _CACHE[1] is not None:
        return _CACHE[1]
    model = build_model(modules)
    _CACHE = (key, model)
    return model
