"""distpow-lint — project-native static analysis (docs/LINT.md).

The repo's correctness rests on invariants that live in comments and
reviewer memory: lock discipline around device dispatch and RPC, the
16-action trace vocabulary that reference parity depends on, the
metrics-counter registry, config-key agreement between readers and the
``runtime/config.py`` dataclasses, host-sync discipline on the hot
path, and never-silent exception handling in the protocol planes.  The
reference repo leaned on Go's race detector and ``go vet``; this
package is the TPU-native analogue — a self-contained AST rule engine
(stdlib only, no jax import) with one module per rule, line-level
suppression via ``# distpow: ok <rule-id> -- <justification>``, JSON
and human output, and an exit-code contract CI can gate on
(``scripts/ci.sh --lint``; the ``lint``-marked tier-1 test enforces a
clean tree on every fast suite run).
"""

from .engine import (  # noqa: F401
    Finding,
    ProjectContext,
    build_context,
    run_analysis,
)
