"""Worker entry point (cmd/worker/main.go equivalent).

    python -m distpow_tpu.cli.worker [--config PATH] [--id ID]
        [--listen ADDR] [--backend {python,jax,jax-mesh,pallas,native}]

``--id`` and ``--listen`` override the config file the same way the
reference's flags do (cmd/worker/main.go:15-16); ``--backend`` selects the
compute path (TPU-native extension).
"""

from __future__ import annotations

import argparse
import logging

from ..nodes.worker import Worker
from ..runtime.config import WorkerConfig, read_json_config


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow worker")
    ap.add_argument("--config", default="config/worker_config.json")
    ap.add_argument("--id", help="Worker ID, e.g. worker1")
    ap.add_argument("--listen", help="Listen address, e.g. 127.0.0.1:5000")
    ap.add_argument("--backend", help="Compute backend override")
    args = ap.parse_args(argv)

    config = read_json_config(args.config, WorkerConfig)
    if args.id:
        config.WorkerID = args.id
    if args.listen:
        config.ListenAddr = args.listen
    if args.backend:
        config.Backend = args.backend
    logging.info("worker config: %s", config)
    Worker(config).run_forever()


if __name__ == "__main__":
    main()
