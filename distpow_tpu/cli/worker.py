"""Worker entry point (cmd/worker/main.go equivalent).

    python -m distpow_tpu.cli.worker [--config PATH] [--id ID]
        [--listen ADDR]
        [--backend {python,jax,jax-mesh,pallas,pallas-mesh,native,auto}]
        [--jax-coordinator HOST:PORT --jax-num-processes N --jax-process-id I]

``--id`` and ``--listen`` override the config file the same way the
reference's flags do (cmd/worker/main.go:15-16); ``--backend`` selects the
compute path (TPU-native extension).

Multi-host: the ``--jax-*`` flags (or ``JaxCoordinator`` etc. in the
config) run ``jax.distributed.initialize`` before any backend is built,
so a single ``jax-mesh`` worker's mesh spans every chip of a multi-host
TPU slice — ``jax.devices()`` becomes the global device list and the
prefix->core ``shard_map`` collectives ride ICI/DCN.  The coordinator
still sees ONE worker RPC endpoint (run the worker CLI on process 0 of
the slice; the other processes run the same command with their process
id and serve only their chips).
"""

from __future__ import annotations

import argparse
import logging

from ..nodes.worker import Worker, maybe_init_distributed
from ..runtime import faults
from ..runtime.config import WorkerConfig, read_json_config


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow worker")
    ap.add_argument("--config", default="config/worker_config.json")
    ap.add_argument("--id", help="Worker ID, e.g. worker1")
    ap.add_argument("--listen", help="Listen address, e.g. 127.0.0.1:5000")
    ap.add_argument("--backend", help="Compute backend override")
    ap.add_argument("--faults", default=None,
                    help="fault-injection plan: JSON file path or inline "
                         "JSON (chaos testing; docs/FAULTS.md)")
    ap.add_argument("--jax-coordinator", default=None,
                    help="jax.distributed coordinator HOST:PORT "
                         "(multi-host mesh)")
    ap.add_argument("--jax-num-processes", type=int, default=None)
    ap.add_argument("--jax-process-id", type=int, default=None)
    args = ap.parse_args(argv)

    config = read_json_config(args.config, WorkerConfig)
    if args.id:
        config.WorkerID = args.id
    if args.listen:
        config.ListenAddr = args.listen
    if args.backend:
        config.Backend = args.backend
    # each --jax-* flag independently overrides its config field, so a
    # shared config can set JaxCoordinator while per-host invocations
    # pass only --jax-process-id
    if args.jax_coordinator is not None:
        config.JaxCoordinator = args.jax_coordinator
    if args.jax_num_processes is not None:
        config.JaxNumProcesses = args.jax_num_processes
    if args.jax_process_id is not None:
        config.JaxProcessId = args.jax_process_id
    plan_spec = args.faults or config.FaultPlanFile
    if plan_spec:
        faults.install_from_spec(plan_spec)
    logging.info("worker config: %s", config)
    worker = Worker(config)  # Worker() runs the multi-host bootstrap
    # graceful teardown on SIGTERM/SIGINT instead of dying mid-shard:
    # run_forever(stop) drains the fleet lease first (docs/FLEET.md —
    # the coordinator finishes this worker's in-flight rounds before
    # the lease releases), then stops the serving plane.  A second
    # signal during a slow drain falls through to the default handler.
    import signal
    import threading

    stop = threading.Event()

    def _term(signum, frame):
        signal.signal(signum, signal.SIG_DFL)
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    worker.run_forever(stop)


if __name__ == "__main__":
    main()
