"""Demo client entry point (cmd/client/main.go equivalent).

Runs the reference's built-in smoke scenario (cmd/client/main.go:40-60):
two clients, four mining requests — two concurrent distinct nonces plus a
repeated nonce at increasing difficulty to exercise the dominance cache's
miss-then-supersede path — then drains both notify queues.

    python -m distpow_tpu.cli.client [--config PATH] [--config2 PATH]
        [--id ID] [--id2 ID] [--difficulty N | --difficulty-bits N]

Difficulty units (SURVEY.md section 0): the protocol's
``numTrailingZeros`` counts trailing ``'0'`` HEX DIGITS of the digest —
nibbles, 4 bits each (worker.go:246-256).  ``--difficulty`` speaks that
native unit; ``--difficulty-bits`` accepts bits (the unit BASELINE.json's
configs use) and divides by 4, so ``--difficulty-bits 32`` ≡
``--difficulty 8``.  Bits must be a multiple of 4 — the digest check has
no sub-nibble resolution.
"""

from __future__ import annotations

import argparse
import logging
import os
import queue

from ..nodes.client import Client
from ..runtime import faults
from ..runtime.config import ClientConfig, read_json_config


def difficulty_nibbles(difficulty, difficulty_bits, default: int = 5) -> int:
    """Resolve the two difficulty flags to the protocol's nibble unit.

    ``difficulty`` is already in nibbles; ``difficulty_bits`` is divided
    by 4 (raising on non-multiples — the trailing-hex-digit check has no
    sub-nibble resolution).  Exactly one may be set; neither means
    ``default``.
    """
    if difficulty_bits is not None:
        if difficulty_bits % 4:
            raise ValueError(
                "--difficulty-bits must be a multiple of 4 (the difficulty "
                "check counts trailing hex digits)"
            )
        return difficulty_bits // 4
    return default if difficulty is None else difficulty


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow demo client")
    ap.add_argument("--config", default="config/client_config.json")
    ap.add_argument(
        "--config2",
        help="second client's config (default: client2_config.json next to "
        "--config, falling back to --config with ClientID 'client2')",
    )
    ap.add_argument("--id", help="Client ID override")
    ap.add_argument("--id2", help="Second client ID override")
    diff_group = ap.add_mutually_exclusive_group()
    diff_group.add_argument(
        "--difficulty", type=int, default=None,
        help="base difficulty in trailing hex digits (nibbles), the "
        "protocol's native numTrailingZeros unit; default 5 "
        "(the repeat-nonce request adds 2)",
    )
    diff_group.add_argument(
        "--difficulty-bits", type=int, default=None,
        help="base difficulty in bits (must be a multiple of 4); "
        "translated to nibbles: --difficulty-bits 32 == --difficulty 8",
    )
    ap.add_argument("--faults", default=None,
                    help="fault-injection plan: JSON file path or inline "
                         "JSON (chaos testing; docs/FAULTS.md)")
    args = ap.parse_args(argv)

    try:
        args.difficulty = difficulty_nibbles(
            args.difficulty, args.difficulty_bits
        )
    except ValueError as exc:
        ap.error(str(exc))

    cfg1 = read_json_config(args.config, ClientConfig)
    plan_spec = args.faults or cfg1.FaultPlanFile
    if plan_spec:
        faults.install_from_spec(plan_spec)
    config2, reused_cfg1 = args.config2, False
    if config2 is None:
        sibling = os.path.join(
            os.path.dirname(args.config), "client2_config.json"
        )
        if os.path.exists(sibling):
            config2 = sibling
        else:
            config2, reused_cfg1 = args.config, True
    cfg2 = read_json_config(config2, ClientConfig)
    if reused_cfg1 and not args.id2:
        cfg2.ClientID = "client2"
    if args.id:
        cfg1.ClientID = args.id
    if args.id2:
        cfg2.ClientID = args.id2

    client1, client2 = Client(cfg1), Client(cfg2)
    client1.initialize()
    client2.initialize()
    try:
        d = args.difficulty
        client1.mine(bytes([1, 2, 3, 4]), d + 2)
        client1.mine(bytes([5, 6, 7, 8]), d)
        client2.mine(bytes([2, 2, 2, 2]), d)
        client2.mine(bytes([2, 2, 2, 2]), d + 2)

        remaining = 4
        while remaining:
            for c in (client1, client2):
                try:
                    r = c.notify_queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                if r.error is not None:
                    logging.error(
                        "MineError nonce=%s difficulty=%d error=%s",
                        r.nonce.hex(), r.num_trailing_zeros, r.error,
                    )
                else:
                    logging.info(
                        "MineResult nonce=%s difficulty=%d secret=%s",
                        r.nonce.hex(), r.num_trailing_zeros, r.secret.hex(),
                    )
                remaining -= 1
    finally:
        client1.close()
        client2.close()


if __name__ == "__main__":
    main()
