"""Config-gen utility (cmd/config-gen/main.go equivalent).

Rewrites the ``config/*.json`` set with pseudo-random local ports
(1024-35534) while keeping cross-references consistent: the tracing server
address lands in every node config, the coordinator's client/worker listen
addresses land in the client/worker configs, and each coordinator worker
slot gets a fresh port.  Keeps the worker list length from the existing
coordinator config (cmd/config-gen/main.go:51-88).

    python -m distpow_tpu.cli.config_gen [--config-dir DIR] [--host HOST]
        [--workers N] [--elastic] [--coordinators N]

Emitted configs carry the full dataclass field set, so the fleet
membership knobs (``FleetLeaseTTLS`` / ``FleetHedge`` /
``FleetHedgeMultiple`` / ``FleetDrainTimeoutS`` on the coordinator;
``FleetRegister`` / ``FleetHeartbeatS`` / ``FleetCalibrationS`` /
``FleetMHS`` / ``FleetDrainTimeoutS`` on the worker — docs/FLEET.md)
appear with their defaults and round-trip through
``runtime/config.py`` (the config-key-sync lint rule keeps consumer
code honest against those fields).  ``--elastic`` flips the emitted
worker config to ``FleetRegister: true``, the shape an elastic worker
boots from (``--listen 127.0.0.1:0`` then works: the worker registers
its real bound port with the coordinator instead of needing a
pre-agreed one).

``--coordinators N`` (docs/CLUSTER.md) emits an N-member coordinator
POOL: shard 0 keeps ``coordinator_config.json`` (back-compat) and
shard ``i>0`` lands in ``coordinator{i}_config.json``; every member
carries the full ``ClusterPeers`` ring-seed list (all client-facing
addresses, shard order), its own ``ClusterSelf`` index, its own
listen ports, and the SAME shared ``Workers`` list.  The client
config gains ``CoordAddrs`` (the same seed list — powlib cluster
mode) while ``CoordAddr`` still points at shard 0 for pre-cluster
tools; the worker config's ``CoordAddr`` points at shard 0's worker
API (pooled rounds stamp their own reply-to, so the default only
matters for which coordinator a static worker appears under).
"""

from __future__ import annotations

import argparse
import os
import random

from ..runtime.config import (
    ClientConfig,
    CoordinatorConfig,
    TracingServerConfig,
    WorkerConfig,
    read_json_config,
    write_json_config,
)


def gen_port(rng: random.Random) -> int:
    return rng.randrange(1024, 35535)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="randomize distpow config ports")
    ap.add_argument("--config-dir", default="config")
    ap.add_argument("--host", default="127.0.0.1",
                    help="host part written into addresses ('' for bare :port)")
    ap.add_argument("--workers", type=int, default=0,
                    help="override worker count (default: keep existing)")
    ap.add_argument("--elastic", action="store_true",
                    help="emit the worker config with FleetRegister=true "
                         "(lease-based membership, docs/FLEET.md)")
    ap.add_argument("--coordinators", type=int, default=1,
                    help="coordinator pool size (docs/CLUSTER.md): >1 "
                         "emits per-shard coordinator configs with ring "
                         "seeds and flips the client to CoordAddrs")
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)
    rng = random.Random(args.seed)

    def addr() -> str:
        return f"{args.host}:{gen_port(rng)}"

    d = args.config_dir
    os.makedirs(d, exist_ok=True)

    def load(name, cls):
        path = os.path.join(d, name)
        return read_json_config(path, cls) if os.path.exists(path) else cls()

    n_coords = max(1, int(args.coordinators))
    tracer_addr = addr()
    client_addrs = [addr() for _ in range(n_coords)]
    worker_api_addrs = [addr() for _ in range(n_coords)]
    coord_client_addr = client_addrs[0]
    coord_worker_addr = worker_api_addrs[0]

    ts = load("tracing_server_config.json", TracingServerConfig)
    ts.ServerBind = tracer_addr
    write_json_config(os.path.join(d, "tracing_server_config.json"), ts)

    def coord_path(i: int) -> str:
        # shard 0 keeps the historical name so pre-cluster tooling
        # (cli.coordinator default --config, the reference scripts)
        # still finds a coordinator
        return os.path.join(
            d, "coordinator_config.json" if i == 0
            else f"coordinator{i}_config.json")

    coord = load("coordinator_config.json", CoordinatorConfig)
    n = args.workers or len(coord.Workers) or 4
    shared_workers = [addr() for _ in range(n)]
    for i in range(n_coords):
        c = load("coordinator_config.json", CoordinatorConfig) \
            if i else coord
        c.Workers = list(shared_workers)  # ONE fleet, shared by the pool
        c.TracerServerAddr = tracer_addr
        c.ClientAPIListenAddr = client_addrs[i]
        c.WorkerAPIListenAddr = worker_api_addrs[i]
        if n_coords > 1:
            c.ClusterPeers = list(client_addrs)
            c.ClusterSelf = i
            if i and c.CacheFile:
                # per-process paths: two shards appending one cache
                # journal (and deriving one restart epoch) would
                # corrupt both — suffix everything i>0 inherits
                c.CacheFile = f"{c.CacheFile}.c{i}"
            if i and c.TelemetryDir:
                c.TelemetryDir = os.path.join(c.TelemetryDir, f"c{i}")
        else:
            c.ClusterPeers = []
            c.ClusterSelf = -1
        write_json_config(coord_path(i), c)

    for name in ("client_config.json", "client2_config.json"):
        c = load(name, ClientConfig)
        if name == "client2_config.json" and c.ClientID == "client1":
            c.ClientID = "client2"
        c.TracerServerAddr = tracer_addr
        c.CoordAddr = coord_client_addr
        c.CoordAddrs = list(client_addrs) if n_coords > 1 else []
        write_json_config(os.path.join(d, name), c)

    w = load("worker_config.json", WorkerConfig)
    w.TracerServerAddr = tracer_addr
    w.CoordAddr = coord_worker_addr
    w.ListenAddr = "PASS VIA COMMAND-LINE"
    if args.elastic:
        w.FleetRegister = True
    write_json_config(os.path.join(d, "worker_config.json"), w)

    pool = (f" pool={n_coords} coordinators, ring seeds {client_addrs}"
            if n_coords > 1 else "")
    print(f"wrote configs to {d}: tracer={tracer_addr} "
          f"coordinator client={coord_client_addr} worker={coord_worker_addr} "
          f"workers={shared_workers}{pool} "
          f"(fleet: lease ttl {coord.FleetLeaseTTLS}s, hedge "
          f"{'on' if coord.FleetHedge else 'off'}, elastic worker "
          f"{'yes' if w.FleetRegister else 'no'})")


if __name__ == "__main__":
    main()
