"""Config-gen utility (cmd/config-gen/main.go equivalent).

Rewrites the ``config/*.json`` set with pseudo-random local ports
(1024-35534) while keeping cross-references consistent: the tracing server
address lands in every node config, the coordinator's client/worker listen
addresses land in the client/worker configs, and each coordinator worker
slot gets a fresh port.  Keeps the worker list length from the existing
coordinator config (cmd/config-gen/main.go:51-88).

    python -m distpow_tpu.cli.config_gen [--config-dir DIR] [--host HOST]
        [--workers N] [--elastic]

Emitted configs carry the full dataclass field set, so the fleet
membership knobs (``FleetLeaseTTLS`` / ``FleetHedge`` /
``FleetHedgeMultiple`` / ``FleetDrainTimeoutS`` on the coordinator;
``FleetRegister`` / ``FleetHeartbeatS`` / ``FleetCalibrationS`` /
``FleetMHS`` / ``FleetDrainTimeoutS`` on the worker — docs/FLEET.md)
appear with their defaults and round-trip through
``runtime/config.py`` (the config-key-sync lint rule keeps consumer
code honest against those fields).  ``--elastic`` flips the emitted
worker config to ``FleetRegister: true``, the shape an elastic worker
boots from (``--listen 127.0.0.1:0`` then works: the worker registers
its real bound port with the coordinator instead of needing a
pre-agreed one).
"""

from __future__ import annotations

import argparse
import os
import random

from ..runtime.config import (
    ClientConfig,
    CoordinatorConfig,
    TracingServerConfig,
    WorkerConfig,
    read_json_config,
    write_json_config,
)


def gen_port(rng: random.Random) -> int:
    return rng.randrange(1024, 35535)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="randomize distpow config ports")
    ap.add_argument("--config-dir", default="config")
    ap.add_argument("--host", default="127.0.0.1",
                    help="host part written into addresses ('' for bare :port)")
    ap.add_argument("--workers", type=int, default=0,
                    help="override worker count (default: keep existing)")
    ap.add_argument("--elastic", action="store_true",
                    help="emit the worker config with FleetRegister=true "
                         "(lease-based membership, docs/FLEET.md)")
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)
    rng = random.Random(args.seed)

    def addr() -> str:
        return f"{args.host}:{gen_port(rng)}"

    d = args.config_dir
    os.makedirs(d, exist_ok=True)

    def load(name, cls):
        path = os.path.join(d, name)
        return read_json_config(path, cls) if os.path.exists(path) else cls()

    tracer_addr = addr()
    coord_client_addr = addr()
    coord_worker_addr = addr()

    ts = load("tracing_server_config.json", TracingServerConfig)
    ts.ServerBind = tracer_addr
    write_json_config(os.path.join(d, "tracing_server_config.json"), ts)

    coord = load("coordinator_config.json", CoordinatorConfig)
    n = args.workers or len(coord.Workers) or 4
    coord.Workers = [addr() for _ in range(n)]
    coord.TracerServerAddr = tracer_addr
    coord.ClientAPIListenAddr = coord_client_addr
    coord.WorkerAPIListenAddr = coord_worker_addr
    write_json_config(os.path.join(d, "coordinator_config.json"), coord)

    for name in ("client_config.json", "client2_config.json"):
        c = load(name, ClientConfig)
        if name == "client2_config.json" and c.ClientID == "client1":
            c.ClientID = "client2"
        c.TracerServerAddr = tracer_addr
        c.CoordAddr = coord_client_addr
        write_json_config(os.path.join(d, name), c)

    w = load("worker_config.json", WorkerConfig)
    w.TracerServerAddr = tracer_addr
    w.CoordAddr = coord_worker_addr
    w.ListenAddr = "PASS VIA COMMAND-LINE"
    if args.elastic:
        w.FleetRegister = True
    write_json_config(os.path.join(d, "worker_config.json"), w)

    print(f"wrote configs to {d}: tracer={tracer_addr} "
          f"coordinator client={coord_client_addr} worker={coord_worker_addr} "
          f"workers={coord.Workers} "
          f"(fleet: lease ttl {coord.FleetLeaseTTLS}s, hedge "
          f"{'on' if coord.FleetHedge else 'off'}, elastic worker "
          f"{'yes' if w.FleetRegister else 'no'})")


if __name__ == "__main__":
    main()
