"""Request forensics inspector (ISSUE 14; docs/FORENSICS.md).

    python -m distpow_tpu.cli.forensics --addr A [--addr B ...]
        [--trace TRACE_ID] [--deadline SECS] [--json]
    python -m distpow_tpu.cli.forensics --discover COORD_ADDR
        [--trace TRACE_ID] [--deadline SECS] [--json]

Fetches the span rings of every listed fleet member concurrently
(``Node.Spans``, one shared ``--deadline`` — an unreachable node is
reported, never waited for), stitches the cross-node timeline for one
trace id, and prints it with the slowness verdicts: the slowest
segment overall and the slowest *shard-attributed* segment ("here is
the shard that made this Mine slow").

``--discover COORD_ADDR`` pulls the scrape list from the coordinator's
live membership table (``Fleet.Members``, docs/FLEET.md) exactly like
``stats --cluster --discover``, so an elastic fleet is swept without a
hand-maintained address list; extra ``--addr`` flags merge in.

Without ``--trace``, a summaries sweep runs first and the SLOWEST
recent trace across the fleet is chosen — "show me the worst request
you remember" with no id in hand.  Trace ids come from anywhere the
tracing plane surfaces them: a client's ``MineResult`` token, histogram
exemplars (``stats --prom --openmetrics``), a ``forensics.slow_request``
flight-recorder capture, or an SLO breach dump's ``slow_requests``.

``--json`` prints the stitched timeline as machine-readable JSON —
the same shape ``scripts/trace_profile.py`` accepts as its span-ring
input format, so offline and live forensics share one renderer.

Exit codes: 0 — timeline stitched; 1 — no spans found for the trace
(or no node answered); 2 — usage error.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch one request's cross-node span timeline"
    )
    ap.add_argument("--addr", action="append", default=None,
                    help="node RPC address host:port (repeatable; each "
                         "flag may hold a comma list)")
    ap.add_argument("--discover", metavar="COORD_ADDR", action="append",
                    default=None,
                    help="pull the sweep list from the coordinators' "
                         "live membership tables (Fleet.Members, "
                         "dedup-merged across the pool — one member of "
                         "a sharded pool names the rest via the ring; "
                         "docs/CLUSTER.md); repeatable, comma lists ok")
    ap.add_argument("--trace", type=int, default=None,
                    help="trace id to stitch; omitted = the slowest "
                         "recent trace any swept node remembers")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="shared sweep deadline in seconds")
    ap.add_argument("--limit", type=int, default=512,
                    help="max spans fetched per node")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable stitched timeline on stdout")
    args = ap.parse_args(argv)

    from ..obs.forensics import (
        fetch_spans,
        render_timeline,
        slowest_trace_id,
        stitch_timeline,
    )
    from ..runtime.rpc import RPCError

    addrs = [a for flag in (args.addr or []) for a in flag.split(",") if a]
    if args.discover:
        from .stats import discover_cluster_addrs

        try:
            discovered = discover_cluster_addrs(args.discover,
                                                timeout=args.deadline)
        except (OSError, RPCError, RuntimeError) as exc:
            print(f"error: membership discovery against "
                  f"{','.join(args.discover)} failed: {exc}",
                  file=sys.stderr)
            return 1
        addrs = discovered + [a for a in addrs if a not in discovered]
    if not addrs:
        ap.error("--addr (or --discover) is required")

    trace_id = args.trace
    if trace_id is None:
        summaries = fetch_spans(addrs, trace_id=None,
                                deadline_s=args.deadline,
                                limit=args.limit)
        trace_id = slowest_trace_id(summaries)
        if trace_id is None:
            print("error: no node remembers any trace (span rings "
                  "empty, or no node answered)", file=sys.stderr)
            return 1
        print(f"# --trace omitted: stitching the slowest recent trace "
              f"{trace_id}", file=sys.stderr)

    fetched = fetch_spans(addrs, trace_id=trace_id,
                          deadline_s=args.deadline, limit=args.limit)
    timeline = stitch_timeline(fetched, trace_id)
    if args.as_json:
        print(json.dumps(timeline, indent=2))
    else:
        print(render_timeline(timeline))
    return 0 if timeline["spans"] else 1


if __name__ == "__main__":
    sys.exit(main())
