"""Cluster SLO gate (distpow_tpu/obs/slo.py; docs/SLO.md).

    python -m distpow_tpu.cli.slo --config config/slo.json \
        --addr COORD [--addr WORKER ...] [--deadline SECS] \
        [--interval SECS --count N] [--json]

Scrapes every ``--addr`` node's Stats concurrently (one shared
deadline; frozen nodes go ``stale``, the verdict still renders), merges
the snapshots, and evaluates the declarative SLO config.  Exit code is
the CI contract:

* ``0`` — every objective passed (warns included: a warn is a page-
  worthy signal, not a gate failure);
* ``1`` — at least one objective BREACHED (the breach also lands as an
  ``slo.breach`` flight-recorder event, plus a ring dump with the
  trace_profile critical path when a telemetry dir is configured);
* ``2`` — config error (malformed JSON, unknown metric name): the gate
  refuses to evaluate rather than pass vacuously.

``--interval``/``--count`` run repeated sweeps feeding the burn-rate
windows (one-shot runs degrade both windows to cumulative —
docs/SLO.md); the final evaluation's exit code is returned.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..obs.scrape import FleetScraper, NodeTarget
from ..obs.slo import SLOConfigError, SLOEngine, load_slo_config


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="evaluate cluster SLOs over merged node metrics")
    ap.add_argument("--config", required=True,
                    help="SLO config JSON (see config/slo.json)")
    ap.add_argument("--addr", action="append", default=None,
                    help="node RPC address (repeatable; comma lists ok)")
    ap.add_argument("--discover", metavar="COORD_ADDR", action="append",
                    default=None,
                    help="pull the sweep list from the coordinators' "
                         "live membership tables (Fleet.Members, "
                         "dedup-merged across the pool — one member of "
                         "a sharded pool names the rest via the ring; "
                         "docs/CLUSTER.md); repeatable, comma lists ok. "
                         "Extra --addr flags merge in.")
    ap.add_argument("--role", choices=["auto", "coordinator", "worker"],
                    default="auto")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="shared sweep deadline (seconds)")
    ap.add_argument("--interval", type=float, default=None,
                    help="sweep every SECS, feeding the burn-rate windows")
    ap.add_argument("--count", type=int, default=0,
                    help="with --interval: evaluate after N sweeps "
                         "(default 3)")
    ap.add_argument("--json", action="store_true",
                    help="print the typed verdict as JSON")
    args = ap.parse_args(argv)
    addrs = [a for flag in (args.addr or []) for a in flag.split(",") if a]
    if args.interval is not None and args.interval <= 0:
        ap.error("--interval SECS must be positive")
    if args.discover:
        from ..runtime.rpc import RPCError
        from .stats import discover_cluster_addrs

        try:
            discovered = discover_cluster_addrs(args.discover,
                                                timeout=args.deadline)
        except (OSError, RPCError, RuntimeError) as exc:
            print(f"error: membership discovery against "
                  f"{','.join(args.discover)} failed: {exc}",
                  file=sys.stderr)
            return 2
        addrs = discovered + [a for a in addrs if a not in discovered]
    if not addrs:
        ap.error("--addr (or --discover) is required")

    try:
        config = load_slo_config(args.config)
    except SLOConfigError as exc:
        print(f"slo config error: {exc}", file=sys.stderr)
        return 2

    # span_addrs: on breach the engine sweeps these nodes' Node.Spans
    # for the slow-request timelines — this gate process has no local
    # span ring of its own (docs/FORENSICS.md)
    engine = SLOEngine(config, span_addrs=addrs)
    scraper = FleetScraper(
        [NodeTarget(addr=a, role=args.role) for a in addrs],
        deadline_s=args.deadline,
    )
    try:
        sweeps = max(1, args.count or 3) if args.interval else 1
        for i in range(sweeps):
            if i:
                time.sleep(args.interval)
            engine.observe(scraper.sweep())
        verdict = engine.evaluate()
    except (OSError, RuntimeError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    finally:
        scraper.close()
    print(json.dumps(verdict.to_dict(), indent=2) if args.json
          else verdict.render(), flush=True)
    return verdict.exit_code()


if __name__ == "__main__":
    sys.exit(main())
