"""Coordinator entry point (cmd/coordinator/main.go equivalent).

    python -m distpow_tpu.cli.coordinator [--config PATH]
"""

from __future__ import annotations

import argparse
import logging

from ..nodes.coordinator import Coordinator
from ..runtime.config import CoordinatorConfig, read_json_config


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow coordinator")
    ap.add_argument("--config", default="config/coordinator_config.json")
    args = ap.parse_args(argv)

    config = read_json_config(args.config, CoordinatorConfig)
    logging.info("coordinator config: %s", config)
    Coordinator(config).run_forever()


if __name__ == "__main__":
    main()
