"""Coordinator entry point (cmd/coordinator/main.go equivalent).

    python -m distpow_tpu.cli.coordinator [--config PATH] [--faults PLAN]

``--faults`` (or ``FaultPlanFile`` in the config, or ``$DISTPOW_FAULTS``)
installs a deterministic fault-injection plan — see docs/FAULTS.md.
"""

from __future__ import annotations

import argparse
import logging

from ..nodes.coordinator import Coordinator
from ..runtime import faults
from ..runtime.config import CoordinatorConfig, read_json_config


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow coordinator")
    ap.add_argument("--config", default="config/coordinator_config.json")
    ap.add_argument("--faults", default=None,
                    help="fault-injection plan: JSON file path or inline "
                         "JSON (chaos testing; docs/FAULTS.md)")
    args = ap.parse_args(argv)

    config = read_json_config(args.config, CoordinatorConfig)
    plan_spec = args.faults or config.FaultPlanFile
    if plan_spec:
        faults.install_from_spec(plan_spec)
    logging.info("coordinator config: %s", config)
    Coordinator(config).run_forever()


if __name__ == "__main__":
    main()
