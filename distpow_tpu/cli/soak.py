"""Long-haul soak gate (distpow_tpu/load/soak.py; docs/SOAK.md).

    python -m distpow_tpu.cli.soak --config config/slo.json \
        [--minutes 2] [--compress 320] [--base-hz 6 --amplitude-hz 4] \
        [--spike-hz 20 --spike-frac 0.6 --spike-width-frac 0.1] \
        [--chaos] [--spool PATH] [--json]
    python -m distpow_tpu.cli.soak --config ... --addr COORD [...]

Default mode boots an in-process cluster (CPU python-backend workers)
and replays a COMPRESSED diurnal-plus-flash-crowd shape against it —
the canonical soak: ``--minutes`` of wall clock standing in for one
``--compress``-times-longer "day".  ``--addr``/``--discover`` instead
attaches to already-running node processes: the FIRST address must be
a coordinator client-API address (it takes the mine traffic and the
judged scrape; the soak sweeps only that node's Stats — merged
registries of separate processes are per-node, so one coordinator's
snapshot is the conservative judged view unless you front it with the
pool's own merge via --discover ordering).

Exit code contract (the SLO CLI's, extended):

* ``0`` — green: every shape phase held the SLO, zero leak suspects,
  ring drops and generator lag within budget (warn-only phases stay 0);
* ``1`` — the soak verdict failed any of those;
* ``2`` — config error (malformed/unknown-metric SLO JSON, bad shape
  parameters): refuses to run rather than pass vacuously.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..load.loadgen import LoadMix
from ..load.shapes import Diurnal, FlashCrowd, Sum, compress
from ..load.soak import run_soak
from ..obs.scrape import NodeTarget
from ..obs.slo import SLOConfigError

#: seeded server-side delay chaos on the worker Mine path — enough to
#: shake the retry/hedge machinery without sinking a green run
CHAOS_SPEC = {"seed": 905, "rules": [
    {"kind": "delay", "side": "server",
     "method": "WorkerRPCHandler.Mine", "delay_s": 0.05, "prob": 0.2},
    {"kind": "drop", "side": "server",
     "method": "WorkerRPCHandler.Mine", "prob": 0.02, "max": 5},
]}


class AttachedCluster:
    """Duck-typed stand-in for ``InProcCluster`` over real processes:
    a powlib client bound to the first address, scrape targets over
    all of them."""

    def __init__(self, addrs, role: str, deadline_s: float):
        from ..nodes import Client
        from ..runtime.config import ClientConfig

        self._targets = [NodeTarget(addr=a, role=role) for a in addrs]
        self.client = Client(ClientConfig(
            ClientID="soak", CoordAddr=addrs[0],
            CoordAddrs=list(addrs) if len(addrs) > 1 else [],
            ChCapacity=100_000,
        ))
        self.client.initialize()

    def scrape_targets(self, include_workers: bool = False):
        return list(self._targets)

    def close(self) -> None:
        self.client.close()


def build_shape(args):
    """The canonical soak shape from CLI knobs: one diurnal "day" of
    ``minutes * compress`` uncompressed seconds plus a flash crowd at
    ``spike_frac`` of the day, all compressed back into ``minutes`` of
    wall clock."""
    day_s = args.minutes * 60.0 * args.compress
    parts = [Diurnal(base=args.base_hz / args.compress,
                     amplitude=args.amplitude_hz / args.compress,
                     period_s=day_s)]
    if args.spike_hz > 0:
        parts.append(FlashCrowd(
            extra_hz=args.spike_hz / args.compress,
            at_s=day_s * args.spike_frac,
            width_s=day_s * args.spike_width_frac,
            duration_s=day_s,
        ))
    shape = Sum(parts=tuple(parts)) if len(parts) > 1 else parts[0]
    return compress(shape, args.compress)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay a shaped soak and judge the typed verdict")
    ap.add_argument("--config", required=True,
                    help="SLO config JSON (see config/slo.json)")
    ap.add_argument("--addr", action="append", default=None,
                    help="attach to running nodes (first = coordinator "
                         "client API; repeatable, comma lists ok)")
    ap.add_argument("--discover", metavar="COORD_ADDR", action="append",
                    default=None,
                    help="pull the node list from the coordinators' "
                         "live membership tables (docs/CLUSTER.md)")
    ap.add_argument("--role", choices=["auto", "coordinator", "worker"],
                    default="auto")
    ap.add_argument("--workers", type=int, default=2,
                    help="in-process cluster size (ignored with --addr)")
    ap.add_argument("--minutes", type=float, default=1.5,
                    help="wall-clock soak length")
    ap.add_argument("--compress", type=float, default=320.0,
                    help="wall-clock compression factor (docs/SOAK.md)")
    ap.add_argument("--base-hz", type=float, default=6.0,
                    help="diurnal base rate (compressed, requests/s)")
    ap.add_argument("--amplitude-hz", type=float, default=4.0,
                    help="diurnal swing (compressed, requests/s)")
    ap.add_argument("--spike-hz", type=float, default=18.0,
                    help="flash-crowd extra rate (compressed; 0 = off)")
    ap.add_argument("--spike-frac", type=float, default=0.55,
                    help="where in the day the flash crowd lands (0..1)")
    ap.add_argument("--spike-width-frac", type=float, default=0.08,
                    help="flash-crowd width as a fraction of the day")
    ap.add_argument("--seed", type=int, default=1805)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="fleet sweep cadence (seconds)")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="shared sweep deadline (seconds)")
    ap.add_argument("--chaos", action="store_true",
                    help="install the canned PR 1 fault plan for the run")
    ap.add_argument("--spool", default=None,
                    help="append sweeps to this JSONL spool (rotated; "
                         "replayable via obs.timeseries.replay_spool)")
    ap.add_argument("--lag-budget", type=float, default=1.0,
                    help="generator lag p99 budget (seconds)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    args = ap.parse_args(argv)

    addrs = [a for flag in (args.addr or []) for a in flag.split(",") if a]
    if args.discover:
        from ..runtime.rpc import RPCError
        from .stats import discover_cluster_addrs

        try:
            discovered = discover_cluster_addrs(args.discover,
                                                timeout=args.deadline)
        except (OSError, RPCError, RuntimeError) as exc:
            print(f"error: membership discovery against "
                  f"{','.join(args.discover)} failed: {exc}",
                  file=sys.stderr)
            return 2
        addrs = discovered + [a for a in addrs if a not in discovered]

    if args.minutes <= 0 or args.compress <= 0:
        print("error: --minutes and --compress must be positive",
              file=sys.stderr)
        return 2
    try:
        shape = build_shape(args)
    except ValueError as exc:
        print(f"shape error: {exc}", file=sys.stderr)
        return 2
    mix = LoadMix(rate_hz=1.0, duration_s=1.0,  # placeholders: shape rules
                  seed=args.seed, n_keys=24, zipf_s=1.1,
                  difficulties=((1, 0.7), (2, 0.3)))

    cluster = None
    try:
        if addrs:
            cluster = AttachedCluster(addrs, args.role, args.deadline)
        try:
            report, verdict = run_soak(
                shape, mix, args.config,
                cluster=cluster, n_workers=args.workers,
                scrape_interval_s=args.interval,
                scrape_deadline_s=args.deadline,
                fault_spec=CHAOS_SPEC if args.chaos else None,
                spool_path=args.spool,
                lag_budget_s=args.lag_budget,
            )
        except SLOConfigError as exc:
            print(f"slo config error: {exc}", file=sys.stderr)
            return 2
        except (OSError, RuntimeError, ValueError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
    finally:
        if cluster is not None:
            cluster.close()
    print(json.dumps(report, indent=2) if args.json
          else verdict.render(), flush=True)
    return verdict.exit_code()


if __name__ == "__main__":
    sys.exit(main())
