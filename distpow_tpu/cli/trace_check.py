"""Trace-log validator entry point (no reference equivalent — the
reference's trace invariants were inspected by hand/ShiViz; SURVEY.md
section 4 makes them this framework's executable acceptance test).

    python -m distpow_tpu.cli.trace_check trace_output.log [shiviz_output.log]

Exits 0 when every ordering invariant holds, 1 otherwise (violations are
printed one per line).
"""

from __future__ import annotations

import argparse
import sys

from ..runtime.trace_check import check_shiviz_log, check_trace_log


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="validate distpow trace logs")
    ap.add_argument("trace_log", help="human trace log (trace_output.log)")
    ap.add_argument("shiviz_log", nargs="?",
                    help="optional ShiViz vector-clock log")
    args = ap.parse_args(argv)

    violations = check_trace_log(args.trace_log)
    if args.shiviz_log:
        violations += check_shiviz_log(args.shiviz_log)
    for v in violations:
        print(f"VIOLATION: {v}")
    print(f"{len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
