"""Tracing-server entry point (cmd/tracing-server/main.go equivalent).

    python -m distpow_tpu.cli.tracing_server [--config PATH]
"""

from __future__ import annotations

import argparse
import logging

from ..runtime.config import TracingServerConfig, read_json_config
from ..runtime.trace_server import TracingServer


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(description="distpow tracing server")
    ap.add_argument("--config", default="config/tracing_server_config.json")
    args = ap.parse_args(argv)

    server = TracingServer(read_json_config(args.config, TracingServerConfig))
    addr = server.open()
    logging.info("tracing server listening on %s", addr)
    server.accept_forever()


if __name__ == "__main__":
    main()
