"""Node metrics inspector (no reference equivalent — SURVEY.md section 5
lists metrics as absent in the reference).

    python -m distpow_tpu.cli.stats --addr HOST:PORT
        [--role auto|coordinator|worker] [--prom] [--watch SECS [--count N]]
    python -m distpow_tpu.cli.stats --cluster --addr A [--addr B ...]
        [--deadline SECS] [--prom]
    python -m distpow_tpu.cli.stats --cluster --discover COORD_ADDR
        [--deadline SECS] [--prom]

Dials the node's RPC port, calls its ``Stats`` method, and prints the
JSON snapshot.  ``--role auto`` (default) tries the role-agnostic
``Node.Stats`` alias first (every current node answers it without
minting a handler error), falling back to the coordinator's then the
worker's service name for pre-alias nodes.  For a coordinator, use the
CLIENT-facing listen address.

``--cluster`` accepts MULTIPLE ``--addr`` flags (each may also be a
comma-separated list), polls every node's Stats concurrently under one
shared ``--deadline``, and prints the bucket-wise MERGED cluster
snapshot (distpow_tpu/obs/, docs/SLO.md): summed counters/gauges,
merged histograms with cluster percentiles, per-node status — a node
that fails to answer in time is reported ``stale`` with its last-seen
age, never waited for.  With ``--prom`` the merged series are emitted
cluster-labelled (``distpow_node_info{node=...}`` /
``distpow_node_stale{node=...}`` per node rides alongside).

``--discover COORD_ADDR`` replaces the hand-maintained ``--addr`` list
with the coordinator's LIVE membership table (``Fleet.Members``,
docs/FLEET.md): the sweep covers the coordinator plus every current
member — static and lease-registered alike — so an elastic fleet is
tracked automatically as workers join, drain and expire.  Extra
``--addr`` flags still merge in (e.g. a node outside this
coordinator's fleet).

``--prom`` renders the snapshot as Prometheus text exposition (version
0.0.4): counters/gauges become ``distpow_<name>`` samples and every
histogram becomes a full ``_bucket{le=...}/_sum/_count`` family built
from the registry's log buckets — point any Prometheus scrape job at a
thin exporter wrapping this, or eyeball percentile movement directly.
``--prom --openmetrics`` upgrades to OpenMetrics: buckets carry their
retained ``{trace_id=...}`` exemplars (the forensics plane's pointer
from "p99 moved" to the one request that landed there —
docs/FORENSICS.md) and the exposition closes with ``# EOF``.
``--watch SECS`` re-fetches every SECS seconds and prints counter
deltas plus live histogram quantiles (``--count N`` bounds the
refreshes; default unbounded, Ctrl-C exits).  docs/METRICS.md is the
registry catalog.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout

from ..runtime.rpc import RPCClient, RPCError


def fetch_stats(addr: str, role: str = "auto", timeout: float = 5.0) -> dict:
    # ONE role->service table for every observability consumer: the
    # fleet scraper owns it (obs/scrape.py _SERVICES — auto tries the
    # role-agnostic Node.Stats alias first); duplicating it here is how
    # the CLI and the scraper would drift apart
    from ..obs.scrape import _SERVICES

    services = _SERVICES[role]
    # pinned to the JSON floor codec: this diagnostic dials a FRESH
    # connection per fetch (watch mode rides out restarts that way), and
    # a per-poll rpc.hello would tick the observed node's negotiation
    # counters — the watcher must not perturb the counters it watches
    client = RPCClient(addr, timeout=timeout, codec="json")
    try:
        last: Exception = RuntimeError("no services tried")
        for method in services:
            try:
                return client.call(method, {}, timeout=timeout)
            except (RPCError, FutureTimeout) as exc:
                # FutureTimeout is only an OSError alias on 3.11+
                last = exc
        raise last
    finally:
        client.close()


def discover_cluster_addrs(coord_addrs, timeout: float = 5.0) -> list:
    """Coordinator membership -> scrape address list (``Fleet.Members``
    dedup-merged across the POOL; docs/FLEET.md, docs/CLUSTER.md).

    ``coord_addrs``: one address (the historical shape) or a list of
    coordinator addresses.  The pool is expanded first: any member's
    Stats snapshot names the whole ring (the coordinator ``cluster``
    key), so ONE seed suffices to cover a sharded pool — and the probe
    rides the error-free ``Node.Stats`` path, never minting
    ``rpc.handler_errors`` on the node being observed (the
    watcher-perturbation class docs/SLO.md documents).  Every reachable
    pool coordinator's ``Fleet.Members`` is then merged with
    de-duplication, so the sweep covers all coordinators plus every
    current member — static and lease-registered alike — across all
    shards.  Draining members are still scraped (they serve until their
    lease releases); expired ones are already gone from the tables.
    Raises only when NO coordinator answered; a partially-dead pool
    still yields the survivors' view.
    """
    seeds = ([coord_addrs] if isinstance(coord_addrs, str)
             else list(coord_addrs))
    coords: list = []
    for flag in seeds:
        for a in flag.split(","):
            if a and a not in coords:
                coords.append(a)
    # pool expansion via the ring advertised in Stats snapshots —
    # probed CONCURRENTLY under one shared deadline (the FleetScraper
    # discipline): a frozen pool member must cost the sweep at most
    # one timeout total, not one per serial probe (review PR 10)
    expansion = _concurrent_probe(
        coords, lambda a: fetch_stats(a, timeout=timeout), timeout)
    for a in list(coords):
        snap = expansion.get(a)
        if not isinstance(snap, dict):
            continue
        ring = (snap.get("cluster") or {}).get("ring") or {}
        for _member, addr in ring.get("members") or []:
            if addr and addr not in coords:
                coords.append(addr)
    addrs = list(coords)

    def members_of(coord: str) -> dict:
        client = RPCClient(coord, timeout=timeout, codec="json")
        try:
            return client.call("Fleet.Members", {}, timeout=timeout)
        finally:
            client.close()

    tables = _concurrent_probe(coords, members_of, timeout)
    reached = 0
    last_exc: Exception = RuntimeError("no coordinator addresses given")
    for coord in coords:
        table = tables.get(coord)
        if not isinstance(table, dict):
            if isinstance(table, Exception):
                last_exc = table
            elif table is None and coords:
                last_exc = RuntimeError(
                    f"{coord} missed the {timeout}s discovery deadline")
            continue
        reached += 1
        for m in table.get("workers") or []:
            a = m.get("addr")
            if a and a not in addrs:
                addrs.append(a)
    if not reached:
        raise last_exc
    return addrs


def _concurrent_probe(addrs, fn, deadline_s: float) -> dict:
    """Run ``fn(addr)`` for every address on its own thread and join
    them all under ONE shared deadline — addr -> result dict, with
    exceptions held as values and deadline-missers absent.  Threads
    are daemons, so an abandoned slow probe cannot pin the CLI."""
    results: dict = {}

    def one(a):
        try:
            results[a] = fn(a)
        except Exception as exc:
            results[a] = exc

    threads = [threading.Thread(target=one, args=(a,), daemon=True)
               for a in addrs]
    for t in threads:
        t.start()
    deadline = time.monotonic() + deadline_s
    for t in threads:
        t.join(timeout=max(0.05, deadline - time.monotonic()))
    return dict(results)


def _prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name (dots and any other
    non-identifier characters become underscores)."""
    safe = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if safe and safe[0].isdigit():
        safe = "_" + safe
    return f"distpow_{safe}"


def _prom_num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snap: dict, openmetrics: bool = False) -> str:
    """Snapshot -> Prometheus text exposition (0.0.4).

    Histograms are re-emitted cumulatively from the snapshot's
    non-cumulative log buckets (runtime/metrics.py Histogram.to_dict),
    closed by the mandatory ``+Inf`` bucket equal to ``_count``.

    ``openmetrics=True`` (``--prom --openmetrics``) upgrades the output
    to OpenMetrics: each bucket that retains an exemplar appends the
    ``# {trace_id="..."} value ts`` clause (docs/FORENSICS.md — the
    pointer from a bucket to the one request that last landed there),
    and the exposition is closed by the mandatory ``# EOF``.
    """
    out = []
    role = snap.get("role", "unknown")
    out.append("# HELP distpow_node_info node role marker (value is 1)")
    out.append("# TYPE distpow_node_info gauge")
    out.append(f'distpow_node_info{{role="{role}"}} 1')
    if "uptime_secs" in snap:
        out.append("# TYPE distpow_uptime_seconds gauge")
        out.append(f"distpow_uptime_seconds {_prom_num(snap['uptime_secs'])}")
    for name, v in sorted((snap.get("counters") or {}).items()):
        pname = _prom_name(name) + "_total"
        out.append(f"# TYPE {pname} counter")
        out.append(f"{pname} {_prom_num(v)}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        pname = _prom_name(name)
        out.append(f"# TYPE {pname} gauge")
        out.append(f"{pname} {_prom_num(v)}")
    for name, h in sorted((snap.get("histograms") or {}).items()):
        pname = _prom_name(name)
        out.append(f"# TYPE {pname} histogram")
        exemplars = {}
        if openmetrics:
            exemplars = {_prom_num(b): (tid, v, ts)
                         for b, tid, v, ts in h.get("exemplars", [])}
        cum = 0
        for le, count in h.get("buckets", []):
            cum += count
            line = f'{pname}_bucket{{le="{_prom_num(le)}"}} {cum}'
            ex = exemplars.get(_prom_num(le))
            if ex is not None:
                tid, v, ts = ex
                line += (f' # {{trace_id="{tid}"}} {_prom_num(v)} '
                         f"{_prom_num(ts)}")
            out.append(line)
        out.append(f'{pname}_bucket{{le="+Inf"}} {h["count"]}')
        out.append(f"{pname}_sum {_prom_num(h.get('sum', 0))}")
        out.append(f"{pname}_count {h['count']}")
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def render_cluster_prometheus(cluster: dict, openmetrics: bool = False) -> str:
    """Merged cluster snapshot -> Prometheus text exposition.

    The merged counters/gauges/histograms render through the same
    single-node path (they share its snapshot shape) under
    ``role="cluster"``; per-node membership, staleness, and last-seen
    age ride as labelled gauges so one scrape shows both the cluster
    view and which nodes it is missing.  ``openmetrics`` appends the
    merged exemplars to the bucket lines (render_prometheus) — the
    ``# EOF`` terminator is re-seated after the per-node block so the
    exposition stays well-formed."""
    body = render_prometheus(dict(cluster, role="cluster"),
                             openmetrics=openmetrics)
    if openmetrics:
        body = body.rstrip("\n").rsplit("\n# EOF", 1)[0] + "\n"
    out = [body.rstrip("\n")]
    per_node = cluster.get("per_node") or {}
    if per_node:
        out.append("# HELP distpow_node_stale node missed the sweep "
                   "deadline (1) or answered (0)")
        out.append("# TYPE distpow_node_stale gauge")
        for name, meta in sorted(per_node.items()):
            role = meta.get("role", "unknown")
            out.append(
                f'distpow_node_info{{role="{role}",node="{name}"}} 1')
            stale = 1 if meta.get("status") == "stale" else 0
            out.append(f'distpow_node_stale{{node="{name}"}} {stale}')
            age = meta.get("age_s")
            if age is not None:
                out.append(
                    f'distpow_node_age_seconds{{node="{name}"}} '
                    f"{_prom_num(age)}")
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def _fmt_quantiles(h: dict) -> str:
    def f(v):
        return "-" if v is None else f"{v:.4g}"

    return (f"n={h['count']} p50={f(h.get('p50'))} "
            f"p95={f(h.get('p95'))} p99={f(h.get('p99'))} "
            f"max={f(h.get('max'))}")


def render_watch_delta(prev: dict, snap: dict) -> str:
    """One --watch refresh frame: counter deltas since the previous
    snapshot (only movers shown), current gauges, histogram quantiles."""
    out = [f"--- {snap.get('role', '?')} @ {time.strftime('%H:%M:%S')} "
           f"(uptime {snap.get('uptime_secs', 0):.0f}s)"]
    pc = (prev.get("counters") or {}) if prev else {}
    moved = False
    for name, v in sorted((snap.get("counters") or {}).items()):
        d = v - pc.get(name, 0)
        if d:
            out.append(f"  {name:34s} {v:>12} (+{d})")
            moved = True
    if not moved:
        out.append("  (no counter movement)")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        out.append(f"  {name:34s} {v:>12}")
    for name, h in sorted((snap.get("histograms") or {}).items()):
        out.append(f"  {name:34s} {_fmt_quantiles(h)}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="print a distpow node's metrics")
    ap.add_argument("--addr", action="append", default=None,
                    help="node RPC address host:port (repeatable with "
                         "--cluster; each flag may hold a comma list)")
    ap.add_argument("--discover", metavar="COORD_ADDR", action="append",
                    default=None,
                    help="with --cluster: pull the scrape list from the "
                         "coordinators' live membership tables "
                         "(Fleet.Members, dedup-merged across the pool) "
                         "instead of --addr flags; repeatable, comma "
                         "lists ok — one member of a sharded pool is "
                         "enough, the ring names the rest "
                         "(docs/CLUSTER.md)")
    ap.add_argument("--role", choices=["auto", "coordinator", "worker"],
                    default="auto")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of JSON")
    ap.add_argument("--openmetrics", action="store_true",
                    help="with --prom: OpenMetrics output — histogram "
                         "buckets carry their retained trace-id "
                         "exemplars and the exposition ends with # EOF "
                         "(docs/FORENSICS.md)")
    ap.add_argument("--watch", type=float, metavar="SECS", default=None,
                    help="refresh every SECS seconds, printing deltas")
    ap.add_argument("--count", type=int, default=0,
                    help="with --watch: stop after N refreshes (0 = forever)")
    ap.add_argument("--cluster", action="store_true",
                    help="scrape every --addr concurrently and print the "
                         "merged cluster snapshot (docs/SLO.md)")
    ap.add_argument("--deadline", type=float, default=5.0,
                    help="with --cluster: shared sweep deadline in seconds"
                         " — slower nodes are reported stale, not waited on")
    args = ap.parse_args(argv)
    addrs = [a for flag in (args.addr or []) for a in flag.split(",") if a]
    if args.watch is not None and args.watch <= 0:
        ap.error("--watch SECS must be positive")
    if args.discover and not args.cluster:
        ap.error("--discover requires --cluster")
    if args.openmetrics and not args.prom:
        ap.error("--openmetrics requires --prom")
    if not addrs and not args.discover:
        ap.error("--addr (or --cluster --discover) is required")
    if args.cluster:
        if args.watch is not None:
            ap.error("--cluster does not support --watch")
        from ..obs.scrape import scrape_cluster

        if args.discover:
            try:
                discovered = discover_cluster_addrs(
                    args.discover, timeout=args.timeout)
            except (OSError, RPCError, FutureTimeout, RuntimeError) as exc:
                print(f"error: membership discovery against "
                      f"{','.join(args.discover)} failed: {exc}",
                      file=sys.stderr)
                return 1
            # explicit --addr extras merge in after the discovered set
            addrs = discovered + [a for a in addrs if a not in discovered]

        cluster = scrape_cluster(addrs, deadline_s=args.deadline,
                                 role=args.role)
        text = render_cluster_prometheus(
            cluster, openmetrics=args.openmetrics
        ) if args.prom else json.dumps(cluster, indent=2, sort_keys=True)
        try:
            print(text, flush=True)
        except BrokenPipeError:
            return 0
        # partial visibility is an error signal for scripts: a sweep
        # that lost nodes exits 1 even though it printed what it saw
        return 1 if cluster.get("stale_nodes") else 0
    if len(addrs) != 1:
        ap.error("multiple --addr values require --cluster")
    args.addr = addrs[0]

    try:
        prev: dict = {}
        n = 0
        while True:
            try:
                snap = fetch_stats(args.addr, args.role, args.timeout)
            except (OSError, RPCError, FutureTimeout) as exc:
                if args.watch is None:
                    raise
                # watch mode exists to observe nodes THROUGH outages: a
                # refused dial during a restart must not end the session
                # at exactly the moment the deltas matter.  A failed
                # fetch still consumes one --count slot, so a bounded
                # watch terminates even against a permanently dead node
                print(f"[stats] fetch failed ({exc}); retrying in "
                      f"{args.watch}s", file=sys.stderr)
                n += 1
                if args.count and n >= args.count:
                    return 1
                time.sleep(args.watch)
                continue
            if args.prom:
                text = render_prometheus(snap,
                                         openmetrics=args.openmetrics)
            elif args.watch is not None:
                text = render_watch_delta(prev, snap)
            else:
                text = json.dumps(snap, indent=2, sort_keys=True)
            try:
                print(text, flush=True)
            except BrokenPipeError:  # e.g. piped into `head`
                return 0
            if args.watch is None:
                return 0
            prev = snap
            n += 1
            if args.count and n >= args.count:
                return 0
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    except (OSError, RPCError, FutureTimeout) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
