"""Node metrics inspector (no reference equivalent — SURVEY.md section 5
lists metrics as absent in the reference).

    python -m distpow_tpu.cli.stats --addr HOST:PORT [--role auto|coordinator|worker]

Dials the node's RPC port, calls its ``Stats`` method, and prints the
JSON snapshot.  ``--role auto`` (default) tries the coordinator service
name first, then the worker's.  For a coordinator, use the CLIENT-facing
listen address.
"""

from __future__ import annotations

import argparse
import json
import sys
from concurrent.futures import TimeoutError as FutureTimeout

from ..runtime.rpc import RPCClient, RPCError


def fetch_stats(addr: str, role: str = "auto", timeout: float = 5.0) -> dict:
    services = {
        "coordinator": ["CoordRPCHandler.Stats"],
        "worker": ["WorkerRPCHandler.Stats"],
        "auto": ["CoordRPCHandler.Stats", "WorkerRPCHandler.Stats"],
    }[role]
    client = RPCClient(addr, timeout=timeout)
    try:
        last: Exception = RuntimeError("no services tried")
        for method in services:
            try:
                return client.call(method, {}, timeout=timeout)
            except (RPCError, FutureTimeout) as exc:
                # FutureTimeout is only an OSError alias on 3.11+
                last = exc
        raise last
    finally:
        client.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="print a distpow node's metrics")
    ap.add_argument("--addr", required=True, help="node RPC address host:port")
    ap.add_argument("--role", choices=["auto", "coordinator", "worker"],
                    default="auto")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    try:
        snap = fetch_stats(args.addr, args.role, args.timeout)
    except (OSError, RPCError, FutureTimeout) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    try:
        print(json.dumps(snap, indent=2, sort_keys=True))
    except BrokenPipeError:  # e.g. piped into `head`
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
