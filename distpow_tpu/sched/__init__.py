"""Continuous-batching search scheduler (docs/SCHEDULER.md).

The serving plane between the RPC protocol and the device:

* :mod:`engine`    — worker-side continuous-batching engine: a slot
  table multiplexing many concurrent puzzle searches onto shared
  batched device launches (ops/search_step.py ``slot_search_step``),
  with deterministic weighted-fair slot allocation and join/leave at
  launch boundaries.
* :mod:`admission` — typed backpressure: the bounded-run-queue
  rejection (``AdmissionReject``) whose ``retry_after_s`` hint rides
  the RPC error frame to powlib's backoff machinery as a non-counting,
  server-paced retry.
* :mod:`coalesce`  — coordinator-side in-flight request coalescing:
  identical ``(nonce, ntz)`` Mines share one fan-out round with a
  multi-waiter reply.
"""

from .admission import AdmissionReject
from .coalesce import Coalescer

__all__ = ["AdmissionReject", "BatchingScheduler", "Coalescer"]


def __getattr__(name):
    # admission + coalesce are stdlib-only and safe for the DEVICE-LESS
    # coordinator/client processes; the engine transitively imports jax
    # (ops/search_step.py), so it loads lazily — only a worker that
    # actually configures Scheduler="batching" pays the import
    if name == "BatchingScheduler":
        from .engine import BatchingScheduler

        return BatchingScheduler
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
