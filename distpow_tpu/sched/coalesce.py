"""In-flight request coalescing — one fan-out round, many waiters.

The coordinator's documented fix for concurrent identical ``Mine``
requests was a per-key mutex: the duplicate BLOCKS until the first
request's whole round completes, then re-checks the cache
(nodes/coordinator.py module docstring).  Correct, but serialized — K
identical requests pay K sequential lock acquisitions and K cache
round-trips, and the (K-1) waiters occupy dispatch threads doing
nothing useful.

Coalescing upgrades that: the FIRST request for a key becomes the
round's *leader* and runs the miss protocol exactly as before; every
concurrent duplicate becomes a *waiter* that parks on the round's
completion event and then replies straight from the dominance cache the
leader's round just filled.  One fan-out, N replies, and each waiter's
trace keeps today's duplicate shape (CoordinatorMine -> CacheMiss ->
CacheHit -> CoordinatorSuccess) — the trace oracle cannot tell the
difference, which is the point: coalescing is a scheduling change, not
a protocol change.

Leader failures propagate: the leader parks its exception on the round
before releasing the waiters, so a rejected (AdmissionReject) or failed
round rejects/fails every coalesced request with the same typed error
instead of stranding the waiters.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


class _Round:
    __slots__ = ("ev", "error", "waiters")

    def __init__(self) -> None:
        self.ev = threading.Event()
        self.error: Optional[BaseException] = None
        self.waiters = 0


class Handle:
    """One request's membership in a coalesced round.

    ``leader`` is fixed at join time.  The leader MUST call
    :meth:`finish` on every exit path (success or error) — waiters
    block on it; :meth:`wait`/:meth:`error` are the waiter side.
    """

    __slots__ = ("_coalescer", "_key", "_round", "leader")

    def __init__(self, coalescer: "Coalescer", key: tuple, round_: _Round,
                 leader: bool) -> None:
        self._coalescer = coalescer
        self._key = key
        self._round = round_
        self.leader = leader

    def finish(self, error: Optional[BaseException] = None) -> None:
        self._coalescer._finish(self._key, self._round, error)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._round.ev.wait(timeout)

    def error(self) -> Optional[BaseException]:
        return self._round.error


class Coalescer:
    """Key -> in-flight round registry with leader election by arrival
    order (first joiner leads; deterministic under the dispatch lock)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rounds: Dict[Tuple, _Round] = {}

    def join(self, key: tuple) -> Handle:
        with self._lock:
            r = self._rounds.get(key)
            if r is None:
                r = self._rounds[key] = _Round()
                return Handle(self, key, r, leader=True)
            r.waiters += 1
            return Handle(self, key, r, leader=False)

    def _finish(self, key: tuple, round_: _Round,
                error: Optional[BaseException]) -> None:
        with self._lock:
            if self._rounds.get(key) is round_:
                del self._rounds[key]
            round_.error = error
        round_.ev.set()

    def inflight(self) -> int:
        with self._lock:
            return len(self._rounds)
