"""Admission control — typed RETRY_AFTER backpressure.

The coordinator's Mine handler bounds the number of concurrently
fanned-out miss rounds (``CoordinatorConfig.SchedMaxInflight``).  A
request arriving beyond the bound is REJECTED with
:class:`AdmissionReject` instead of queueing without limit: the
exception's ``retry_after_s`` hint travels in the RPC response frame as
a dedicated ``retry_after`` field — a JSON key on wire v1, a typed
header flag + f64 on wire v2 (runtime/wire.py ``FLAG_RETRY_AFTER``;
golden-vectored in tests/test_wire.py) — which runtime/rpc.py surfaces
as ``RPCRetryAfter`` on the client, and powlib treats it as a
*server-paced, non-counting* retry — backpressure never burns the
client's transport-failure retry budget toward the terminal
``degraded:`` error (nodes/powlib.py).

Shedding at admission rather than queueing is the standard serving-
stack trade (the inference-server analogue is a 429 + Retry-After):
the coordinator's memory stays bounded under any client storm, clients
pace themselves off the server's own hint instead of a guessed
backoff, and the requests that ARE admitted keep their latency instead
of aging in an unbounded queue.
"""

from __future__ import annotations


class AdmissionReject(RuntimeError):
    """Run queue full — retry after ``retry_after_s`` seconds.

    The ``retry_after_s`` attribute is the typed payload the RPC server
    copies into the response frame (runtime/rpc.py ``_dispatch`` duck-
    types on the attribute so the runtime layer never imports sched).
    The message embeds the hint too, so an untyped transport still
    shows a human-actionable error.
    """

    def __init__(self, retry_after_s: float, detail: str = "") -> None:
        self.retry_after_s = float(retry_after_s)
        msg = f"retry-after:{self.retry_after_s:.3f}s"
        if detail:
            msg = f"{msg} {detail}"
        super().__init__(msg)
