"""Continuous-batching engine — many searches, one device loop.

Before this subsystem, each worker Mine owned the device: K concurrent
requests piled up K miner threads that serialized through
``parallel/search.py`` (the PR-3 contention stress test recorded the
pile-up in ``worker.active_searches``), wasting the batch dimension the
device sweeps anyway.  This engine inverts the ownership: ONE device
loop thread holds the accelerator, and requests become *slots* in a
table the loop packs into shared batched launches via
``ops/search_step.py slot_search_step`` — the same continuous-batching
insight that powers modern inference servers, applied to puzzle search.

Slot lifecycle (docs/SCHEDULER.md):

* **join** — ``submit()`` appends a slot to the run queue; the loop
  admits it at the next launch boundary.  A new Mine never waits for
  another request's search to *finish* — only for the in-flight launch
  (the same one-launch granularity solo cancellation already had).
* **run** — each iteration the loop picks the most-starved slot
  (minimum virtual time; deterministic ``(vtime, seq)`` order), packs
  every compatible active slot into one vmapped dispatch, and fetches
  the per-slot first-hit vector in a single host sync.  Per-slot
  difficulty masks and partitions are runtime operands, so slots at
  different difficulties share one compiled program.
* **leave** — a hit (host-verified), a cancel (polled per boundary), or
  an exhausted enumeration finishes the slot and wakes its waiter.

Weighted-fair allocation: a slot's virtual time advances by
``candidates / weight`` per launch, and both launch selection and
oversubscription preemption order by ``(vtime, seq)`` — a hard
(high-ntz) puzzle therefore gets exactly its fair share of launches and
can never starve cheap ones, while cheap ones finish within a bounded
number of quanta.  When the slot table is full, the loop preempts the
most-served active slot back to the run queue once it is a full quantum
ahead of the queue head (flight-recorder event ``sched.slot_preempt``).

Searches the packed step cannot express — non-power-of-two partitions,
unsatisfiable difficulties — fall back to the wrapped solo backend, so
the engine is always a drop-in for ``backend.search``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..models import puzzle
from ..models.registry import get_hash_model
from ..ops.difficulty import nibble_masks
from ..ops.packing import build_tail_spec
from ..ops.search_step import (
    SENTINEL,
    XLA_SERVING_COMPILE_IMPRACTICAL,
    mixed_slot_search_step,
    slot_search_step,
)
from ..parallel.partition import contiguous_bounds
from ..parallel.search import assemble_secret, effective_batch, width_segments
from ..runtime.metrics import REGISTRY as metrics
from .lanes import LanePlanner
from ..runtime.spans import SPANS
from ..runtime.telemetry import RECORDER
from ..runtime.watchdog import FIRST_COMPILE_GRACE_S, WATCHDOG

log = logging.getLogger("distpow.sched")

# Idle/reap poll period: how often queued-slot cancels are honored when
# the device is otherwise quiet (active slots are reaped every launch
# boundary, which is far more frequent under load).
_IDLE_TICK_S = 0.02


class Slot:
    """One active search's scheduler state.  ``done`` fires exactly once
    with either ``secret`` set (hit), ``secret=None`` (cancelled or
    enumeration exhausted), or ``error`` set (engine failure).
    ``model`` is the slot's hash model — slots of different models can
    share a mixed-hash launch (docs/SERVING.md)."""

    __slots__ = (
        "seq", "nonce", "ntz", "tb_lo", "tbc", "log_tbc", "weight",
        "cancel_check", "masks", "done", "secret", "error", "vtime",
        "launches", "submitted_t", "first_launch_t", "exhausted",
        "_segments", "vw", "seg_hi", "extra", "spec", "chunk0",
        "_cancelled", "model", "span", "preemptions",
    )

    def __init__(self, seq: int, nonce: bytes, ntz: int, tb_lo: int,
                 tbc: int, cancel_check: Optional[Callable[[], bool]],
                 weight: float, masks: object, segments: object,
                 model: object) -> None:
        self.model = model
        self.seq = seq
        self.nonce = nonce
        self.ntz = ntz
        self.tb_lo = tb_lo
        self.tbc = tbc
        self.log_tbc = tbc.bit_length() - 1
        self.weight = weight
        self.cancel_check = cancel_check
        self.masks = masks
        self.done = threading.Event()
        self.secret: Optional[bytes] = None
        self.error: Optional[str] = None
        self.vtime = 0.0
        self.launches = 0
        self.submitted_t = time.monotonic()
        self.first_launch_t: Optional[float] = None
        self.exhausted = False
        self._segments = segments
        self._cancelled = False
        self.vw = 0
        self.seg_hi = 0
        self.extra = b""
        self.spec = None
        self.chunk0 = 0
        self.span = None  # sched.slot forensics span (docs/FORENSICS.md)
        self.preemptions = 0

    def cancel(self) -> None:
        """Request cancellation; honored at the next launch boundary."""
        # distpow: ok unguarded-shared-write -- monotonic False->True
        # flag set from caller threads; the device loop re-reads it at
        # every launch boundary (cancel_requested), so the worst a
        # bare store costs is one extra launch, never a missed cancel
        self._cancelled = True

    def cancel_requested(self) -> bool:
        if self._cancelled:
            return True
        if self.cancel_check is not None and self.cancel_check():
            self._cancelled = True
        return self._cancelled

    def result(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Block for the slot's outcome; raises on engine failure."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"slot {self.seq} not done in {timeout}s")
        if self.error is not None:
            raise RuntimeError(self.error)
        return self.secret


class BatchingScheduler:
    """Drop-in for ``backend.search`` that multiplexes concurrent
    searches onto shared batched launches (module docstring).

    ``fallback`` is the wrapped solo backend for shapes the packed step
    cannot express.  ``start=False`` defers the device loop (tests
    submit a deterministic slot set first, then :meth:`start`).

    ``lane`` pins the launch-lane ranking (``WorkerConfig.SchedLane``):
    "auto" lets the planner rank by hardware capability, "pallas" /
    "mesh" / "xla" forces that lane first (sched/lanes.py).
    """

    def __init__(self, hash_model: str = "md5", batch_size: int = 1 << 20,
                 max_slots: int = 8, max_width: int = 8,
                 fallback: object = None,
                 start: bool = True,
                 extra_models: Sequence[str] = (),
                 lane: str = "auto") -> None:
        self.model = get_hash_model(hash_model)
        # models the packed step serves: the default plus any configured
        # extras (WorkerConfig.SchedHashModels).  Slots of different
        # models share one mixed-hash launch; models whose fused XLA
        # serving step is impractical to compile stay on the solo route
        # regardless (XLA_SERVING_COMPILE_IMPRACTICAL — on TPU those are
        # served by the Pallas kernels through a solo backend).
        self.models = {self.model.name: self.model}
        for name in extra_models:
            m = get_hash_model(name)
            if m.name not in XLA_SERVING_COMPILE_IMPRACTICAL:
                self.models[m.name] = m
        self.batch = effective_batch(batch_size)
        self.max_slots = max(1, int(max_slots))
        self.max_width = max_width
        self.fallback = fallback
        self.lane = lane
        self.planner = LanePlanner(override=lane)
        self._cond = threading.Condition()
        self._pending: List[Slot] = []
        self._active: List[Slot] = []
        self._seq = 0
        self._stop = threading.Event()
        self._dead = False
        self._compiled: set = set()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="sched-batching-loop", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Stop the loop; unfinished slots complete with ``None`` (the
        cancelled shape — callers see a clean no-result, not a hang)."""
        self._stop.set()
        with self._cond:
            # reject submissions racing with shutdown BEFORE draining:
            # a slot appended after the drain would have no loop left
            # to ever finish it (search() routes the refusal to the
            # fallback backend)
            self._dead = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        with self._cond:
            leftovers = self._pending + self._active
            self._pending = []
            self._active = []
            self._publish_gauges_locked()
        for s in leftovers:
            self._finish(s, None)

    # -- submission ---------------------------------------------------------
    def supports(self, difficulty: int, thread_bytes: Sequence[int],
                 hash_model: Optional[str] = None) -> bool:
        """True when the packed step can serve this shape: an admitted
        hash model, a contiguous power-of-two partition and a
        satisfiable difficulty."""
        model = self.models.get(hash_model or self.model.name)
        if model is None:
            return False
        try:
            _, tbc = contiguous_bounds(thread_bytes)
        except ValueError:
            return False
        return (0 < tbc <= 256 and tbc & (tbc - 1) == 0
                and difficulty <= model.max_difficulty)

    def submit(self, nonce: bytes, difficulty: int,
               thread_bytes: Sequence[int],
               cancel_check: Optional[Callable[[], bool]] = None,
               weight: float = 1.0,
               hash_model: Optional[str] = None) -> Slot:
        model = self.models[hash_model or self.model.name]
        nonce = bytes(nonce)
        tb_lo, tbc = contiguous_bounds(thread_bytes)
        masks = nibble_masks(difficulty, model)
        segments = self._segment_stream()
        with self._cond:
            if self._dead:
                raise RuntimeError(
                    "batching scheduler is closed or its device loop died"
                )
            self._seq += 1
            slot = Slot(self._seq, nonce, difficulty, tb_lo, tbc,
                        cancel_check, weight, masks, segments, model)
            # slot-residency forensics span (docs/FORENSICS.md): the
            # submitting miner thread carries the request's trace id
            # (SPANS.bind in nodes/worker.py), so the slot's whole
            # scheduler life — queue wait, launches, preemptions —
            # lands on that request's timeline.
            # distpow: ok unclosed-span -- slot spans cross the
            # submit(miner)->device-loop thread boundary by design;
            # _finish() is the single exit point for every slot (hit,
            # cancel, exhaustion, loop death, close) and finishes the
            # handle exactly once
            slot.span = SPANS.begin("sched.slot", seq=slot.seq,
                                    model=model.name)
            # virtual-clock floor: a joining slot starts at the
            # currently most-starved slot's vtime, not 0 — otherwise a
            # stream of fresh arrivals (each sorting first at vtime 0)
            # would outrank a long-running slot forever and starve it,
            # the exact failure the fair clock exists to prevent
            slot.vtime = min(
                (s.vtime for s in self._active + self._pending),
                default=0.0,
            )
            if not self._advance_segment(slot):
                raise RuntimeError("empty enumeration")  # unreachable
            self._pending.append(slot)
            self._publish_gauges_locked()
            self._cond.notify_all()
        return slot

    def _solo(self, nonce: bytes, difficulty: int, thread_bytes: bytes,
              cancel_check: Optional[Callable[[], bool]],
              hash_model: Optional[str]) -> Optional[bytes]:
        """Route one search outside the packed step.

        Default-model shapes go to the wrapped fallback backend (it was
        built for that model).  Off-default models the packed step
        cannot serve — impractical-to-compile, not configured, or an
        unsupported shape — run through the solo XLA driver with the
        requested model instead: the fallback backend's model would be
        WRONG for them (docs/SERVING.md; on TPU, serve those models
        from a worker whose configured backend is their Pallas kernel).
        """
        if hash_model is None or hash_model == self.model.name:
            if self.fallback is None:
                raise ValueError(
                    f"unsupported search shape for the batching scheduler "
                    f"(difficulty={difficulty}) and no fallback backend"
                )
            metrics.inc("sched.fallback_searches")
            return self.fallback.search(
                nonce, difficulty, thread_bytes, cancel_check=cancel_check
            )
        model = get_hash_model(hash_model)
        if model.name in XLA_SERVING_COMPILE_IMPRACTICAL:
            # never run these through the solo XLA driver either: the
            # fused serving step is the thing that is impractical to
            # compile (>30 min observed on the TPU backend, r4c), and
            # a "fallback" that wedges the miner thread and device in
            # that compile is worse than an honest refusal
            raise ValueError(
                f"hash model {model.name!r} is never admitted to the XLA "
                f"serving path (XLA_SERVING_COMPILE_IMPRACTICAL): serve "
                f"it from a worker whose configured backend is its "
                f"Pallas kernel"
            )
        metrics.inc("sched.fallback_searches")
        from ..parallel.search import persistent_search
        from .lanes import persistent_step_builder

        tb_lo, tbc = contiguous_bounds(thread_bytes)
        res = persistent_search(
            nonce, difficulty, thread_bytes,
            model=model, batch_size=self.batch,
            cancel_check=cancel_check,
            step_builder=persistent_step_builder(
                nonce, difficulty, tb_lo, tbc, model,
                override=self.lane,
            ),
        )
        return None if res is None else res.secret

    def search(self, nonce: bytes, difficulty: int, thread_bytes: bytes,
               cancel_check: Optional[Callable[[], bool]] = None,
               hash_model: Optional[str] = None) -> Optional[bytes]:
        """Backend-compatible facade: first solving secret or None."""
        if self._dead or not self.supports(difficulty, thread_bytes,
                                           hash_model):
            return self._solo(nonce, difficulty, thread_bytes,
                              cancel_check, hash_model)
        try:
            slot = self.submit(nonce, difficulty, thread_bytes,
                               cancel_check=cancel_check,
                               hash_model=hash_model)
        except RuntimeError:
            # closed/died between the liveness check and the append —
            # the slot was never queued, so serve solo rather than
            # hang or leak the race to the miner thread
            if self.fallback is None and (hash_model is None
                                          or hash_model == self.model.name):
                raise
            return self._solo(nonce, difficulty, thread_bytes,
                              cancel_check, hash_model)
        return slot.result()

    # -- cursor -------------------------------------------------------------
    def _segment_stream(self):
        for width in range(0, self.max_width + 1):
            yield from width_segments(width)

    def _advance_segment(self, slot: Slot) -> bool:
        """Move the slot to its next width segment; False = exhausted."""
        for vw, lo, hi, extra in slot._segments:
            slot.vw = vw
            slot.seg_hi = hi
            slot.extra = extra
            slot.chunk0 = lo
            slot.spec = build_tail_spec(slot.nonce, vw, slot.model, extra)
            return True
        return False

    @staticmethod
    def _group_key(slot: Slot) -> tuple:
        # slots sharing (model, tail layout) can share one vmapped lane
        # stack; DIFFERENT groups still share the LAUNCH through the
        # mixed step, whose compile key is the ordered group-key set
        # (ops/search_step.py mixed_slot_search_step)
        spec = slot.spec
        return (slot.model.name, spec.n_blocks, spec.tb_loc,
                spec.chunk_locs)

    # -- the device loop ----------------------------------------------------
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                with self._cond:
                    self._reap_locked(self._active)
                    self._reap_locked(self._pending)
                    self._admit_locked()
                    group = self._pick_locked()
                    if group is None:
                        self._cond.wait(timeout=_IDLE_TICK_S)
                        continue
                self._launch(group)
        except Exception as exc:  # the loop must never die silently
            log.exception("batching scheduler device loop died: %s", exc)
            metrics.inc("sched.loop_failures")
            RECORDER.record("sched.loop_failure", error=str(exc))
            with self._cond:
                self._dead = True
                slots = self._pending + self._active
                self._pending = []
                self._active = []
                self._publish_gauges_locked()
            for s in slots:
                self._finish(s, None, error=f"scheduler loop died: {exc}")

    def _publish_gauges_locked(self) -> None:
        metrics.gauge("sched.active_slots", len(self._active))
        metrics.gauge("sched.run_queue_depth", len(self._pending))

    def _reap_locked(self, slots: List[Slot]) -> None:
        for s in list(slots):
            if s.cancel_requested():
                slots.remove(s)
                metrics.inc("search.cancelled")
                self._finish(s, None)
        self._publish_gauges_locked()

    def _admit_locked(self) -> None:
        self._pending.sort(key=lambda s: (s.vtime, s.seq))
        while self._pending and len(self._active) < self.max_slots:
            self._active.append(self._pending.pop(0))
        if self._pending and self._active:
            # oversubscribed: preempt the most-served active slot once
            # it is a full quantum ahead of the queue head — bounded
            # round-robin between the overflow set, at most one swap
            # per boundary so the table never thrashes
            head = self._pending[0]
            victim = max(self._active, key=lambda s: (s.vtime, s.seq))
            if victim.vtime >= head.vtime + self.batch / victim.weight:
                self._active.remove(victim)
                self._pending.append(victim)
                self._active.append(self._pending.pop(0))
                victim.preemptions += 1
                metrics.inc("sched.slots_preempted")
                RECORDER.record(
                    "sched.slot_preempt", slot=victim.seq,
                    for_slot=head.seq, vtime=round(victim.vtime, 1),
                )
        self._publish_gauges_locked()

    def _pick_locked(self) -> Optional[List[Slot]]:
        if not self._active:
            return None
        # most-starved first across ALL groups: slots of different
        # models share a mixed-hash launch, so fairness ordering no
        # longer forfeits batching at model boundaries
        cohort = sorted(self._active, key=lambda s: (s.vtime, s.seq))
        cohort = cohort[: self.max_slots]
        # serve at most ONE layout group per model: batching across
        # models is the occupancy win (solo fallback served exactly 1),
        # but batching across LAYOUTS buys nothing the fair clock
        # doesn't already deliver by rotating groups — and every layout
        # SUBSET the join/leave churn produced would be a fresh
        # mixed-step compile key (the power-of-two lane pad bounds
        # pads, not subsets).  This caps a launch's group count at the
        # admitted-model count, so compile keys stay bounded by
        # model-subsets x per-model (layout, pad).
        keep = {}
        for s in cohort:  # cohort is (vtime, seq)-ordered: first slot
            keep.setdefault(s.model.name, self._group_key(s))  # leads
        cohort = [s for s in cohort
                  if self._group_key(s) == keep[s.model.name]]
        return cohort

    @staticmethod
    def _lane_ops(lanes: List[Slot]) -> tuple:
        import jax.numpy as jnp

        return (
            jnp.asarray([s.spec.init_state for s in lanes], jnp.uint32),
            jnp.asarray([s.spec.base_words for s in lanes], jnp.uint32),
            jnp.asarray([s.masks for s in lanes], jnp.uint32),
            jnp.asarray([s.tb_lo for s in lanes], jnp.uint32),
            jnp.asarray([s.log_tbc for s in lanes], jnp.uint32),
            jnp.asarray([s.chunk0 & 0xFFFFFFFF for s in lanes],
                        jnp.uint32),
        )

    def _launch(self, group: List[Slot]) -> None:
        import jax

        # group the cohort by (model, layout): each group is one vmapped
        # lane stack; all groups share the single dispatch.  Per-group
        # lane counts pad to a power of two so the compile-key space
        # stays bounded the way the single-group n_pad already was.
        by_key: dict = {}
        for s in group:
            by_key.setdefault(self._group_key(s), []).append(s)
        ordered = sorted(by_key.items(), key=lambda kv: kv[0])
        gdefs, gops, gslots, gkeys = [], [], [], []
        for key, slots in ordered:
            model_name, n_blocks, tb_loc, chunk_locs = key
            n_pad = 1 << (len(slots) - 1).bit_length()
            lanes = slots + [slots[-1]] * (n_pad - len(slots))
            gdefs.append((model_name, n_blocks, tb_loc, chunk_locs, n_pad))
            gops.append(self._lane_ops(lanes))
            gslots.append(slots)
            # slot-membership key for the mesh lane's replicated operand
            # cache: static rows only change when the lane stack does
            gkeys.append(tuple(
                (s.seq, s.vw, s.ntz, s.extra) for s in lanes
            ))
        # resolve each group's launch lane (sched/lanes.py): pallas /
        # mesh groups dispatch their own steps; every xla group shares
        # the classic slot/mixed dispatch.  Resolution is cached, so the
        # per-launch planner cost is a dict hit per group.
        resolved = [self.planner.resolve(gd, self.batch)
                    for gd in gdefs]
        lanes_used = [lane for lane, _ in resolved]
        compile_key = (tuple(gdefs), tuple(lanes_used), self.batch)
        first_compile = compile_key not in self._compiled

        def run():
            pending: List[Tuple[int, object]] = []
            xla_idx = [i for i, lane in enumerate(lanes_used)
                       if lane == "xla"]
            for i, (lane, gstep) in enumerate(resolved):
                if lane == "xla":
                    continue
                try:
                    pending.append((i, gstep(gops[i], gkeys[i])))
                except Exception as exc:
                    # dispatch/compile failure: demote this lane for the
                    # key and serve the group through xla in THIS launch
                    # — no request ever observes the demotion
                    self.planner.demote(gdefs[i], self.batch, lane, exc)
                    lanes_used[i] = "xla"
                    xla_idx.append(i)
            if xla_idx:
                xla_idx.sort()
                if len(xla_idx) == 1:
                    i = xla_idx[0]
                    m, nb, tl, cl, n_pad = gdefs[i]
                    s = slot_search_step(m, nb, tl, cl, self.batch, n_pad)
                    pending.append((i, s(*gops[i])))
                else:
                    s = mixed_slot_search_step(
                        tuple(gdefs[i] for i in xla_idx), self.batch
                    )
                    pending.extend(
                        zip(xla_idx, s(tuple(gops[i] for i in xla_idx)))
                    )
            # one host sync for the whole launch regardless of how many
            # lanes served it — the engine's single-sync discipline
            fetched = jax.device_get([r for _, r in pending])
            out: List[object] = [None] * len(gdefs)
            for (i, _), v in zip(pending, fetched):
                out[i] = v
            return out

        now = time.monotonic()
        with WATCHDOG.active():
            WATCHDOG.beat()
            if first_compile:
                self._compiled.add(compile_key)
                with WATCHDOG.grace(FIRST_COMPILE_GRACE_S):
                    res_groups = run()
            else:
                res_groups = run()

        # per-group launch coverage: specialized lanes may sweep more
        # than self.batch candidates per slot per launch (the mesh lane
        # covers n_dev x batch) — every cursor/fairness/throughput
        # account below uses the group's own coverage
        coverages = [
            self.batch if lanes_used[i] == "xla"
            else resolved[i][1].coverage
            for i in range(len(gdefs))
        ]
        metrics.observe("sched.batch_occupancy", len(group))
        metrics.inc("sched.launches")
        for lane in lanes_used:
            metrics.inc(f"sched.lane_launches.{lane}")
        if len({d[0] for d in gdefs}) > 1:
            metrics.inc("sched.mixed_hash_launches")
        metrics.inc("search.hashes",
                    sum(len(sl) * c for sl, c in zip(gslots, coverages)))
        finished: List[Tuple[Slot, Optional[bytes]]] = []
        for slots, res, cov in zip(gslots, res_groups, coverages):
            for i, s in enumerate(slots):
                s.launches += 1
                s.vtime += cov / s.weight
                if s.first_launch_t is None:
                    s.first_launch_t = now
                    metrics.observe("sched.slot_wait_s",
                                    now - s.submitted_t)
                # distpow: ok relaunch-loop-sync -- res is a fetched host array (the single device_get above is this launch's one sanctioned sync); converting lanes here cannot block on the device
                f = int(res[i])
                if f != SENTINEL:
                    secret, _ = assemble_secret(
                        s.chunk0, f, s.vw, s.extra, s.tb_lo, s.tbc
                    )
                    if not puzzle.check_secret(s.nonce, secret, s.ntz,
                                               s.model.name):
                        # kernel/oracle divergence: fail THIS slot
                        # loudly, keep the loop serving the others (the
                        # solo driver kills its whole miner thread here)
                        finished.append((s, None))
                        s.error = (
                            f"packed step returned non-solving candidate "
                            f"{secret.hex()} (kernel/oracle divergence)"
                        )
                        continue
                    metrics.inc("search.found")
                    finished.append((s, secret))
                    continue
                s.chunk0 += cov >> s.log_tbc
                if s.chunk0 >= s.seg_hi and not self._advance_segment(s):
                    s.exhausted = True
                    finished.append((s, None))
        with self._cond:
            for s, _ in finished:
                if s in self._active:
                    self._active.remove(s)
            self._publish_gauges_locked()
        for s, secret in finished:
            self._finish(s, secret, error=s.error)

    def _finish(self, slot: Slot, secret: Optional[bytes],
                error: Optional[str] = None) -> None:
        slot.secret = secret
        slot.error = error
        if slot.span is not None:
            slot.span.finish(
                launches=slot.launches, preemptions=slot.preemptions,
                outcome=("found" if secret is not None
                         else "error" if error else "no-result"),
            )
        slot.done.set()
