"""Kernel-lane launch planner — pick the fastest device form per group.

The batching engine (sched/engine.py) packs its slot table into
(model, tail-layout) groups; before this module every group dispatched
through one device form, the vmapped XLA slot step.  BENCH_r05 measured
what that leaves on the table: the repo's own Pallas kernels serve the
same hashes 60-90x faster (sha3_256 6.3 MH/s served vs 570 in-kernel),
and one worker never spanned more than one chip.  The planner closes
both gaps at the launch layer: each group resolves to a ranked **lane**

* ``pallas`` — the hand-written per-model kernel (ops/md5_pallas.py),
  one kernel dispatch per slot lane sharing a single host sync; TPU
  hardware (or the interpret dev knob), pow2 geometry validated through
  the same ``plan_launch_geometry`` the pallas backend plans with.
* ``mesh`` — the vmapped slot step spread over every local device
  (parallel/mesh_search.py ``mesh_slot_search_step``): one launch
  covers ``n_dev x MESH_SPAN x batch`` candidates per slot, the
  VaultxGPU multi-chip throughput lever applied to serving.  The span
  factor widens each device's per-launch slice beyond the configured
  batch so the single host dispatch — the scarce resource in the
  serving loop — is amortized over more of the search segment.
* ``xla`` — the existing single-device vmapped step; always available,
  always last, so no environment regresses.

Resolution happens once per compile key and is CACHED; a lane whose
build or first dispatch fails is **demoted** for that key (the engine
falls back to ``xla`` within the same launch) and never retried —
compile-failure demotion, the same transparent-fallback contract the
pallas-mesh backend already has per width.  ``SchedLane`` in
WorkerConfig (``override`` here) pins the ranking for operators and
tests.  Every launch counts ``sched.lane_launches.<lane>`` per group
served (runtime/metrics.py registry).

The solo/persistent route shares the planner through
``persistent_step_builder``: a multi-device worker with
``SearchLoop="persistent"`` serves each dispatch through the mesh
persistent step (``mesh_persistent_factory``) and so does the fleet
self-calibration that measures through ``backend.search`` — a mesh
worker advertises its real multi-chip rate with zero coordinator
changes (docs/FLEET.md).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

log = logging.getLogger("distpow.sched.lanes")

#: Ranked lane names, fastest-first; ``xla`` is the always-available tail.
LANES = ("pallas", "mesh", "xla")

#: Default per-device span multiplier for the mesh lane.  Each mesh
#: launch sweeps ``span x batch`` candidates per device: host dispatch
#: cost (python launch assembly + executable invocation + the result
#: sync) is paid once per launch regardless of span, so widening the
#: slice divides that fixed cost across more candidates.  4 keeps the
#: per-launch latency within one engine tick at default batch sizes
#: while recovering most of the amortization headroom.
MESH_SPAN = 4


def mesh_span() -> int:
    """The mesh lane's span multiplier (``DISTPOW_MESH_SPAN`` to tune,
    floor 1)."""
    import os

    try:
        return max(1, int(os.environ.get("DISTPOW_MESH_SPAN", MESH_SPAN)))
    except ValueError:
        return MESH_SPAN


@dataclass(frozen=True)
class LaneCaps:
    """Hardware capabilities the ranking keys on.  Injectable so the
    selection matrix is testable off-TPU (tests/test_lanes.py)."""

    platform: str          # jax.default_backend(): "tpu" | "cpu" | ...
    n_devices: int         # local device count (mesh span)
    interpret: bool = False  # allow interpret-mode pallas off-TPU (dev knob)


def detect_caps() -> LaneCaps:
    import jax

    return LaneCaps(platform=jax.default_backend(),
                    n_devices=len(jax.devices()))


class _MeshGroupStep:
    """Mesh-lane group step: ``mesh_slot_search_step`` plus the
    replicated operand cache.

    Pre-placing the five static operand rows on the mesh
    (``jax.device_put`` with a replicated ``NamedSharding``) keyed on
    the group's slot membership is what makes the lane pay off: fresh
    host arrays would re-lay-out onto every device each launch (~2.5x
    the dispatch cost, measured) while the chunk cursor row — the only
    per-launch change — is a tiny transfer.
    """

    lane = "mesh"

    def __init__(self, dyn, mesh, coverage: int) -> None:
        import jax
        from ..parallel.compat import NamedSharding, PartitionSpec

        self._dyn = dyn
        self._jax = jax
        self._repl = NamedSharding(mesh, PartitionSpec())
        self.coverage = coverage
        self._key: object = None
        self._placed: Optional[tuple] = None

    def __call__(self, ops: tuple, key: object):
        if key != self._key:
            self._placed = tuple(
                self._jax.device_put(o, self._repl) for o in ops[:5]
            )
            self._key = key
        chunk0 = self._jax.device_put(ops[5], self._repl)
        return self._dyn(*self._placed, chunk0)


class _PallasGroupStep:
    """Pallas-lane group step: one layout-keyed kernel dispatch per slot
    lane, stacked on device so the launch keeps the engine's single host
    sync.  Per-lane runtime operands (masks, partition, chunk cursor)
    ride the same slot-op rows the XLA lane builds."""

    lane = "pallas"

    def __init__(self, step, coverage: int) -> None:
        self._step = step
        self.coverage = coverage

    def __call__(self, ops: tuple, key: object):
        return self._step(*ops)


def build_pallas_group_step(gdef: tuple, batch: int,
                            caps: LaneCaps) -> _PallasGroupStep:
    """Build the pallas lane for one launch group, or raise ValueError
    when the kernel cannot express it (no tile for the model,
    multi-block tail, off-TPU without the interpret knob, or a batch
    that does not align to the kernel's pow2 tile grid as judged by
    ``plan_launch_geometry`` — the same planner the pallas backend
    uses).  The raise IS the demotion signal."""
    import jax
    import jax.numpy as jnp

    from ..backends.pallas_backend import plan_launch_geometry
    from ..models.registry import get_hash_model
    from ..ops.md5_pallas import (
        INTERPRET_XLA_FALLBACK,
        LANES as KERNEL_LANES,
        MODEL_GEOMETRY,
        _dyn_pallas_step,
        default_geometry,
    )

    model_name, n_blocks, tb_loc, chunk_locs, n_pad = gdef
    if model_name not in MODEL_GEOMETRY:
        raise ValueError(f"no pallas kernel for model {model_name}")
    if n_blocks != 1:
        raise ValueError("pallas kernel requires a single-block tail")
    interpret = caps.platform != "tpu"
    if interpret and not caps.interpret:
        raise ValueError(
            f"pallas lane requires TPU hardware (platform is "
            f"{caps.platform!r} and the interpret dev knob is off)"
        )
    if interpret and model_name in INTERPRET_XLA_FALLBACK:
        raise ValueError(
            f"{model_name} pallas tile is TPU-only (interpret-mode "
            f"XLA:CPU compile of the limb-pair graph is pathological)"
        )
    sublanes, inner = default_geometry(model_name, interpret)
    tile = sublanes * KERNEL_LANES
    # pow2-geometry validation through the shared launch planner: with
    # tbc=1 the requested chunk count IS the batch, so any padding or
    # launch split the plan reports means the batch cannot ride the
    # kernel's tile grid as-is — the engine's fixed per-launch coverage
    # cannot absorb either
    planned_batch, _, planned_k = plan_launch_geometry(
        batch, 1, tile, inner, 1, (1 << 31) - 1
    )
    if planned_batch != batch or planned_k != 1:
        raise ValueError(
            f"batch {batch} does not align to the {model_name} kernel "
            f"tile grid (tile={tile}: planned {planned_batch} x "
            f"{planned_k})"
        )
    inner_eff = max(1, inner)
    tiles = batch // tile
    while tiles % inner_eff:
        inner_eff //= 2
    grid = tiles // inner_eff
    model = get_hash_model(model_name)
    _, tb_w, tb_s = tb_loc
    chunk_ws = tuple((w, s) for _, w, s in chunk_locs)
    # mask_words = full digest width: slot rows carry every mask word so
    # per-slot difficulty stays a runtime operand (the slot_search_step
    # discipline), trading the dead-round skip for program sharing
    kernel = _dyn_pallas_step(
        tb_w, tb_s, chunk_ws, grid, sublanes, interpret, inner_eff,
        model.digest_words, model_name,
    )

    @jax.jit
    def step(init, base, masks, tb_lo, log_tbc, chunk0):
        outs = [
            kernel(
                chunk0[i], init[i], base[i][0], masks[i],
                jnp.stack([tb_lo[i], log_tbc[i]]),
            )
            for i in range(n_pad)
        ]
        return jnp.stack(outs)

    return _PallasGroupStep(step, batch)


class LanePlanner:
    """Per-compile-key lane resolution with sticky demotion (module
    docstring).  ``override`` pins the first-ranked lane ("auto" ranks
    by capability); a demoted override falls straight to ``xla`` —
    never silently onto the other specialized lane."""

    def __init__(self, caps: Optional[LaneCaps] = None,
                 override: str = "auto") -> None:
        override = (override or "auto").lower()
        if override not in ("auto",) + LANES:
            raise ValueError(
                f"unknown scheduler lane {override!r}: expected one of "
                f"{('auto',) + LANES}"
            )
        self.override = override
        self._caps = caps
        self._mesh = None
        self._choice: Dict[tuple, str] = {}
        self._demoted: Dict[tuple, Set[str]] = {}
        self._steps: Dict[tuple, object] = {}

    @property
    def caps(self) -> LaneCaps:
        if self._caps is None:
            self._caps = detect_caps()
        return self._caps

    def _get_mesh(self):
        if self._mesh is None:
            import jax

            from ..parallel.mesh_search import make_mesh

            self._mesh = make_mesh(jax.devices()[: self.caps.n_devices])
        return self._mesh

    # -- ranking ------------------------------------------------------------
    def _eligible(self, lane: str, gdef: tuple, batch: int) -> bool:
        """Cheap static screen; build failures demote the rest."""
        if lane == "xla":
            return True
        # the width-0 probe layout (no chunk words): its whole segment
        # is at most one tb row — far below one batch, so a specialized
        # lane's per-layout compile could never pay for itself
        if not gdef[3]:
            return False
        if lane == "mesh":
            return (self.caps.n_devices > 1
                    and batch * mesh_span() * self.caps.n_devices < 1 << 31)
        # pallas: platform screen only — geometry/model checks live in
        # the builder so the demotion log carries the precise reason
        return self.caps.platform == "tpu" or self.caps.interpret

    def rank(self, gdef: tuple, batch: int) -> Tuple[str, ...]:
        """Ranked candidate lanes for a group, override applied and
        ineligible/demoted lanes dropped — always ends in ``xla``."""
        if self.override == "auto":
            ranked = LANES
        elif self.override == "xla":
            ranked = ("xla",)
        else:
            ranked = (self.override, "xla")
        demoted = self._demoted.get((gdef, batch), set())
        out = tuple(
            lane for lane in ranked
            if lane == "xla"
            or (lane not in demoted and self._eligible(lane, gdef, batch))
        )
        return out if out[-1] == "xla" else out + ("xla",)

    # -- resolution ---------------------------------------------------------
    def resolve(self, gdef: tuple, batch: int):
        """(lane, step) for a launch group.  ``step`` is None for the
        ``xla`` lane (the engine owns that dispatch — mixed groups share
        it); otherwise a group-step callable ``step(ops, key)`` with a
        ``coverage`` attribute (candidates per slot per launch).  Build
        failures demote and fall through, so this always returns."""
        key = (gdef, batch)
        while True:
            lane = self._choice.get(key)
            if lane is None:
                lane = self.rank(gdef, batch)[0]
                self._choice[key] = lane
            if lane == "xla":
                return "xla", None
            step = self._steps.get((gdef, batch, lane))
            if step is not None:
                return lane, step
            try:
                step = self._build(lane, gdef, batch)
            except Exception as exc:
                self.demote(gdef, batch, lane, exc)
                continue
            self._steps[(gdef, batch, lane)] = step
            return lane, step

    def demote(self, gdef: tuple, batch: int, lane: str,
               exc: Exception) -> None:
        """Sticky per-key demotion — the compile-failure contract."""
        self._demoted.setdefault((gdef, batch), set()).add(lane)
        self._choice.pop((gdef, batch), None)
        self._steps.pop((gdef, batch, lane), None)
        log.warning(
            "lane %s demoted for group %s (batch %d): %s", lane,
            gdef[0], batch, exc,
        )

    def _build(self, lane: str, gdef: tuple, batch: int):
        if lane == "pallas":
            return build_pallas_group_step(gdef, batch, self.caps)
        assert lane == "mesh", lane
        from ..parallel.mesh_search import AXIS, mesh_slot_search_step

        model_name, n_blocks, tb_loc, chunk_locs, n_pad = gdef
        mesh = self._get_mesh()
        n_dev = int(mesh.devices.size)
        # per-device slice = span x batch: the step enumerates each
        # device's contiguous flat-index range, so widening the local
        # batch IS the span — no program change, just fewer launches
        # per segment (engine cursor advances by the step's coverage)
        local = batch * mesh_span()
        dyn = mesh_slot_search_step(
            mesh, AXIS, model_name, n_blocks, tb_loc, chunk_locs, local,
            n_pad,
        )
        return _MeshGroupStep(dyn, mesh, local * n_dev)


def persistent_step_builder(nonce: bytes, difficulty: int, tb_lo: int,
                            tbc: int, model,
                            caps: Optional[LaneCaps] = None,
                            override: str = "auto"):
    """Lane plan for one solo/persistent request — the
    ``parallel.search.persistent_search`` ``step_builder`` hook.

    Returns None when the single-device persistent step IS the plan
    (one device, or the override pins ``xla``); otherwise a builder
    whose per-width result is the mesh persistent step, compile-probed
    at bind time with a SET stop flag (the warmup trick: the on-device
    loop exits at its first condition check, so probing compiles the
    real program at near-zero device cost).  Any bind or probe failure
    demotes the whole request to the single-device path — per-lane
    compile-failure demotion, solo edition.
    """
    caps = caps or detect_caps()
    # the persistent route has exactly two lanes, mesh or the default
    # single-device step: only "auto"/"mesh" rankings enable mesh here
    # (a "pallas" override pins the PACKED lanes, not this one)
    if override not in ("auto", "mesh") or caps.n_devices <= 1:
        return None
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh_search import AXIS, make_mesh, \
        mesh_persistent_factory

    mesh = make_mesh(jax.devices()[: caps.n_devices])
    factory = mesh_persistent_factory(
        bytes(nonce), difficulty, tb_lo, tbc, model, mesh, AXIS
    )
    demoted = []

    def builder(vw: int, extra: bytes, target_chunks: int, segments: int):
        if demoted:
            return None
        try:
            bound, chunks_each, chunks_per_step = factory(
                vw, bytes(extra), target_chunks, segments
            )
            # stop-set compile probe: surfaces compile failures here,
            # where demotion is cheap, instead of mid-pipeline
            int(bound(jnp.uint32(0), jnp.uint32(1))[1])
        except Exception as exc:
            demoted.append(True)
            log.warning(
                "mesh persistent lane demoted for width %d "
                "(model %s): %s", vw, model.name, exc,
            )
            return None
        return bound, chunks_each, chunks_per_step

    return builder
