#!/usr/bin/env python
"""Deterministic mesh-lane smoke (scripts/ci.sh --mesh-smoke).

Boots 4 virtual CPU devices (pre-backend-init, via the version-portable
compat shim) and drives the kernel-lane launch planner end to end on
the simulated mesh:

* the planner's auto ranking picks the ``mesh`` lane for a real
  scheduler solve and ``sched.lane_launches.mesh`` counts the serving;
* the mesh-lane secret is byte-identical to the pure-python oracle
  (first-hit parity across the sharded span);
* the solo route gains the same mesh through
  ``persistent_step_builder`` and agrees with the oracle too;
* ``search.mesh_devices`` reports the full simulated span.

Prints one JSON summary line on stdout (details to stderr); exit 0 on
success — the shape scripts/chaos_smoke.py established for CI lanes.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distpow_tpu.parallel import compat  # noqa: E402

N_DEVICES = int(os.environ.get("MESH_SMOKE_DEVICES", "4"))
compat.request_cpu_devices(N_DEVICES)

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.models.registry import get_hash_model  # noqa: E402
from distpow_tpu.parallel.search import persistent_search  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY  # noqa: E402
from distpow_tpu.sched.engine import BatchingScheduler  # noqa: E402
from distpow_tpu.sched.lanes import persistent_step_builder  # noqa: E402

NTZ = 3
THREADS = list(range(256))


def main() -> int:
    import jax

    devices = len(jax.devices())
    assert devices == N_DEVICES, (
        f"expected {N_DEVICES} simulated CPU devices, backend has "
        f"{devices} — compat.request_cpu_devices ran too late?"
    )

    # scheduler route: auto ranking on a multi-device CPU host must
    # pick the mesh lane, and the answer must match the oracle
    before = REGISTRY.get("sched.lane_launches.mesh")
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 12,
                            max_slots=4)
    try:
        sched_secrets = {}
        for seed in (0x21, 0x22):
            nonce = bytes([seed, 0xA5])
            got = eng.search(nonce, NTZ, THREADS)
            want = puzzle.python_search(nonce, NTZ, THREADS)
            assert got == want, (
                f"mesh-lane scheduler diverged from oracle for nonce "
                f"{nonce.hex()}: {got!r} != {want!r}"
            )
            sched_secrets[nonce.hex()] = got.hex()
    finally:
        eng.close()
    mesh_launches = REGISTRY.get("sched.lane_launches.mesh") - before
    assert mesh_launches > 0, (
        "scheduler served zero launches on the mesh lane — planner "
        "fell back to xla on a multi-device host"
    )

    # solo route: the persistent step builder binds the mesh
    # persistent step for the same span
    nonce = b"\x23\xa5\x5a"
    sb = persistent_step_builder(nonce, NTZ, 0, 256, get_hash_model("md5"))
    assert sb is not None, "persistent builder declined a 4-device host"
    res = persistent_search(nonce, NTZ, THREADS, batch_size=1 << 12,
                            step_builder=sb)
    want = puzzle.python_search(nonce, NTZ, THREADS)
    assert res is not None and res.secret == want, (
        f"mesh persistent route diverged from oracle: "
        f"{getattr(res, 'secret', None)!r} != {want!r}"
    )

    gauge = REGISTRY.get("search.mesh_devices")
    assert gauge == devices, (
        f"search.mesh_devices gauge {gauge} != device count {devices}"
    )

    print(json.dumps({
        "devices": devices,
        "mesh_launches": mesh_launches,
        "sched_secrets": sched_secrets,
        "persistent_secret": res.secret.hex(),
        "mesh_devices_gauge": gauge,
        "ok": True,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
