#!/usr/bin/env bash
# Second r4 hardware batch (after the r4b queue drained): the follow-ups
# the r4b results themselves motivated — (1) ripemd160 kernel geometry
# sweep (its pallas tile measured 69 MH/s vs 1285 XLA serving in the r4b
# bench: is it geometry or the tile form?), (2) the sha512 compress-form
# probe (the unrolled form's first compile out-waited the 420 s bench
# watchdog; is the fori_loop form competitive at a fraction of the
# compile cost?), (3) a full bench re-run on the NEW swept geometries
# (sha1 (32,2048) +12.5%, sha256 (32,256)) so last_measured provenance
# reflects the shipped configuration.  Sequential, no kills (an
# interrupted TPU client has twice wedged the tunnel for hours).
set -uo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-docs/artifacts/r4c}"
mkdir -p "$OUT"

echo "=== waiting for device ($(date +%T)) ===" | tee "$OUT/session.log"
UP=0
for i in $(seq 1 200); do
  timeout 150 python -c "import jax, jax.numpy as jnp; assert int(jnp.uint32(2)+jnp.uint32(3))==5" 2>"$OUT/probe.err"
  RC=$?
  if [ "$RC" -eq 0 ]; then
    echo "device up at $(date +%T)" | tee -a "$OUT/session.log"
    UP=1
    break
  elif [ "$RC" -ne 124 ] && [ "$RC" -ne 143 ]; then
    echo "probe CRASHED (rc=$RC) — broken environment, aborting:" \
      | tee -a "$OUT/session.log"
    tail -5 "$OUT/probe.err" | tee -a "$OUT/session.log"
    exit 1
  fi
  sleep 90
done
if [ "$UP" -ne 1 ]; then
  echo "device never appeared; aborting session" | tee -a "$OUT/session.log"
  exit 1
fi

echo "=== ripemd160 kernel sweep ===" | tee -a "$OUT/session.log"
timeout 2400 python scripts/sweep_sha256_pallas.py --model ripemd160 \
  >"$OUT/sweep_ripemd160.log" 2>&1
tail -6 "$OUT/sweep_ripemd160.log" | tee -a "$OUT/session.log"

echo "=== sha512 compress-form probe ===" | tee -a "$OUT/session.log"
timeout 2400 python scripts/probe_sha512_forms.py 20 \
  >"$OUT/sha512_forms.json" 2>"$OUT/sha512_forms.log"
cat "$OUT/sha512_forms.json" | tee -a "$OUT/session.log"
tail -3 "$OUT/sha512_forms.log" | tee -a "$OUT/session.log"

echo "=== full bench (swept geometries) ===" | tee -a "$OUT/session.log"
python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log"
cat "$OUT/bench.json" | tee -a "$OUT/session.log"

echo "=== done $(date +%T) ===" | tee -a "$OUT/session.log"
