#!/usr/bin/env bash
# Round-5 follow-up hardware batch: the ninth model (sha256d, composed
# double SHA-256) landed after scripts/tpu_session_r5.sh was already
# armed, and a RUNNING bash script must not be edited in place (bash
# reads by file offset).  This batch adds sha256d's hardware evidence:
# geometry sweep + a bench refresh (bench.py's model loop already
# includes sha256d, so the refresh lands its serving + kernel lines
# into last_measured.json).  Run AFTER the main r5 session completes.
set -uo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-docs/artifacts/r5}"
mkdir -p "$OUT"
LOG="$OUT/session_b.log"

note() { echo "[$(date +%T)] $*" | tee -a "$LOG"; }

wait_device() {
  for i in $(seq 1 "${1:-200}"); do
    timeout 150 python -c \
      "import jax, jax.numpy as jnp; assert int(jnp.uint32(2)+jnp.uint32(3))==5" \
      2>"$OUT/probe_b.err" && { note "device up"; return 0; }
    local rc=$?
    if [ "$rc" -ne 124 ] && [ "$rc" -ne 143 ] && [ "$rc" -ne 1 ]; then
      note "probe CRASHED (rc=$rc), aborting"; exit 1
    fi
    sleep 90
  done
  note "device never appeared"; return 1
}

note "r5b session start"
wait_device 200 || exit 1

# sweep FIRST, with --no-xla-ref: the geometry table is this batch's
# primary artifact and the Mosaic tile compiles in seconds — no
# unknown-cost XLA compile stands in front of it (review r5).
note "=== sha256d kernel geometry sweep (no XLA ref) ==="
timeout 2400 python scripts/sweep_sha256_pallas.py --model sha256d \
  --no-xla-ref >"$OUT/sweep_sha256d.log" 2>&1
note "sweep rc=$?"
tail -6 "$OUT/sweep_sha256d.log" | tee -a "$LOG"
wait_device 200 || exit 1

# bench AFTER: it meets sha256d's unknown-cost fused serving compile
# right after the budget-capped HBM lines, while the deadline still
# admits it.  If that compile proves sha512-class, the 1800 s compile
# grace expires into bench.py's hang bailout, which SALVAGES every
# already-measured stage into provenance and exits cleanly — the
# timeout must therefore exceed deadline + grace + slack (1200 + 1800
# + headroom), or the SIGTERM would land first and discard the run
# (review r5).
note "=== bench refresh (sha256d lines) ==="
BENCH_DEADLINE_S=1200 timeout 4000 python bench.py \
  >"$OUT/bench4.json" 2>"$OUT/bench4.log"
note "bench4 rc=$?"
cat "$OUT/bench4.json" | tee -a "$LOG"
wait_device 200 || exit 1

note "=== sha256d hardware parity ==="
timeout 1200 python scripts/check_pallas_parity.py sha256d \
  >"$OUT/parity_sha256d.log" 2>&1
note "parity rc=$?"
tail -3 "$OUT/parity_sha256d.log" | tee -a "$LOG"

note "r5b session done"
