#!/usr/bin/env python
"""Deterministic serving-loop smoke (scripts/ci.sh --serving-smoke).

Two halves on the CPU platform (docs/SERVING.md):

1. **Persistent loop** — one solve through the persistent driver next
   to the same solve through the serial baseline: first hits must be
   byte-identical and the persistent drain must issue ZERO blocking
   host syncs while the serial loop pays one per launch.
2. **Mixed-hash batch** — an in-process worker (real WorkerRPCHandler,
   real miner threads, real result queue) with a md5+sha1 batching
   scheduler serves an interleaved md5/sha1 Mine batch; every secret is
   host-verified under ITS OWN model, the batch must spend fewer
   launches than the same requests served one at a time (the per-model
   solo baseline), and at least one launch must actually mix models
   (``sched.mixed_hash_launches``).

Prints one JSON summary line on stdout (details to stderr); exit 0 on
success — the shape scripts/sched_smoke.py established for CI lanes.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distpow_tpu.backends import get_backend  # noqa: E402
from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.nodes.worker import WorkerRPCHandler  # noqa: E402
from distpow_tpu.parallel.search import (  # noqa: E402
    persistent_search,
    search,
)
from distpow_tpu.runtime.metrics import REGISTRY  # noqa: E402
from distpow_tpu.runtime.tracing import (  # noqa: E402
    MemorySink,
    Tracer,
    wire_token,
)
from distpow_tpu.sched.engine import BatchingScheduler  # noqa: E402

K = int(os.environ.get("SERVING_SMOKE_REQUESTS", "8"))
NTZ = 3
BATCH = 1 << 10


def persistent_half() -> dict:
    nonce = b"\xe0\x01\x5a"
    b0 = REGISTRY.get("search.blocking_syncs")
    serial = search(nonce, NTZ, list(range(256)), batch_size=BATCH,
                    launch_candidates=1 << 12)
    serial_syncs = REGISTRY.get("search.blocking_syncs") - b0
    b1 = REGISTRY.get("search.blocking_syncs")
    persistent = persistent_search(nonce, NTZ, list(range(256)),
                                   batch_size=BATCH,
                                   launch_candidates=1 << 12)
    persistent_syncs = REGISTRY.get("search.blocking_syncs") - b1
    assert serial is not None and persistent is not None
    if persistent.secret != serial.secret:
        raise AssertionError(
            f"parity violation: persistent {persistent.secret.hex()} vs "
            f"serial {serial.secret.hex()}"
        )
    return {
        "secret": persistent.secret.hex(),
        "serial_blocking_syncs": serial_syncs,
        "persistent_blocking_syncs": persistent_syncs,
        "persistent_steps": REGISTRY.get("search.persistent_steps"),
    }


def mixed_half() -> dict:
    reqs = [(("sha1" if i % 2 else "md5"), bytes([0xE1, i]))
            for i in range(K)]

    # per-model solo baseline: same requests, one at a time
    sl0 = REGISTRY.get("sched.launches")
    solo_eng = BatchingScheduler(hash_model="md5", batch_size=BATCH,
                                 max_slots=K, extra_models=("sha1",))
    try:
        for m, nonce in reqs:
            s = solo_eng.search(nonce, NTZ, list(range(256)), hash_model=m)
            assert puzzle.check_secret(nonce, s, NTZ, m)
    finally:
        solo_eng.close()
    solo_launches = REGISTRY.get("sched.launches") - sl0

    # the batch, through a REAL in-process worker handler
    tracer = Tracer("serving-smoke", MemorySink())
    result_queue: "queue.Queue" = queue.Queue()
    backend = get_backend("jax", batch_size=BATCH)
    sched = BatchingScheduler(hash_model="md5", batch_size=BATCH,
                              max_slots=K, extra_models=("sha1",),
                              fallback=backend, start=False)
    handler = WorkerRPCHandler(tracer, result_queue, backend,
                               scheduler=sched)
    occ0 = REGISTRY.get_histogram("sched.batch_occupancy") or \
        {"count": 0, "sum": 0.0}
    mh0 = REGISTRY.get("sched.mixed_hash_launches")
    sl1 = REGISTRY.get("sched.launches")
    try:
        for m, nonce in reqs:
            trace = tracer.create_trace()
            handler.Mine({
                "nonce": nonce, "num_trailing_zeros": NTZ,
                "worker_byte": 0, "worker_bits": 0,
                "token": wire_token(trace.generate_token()),
                "round": None, "hash_model": m,
            })
        sched.start()  # all K slots queued: the batch is deterministic
        by_nonce = dict()
        deadline = time.time() + 300
        while len(by_nonce) < K and time.time() < deadline:
            res = result_queue.get(timeout=120)
            if res["secret"] is not None:
                by_nonce[bytes(res["nonce"])] = bytes(res["secret"])
        for m, nonce in reqs:
            secret = by_nonce.get(nonce)
            assert secret is not None, f"no result for {nonce.hex()}"
            assert puzzle.check_secret(nonce, secret, NTZ, m), \
                f"{nonce.hex()} secret fails under {m}"
        batched_launches = REGISTRY.get("sched.launches") - sl1
        occ1 = REGISTRY.get_histogram("sched.batch_occupancy")
        n = occ1["count"] - occ0["count"]
        mean_occ = (occ1["sum"] - occ0["sum"]) / max(n, 1)
        return {
            "requests": K,
            "models": ["md5", "sha1"],
            "solo_launches": solo_launches,
            "batched_launches": batched_launches,
            "mean_occupancy": round(mean_occ, 3),
            "mixed_hash_launches":
                REGISTRY.get("sched.mixed_hash_launches") - mh0,
        }
    finally:
        sched.close()


def main() -> int:
    t0 = time.monotonic()
    persistent = persistent_half()
    mixed = mixed_half()
    summary = {
        "persistent": persistent,
        "mixed_hash": mixed,
        "wall_s": round(time.monotonic() - t0, 3),
    }
    print(json.dumps(summary))
    if persistent["persistent_blocking_syncs"] != 0:
        print("[serving-smoke] FAIL: persistent drain issued blocking "
              "syncs", file=sys.stderr)
        return 1
    if persistent["serial_blocking_syncs"] < 1:
        print("[serving-smoke] FAIL: serial baseline recorded no "
              "blocking syncs (instrumentation broken)", file=sys.stderr)
        return 1
    if mixed["batched_launches"] >= mixed["solo_launches"]:
        print(f"[serving-smoke] FAIL: mixed batch spent "
              f"{mixed['batched_launches']} launches vs "
              f"{mixed['solo_launches']} solo", file=sys.stderr)
        return 1
    if mixed["mean_occupancy"] <= 1 or mixed["mixed_hash_launches"] < 1:
        print("[serving-smoke] FAIL: no mixed-hash batching observed",
              file=sys.stderr)
        return 1
    print(f"[serving-smoke] OK: {persistent['serial_blocking_syncs']} "
          f"serial syncs vs 0 persistent; mixed batch "
          f"{mixed['batched_launches']} launches vs "
          f"{mixed['solo_launches']} solo, occupancy "
          f"{mixed['mean_occupancy']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
