#!/usr/bin/env python
"""distpow-lint CLI — run the project-native AST rule engine.

Usage:
    python scripts/lint.py [PATHS...] [--json] [--list-rules]
                           [--rule ID [--rule ID ...]]
                           [--baseline FILE] [--rewrite-baseline]

Defaults to scanning ``distpow_tpu/``.  Exit codes: 0 clean (suppressed
findings allowed), 1 active findings, 2 usage/internal error.  Baseline
hygiene: an entry that no longer matches any current finding is itself
a ``stale-baseline`` finding (exit 1) — grandfathered debt must shrink
monotonically, never rot.  ``--rewrite-baseline`` prunes the stale
entries in place instead of failing.  The rule
catalog with rationale, examples and the suppression policy lives in
docs/LINT.md; ``scripts/ci.sh --lint`` runs this plus ruff and mypy
(both skipped with a note when not installed — the container policy is
stdlib-only for the gate itself).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distpow_tpu.analysis import build_context, run_analysis  # noqa: E402
from distpow_tpu.analysis.engine import Finding, load_baseline  # noqa: E402
from distpow_tpu.analysis.rules import ALL_RULES  # noqa: E402

STALE_BASELINE = "stale-baseline"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distpow-lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to scan (default: distpow_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--rule", action="append", dest="rules", metavar="ID",
                    help="run only the named rule (repeatable)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="JSON baseline of grandfathered findings "
                         "(the committed one is empty and stays empty)")
    ap.add_argument("--rewrite-baseline", action="store_true",
                    help="prune baseline entries that no longer match "
                         "any finding (requires --baseline)")
    args = ap.parse_args(argv)

    if args.rewrite_baseline and not args.baseline:
        print("lint: --rewrite-baseline requires --baseline",
              file=sys.stderr)
        return 2

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.RULE_ID:24s} {rule.DESCRIPTION}")
        return 0

    paths = args.paths or [os.path.join(REPO, "distpow_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"lint: no such path: {p}", file=sys.stderr)
            return 2
    known = {r.RULE_ID for r in ALL_RULES}
    if args.rules and not set(args.rules) <= known:
        print(f"lint: unknown rule(s): {sorted(set(args.rules) - known)}",
              file=sys.stderr)
        return 2

    pkg_root = os.path.join(REPO, "distpow_tpu")
    context = build_context(pkg_root) if os.path.isdir(pkg_root) else None
    report = run_analysis(paths, context=context, rule_ids=args.rules,
                          rel_to=os.getcwd())

    findings = report.findings
    if args.baseline:
        try:
            grandfathered = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as exc:
            print(f"lint: unreadable baseline {args.baseline}: {exc}",
                  file=sys.stderr)
            return 2
        current = {(f.rule, f.path, f.message) for f in findings}
        findings = [f for f in findings
                    if (f.rule, f.path, f.message) not in grandfathered]
        stale = sorted(grandfathered - current)
        if stale and args.rewrite_baseline:
            with open(args.baseline) as fh:
                data = json.load(fh)
            keep = [f for f in data.get("findings", ())
                    if (f["rule"], f["path"], f["message"]) in current]
            data["findings"] = keep
            with open(args.baseline, "w") as fh:
                json.dump(data, fh, indent=2)
                fh.write("\n")
            print(f"lint: pruned {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} from "
                  f"{args.baseline}", file=sys.stderr)
        elif stale:
            findings = findings + [
                Finding(STALE_BASELINE, args.baseline, 0,
                        f"baseline entry [{rule}] {path}: {msg!r} no "
                        f"longer matches any finding — delete it or run "
                        f"--rewrite-baseline (grandfathered debt must "
                        f"shrink, never rot)")
                for rule, path, msg in stale
            ]

    if args.as_json:
        payload = report.to_json()
        payload["findings"] = [f.to_json() for f in findings]
        payload["ok"] = not findings
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"distpow-lint: {report.checked_files} file(s), "
            f"{len(findings)} finding(s), "
            f"{len(report.suppressed)} suppressed (all justified)",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
