"""Cold vs cache-hot worker boot: does the persistent compile cache pay?

VERDICT r4 item 2: ``runtime/compile_cache.py`` exists and every entry
point shares it, yet no artifact demonstrates a cache-hot process
restart booting faster than a cold one — and r4's e2e runs still showed
38-41 s warmups for sha384/sha512.  This script measures it directly:

for each model, boot a FRESH process twice against a dedicated cache
directory — once with the directory emptied (cold: every program
compiles), once reusing what the first boot persisted (warm: disk
hits) — timing ``backend.warmup([4], [0..4])`` exactly as a booted
worker warms (``WorkerConfig.WarmupNonceLens``).  Each child also
reports ``compile_cache.error_count()`` so a silently failing cache
(the bench7 ``UNAVAILABLE`` read error) shows up as a nonzero count
next to a bogus "warm" time instead of invisibly poisoning the
comparison.

Usage:
    python scripts/compile_cache_restart.py [models...] [--out FILE]
Defaults: sha384 sha512 (the r4 worst cases) plus md5 as the fast
control.  Reference contrast: a restarted reference worker starts
completely cold every time (/root/reference/worker.go:116-126 — its
caches are in-memory only and there is nothing like a compile to
persist); ours must provably not.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

DEFAULT_MODELS = ["md5", "sha384", "sha512"]
CACHE_DIR = "/tmp/xla_cache_restart_probe"

_CHILD = r"""
import json, os, sys, time
model, cache_dir = sys.argv[1], sys.argv[2]
force = os.environ.get("BENCH_FORCE_PLATFORM")
if force:
    import jax
    jax.config.update("jax_platforms", force)
from distpow_tpu.runtime import compile_cache
compile_cache.enable(cache_dir)
from distpow_tpu.backends import get_backend
t0 = time.time()
backend = get_backend("auto", hash_model=model, batch_size=1 << 21)
backend.warmup([4], [0, 1, 2, 3, 4])
warm_s = time.time() - t0
print(json.dumps({
    "model": model,
    "backend": type(backend).__name__,
    "warmup_s": round(warm_s, 2),
    "cache_errors": compile_cache.error_count(),
}))
"""


def boot_once(model: str, timeout_s: float) -> dict:
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-c", _CHILD, model, CACHE_DIR],
        capture_output=True, text=True, timeout=timeout_s,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if out.returncode != 0:
        raise RuntimeError(f"{model} boot failed: {out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    rec["process_s"] = round(time.time() - t0, 2)
    for line in out.stderr.splitlines():
        if "compile cache error" in line:
            print(f"  [child stderr] {line}", file=sys.stderr)
    return rec


def main() -> None:
    args = [a for a in sys.argv[1:]]
    outfile = None
    if "--out" in args:
        i = args.index("--out")
        outfile = args[i + 1]
        del args[i:i + 2]
    models = args or DEFAULT_MODELS
    # one boot can legitimately take tens of minutes cold on the
    # tunneled backend (sha512's serving compile is why the pallas
    # backend exists); the warmup path compiles kernels, not that graph,
    # so 15 min is a generous per-boot ceiling
    timeout_s = float(os.environ.get("RESTART_PROBE_TIMEOUT_S", "900"))

    report = {"cache_dir": CACHE_DIR, "models": {}}
    for model in models:
        # per-model isolation: on the fragile tunnel one boot hanging
        # must cost that model's rows, not the whole report (the same
        # per-stage degradation bench.py uses)
        try:
            # cold: empty the dedicated directory so nothing carries
            # over from previous probes (the shared /tmp/xla_cache is
            # untouched)
            shutil.rmtree(CACHE_DIR, ignore_errors=True)
            os.makedirs(CACHE_DIR, exist_ok=True)
            print(f"[restart] {model}: cold boot ...", file=sys.stderr)
            cold = boot_once(model, timeout_s)
            print(f"[restart] {model}: cold warmup {cold['warmup_s']}s "
                  f"(errors={cold['cache_errors']})", file=sys.stderr)
            print(f"[restart] {model}: warm boot ...", file=sys.stderr)
            warm = boot_once(model, timeout_s)
            print(f"[restart] {model}: warm warmup {warm['warmup_s']}s "
                  f"(errors={warm['cache_errors']})", file=sys.stderr)
        except (RuntimeError, subprocess.TimeoutExpired, ValueError) as exc:
            print(f"[restart] {model}: FAILED: {exc}", file=sys.stderr)
            report["models"][model] = {"error": str(exc)[:500]}
            continue
        entry = {
            "backend": cold["backend"],
            "cold_warmup_s": cold["warmup_s"],
            "warm_warmup_s": warm["warmup_s"],
            "speedup": round(cold["warmup_s"] / max(warm["warmup_s"], 1e-9),
                             1),
            "cold_cache_errors": cold["cache_errors"],
            "warm_cache_errors": warm["cache_errors"],
        }
        report["models"][model] = entry

    line = json.dumps(report)
    print(line)
    if outfile:
        with open(outfile, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
