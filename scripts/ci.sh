#!/usr/bin/env bash
# One-command verification: native build, fast test suite, multichip
# dryrun.  The full suite (incl. slow interpret-mode Pallas and
# multi-process tests, excl. the nightly veryslow tier — README Tests)
# is `--full` (~10 min of pytest); this fast lane is what a pre-commit
# check should run (~4 min).  `--nightly` adds the veryslow tier.
# `--chaos` runs only the deterministic fault-injection matrix plus the
# canned chaos smoke replay (docs/FAULTS.md) — the fast/full lanes
# already include the matrix via the un-slow `faults` marker.
# `--lint` runs the static gate alone: distpow-lint (docs/LINT.md)
# against the committed empty baseline, then ruff and mypy when
# installed (`pip install -e .[lint]`; skipped with a note otherwise —
# the gate itself is stdlib-only).  The fast/full lanes already enforce
# distpow-lint via the un-slow `lint` marker.
# Usage: scripts/ci.sh [--full|--nightly|--chaos|--lint]
set -euo pipefail
cd "$(dirname "$0")/.."

run_lint() {
  echo "=== distpow-lint (AST rule engine, docs/LINT.md) ==="
  python scripts/lint.py distpow_tpu/ --baseline scripts/lint_baseline.json
  echo "=== ruff ==="
  if command -v ruff >/dev/null 2>&1; then
    ruff check distpow_tpu/ scripts/ tests/
  else
    echo "ruff not installed; skipping (pip install -e .[lint])"
  fi
  echo "=== mypy (strict-leaning on runtime/ + nodes/) ==="
  if command -v mypy >/dev/null 2>&1; then
    mypy distpow_tpu/runtime distpow_tpu/nodes
  else
    echo "mypy not installed; skipping (pip install -e .[lint])"
  fi
  echo "=== lint OK ==="
}

# the static gate needs no native build — run and exit early
if [ "${1:-}" = "--lint" ]; then
  run_lint
  exit 0
fi

echo "=== native miner build ==="
make -C distpow_tpu/backends/native

echo "=== test suite ==="
case "${1:-}" in
  --nightly) python -m pytest tests/ -q ;;
  --full) python -m pytest tests/ -q -m "not veryslow" ;;
  --chaos) python -m pytest tests/ -q -m faults
           echo "=== chaos smoke replay ==="
           python scripts/chaos_smoke.py
           echo "=== chaos OK ==="
           exit 0 ;;
  "")     python -m pytest tests/ -q -m "not slow and not veryslow" ;;
  *)      echo "unknown argument: $1" >&2
          echo "usage: scripts/ci.sh [--full|--nightly|--chaos|--lint]" >&2
          exit 2 ;;
esac

echo "=== multichip dryrun (8 virtual devices) ==="
python - <<'EOF'
from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
EOF

echo "=== ci OK ==="
