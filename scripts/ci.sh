#!/usr/bin/env bash
# One-command verification: native build, fast test suite, multichip
# dryrun.  The full suite (incl. slow interpret-mode Pallas and
# multi-process tests, excl. the nightly veryslow tier — README Tests)
# is `--full` (~10 min of pytest); this fast lane is what a pre-commit
# check should run (~4 min).  `--nightly` adds the veryslow tier.
# `--chaos` runs only the deterministic fault-injection matrix plus the
# canned chaos smoke replay (docs/FAULTS.md) — the fast/full lanes
# already include the matrix via the un-slow `faults` marker.
# `--lint` runs the static gate alone: distpow-lint (docs/LINT.md)
# against the committed empty baseline, then ruff and mypy when
# installed (`pip install -e .[lint]`; skipped with a note otherwise —
# the gate itself is stdlib-only).  The fast/full lanes already enforce
# distpow-lint via the un-slow `lint` marker.
# `--bench-rehearsal` runs the FULL outage-shaped bench (bench.py) on
# the CPU platform against a temp provenance file — proving the bench
# plumbing (stage order, anomaly screen, last_measured write) end to
# end before the next hardware window, without touching the checked-in
# hardware provenance (VERDICT r5 weak #3).  ~3-6 min of CPU compiles.
# `--sched-smoke` runs the deterministic continuous-batching smoke
# (scripts/sched_smoke.py, docs/SCHEDULER.md): K concurrent Mines on
# one CPU worker must batch (mean occupancy > 1), coalesce duplicates,
# and drain — ~30 s.
# `--wire-smoke` runs the deterministic RPC data-plane smoke
# (scripts/wire_smoke.py, docs/RPC.md): wire-v2 negotiation, parallel
# fan-out seams recorded, chaos on binary frames ridden out, and a
# JSON-pinned client interoperating — ~20 s, pure CPU.
# `--serving-smoke` runs the deterministic serving-loop smoke
# (scripts/serving_smoke.py, docs/SERVING.md): the persistent loop must
# match the serial driver's first hit with zero blocking host syncs,
# and a mixed-hash (md5+sha1) batch through an in-process worker must
# spend fewer launches than the per-model solo baseline — ~30 s, CPU.
# `--slo-smoke` runs the deterministic SLO-gate smoke
# (scripts/slo_smoke.py, docs/SLO.md): an open-loop Poisson burst on an
# in-process cluster must pass the checked-in config/slo.json (exit 0)
# while a tightened copy must breach (nonzero exit + slo.breach
# flight-recorder event + ring dump) — ~15 s, CPU.
# `--fleet-smoke` runs the deterministic elastic-membership smoke
# (scripts/fleet_smoke.py, docs/FLEET.md): two workers join by
# Fleet.Register with a 4:1 rate skew, a round fans out weighted byte
# ranges and solves, a frozen straggler's shard is hedged, `stats
# --discover`'s membership pull tracks the fleet, and a drain releases
# only after its in-flight rounds finish — ~20 s, CPU, no jax.
# `--cluster-smoke` runs the coordinator-pool chaos smoke
# (scripts/cluster_smoke.py, docs/CLUSTER.md): a REAL 2-process
# coordinator pool over one shared worker fleet, discovery expanding
# one seed to the whole pool, then one shard SIGKILLed mid-load —
# zero client-visible Mine errors via ring failover, and trace_check
# must still report 0 violations — ~20 s, CPU, no jax.
# `--ha-smoke` runs the replicated-dominance-cache crash/restart gate
# (scripts/ha_smoke.py, docs/CLUSTER.md "Replication & HA"): a REAL
# 2-process coordinator pool with write-behind replication on, one
# member SIGKILLed mid-load — the survivor must serve the dead
# member's repeat keys from its REPLICATED cache (hits, zero fan-outs,
# zero client errors), and the restarted member must rejoin warm from
# its journal — ~30 s, CPU, no jax.
# `--forensics-smoke` runs the request-forensics smoke
# (scripts/forensics_smoke.py, docs/FORENSICS.md): a REAL 3-process
# cluster (coordinator + 2 workers, one delayed by the PR 1 fault
# plane), one slow Mine, then the forensics CLI's cross-process
# Node.Spans sweep must stitch a timeline naming the delayed worker's
# shard; trace_check must still report 0 violations — ~15 s, CPU,
# no jax.
# `--soak-smoke` runs the long-haul soak gate smoke
# (scripts/soak_smoke.py, docs/SOAK.md): a seeded COMPRESSED
# diurnal+flash-crowd "day" on an in-process cluster with chaos on
# must end in a green SoakVerdict (every phase SLO-clean, zero leak
# suspects, bounded ring drops/lag) with a replayable JSONL spool,
# and a PLANTED thread-per-request leak must flip the verdict nonzero
# naming proc.threads — ~90 s, CPU.
# `--race-audit` runs the concurrency suites (fleet, cluster, sched,
# chaos matrix, lockcheck's own tests) under the RUNTIME lock-order
# audit (DISTPOW_LOCK_CHECK=1, runtime/lockcheck.py): every repo lock
# acquisition is recorded into an order graph and the session FAILS on
# any observed inversion — the dynamic twin of the static
# lock-order-inversion rule (docs/CONCURRENCY.md) — ~2 min, CPU.
# `--mesh-smoke` runs the kernel-lane launch planner smoke
# (scripts/mesh_smoke.py, docs/SERVING.md "Kernel-lane launch
# planner"): 4 virtual CPU devices booted through the version-portable
# compat shim, a scheduler solve and a solo persistent solve must both
# ride the mesh lane and match the pure-python oracle byte-for-byte,
# with sched.lane_launches.mesh and search.mesh_devices counting the
# span — ~30 s, CPU.
# Usage: scripts/ci.sh [--full|--nightly|--chaos|--lint|--race-audit|--bench-rehearsal|--sched-smoke|--wire-smoke|--serving-smoke|--slo-smoke|--soak-smoke|--mesh-smoke|--fleet-smoke|--forensics-smoke|--cluster-smoke|--ha-smoke]
set -euo pipefail
cd "$(dirname "$0")/.."

run_lint() {
  echo "=== distpow-lint (AST rule engine, docs/LINT.md) ==="
  python scripts/lint.py distpow_tpu/ --baseline scripts/lint_baseline.json
  echo "=== ruff ==="
  if command -v ruff >/dev/null 2>&1; then
    ruff check distpow_tpu/ scripts/ tests/
  else
    echo "ruff not installed; skipping (pip install -e .[lint])"
  fi
  echo "=== mypy (strict-leaning on runtime/ nodes/ cluster/ fleet/ sched/) ==="
  if command -v mypy >/dev/null 2>&1; then
    mypy distpow_tpu/runtime distpow_tpu/nodes distpow_tpu/cluster \
         distpow_tpu/fleet distpow_tpu/sched
  else
    echo "mypy not installed; skipping (pip install -e .[lint])"
  fi
  echo "=== lint OK ==="
}

# the static gate needs no native build — run and exit early
if [ "${1:-}" = "--lint" ]; then
  run_lint
  exit 0
fi

if [ "${1:-}" = "--race-audit" ]; then
  echo "=== race audit (runtime lock-order instrumentation, docs/CONCURRENCY.md) ==="
  DISTPOW_LOCK_CHECK=1 python -m pytest -q \
    tests/test_lockcheck.py tests/test_fleet.py tests/test_cluster.py \
    tests/test_sched.py tests/test_faults.py \
    -m "not slow and not veryslow"
  echo "=== race audit OK (zero observed lock-order inversions) ==="
  exit 0
fi

if [ "${1:-}" = "--sched-smoke" ]; then
  echo "=== scheduler smoke (continuous batching, CPU platform) ==="
  JAX_PLATFORMS=cpu python scripts/sched_smoke.py
  echo "=== sched smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--wire-smoke" ]; then
  echo "=== wire smoke (codec negotiation + parallel fan-out + chaos-on-binary) ==="
  JAX_PLATFORMS=cpu python scripts/wire_smoke.py
  echo "=== wire smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--serving-smoke" ]; then
  echo "=== serving smoke (persistent loop + mixed-hash batch, CPU platform) ==="
  JAX_PLATFORMS=cpu python scripts/serving_smoke.py
  echo "=== serving smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--slo-smoke" ]; then
  echo "=== SLO gate smoke (open-loop load + cluster merge + breach evidence) ==="
  JAX_PLATFORMS=cpu python scripts/slo_smoke.py
  echo "=== slo smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--soak-smoke" ]; then
  echo "=== soak gate smoke (compressed diurnal+flash day + planted leak) ==="
  JAX_PLATFORMS=cpu python scripts/soak_smoke.py
  echo "=== soak smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--mesh-smoke" ]; then
  echo "=== mesh lane smoke (4 virtual CPU devices + lane planner parity) ==="
  JAX_PLATFORMS=cpu python scripts/mesh_smoke.py
  echo "=== mesh smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--fleet-smoke" ]; then
  echo "=== fleet smoke (elastic join + weighted shards + hedge + drain) ==="
  JAX_PLATFORMS=cpu python scripts/fleet_smoke.py
  echo "=== fleet smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--cluster-smoke" ]; then
  echo "=== cluster smoke (2-process coordinator pool + SIGKILL failover) ==="
  JAX_PLATFORMS=cpu python scripts/cluster_smoke.py
  echo "=== cluster smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--ha-smoke" ]; then
  echo "=== cache-HA smoke (replicated cache + SIGKILL + warm restart) ==="
  JAX_PLATFORMS=cpu python scripts/ha_smoke.py
  echo "=== ha smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--forensics-smoke" ]; then
  echo "=== forensics smoke (3-process cluster + delayed worker + stitched timeline) ==="
  JAX_PLATFORMS=cpu python scripts/forensics_smoke.py
  echo "=== forensics smoke OK ==="
  exit 0
fi

if [ "${1:-}" = "--bench-rehearsal" ]; then
  echo "=== bench rehearsal (CPU platform, temp provenance) ==="
  tmp="$(mktemp -d)"
  trap 'rm -rf "$tmp"' EXIT
  BENCH_FORCE_PLATFORM=cpu \
  BENCH_LAST_MEASURED_PATH="$tmp/last_measured.json" \
  BENCH_DEADLINE_S="${BENCH_DEADLINE_S:-60}" \
    python bench.py > "$tmp/bench_line.json"
  python - "$tmp" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
line = json.load(open(os.path.join(tmp, "bench_line.json")))
lm = json.load(open(os.path.join(tmp, "last_measured.json")))
assert line.get("unit") == "MH/s" and line.get("value", 0) > 0, line
assert lm.get("value", 0) > 0 and lm.get("rates_mhs"), lm
assert lm.get("run_id", "").startswith("bench.py@"), lm
print(f"[rehearsal] headline {line['value']} MH/s (cpu), "
      f"{len(lm['rates_mhs'])} stage(s) in temp provenance, "
      f"run_id={lm['run_id']}")
EOF
  echo "=== bench rehearsal OK ==="
  exit 0
fi

echo "=== native miner build ==="
make -C distpow_tpu/backends/native

echo "=== test suite ==="
case "${1:-}" in
  --nightly) python -m pytest tests/ -q ;;
  --full) python -m pytest tests/ -q -m "not veryslow" ;;
  --chaos) python -m pytest tests/ -q -m faults
           echo "=== chaos smoke replay ==="
           python scripts/chaos_smoke.py
           echo "=== chaos OK ==="
           exit 0 ;;
  "")     python -m pytest tests/ -q -m "not slow and not veryslow" ;;
  *)      echo "unknown argument: $1" >&2
          echo "usage: scripts/ci.sh [--full|--nightly|--chaos|--lint|--race-audit|--bench-rehearsal|--sched-smoke|--wire-smoke|--serving-smoke|--slo-smoke|--soak-smoke|--mesh-smoke|--fleet-smoke|--forensics-smoke|--cluster-smoke|--ha-smoke]" >&2
          exit 2 ;;
esac

echo "=== multichip dryrun (8 virtual devices) ==="
python - <<'EOF'
from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
EOF

echo "=== ci OK ==="
