#!/usr/bin/env bash
# One-command verification: native build, fast test suite, multichip
# dryrun.  The full suite (incl. slow interpret-mode Pallas and
# multi-process tests, excl. the nightly veryslow tier — README Tests)
# is `--full` (~10 min of pytest); this fast lane is what a pre-commit
# check should run (~4 min).  `--nightly` adds the veryslow tier.
# `--chaos` runs only the deterministic fault-injection matrix plus the
# canned chaos smoke replay (docs/FAULTS.md) — the fast/full lanes
# already include the matrix via the un-slow `faults` marker.
# Usage: scripts/ci.sh [--full|--nightly|--chaos]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== native miner build ==="
make -C distpow_tpu/backends/native

echo "=== test suite ==="
case "${1:-}" in
  --nightly) python -m pytest tests/ -q ;;
  --full) python -m pytest tests/ -q -m "not veryslow" ;;
  --chaos) python -m pytest tests/ -q -m faults
           echo "=== chaos smoke replay ==="
           python scripts/chaos_smoke.py
           echo "=== chaos OK ==="
           exit 0 ;;
  "")     python -m pytest tests/ -q -m "not slow and not veryslow" ;;
  *)      echo "unknown argument: $1" >&2
          echo "usage: scripts/ci.sh [--full|--nightly|--chaos]" >&2
          exit 2 ;;
esac

echo "=== multichip dryrun (8 virtual devices) ==="
python - <<'EOF'
from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
EOF

echo "=== ci OK ==="
