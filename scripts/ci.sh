#!/usr/bin/env bash
# One-command verification: native build, fast test suite, multichip
# dryrun.  The full suite (incl. slow interpret-mode Pallas and
# multi-process tests) is `pytest tests/ -q` (~15 min); this fast lane
# is what a pre-commit check should run (~4 min).
# Usage: scripts/ci.sh [--full]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== native miner build ==="
make -C distpow_tpu/backends/native

echo "=== test suite ==="
case "${1:-}" in
  --full) python -m pytest tests/ -q ;;
  "")     python -m pytest tests/ -q -m "not slow" ;;
  *)      echo "unknown argument: $1 (usage: scripts/ci.sh [--full])" >&2
          exit 2 ;;
esac

echo "=== multichip dryrun (8 virtual devices) ==="
python - <<'EOF'
from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
EOF

echo "=== ci OK ==="
