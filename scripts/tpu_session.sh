#!/usr/bin/env bash
# One uninterrupted TPU work session: waits for the device, then runs
# the round-4 hardware queue in value order — (1) the full bench,
# (2) the config-5 process-level run, (3) the pallas parity
# distribution, (4) the sha1 kernel geometry sweep, (5) the full sha256
# sweep — sequentially, in one process tree, with NO kills in between
# (interrupting an active TPU client has twice left the tunnel
# unresponsive for hours; see docs/KERNELS.md + BASELINE.md provenance
# notes).  Output goes INSIDE the repo (docs/artifacts/) so every
# number lands in a committable file (VERDICT r3 item 2: round 3's raw
# sweep log lived in /tmp and was lost with the machine).
# Usage: scripts/tpu_session.sh [outdir]   (default docs/artifacts/r4)
set -uo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-docs/artifacts/r4}"
mkdir -p "$OUT"

echo "=== waiting for device ($(date +%T)) ===" | tee "$OUT/session.log"
UP=0
for i in $(seq 1 200); do
  timeout 150 python -c "import jax, jax.numpy as jnp; assert int(jnp.uint32(2)+jnp.uint32(3))==5" 2>"$OUT/probe.err"
  RC=$?
  if [ "$RC" -eq 0 ]; then
    echo "device up at $(date +%T)" | tee -a "$OUT/session.log"
    UP=1
    break
  elif [ "$RC" -ne 124 ] && [ "$RC" -ne 143 ]; then
    # fast nonzero exit = broken environment (ImportError, bad venv),
    # not an outage — looping for hours could never help
    echo "probe CRASHED (rc=$RC) — broken environment, aborting:" \
      | tee -a "$OUT/session.log"
    tail -5 "$OUT/probe.err" | tee -a "$OUT/session.log"
    exit 1
  fi
  sleep 90
done
if [ "$UP" -ne 1 ]; then
  echo "device never appeared; aborting session" | tee -a "$OUT/session.log"
  exit 1
fi

# Stage order = value per TPU-minute: the headline bench first (the
# 2026-07-29/30 outages both struck mid-session; whatever runs first is
# whatever gets measured), then the process-level config-5 drive
# (VERDICT r3 #3), the pallas parity distribution (#5), the sha1
# geometry sweep (#4), and the open-ended full sha256 sweep last (#2).
echo "=== full bench ===" | tee -a "$OUT/session.log"
python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log"
cat "$OUT/bench.json" | tee -a "$OUT/session.log"

echo "=== config-5 TPU-backed process run ===" | tee -a "$OUT/session.log"
bash scripts/run_config5_tpu.sh 6 "$OUT/config5" >"$OUT/config5.log" 2>&1
grep -E "MineResult|violation|wall-clock|warmup" "$OUT/config5.log" | tee -a "$OUT/session.log"

echo "=== pallas parity distribution (12 fresh nonces) ===" | tee -a "$OUT/session.log"
python scripts/parity_pallas.py 12 >"$OUT/parity.json" 2>"$OUT/parity.log"
cat "$OUT/parity.json" | tee -a "$OUT/session.log"

echo "=== sha1 kernel sweep ===" | tee -a "$OUT/session.log"
python scripts/sweep_sha256_pallas.py --model sha1 >"$OUT/sweep_sha1.log" 2>&1
tail -12 "$OUT/sweep_sha1.log" | tee -a "$OUT/session.log"

echo "=== sha256 kernel sweep (full) ===" | tee -a "$OUT/session.log"
python scripts/sweep_sha256_pallas.py >"$OUT/sweep_sha256.log" 2>&1
tail -12 "$OUT/sweep_sha256.log" | tee -a "$OUT/session.log"

echo "=== done $(date +%T) ===" | tee -a "$OUT/session.log"
